"""GAME coordinates: per-coordinate training + scoring units.

Counterpart of photon-lib algorithm/Coordinate.scala + ModelCoordinate.scala
and photon-api algorithm/ (FixedEffectCoordinate.scala:33-156,
RandomEffectCoordinate.scala:37-221, FixedEffectModelCoordinate.scala,
RandomEffectModelCoordinate.scala, CoordinateFactory.scala:51).

Execution model:
  * FixedEffectCoordinate: one distributed GLM solve. The reference
    broadcasts coefficients and treeAggregates gradients per L-BFGS/TRON
    iteration (FixedEffectCoordinate.scala:126-133); here the whole optimizer
    loop is one jitted XLA program over the (sharded) batch — coefficient
    "broadcast" is replication, gradient reduction is an ICI all-reduce
    inserted by XLA.
  * RandomEffectCoordinate: the reference joins co-partitioned activeData
    with per-entity problems and runs a JVM optimizer per entity
    (RandomEffectCoordinate.scala:95-131); here each size-bucket of entities
    is one vmapped solver call over (E, S, ...) blocks — thousands of
    co-resident L-BFGS/TRON instances in one XLA program, each stopping via
    its own convergence mask. Per-entity warm start (:110-121) is a gather of
    the previous coefficient matrix. Same-shape buckets additionally fuse
    into ONE lax.scan program per sweep (sweep_scan_enabled, r06): block
    gather, vmapped solve, coefficient scatter and variance all run inside
    it, so a sweep costs O(distinct block shapes) dispatches instead of
    3-4 per bucket — bitwise equal to the per-bucket loop. On an
    entity-sharded mesh (r07) the scan keeps the coefficient matrix
    row-sharded end to end: warm-start gathers and coefficient scatters
    ride the ring collectives INSIDE the scan body, so per-device
    coefficient state stays total/n_devices — the reference's
    RDD-partitioned store (RandomEffectModel.scala:36-239) with XLA
    collectives instead of Spark shuffles.

Each coordinate builds its jitted train/score callables ONCE (per bucket
shape); repeated coordinate-descent iterations and regularization-weight
sweeps hit the compile cache because reg weights and PRNG keys are traced
arguments, not constants.

Residuals enter through the offsets argument (`dataset.addScoresToOffsets`
in the reference, Coordinate.scala); train/score take explicit offset vectors.
"""

from __future__ import annotations

import dataclasses
import logging
from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)

from photon_ml_tpu.data.containers import LabeledData, SparseFeatures
from photon_ml_tpu.data.game_dataset import (
    GameDataset,
    RandomEffectDataset,
    gather_block_data,
)
from photon_ml_tpu.data.sampling import down_sample_weights, down_sampler_for_task
from photon_ml_tpu.ops import objective
from photon_ml_tpu.ops.losses import PointwiseLoss, loss_for_task
from photon_ml_tpu.ops.normalization import NormalizationContext
from photon_ml_tpu.optimize import problem
from photon_ml_tpu.optimize.common import OptResult
from photon_ml_tpu.utils import faults
from photon_ml_tpu.utils.knobs import get_knob
from photon_ml_tpu.optimize.config import CoordinateOptimizationConfig
from photon_ml_tpu.game.model import (
    Coefficients,
    FixedEffectModel,
    RandomEffectModel,
)
from photon_ml_tpu.types import TaskType, VarianceComputationType

Array = jax.Array

# bucketed_cache sentinel: distinguishes "never evaluated" from a cached
# decline (None), so pack economics are decided once per dataset shard.
_PACK_UNDECIDED = object()

# Process-wide jitted-program cache for random-effect bucket solvers,
# keyed by the STATIC training recipe (optimizer config statics, task,
# sampling). Two coordinates with the same recipe (e.g. per-user and
# per-movie trained under one GameOptimizationConfiguration) then share
# compiled programs for equal block shapes — with the canonical bucket
# shapes from build_random_effect_dataset this cuts a GLMix fit's XLA
# program count by ~2x (each compile costs seconds on a remote-compile
# backend). Only the norm-free case caches (normalization contexts carry
# arrays, which must not leak across coordinates via a closure).
_RE_JIT_CACHE: dict = {}


def _config_with_traced_weight(
    config: CoordinateOptimizationConfig, reg_weight: Array
) -> CoordinateOptimizationConfig:
    """Swap the (static) reg weight for a traced scalar inside jit."""
    return dataclasses.replace(config, reg_weight=reg_weight)


def sweep_scan_enabled() -> bool:
    """Scan-dispatch the random-effect bucket sweep (PHOTON_SWEEP_SCAN,
    default on): same-shape entity buckets run as ONE lax.scan program —
    block gather, vmapped solve, coefficient scatter and (optional)
    variance all inside it — instead of 3-4 XLA dispatches per bucket.
    Flare's whole-pipeline-compilation thesis applied to the solver loop:
    at bench scale the per-sweep program count drops from O(buckets) to
    O(distinct block shapes), which is what dominates small-coordinate
    fits on a dispatch-latency-bound (remote or contended) backend.

    Reads through the typed knob registry at DISPATCH-DECISION time only
    (train_sweep's host-side gate) — never from inside a traced body, so
    the compiled programs stay pure (analysis/jit_purity)."""
    return bool(get_knob("PHOTON_SWEEP_SCAN"))


def _fusion_chunks(idxs, shape, planned_shapes):
    """Split one same-shape bucket index list into scan-dispatch chunks
    per the planned fusion granularity (ISSUE 14): scan_fusion_max 0 =
    unbounded (the pre-planner default, one program per shape); shapes
    absent from the plan's proven re_bucket_shapes set additionally cap
    at NOVEL_SHAPE_FUSE. Consecutive chunks preserve bucket order, so
    any split is bitwise-identical to the fused program."""
    from photon_ml_tpu import planner
    from photon_ml_tpu.planner.plan import NOVEL_SHAPE_FUSE

    cap = max(0, int(planner.planned_value("scan_fusion_max")))
    if planned_shapes is not None and tuple(shape) not in planned_shapes:
        cap = min(cap, NOVEL_SHAPE_FUSE) if cap else NOVEL_SHAPE_FUSE
    if cap <= 0 or len(idxs) <= cap:
        return [list(idxs)]
    return [list(idxs[i : i + cap]) for i in range(0, len(idxs), cap)]




class FixedEffectCoordinate:
    """One fixed-effect coordinate (FixedEffectCoordinate.scala:33-156)."""

    def __init__(
        self,
        dataset: GameDataset,
        config_data_shard: str,
        opt_config: CoordinateOptimizationConfig,
        task: TaskType,
        norm: Optional[NormalizationContext] = None,
    ):
        self.dataset = dataset
        self.shard = config_data_shard
        self.config = opt_config
        self.task = task
        self.loss: PointwiseLoss = loss_for_task(task)
        self.norm = norm
        # Decide the fused-Pallas objective path ONCE here, on the concrete
        # array — its dtype/shape/sharding are all visible, unlike inside the
        # jit trace where should_use would have to guess. The decision is
        # closed over by the jitted train_fn (ragged tails are masked inside
        # the kernel, so no alignment precondition). Batch-sharded data gets
        # a ShardedDispatch: per-device fused kernel + psum under shard_map.
        from photon_ml_tpu.ops import pallas_glm

        # Peek without forcing a device upload: if the bucketed pack
        # engages below, the raw ELL never ships to the device at all.
        feats = (
            dataset.peek_shard(config_data_shard)
            if hasattr(dataset, "peek_shard")
            else dataset.shards[config_data_shard]
        )
        if not isinstance(feats, SparseFeatures) and pallas_glm.prefers_bf16_storage(
            feats, jnp.zeros((feats.shape[-1],), feats.dtype)
        ):
            # bf16-STORED design matrix for the fused kernels: half the HBM
            # bytes per objective pass, single MXU pass in hilo mode. The
            # converted array is coordinate-local and used for BOTH train
            # and score so CD residuals stay consistent; the dataset's f32
            # shard is untouched for other consumers. Cached on the dataset
            # so sweep steps that rebuild coordinates convert once.
            cache = getattr(dataset, "bucketed_cache", {})
            ckey = ("bf16x", config_data_shard)
            feats = cache.get(ckey)
            if feats is None:
                feats = dataset.shards[config_data_shard].astype(jnp.bfloat16)
                cache[ckey] = feats
        self._use_pallas = (
            False
            if isinstance(feats, SparseFeatures)
            else pallas_glm.dispatch(
                feats, jnp.zeros((feats.shape[-1],), jnp.float32)
            )
        )
        # Sparse shards repack once into the bucketed layout so the
        # objective's matvec/rmatvec run the Pallas sparse kernels
        # (ops/pallas_sparse.py) instead of XLA gather/scatter — the sparse
        # counterpart of the dense fused-kernel decision above. maybe_pack
        # owns the whole decision (backend, dtype, sharding, size, padding
        # economics) and returns None when the ELL/XLA path should stay.
        self._features = feats
        if isinstance(feats, SparseFeatures):
            from photon_ml_tpu.ops import pallas_sparse

            bf = None
            if pallas_sparse.kernels_eligible():
                # Pack once per dataset: sweeps/warm-start chains that
                # rebuild this coordinate reuse the cached layout — and a
                # cached DECLINE, so a shard whose pack isn't worth it is
                # evaluated once, not re-pulled per configuration.
                cache = getattr(dataset, "bucketed_cache", {})
                cached = cache.get(config_data_shard, _PACK_UNDECIDED)
                if cached is _PACK_UNDECIDED:
                    # Preferred path: pack from the host CSR the ingest
                    # stashed on the dataset — no device->host pull of the
                    # ELL arrays (the r03 bench measured that round trip at
                    # 275x the solve time on a remote-device backend). The
                    # stash is consumed here so the arrays don't pin host
                    # RAM for the run's lifetime; COO expansion is deferred
                    # to this point so ingest never pays it. Fallback keeps
                    # the device-ELL pack for hand-built datasets.
                    csr = getattr(dataset, "host_csr", {}).pop(
                        config_data_shard, None
                    )
                    if csr is not None:
                        # The stash holds the same matrix as the device ELL,
                        # so its pack decision is authoritative — a decline
                        # (size/padding economics) must NOT fall through to
                        # maybe_pack's device->host pull of identical data.
                        # Ingest normally started the host pack on a
                        # background thread (begin_pack_async); this joins
                        # it and pays only the upload.
                        bf = pallas_sparse.finish_pack(
                            csr, dataset.num_samples
                        )
                    else:
                        bf = pallas_sparse.maybe_pack(
                            feats, dataset.num_samples
                        )
                    cache[config_data_shard] = bf
                else:
                    bf = cached
            if bf is not None:
                self._features = bf
                # The bucketed repack succeeded, so the objective's fused
                # sparse gate (objective.value_and_gradient: `use_pallas is
                # not False and isinstance(..., BucketedSparseFeatures)`)
                # must be allowed to engage: None = auto.  False stays the
                # caller's genuine escape hatch for shards where the pack was
                # declined and the ELL/XLA composition is the right path.
                self._use_pallas = None
        if isinstance(self._features, SparseFeatures) and not isinstance(
            self._features.indices, jax.Array
        ):
            # ELL path it is (pack declined/ineligible): materialize the
            # device copy through the dataset so other consumers share it.
            self._features = dataset.shards[config_data_shard]
        self._build_jits()

    def _build_jits(self) -> None:
        cfg = self.config
        loss = self.loss
        norm = self.norm
        task = self.task
        use_sampling = cfg.down_sampling_rate < 1.0
        use_pallas = self._use_pallas

        @jax.jit
        def train_fn(features, labels, offsets, weights, w0, reg_weight, key):
            if use_sampling:
                weights = down_sample_weights(
                    key,
                    labels,
                    weights,
                    cfg.down_sampling_rate,
                    negatives_only=down_sampler_for_task(task),
                )
            data = LabeledData(features, labels, offsets, weights)
            res = problem.solve(
                loss,
                data,
                _config_with_traced_weight(cfg, reg_weight),
                w0,
                norm,
                use_pallas=use_pallas,
            )
            return res

        def score_fn(features, w):
            # The transformer's jitted _fe_margins IS the scoring program:
            # CD residual scoring compiles it and evaluation of the
            # training dataset (training_prepared passes this coordinate's
            # `_features`) reuses the compiled program.
            from photon_ml_tpu.transformers.game_transformer import _fe_margins

            return _fe_margins(features, w, norm)

        @jax.jit
        def variance_fn(features, labels, offsets, weights, w, reg_weight):
            data = LabeledData(features, labels, offsets, weights)
            return problem.compute_variances(
                loss, data, _config_with_traced_weight(cfg, reg_weight), w, norm
            )

        self._train_fn = train_fn
        self._score_fn = score_fn
        self._variance_fn = variance_fn

    def train(
        self,
        offsets: Array,
        initial_model: Optional[FixedEffectModel] = None,
        *,
        reg_weight: Optional[float] = None,
        key: Optional[jax.Array] = None,
    ) -> Tuple[FixedEffectModel, OptResult]:
        ds = self.dataset
        feats = self._features
        dim = feats.dim if hasattr(feats, "dim") else feats.shape[-1]
        w0 = (
            initial_model.coefficients.means
            if initial_model is not None
            else jnp.zeros((dim,), ds.labels.dtype)
        )
        rw = jnp.asarray(
            self.config.reg_weight if reg_weight is None else reg_weight,
            ds.labels.dtype,
        )
        if key is None:
            key = jax.random.PRNGKey(0)
        res = self._train_fn(feats, ds.labels, offsets, ds.weights, w0, rw, key)
        variances = None
        if self.config.variance_computation != VarianceComputationType.NONE:
            variances = self._variance_fn(
                feats, ds.labels, offsets, ds.weights, res.coefficients, rw
            )
        model = FixedEffectModel(Coefficients(res.coefficients, variances), self.task)
        return model, res

    @property
    def training_features(self):
        """The representation training actually ran on (bucketed layout,
        bf16-stored matrix, or the ELL) — scoring the training dataset
        through it reuses compiled programs and device residency."""
        return self._features

    # -- stacked-trial hooks (hyperparameter/sweep.py) ----------------------
    # Traceable single-trial train/score: the SAME jitted recipes train()
    # and score() dispatch, taken with traced (offsets, w0, reg_weight)
    # so the sweep executor can lax.scan k reg-weight trials inside ONE
    # XLA program. A jitted callable invoked under tracing inlines, and
    # scan sequences the trial axis (it does NOT vmap it — batched matmul
    # lowering changes reduction order), so each trial's ops — and bits —
    # are identical to a standalone train()/score() call.

    def trial_train(self, offsets, w0, reg_weight, key):
        """One trial's solve as traced values; returns the (coefficients,
        variances) arrays (variances None unless configured)."""
        ds = self.dataset
        res = self._train_fn(
            self._features, ds.labels, offsets, ds.weights, w0, reg_weight, key
        )
        variances = None
        if self.config.variance_computation != VarianceComputationType.NONE:
            variances = self._variance_fn(
                self._features, ds.labels, offsets, ds.weights,
                res.coefficients, reg_weight,
            )
        return res.coefficients, variances

    def trial_score(self, coefficients):
        return self._score_fn(self._features, coefficients)

    def prefetch(self) -> None:
        """Start any pending device upload this coordinate's train/score
        will fault on (coordinate-descent calls this on coordinate k+1
        while coordinate k solves). Fixed effects train and score through
        `self._features`, which construction already materialized — and
        deliberately NOT through the raw ELL shard when the bucketed pack
        engaged — so there is nothing to ship: prefetching the shard here
        would force the very upload the lazy ShardDict avoids."""

    def score(self, model: FixedEffectModel) -> Array:
        """Raw per-sample margins x.w — residual bookkeeping happens in the
        coordinate-descent loop, so no offsets here."""
        return self._score_fn(self._features, model.coefficients.means)


def _infer_entity_mesh(re_dataset):
    """The 1-D mesh the RE dataset's entity blocks are sharded over, if any."""
    from photon_ml_tpu.parallel.mesh import leading_axis_mesh

    if not re_dataset.buckets:
        return None
    return leading_axis_mesh(re_dataset.buckets[0].entity_rows)


class RandomEffectCoordinate:
    """One random-effect coordinate (RandomEffectCoordinate.scala:37-221)."""

    def __init__(
        self,
        dataset: GameDataset,
        re_dataset: RandomEffectDataset,
        opt_config: CoordinateOptimizationConfig,
        task: TaskType,
        norm: Optional[NormalizationContext] = None,
    ):
        self.dataset = dataset
        self.re_dataset = re_dataset
        self.config = opt_config
        self.task = task
        self.loss = loss_for_task(task)
        self.norm = norm
        # Peek: construction needs only the dim — the shard's device upload
        # is deferred to the first gather (prefetch-overlapped with the
        # previous coordinate's solve by the coordinate-descent loop).
        feats = (
            dataset.peek_shard(re_dataset.feature_shard)
            if hasattr(dataset, "peek_shard")
            else dataset.shards[re_dataset.feature_shard]
        )
        self.dim = feats.dim if isinstance(feats, SparseFeatures) else feats.shape[-1]
        # Entity-sharded coefficient store: when the RE dataset's entity
        # blocks are sharded over a mesh, the (E+1, D) matrix is row-sharded
        # over the same axis and accessed through ring collectives
        # (parallel/mesh.py) — per-device coefficient state is total/n_devices
        # instead of a full replica, which is what lets the framework chase
        # the reference's RDD-partitioned coefficient scale
        # (RandomEffectModel.scala:36-239). PerEntityNormalization keeps the
        # replicated path: its per-entity factor/shift arrays would need the
        # same sharding treatment to be meaningful at that scale.
        self._entity_mesh = None
        from photon_ml_tpu.ops.normalization import PerEntityNormalization as _PEN

        if not isinstance(norm, _PEN):
            self._entity_mesh = _infer_entity_mesh(re_dataset)
        self._build_jits()

    def _build_jits(self) -> None:
        cfg = self.config
        loss = self.loss
        norm = self.norm
        from photon_ml_tpu.ops.normalization import PerEntityNormalization

        per_entity_norm = isinstance(norm, PerEntityNormalization)

        if per_entity_norm:
            # Projected-space normalization: each entity's solve gets its own
            # (factors, shifts) row, vmapped alongside its data block
            # (IndexMapProjectorRDD.scala:133).
            @jax.jit
            def train_bucket(block_data, w0_block, f_block, s_block, reg_weight):
                def one(data_e, w0_e, f_e, s_e):
                    return problem.solve(
                        loss,
                        data_e,
                        _config_with_traced_weight(cfg, reg_weight),
                        w0_e,
                        norm.row_context(f_e, s_e),
                        use_pallas=False,
                    )

                return jax.vmap(one)(block_data, w0_block, f_block, s_block)

            @jax.jit
            def variance_bucket(block_data, w_block, f_block, s_block, reg_weight):
                def one(data_e, w_e, f_e, s_e):
                    return problem.compute_variances(
                        loss,
                        data_e,
                        _config_with_traced_weight(cfg, reg_weight),
                        w_e,
                        norm.row_context(f_e, s_e),
                    )

                return jax.vmap(one)(block_data, w_block, f_block, s_block)

            def norm_blocks(entity_rows):
                f = None if norm.factors is None else norm.factors[entity_rows]
                s = None if norm.shifts is None else norm.shifts[entity_rows]
                return f, s

            self._norm_blocks = norm_blocks
        else:
            cache_key = None
            if norm is None:
                from photon_ml_tpu.optimize.config import static_config_key

                cache_key = ("re", static_config_key(cfg), self.task)
            cached = _RE_JIT_CACHE.get(cache_key) if cache_key else None
            if cached is not None:
                train_bucket, variance_bucket = cached
            else:

                @jax.jit
                def train_bucket(block_data: LabeledData, w0_block, reg_weight):
                    # use_pallas=False: the per-entity solves are vmapped;
                    # the fused kernels are single-problem programs and the
                    # vmapped XLA path is the one that batches these small
                    # solves efficiently.
                    def one(data_e, w0_e):
                        return problem.solve(
                            loss,
                            data_e,
                            _config_with_traced_weight(cfg, reg_weight),
                            w0_e,
                            norm,
                            use_pallas=False,
                        )

                    return jax.vmap(one)(block_data, w0_block)

                @jax.jit
                def variance_bucket(block_data: LabeledData, w_block, reg_weight):
                    def one(data_e, w_e):
                        return problem.compute_variances(
                            loss, data_e, _config_with_traced_weight(cfg, reg_weight), w_e, norm
                        )

                    return jax.vmap(one)(block_data, w_block)

                if cache_key:
                    _RE_JIT_CACHE[cache_key] = (train_bucket, variance_bucket)
            self._norm_blocks = None
        self._per_entity_norm = per_entity_norm

        def score_fn(features, entity_rows, matrix):
            # THE shared scoring program: the transformer's jitted
            # _re_margins, with norm passed as a pytree argument. The
            # coordinate-descent residual scoring compiles it, and
            # GameTransformer evaluation of the training dataset
            # (training_prepared: same feature arrays, same shapes) then
            # reuses the compiled program instead of paying a fresh
            # multi-second remote compile per coordinate.
            from photon_ml_tpu.transformers.game_transformer import _re_margins

            return _re_margins(features, entity_rows, matrix, norm)

        self._train_bucket = train_bucket
        self._variance_bucket = variance_bucket
        self._score_fn = score_fn

        # Scan-dispatched sweep (sweep_scan_enabled): all same-shape entity
        # buckets run as ONE XLA program — block gather, vmapped solve,
        # coefficient scatter, optional variance — with (matrix, variances)
        # as the scan carry. Same update order and the same ops as the
        # per-bucket loop, so results are bitwise identical
        # (tests/test_game.py::test_sweep_scan_matches_bucket_loop); only
        # the dispatch count changes: O(distinct shapes) programs per sweep
        # instead of 3-4 dispatches per bucket.
        scan_cache_key = None
        if norm is None:
            from photon_ml_tpu.optimize.config import static_config_key

            scan_cache_key = ("re_scan", static_config_key(cfg), self.task)
        cached_scan = (
            _RE_JIT_CACHE.get(scan_cache_key) if scan_cache_key else None
        )
        if cached_scan is not None:
            self._train_scan = cached_scan
            self._build_sharded_scan()
            return

        @jax.jit
        def train_scan(
            features,
            labels,
            weights,
            offsets,
            matrix,
            var_matrix,
            gathers,
            masks,
            ents,
            feature_mask,
            norm_factors,
            norm_shifts,
            reg_weight,
        ):
            from photon_ml_tpu.data.game_dataset import gather_block_arrays

            traced_cfg = _config_with_traced_weight(cfg, reg_weight)

            def step(carry, xs):
                m, v = carry
                gather, mask, ent = xs
                block = gather_block_arrays(
                    features, labels, weights, offsets, gather, mask, ent,
                    feature_mask,
                )
                w0 = m[ent]
                if per_entity_norm:
                    # Per-entity norm rows arrive as ARGUMENTS (closing
                    # over norm.factors would bake the whole (E+1, D)
                    # matrix into the program as a constant).
                    f_blk = (
                        None if norm_factors is None else norm_factors[ent]
                    )
                    s_blk = (
                        None if norm_shifts is None else norm_shifts[ent]
                    )

                    def one(data_e, w0_e, f_e, s_e):
                        return problem.solve(
                            loss, data_e, traced_cfg, w0_e,
                            norm.row_context(f_e, s_e), use_pallas=False,
                        )

                    res = jax.vmap(one)(block, w0, f_blk, s_blk)
                else:

                    def one(data_e, w0_e):
                        return problem.solve(
                            loss, data_e, traced_cfg, w0_e, norm,
                            use_pallas=False,
                        )

                    res = jax.vmap(one)(block, w0)
                m = m.at[ent].set(res.coefficients)
                if v is not None:
                    if per_entity_norm:

                        def onev(data_e, w_e, f_e, s_e):
                            return problem.compute_variances(
                                loss, data_e, traced_cfg, w_e,
                                norm.row_context(f_e, s_e),
                            )

                        vv = jax.vmap(onev)(
                            block, res.coefficients, f_blk, s_blk
                        )
                    else:

                        def onev(data_e, w_e):
                            return problem.compute_variances(
                                loss, data_e, traced_cfg, w_e, norm
                            )

                        vv = jax.vmap(onev)(block, res.coefficients)
                    v = v.at[ent].set(vv)
                return (m, v), res.iterations

            (matrix, var_matrix), iters = jax.lax.scan(
                step, (matrix, var_matrix), (gathers, masks, ents)
            )
            return matrix, var_matrix, iters

        if scan_cache_key:
            _RE_JIT_CACHE[scan_cache_key] = train_scan
        self._train_scan = train_scan
        self._build_sharded_scan()

    def _build_sharded_scan(self) -> None:
        """Scan-dispatched sweep for the ENTITY-SHARDED store: same shape
        grouping as the replicated scan, but the coefficient matrix carry
        stays row-sharded over the mesh and every bucket step moves rows
        through the ring collectives (parallel/mesh.py) INSIDE the program —
        gather w0, vmapped shard-local solves, scatter coefficients (and
        variances) — so a sweep is O(distinct block shapes) XLA programs
        with per-device coefficient state of total/n_devices, never a full
        replica. Ops per entity are identical to the sharded per-bucket
        loop, so the two are bitwise equal
        (tests/test_parallel.py::test_sharded_scan_sweep_matches_bucket_loop).
        """
        self._train_scan_sharded = None
        mesh = self._entity_mesh
        if mesh is None or self._per_entity_norm:
            return
        cfg = self.config
        loss = self.loss
        norm = self.norm
        sh_cache_key = None
        if norm is None:
            from photon_ml_tpu.optimize.config import static_config_key

            sh_cache_key = ("re_scan_sh", static_config_key(cfg), self.task, mesh)
        cached = _RE_JIT_CACHE.get(sh_cache_key) if sh_cache_key else None
        if cached is not None:
            self._train_scan_sharded = cached
            return

        from photon_ml_tpu.parallel.mesh import ring_gather_rows, ring_scatter_rows

        @jax.jit
        def train_scan_sharded(
            features,
            labels,
            weights,
            offsets,
            matrix,
            var_matrix,
            gathers,
            masks,
            ents,
            feature_mask,
            reg_weight,
        ):
            from photon_ml_tpu.data.game_dataset import gather_block_arrays

            traced_cfg = _config_with_traced_weight(cfg, reg_weight)

            def step(carry, xs):
                m, v = carry
                gather, mask, ent = xs
                block = gather_block_arrays(
                    features, labels, weights, offsets, gather, mask, ent,
                    feature_mask,
                )
                w0 = ring_gather_rows(m, ent, mesh)

                def one(data_e, w0_e):
                    return problem.solve(
                        loss, data_e, traced_cfg, w0_e, norm, use_pallas=False
                    )

                res = jax.vmap(one)(block, w0)
                m = ring_scatter_rows(m, ent, res.coefficients, mesh)
                if v is not None:

                    def onev(data_e, w_e):
                        return problem.compute_variances(
                            loss, data_e, traced_cfg, w_e, norm
                        )

                    vv = jax.vmap(onev)(block, res.coefficients)
                    v = ring_scatter_rows(v, ent, vv, mesh)
                return (m, v), res.iterations

            (matrix, var_matrix), iters = jax.lax.scan(
                step, (matrix, var_matrix), (gathers, masks, ents)
            )
            return matrix, var_matrix, iters

        if sh_cache_key:
            _RE_JIT_CACHE[sh_cache_key] = train_scan_sharded
        self._train_scan_sharded = train_scan_sharded

    def train(
        self,
        offsets: Array,
        initial_model: Optional[RandomEffectModel] = None,
        *,
        reg_weight: Optional[float] = None,
    ) -> Tuple[RandomEffectModel, dict]:
        """Train every entity bucket; returns the new coefficient matrix model.

        Per-entity warm start: gather previous rows (the reference's
        leftOuterJoin of prior models, RandomEffectCoordinate.scala:110-121).
        """
        ds = self.dataset
        red = self.re_dataset
        dtype = ds.labels.dtype
        e_total = red.num_entities
        mesh = self._entity_mesh
        n_rows = e_total + 1
        if mesh is not None:
            from photon_ml_tpu.parallel.mesh import (
                matrix_row_sharding,
                pad_rows_for_mesh,
                put_row_sharded,
                ring_gather_rows,
                ring_scatter_rows,
                sharded_zeros,
            )

            n_rows = pad_rows_for_mesh(n_rows, mesh)
            row_sh = matrix_row_sharding(mesh)
        if initial_model is not None:
            matrix = initial_model.coefficients_matrix
            if matrix.shape[0] < n_rows:
                matrix = np.pad(
                    np.asarray(matrix), ((0, n_rows - matrix.shape[0]), (0, 0))
                )
            if mesh is not None:
                matrix = put_row_sharded(matrix, row_sh)
        elif mesh is not None:
            matrix = sharded_zeros((n_rows, self.dim), dtype, row_sh)
        else:
            matrix = jnp.zeros((n_rows, self.dim), dtype)
        want_var = self.config.variance_computation != VarianceComputationType.NONE
        if not want_var:
            var_matrix = None
        elif mesh is not None:
            var_matrix = sharded_zeros((n_rows, self.dim), dtype, row_sh)
        else:
            var_matrix = jnp.zeros((n_rows, self.dim), dtype)
        rw = jnp.asarray(
            self.config.reg_weight if reg_weight is None else reg_weight, dtype
        )

        # Analytic wire bytes this sweep will move through the entity-shard
        # collectives (0 on the replicated path) — read by the
        # coordinate-descent loop / estimator for the sharding artifact keys.
        self.last_train_collective_bytes = self.sweep_collective_bytes()
        # No host syncs inside the loop: bucket programs dispatch back-to-back
        # and stats materialize once at the end.
        bucket_iters: List = [None] * len(red.buckets)
        if (
            red.buckets
            and sweep_scan_enabled()
            and (mesh is None or self._train_scan_sharded is not None)
        ):
            # Scan-dispatched sweep: one program per distinct block shape
            # (on the entity-sharded path with ring gather/scatter on
            # shard-local rows INSIDE it). Each group dispatch runs under
            # the mesh failure domain: the `collective` fault site +
            # bounded re-dispatch (entity-sharded groups), the optional
            # hang watchdog, and — when retries exhaust — a degraded
            # fallback to the bitwise-equal per-bucket loop for exactly
            # that group's buckets (entity buckets are disjoint, so the
            # carry update order across groups cannot change any row).
            from photon_ml_tpu.parallel.mesh import (
                collective_faults_suppressed,
            )
            from photon_ml_tpu.utils.watchdog import Watchdog, watchdog_ms

            wd_ms = watchdog_ms()
            wd = Watchdog() if wd_ms > 0 else None
            try:
                for group in self._scan_group_list():
                    idxs = group[0]
                    try:
                        matrix, var_matrix, iters = self._dispatch_scan_group(
                            group, matrix, var_matrix, offsets, rw, wd, wd_ms
                        )
                    except BaseException as exc:  # noqa: BLE001 - gated below
                        if not faults.is_device_error(exc):
                            raise
                        # Bounded re-dispatches exhausted on a device-shaped
                        # failure: degrade THIS group to the per-bucket
                        # loop, with the armed `collective` site suppressed
                        # (a degradation tier must keep working precisely
                        # while the primary path is broken).
                        faults.COUNTERS.increment("collective_fallbacks")
                        logger.warning(
                            "scan sweep group of %d bucket(s) failed (%s); "
                            "degrading to the per-bucket loop",
                            len(idxs),
                            exc,
                        )
                        with collective_faults_suppressed():
                            matrix, var_matrix = self._train_buckets(
                                idxs, matrix, var_matrix, bucket_iters,
                                offsets, rw,
                            )
                        continue
                    for k, bi in enumerate(idxs):
                        bucket_iters[bi] = iters[k]
            finally:
                if wd is not None:
                    wd.close()
            return self._finish_train(matrix, var_matrix, bucket_iters)
        matrix, var_matrix = self._train_buckets(
            range(len(red.buckets)), matrix, var_matrix, bucket_iters,
            offsets, rw,
        )
        return self._finish_train(matrix, var_matrix, bucket_iters)

    def _dispatch_scan_group(
        self, group, matrix, var_matrix, offsets, rw, wd, wd_ms
    ):
        """One scan-group device dispatch under the mesh failure domain:
        `collective` fault site (entity-sharded groups — the program's ring
        gather/scatters are inside the trace, so the host dispatch carries
        the site), bounded re-dispatch (PHOTON_COLLECTIVE_RETRIES), and
        the hang watchdog when armed. Deterministic programs make a
        re-dispatch bitwise-identical; with the watchdog armed the carry
        is blocked on INSIDE the guard so a wedged dispatch is observable
        (trading the back-to-back pipelining for hang detection)."""
        from photon_ml_tpu.parallel.mesh import collective_retry_policy

        idxs, gathers, masks, ents = group
        ds, red = self.dataset, self.re_dataset
        mesh = self._entity_mesh

        def run():
            if mesh is not None:
                m, v, iters = self._train_scan_sharded(
                    ds.shards[red.feature_shard], ds.labels, ds.weights,
                    offsets, matrix, var_matrix, gathers, masks, ents,
                    red.feature_mask, rw,
                )
            else:
                norm_f = norm_s = None
                if self._per_entity_norm:
                    norm_f, norm_s = self.norm.factors, self.norm.shifts
                m, v, iters = self._train_scan(
                    ds.shards[red.feature_shard], ds.labels, ds.weights,
                    offsets, matrix, var_matrix, gathers, masks, ents,
                    red.feature_mask, norm_f, norm_s, rw,
                )
            if wd is not None:
                jax.block_until_ready(m)
            return m, v, iters

        def attempt():
            if mesh is not None:
                faults.fault_point("collective")
            if wd is None:
                return run()
            with wd.guard(wd_ms, f"scan sweep group ({len(idxs)} buckets)"):
                return run()

        return faults.retry(
            attempt,
            collective_retry_policy(),
            label=f"scan sweep group of {len(idxs)} bucket(s)",
            counter="collective_retries" if mesh is not None else "retries",
        )

    def _train_buckets(
        self, bucket_indices, matrix, var_matrix, bucket_iters, offsets, rw
    ):
        """The per-bucket dispatch loop over `bucket_indices` — the default
        path with the scan sweep off, and the degraded fallback tier for a
        scan group whose collective dispatch exhausted its retries (bitwise
        equal to the scan by construction — same ops per entity)."""
        ds, red = self.dataset, self.re_dataset
        mesh = self._entity_mesh
        if mesh is not None:
            from photon_ml_tpu.parallel.mesh import (
                ring_gather_rows,
                ring_scatter_rows,
            )
        for bi in bucket_indices:
            blocks = red.buckets[bi]
            block_data = gather_block_data(
                ds, red.feature_shard, blocks, offsets, feature_mask=red.feature_mask
            )
            if mesh is not None:
                w0 = ring_gather_rows(matrix, blocks.entity_rows, mesh)
            else:
                w0 = matrix[blocks.entity_rows]
            if self._per_entity_norm:
                f_blk, s_blk = self._norm_blocks(blocks.entity_rows)
                res: OptResult = self._train_bucket(block_data, w0, f_blk, s_blk, rw)
            else:
                res = self._train_bucket(block_data, w0, rw)
            if mesh is not None:
                matrix = ring_scatter_rows(
                    matrix, blocks.entity_rows, res.coefficients, mesh
                )
            else:
                matrix = matrix.at[blocks.entity_rows].set(res.coefficients)
            if var_matrix is not None:
                if self._per_entity_norm:
                    v = self._variance_bucket(
                        block_data, res.coefficients, f_blk, s_blk, rw
                    )
                else:
                    v = self._variance_bucket(block_data, res.coefficients, rw)
                if mesh is not None:
                    var_matrix = ring_scatter_rows(
                        var_matrix, blocks.entity_rows, v, mesh
                    )
                else:
                    var_matrix = var_matrix.at[blocks.entity_rows].set(v)
            bucket_iters[bi] = res.iterations
        return matrix, var_matrix

    def _scan_group_list(self):
        """Buckets grouped by block shape, each stacked into (K, E, S)
        scan operands. Built once per coordinate; every (capacity, E)
        shape comes from the canonical discrete set, so the group count —
        and hence the per-sweep program count — is small by construction.
        On the entity-sharded path the stacked operands are re-laid-out
        with the ENTITY axis (axis 1) sharded over the mesh, so the scan's
        per-step slices arrive already shard-local."""
        groups = getattr(self, "_scan_groups_cache", None)
        if groups is None:
            by_shape: dict = {}
            bl = self.re_dataset.buckets
            for i, b in enumerate(bl):
                by_shape.setdefault((b.num_entities, b.capacity), []).append(i)
            # Scan-fusion granularity is a PLANNED quantity (ISSUE 14):
            # default 0 = unbounded (one program per shape, the pre-
            # planner behavior). A plan caps how many same-shape buckets
            # fuse into one scan dispatch — and shapes the plan's profile
            # never proved on this hardware (re_bucket_shapes) chunk at
            # the cap even when proven shapes fuse unboundedly, so a
            # first-dispatch failure or hang costs one small group.
            # Chunking preserves per-bucket op order (the scan body runs
            # buckets sequentially either way), so ANY cap is bitwise-
            # identical to unbounded fusion.
            shape_chunks = []
            for shape, idxs in by_shape.items():
                for chunk in _fusion_chunks(
                    idxs, shape, self._planned_shape_set()
                ):
                    shape_chunks.append(chunk)
            groups = [
                (
                    idxs,
                    jnp.stack([bl[i].gather for i in idxs]),
                    jnp.stack([bl[i].mask for i in idxs]),
                    jnp.stack([bl[i].entity_rows for i in idxs]),
                )
                for idxs in shape_chunks
            ]
            if self._entity_mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P

                mesh = self._entity_mesh
                ax = mesh.axis_names[0]
                s3 = NamedSharding(mesh, P(None, ax, None))
                s2 = NamedSharding(mesh, P(None, ax))
                groups = [
                    (
                        idxs,
                        jax.device_put(g, s3),
                        jax.device_put(mk, s3),
                        jax.device_put(e, s2),
                    )
                    for idxs, g, mk, e in groups
                ]
            self._scan_groups_cache = groups
        return groups

    def _planned_shape_set(self):
        """The (entities, capacity) shapes the installed plan's profile
        proved on this hardware, or None when no plan carries shape
        evidence (then every shape fuses unboundedly, the default)."""
        from photon_ml_tpu import planner

        plan = planner.current_plan()
        if plan is None or "re_bucket_shapes" not in plan.decisions:
            return None
        planned = plan.decisions["re_bucket_shapes"].value or {}
        shapes = {
            (int(pair[0]), int(pair[1]))
            for shape_list in planned.values()
            for pair in shape_list
        }
        return shapes or None

    @property
    def entity_mesh(self):
        """The mesh this coordinate's entity store is sharded over (None =
        replicated). Public because the elastic-resume layer keys on it:
        a device-shaped failure that beats this coordinate's own failure
        domain is a MESH loss only when there IS a mesh
        (game/coordinate_descent.py's sweep-boundary handler)."""
        return self._entity_mesh

    def sweep_collective_bytes(self) -> int:
        """Analytic wire bytes one full sweep moves through the ring
        collectives (gather of warm starts + scatter of coefficients and,
        when enabled, variances) — 0 on the replicated path. Purely a
        function of the bucket layout and mesh, so it is exact for both
        the per-bucket loop and the scan sweep (same calls, same shapes)."""
        mesh = self._entity_mesh
        if mesh is None:
            return 0
        from photon_ml_tpu.parallel.mesh import (
            pad_rows_for_mesh,
            ring_gather_wire_bytes,
            ring_scatter_wire_bytes,
        )

        n_rows = pad_rows_for_mesh(self.re_dataset.num_entities + 1, mesh)
        want_var = self.config.variance_computation != VarianceComputationType.NONE
        scatters = 2 if want_var else 1
        total = 0
        for b in self.re_dataset.buckets:
            total += ring_gather_wire_bytes(mesh, n_rows, self.dim)
            total += scatters * ring_scatter_wire_bytes(
                mesh, b.num_entities, self.dim
            )
        return total

    def sharding_info(self) -> dict:
        """The sharding decision this coordinate trains under, as the
        proper-JSON keys `fit_timing`/bench artifacts record."""
        mesh = self._entity_mesh
        n_rows = self.re_dataset.num_entities + 1
        if mesh is None:
            return {
                "entity_sharded": False,
                "axis_size": 1,
                "rows_per_shard": int(n_rows),
                "collective_bytes_per_sweep": 0,
            }
        from photon_ml_tpu.parallel.mesh import pad_rows_for_mesh

        padded = pad_rows_for_mesh(n_rows, mesh)
        return {
            "entity_sharded": True,
            "axis_size": int(mesh.devices.size),
            "rows_per_shard": int(padded // mesh.devices.size),
            "collective_bytes_per_sweep": self.sweep_collective_bytes(),
        }

    def _finish_train(self, matrix, var_matrix, bucket_iters):
        red = self.re_dataset
        e_total = red.num_entities
        stats = {
            "buckets": [
                dict(
                    capacity=b.capacity,
                    entities=b.num_entities,
                    mean_iterations=float(jnp.mean(its)),
                )
                for b, its in zip(red.buckets, bucket_iters)
            ],
            "total_iterations": int(sum(int(jnp.sum(its)) for its in bucket_iters)),
        }
        # Keep the unseen-entity row pinned to zero — in BOTH matrices:
        # dummy-padded chunk entities (build_random_effect_dataset block
        # splitting) scatter their inert solves into this row.
        matrix = matrix.at[e_total].set(0.0)
        if var_matrix is not None:
            var_matrix = var_matrix.at[e_total].set(0.0)
        model = RandomEffectModel(
            matrix,
            var_matrix,
            self.task,
            n_entities=e_total if matrix.shape[0] != e_total + 1 else None,
        )
        return model, stats

    # -- stacked-trial hooks (hyperparameter/sweep.py) ----------------------

    def trial_train(self, offsets, matrix, var_matrix, reg_weight):
        """One trial's full bucket sweep as traced values (replicated store
        only): every scan group's `_train_scan` program runs in bucket
        order with the trial's (offsets, matrix, reg_weight), then the
        unseen-entity row pins to zero — the exact op sequence train()
        dispatches, so a lax.scan of this body over a trial axis is
        bitwise-equal per trial to the serial per-trial loop
        (tests/test_sweep.py). Entity-sharded coordinates evaluate trials
        via shard groups instead (SweepExecutor)."""
        if self._entity_mesh is not None:
            raise ValueError(
                "trial_train is the replicated stacked-trial hook; "
                "entity-sharded coordinates run one trial per shard group"
            )
        ds, red = self.dataset, self.re_dataset
        for group in self._scan_group_list():
            _idxs, gathers, masks, ents = group
            norm_f = norm_s = None
            if self._per_entity_norm:
                norm_f, norm_s = self.norm.factors, self.norm.shifts
            matrix, var_matrix, _iters = self._train_scan(
                ds.shards[red.feature_shard], ds.labels, ds.weights, offsets,
                matrix, var_matrix, gathers, masks, ents, red.feature_mask,
                norm_f, norm_s, reg_weight,
            )
        matrix = matrix.at[red.num_entities].set(0.0)
        if var_matrix is not None:
            var_matrix = var_matrix.at[red.num_entities].set(0.0)
        return matrix, var_matrix

    def trial_score(self, matrix):
        return self._score_fn(
            self.dataset.shards[self.re_dataset.feature_shard],
            self.re_dataset.sample_entity_rows,
            matrix,
        )

    def prefetch(self) -> None:
        """Start the background device upload of the feature shard the
        entity-block gathers and residual scoring read — so the transfer
        overlaps the previous coordinate's solve instead of faulting
        synchronously at this coordinate's first gather."""
        shards = self.dataset.shards
        if hasattr(shards, "prefetch"):
            shards.prefetch(self.re_dataset.feature_shard)

    def score(self, model: RandomEffectModel) -> Array:
        if self._entity_mesh is not None and model.coefficients_matrix.shape[0] % (
            self._entity_mesh.devices.size
        ) == 0:
            from photon_ml_tpu.game.model import random_effect_margins_sharded

            return random_effect_margins_sharded(
                self.dataset.shards[self.re_dataset.feature_shard],
                self.re_dataset.sample_entity_rows,
                model.coefficients_matrix,
                self.norm,
                self._entity_mesh,
            )
        return self._score_fn(
            self.dataset.shards[self.re_dataset.feature_shard],
            self.re_dataset.sample_entity_rows,
            model.coefficients_matrix,
        )

"""Random-effect feature-space projectors.

Counterpart of photon-api projector/* — Projector.scala:58,
IndexMapProjector.scala:92, IndexMapProjectorRDD.scala:36-218,
ProjectionMatrix.scala:32-99, ProjectionMatrixBroadcast.scala:32-131,
IdentityProjector.scala, ProjectorType.scala, RandomEffectProjector.scala:74
and model/RandomEffectModelInProjectedSpace.scala:129.

Purpose (same as the reference): shrink each entity's feature space so the
per-entity random-effect models are dense-small. The reference builds one
projector per entity as an RDD keyed by REId, each with its own projected
dimension. On TPU the per-entity coefficient store is ONE (E+1, D_proj)
matrix, so every entity shares a common padded projected dimension:

  * IndexMapProjector: per-entity index compaction. For each entity, the
    distinct global feature indices appearing in its samples (active +
    passive, IndexMapProjectorRDD.scala:60-90) are assigned local slots
    0..k_e-1; D_proj = max_e k_e (padded). Projection rewrites the ELL
    `indices` arrays host-side ONCE at dataset-build time — on device nothing
    changes except that gathers/scatters run over D_proj instead of the full
    shard width. Back-projection scatters each row through its entity's
    slot->global table.
  * RandomProjector: a shared Gaussian matrix P (D, d) with N(0, 1/d)
    entries (ProjectionMatrix.scala:99); features are densified through the
    MXU (X @ P), models live in projected space, and back-projection is
    w_orig = P w_proj (the reference's projectCoefficients transpose map).
  * IdentityProjector: no-op.

All projectors expose the same surface: `project_features` (global ->
projected sample features), `back_project_matrix` (projected coefficient
matrix -> original-space rows, for saving/inspection), and `projected_dim`.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.containers import Features, SparseFeatures
from photon_ml_tpu.types import ProjectorType

Array = jax.Array


class IdentityProjector:
    """ProjectorType.IDENTITY — original space == projected space
    (IdentityProjector.scala)."""

    def __init__(self, dim: int):
        self.original_dim = dim
        self.projected_dim = dim

    def project_features(
        self, features: Features, entity_rows: np.ndarray, host_planes=None
    ) -> Features:
        return features

    def back_project_matrix(self, matrix: Array) -> Array:
        return matrix

    def project_matrix(self, matrix: Array) -> Array:
        return matrix


class IndexMapProjector:
    """Per-entity index compaction (IndexMapProjectorRDD.scala:36-218).

    `slot_tables[e, j]` = global feature index occupying local slot j of
    entity e (or -1 for padding). Row E (the unseen-entity row) has an empty
    table. Built host-side from the samples' sparse indices; the projected
    dimension is the max per-entity distinct-feature count, optionally
    rounded up to a multiple of 8 for TPU lane alignment.
    """

    def __init__(self, slot_tables: np.ndarray, original_dim: int):
        self.slot_tables = slot_tables  # (E + 1, D_proj) int64, -1 = pad
        self.original_dim = int(original_dim)
        self.projected_dim = int(slot_tables.shape[1])
        # Device-side mapper (data/device_assemble.DeviceIndexMapper) when
        # the build ran on device: later projections (training shard,
        # validation data) are one XLA program each instead of host
        # searchsorted sweeps. None on the host path — consumers fall back.
        self._device_mapper = None
        # Fused-pass byproduct: the original shard's feature summary,
        # computed in the SAME program as the projector key sort when the
        # caller asked for it (GameEstimator's normalization contexts).
        self.original_stats = None

    @classmethod
    def build(
        cls,
        features: SparseFeatures,
        entity_rows: np.ndarray,
        num_entities: int,
        *,
        pad_multiple: int = 8,
        host_planes=None,
        want_stats: bool = False,
    ) -> "IndexMapProjector":
        """Collect each entity's distinct active feature indices
        (IndexMapProjectorRDD.scala:60-90 unions active+passive; here
        `entity_rows` covers every sample so both are included).
        `host_planes` is ingest's (indices, values) host copy
        (GameDataset.host_ell) — without it, np.asarray on a remote-device
        array pulls the whole shard back over the interconnect.

        Device path (data/device_assemble.py, PHOTON_DEVICE_ASSEMBLY):
        the nnz-sized key sort/unique/table scatter runs as XLA programs
        — bitwise-identical slot tables, with only the E-sized counts
        crossing back to host. `want_stats` additionally folds the
        feature-summary moments into the same sweep (the fused auxiliary
        pass); the host path ignores it (stats run separately there)."""
        if host_planes is not None:
            idx, val = host_planes
        else:
            idx = np.asarray(features.indices)
            val = np.asarray(features.values)
        ent = np.asarray(entity_rows)

        from photon_ml_tpu.data import device_assemble

        if device_assemble.enabled() and device_assemble.projector_supported(
            num_entities, features.dim
        ):
            built = device_assemble.build_index_mapper(
                idx,
                val,
                ent,
                num_entities,
                features.dim,
                pad_multiple=pad_multiple,
                want_stats=want_stats,
            )
            if built is not None:
                tables, mapper, stats = built
                proj = cls(tables, features.dim)
                proj._device_mapper = mapper
                proj.original_stats = stats
                return proj
        # Flatten to (entity, global-index) pairs for nonzero entries and
        # take per-entity distinct indices in one vectorized pass. The pair
        # is packed into ONE int64 key — np.unique on a 2-D stack sorts a
        # void view with per-element memcmp comparators, which measured ~25x
        # slower than the integer sort at 2.4M pairs (the dominant cost of
        # GameEstimator.prepare before this).
        ent_flat = np.repeat(ent, idx.shape[1])
        idx_flat = idx.reshape(-1)
        keep = (val.reshape(-1) != 0.0) & (ent_flat < num_entities)
        dimw = np.int64(features.dim)
        keys = np.unique(ent_flat[keep] * dimw + idx_flat[keep])
        pair_ent = keys // dimw
        pair_idx = keys % dimw
        counts = np.bincount(pair_ent, minlength=num_entities)
        d_proj = max(1, int(counts.max()) if len(counts) else 1)
        if pad_multiple > 1:
            d_proj = ((d_proj + pad_multiple - 1) // pad_multiple) * pad_multiple
        tables = np.full((num_entities + 1, d_proj), -1, np.int64)
        # keys are sorted by (entity, global); slot j of entity e is the
        # j-th distinct global index of e.
        starts = np.searchsorted(pair_ent, np.arange(num_entities))
        slot = np.arange(len(keys)) - starts[pair_ent]
        tables[pair_ent, slot] = pair_idx
        return cls(tables, features.dim)

    def project_arrays(
        self, idx: np.ndarray, val: np.ndarray, ent: np.ndarray
    ):
        """Host-side core of project_features on numpy planes; returns the
        projected (indices int32, values) numpy pair."""
        # One GLOBAL searchsorted instead of a per-entity loop: each
        # entity's valid slots, keyed as entity * (dim + 1) + global_index,
        # concatenate into one array that is sorted by construction (tables
        # are per-entity sorted and entity ids increase). An ELL entry's
        # local slot is then its position within its entity's segment.
        valid_mask = self.slot_tables >= 0
        seg_lens = valid_mask.sum(axis=1)
        offsets = np.zeros(len(seg_lens) + 1, np.int64)
        np.cumsum(seg_lens, out=offsets[1:])
        dimw = np.int64(self.original_dim + 1)
        flat_ent = np.repeat(
            np.arange(self.slot_tables.shape[0], dtype=np.int64), seg_lens
        )
        flat_keys = flat_ent * dimw + self.slot_tables[valid_mask]
        entry_keys = ent[:, None] * dimw + idx
        pos = np.searchsorted(flat_keys, entry_keys.reshape(-1)).reshape(idx.shape)
        pos_c = np.minimum(pos, max(len(flat_keys) - 1, 0))
        hit = (
            (flat_keys[pos_c] == entry_keys) & (val != 0.0)
            if len(flat_keys)
            else np.zeros(idx.shape, bool)
        )
        local = pos_c - offsets[ent][:, None]
        out = np.where(hit, local, 0).astype(np.int32)
        val = np.where(hit, val, 0.0).astype(val.dtype)
        return out, val

    def project_features(
        self,
        features: SparseFeatures,
        entity_rows: np.ndarray,
        host_planes=None,
    ) -> SparseFeatures:
        """Rewrite global ELL indices to per-entity local slots (one-time).
        Entries whose feature is absent from the entity's table (value-0
        padding, or unseen entities) are zeroed out. `host_planes` avoids
        the remote-device pull (see build). A device-built projector
        projects as one XLA program (bitwise-equal to the host sweep)."""
        if host_planes is not None:
            idx, val = host_planes
        else:
            idx = np.asarray(features.indices)
            val = np.asarray(features.values)
        from photon_ml_tpu.data import device_assemble

        if self._device_mapper is not None and device_assemble.enabled():
            out_d, v_d = device_assemble.project_ell_device(
                self._device_mapper, idx, val, np.asarray(entity_rows)
            )
            return SparseFeatures(out_d, v_d, self.projected_dim)
        out, v = self.project_arrays(idx, val, np.asarray(entity_rows))
        return SparseFeatures(
            jnp.asarray(out), jnp.asarray(v), self.projected_dim
        )

    def back_project_matrix(self, matrix: Array) -> Array:
        """(E+1, D_proj) -> (E+1, D) scatter through the slot tables
        (projectCoefficients direction, IndexMapProjectorRDD.scala:96-120).
        Padding slots scatter into a dummy extra column that is dropped."""
        m = np.asarray(matrix)
        e1, _ = m.shape
        out = np.zeros((e1, self.original_dim + 1), m.dtype)
        cols = np.where(self.slot_tables >= 0, self.slot_tables, self.original_dim)
        np.add.at(out, (np.arange(e1)[:, None], cols), m)
        return jnp.asarray(out[:, : self.original_dim])

    def project_matrix(self, matrix: Array) -> Array:
        """(E+1, D) original-space rows -> (E+1, D_proj) projected rows (the
        warm-start direction: gather each entity's slots). Exact inverse of
        back_project_matrix on this projector's support."""
        m = np.asarray(matrix)
        cols = np.where(self.slot_tables >= 0, self.slot_tables, 0)
        out = np.take_along_axis(m, cols, axis=1)
        out[self.slot_tables < 0] = 0.0
        return jnp.asarray(out)

    def entity_coefficients(self, matrix: Array, entity_row: int) -> Dict[int, float]:
        """One entity's model as {global feature index: weight} (sparse save
        path, ModelProcessingUtils.saveModelsRDDToHDFS)."""
        row = np.asarray(matrix[entity_row])
        table = self.slot_tables[entity_row]
        return {int(g): float(w) for g, w in zip(table, row) if g >= 0 and w != 0.0}


class RandomProjector:
    """Shared Gaussian random projection (ProjectionMatrix.scala:32-99,
    ProjectionMatrixBroadcast.scala).

    P has i.i.d. N(0, 1/d_proj) entries (ProjectionMatrix.scala:99's
    Gaussian generation); projection is a dense matmul so sparse shards are
    densified through the MXU. The reference broadcasts P to executors; here
    it is a replicated device array.
    """

    def __init__(self, matrix: Array):
        self.matrix = matrix  # (D, d_proj)
        self.original_dim = int(matrix.shape[0])
        self.projected_dim = int(matrix.shape[1])

    @classmethod
    def build(cls, original_dim: int, projected_dim: int, seed: int = 0) -> "RandomProjector":
        key = jax.random.PRNGKey(seed)
        p = jax.random.normal(key, (original_dim, projected_dim)) / jnp.sqrt(
            jnp.asarray(projected_dim, jnp.float32)
        )
        return cls(p)

    def project_features(
        self, features: Features, entity_rows: np.ndarray, host_planes=None
    ) -> Array:
        if isinstance(features, SparseFeatures):
            # Sparse x P: gather P rows at the ELL indices and reduce —
            # avoids densifying X itself.
            rows = jnp.take(self.matrix, features.indices, axis=0)  # (N, K, d)
            return jnp.einsum("nk,nkd->nd", features.values, rows)
        return features @ self.matrix

    def back_project_matrix(self, matrix: Array) -> Array:
        """w_orig = P w_proj per entity row (ProjectionMatrix
        projectCoefficients)."""
        return matrix @ self.matrix.T

    def project_matrix(self, matrix: Array) -> Array:
        """Approximate original->projected coefficient map (warm start only):
        least-squares through P, i.e. w_proj = (P^T P)^-1 P^T w_orig."""
        p = self.matrix
        gram = p.T @ p
        return jnp.linalg.solve(gram, p.T @ matrix.T).T


Projector = object  # IdentityProjector | IndexMapProjector | RandomProjector


def build_projector(
    projector_type: ProjectorType,
    features: Features,
    entity_rows: np.ndarray,
    num_entities: int,
    *,
    projected_dim: Optional[int] = None,
    seed: int = 0,
    host_planes=None,
    want_stats: bool = False,
) -> Projector:
    """RandomEffectProjector.build (RandomEffectProjector.scala:74). The
    default for random-effect coordinates is INDEX_MAP
    (CoordinateDataConfiguration.scala:59-66)."""
    if isinstance(features, SparseFeatures):
        dim = features.dim
    else:
        dim = int(features.shape[-1])
    if projector_type == ProjectorType.IDENTITY:
        return IdentityProjector(dim)
    if projector_type == ProjectorType.RANDOM:
        if projected_dim is None:
            raise ValueError("RANDOM projector requires projected_dim")
        return RandomProjector.build(dim, projected_dim, seed)
    if projector_type == ProjectorType.INDEX_MAP:
        if not isinstance(features, SparseFeatures):
            # Dense shards have nothing to compact per entity; identity.
            return IdentityProjector(dim)
        return IndexMapProjector.build(
            features,
            entity_rows,
            num_entities,
            host_planes=host_planes,
            want_stats=want_stats,
        )
    raise ValueError(f"unknown projector type {projector_type}")


@dataclasses.dataclass
class ProjectedShard:
    """A projected feature shard + its projector, registered on the dataset
    under `shard_name` for the owning random-effect coordinate."""

    shard_name: str
    projector: Projector


def project_shard(
    dataset,
    re_dataset,
    projector_type: ProjectorType,
    *,
    projected_dim: Optional[int] = None,
    seed: int = 0,
    want_stats: bool = False,
) -> ProjectedShard:
    """Create the projected view of `re_dataset`'s feature shard and register
    it on the GameDataset under '<shard>@<re_type>' — the per-coordinate
    projected space of RandomEffectCoordinateInProjectedSpace.scala:31. The
    RandomEffectDataset is repointed at the projected shard; its gather
    blocks are unchanged (projection is per-sample, not per-slot).
    """
    shard = re_dataset.feature_shard
    entity_rows = np.asarray(re_dataset.sample_entity_rows)
    host_planes = getattr(dataset, "host_ell", {}).get(shard)
    # Peek (ShardDict.host_view): projector construction must not force the
    # raw shard's device upload — with host planes the projection runs
    # entirely on host, and only the PROJECTED shard ships to the device.
    feats_src = (
        dataset.peek_shard(shard)
        if hasattr(dataset, "peek_shard")
        else dataset.shards[shard]
    )
    projector = build_projector(
        projector_type,
        feats_src,
        entity_rows,
        re_dataset.num_entities,
        projected_dim=projected_dim,
        seed=seed,
        host_planes=host_planes,
        want_stats=want_stats,
    )
    if isinstance(projector, IdentityProjector):
        return ProjectedShard(shard, projector)
    new_name = f"{shard}@{re_dataset.config.random_effect_type}"
    # Never overwrite an existing projected shard (two coordinates may share
    # (shard, re_type) with different projector configs).
    suffix = 2
    while new_name in dataset.shards:
        new_name = f"{shard}@{re_dataset.config.random_effect_type}#{suffix}"
        suffix += 1
    if isinstance(projector, IndexMapProjector) and host_planes is None:
        # No ingest host copy (hand-built dataset): fall back to reading
        # the (possibly device) arrays once.
        host_planes = (
            np.asarray(feats_src.indices),
            np.asarray(feats_src.values),
        )
    if (
        isinstance(projector, IndexMapProjector)
        and projector._device_mapper is not None
    ):
        # Device-resident path: the projection and the (K, N) transpose
        # run as XLA programs and the projected shard is BORN in device
        # memory — no host planes, no upload stage, bitwise-equal entries.
        # (No host_ell stash: the projected planes have no host consumer —
        # Pearson statistics read the ORIGINAL shard, before repointing.)
        # The build's device-resident planes are reused (take_planes) so
        # the raw ELL ships host->device exactly once.
        from photon_ml_tpu.data import device_assemble

        staged = projector._device_mapper.take_planes()
        src_idx, src_val = staged if staged is not None else (
            host_planes[0],
            host_planes[1],
        )
        out_d, v_d = device_assemble.project_ell_device(
            projector._device_mapper, src_idx, src_val, entity_rows
        )
        idx_t_d, val_t_d = device_assemble.transpose_planes_device(
            out_d, v_d, projector.projected_dim
        )
        dataset.shards[new_name] = SparseFeatures(
            idx_t_d, val_t_d, projector.projected_dim, ell_axis=-2
        )
    elif isinstance(projector, IndexMapProjector):
        # Host-plane path: project on host, stash the projected planes
        # (Pearson stats / downstream host consumers), then upload ONCE in
        # the TRANSPOSED (K, N) block layout — the orientation the
        # entity-block gathers consume directly (gather_block_features), so
        # no per-bucket transpose copies ever materialize on device.
        # Projected dims are small, so indices ship as int16 when they fit
        # (halves the index-plane transfer and HBM residence).
        out, v = projector.project_arrays(
            host_planes[0], host_planes[1], entity_rows
        )
        dataset.host_ell[new_name] = (out, v)
        idx_t = out.T
        if projector.projected_dim < (1 << 15):
            idx_t = idx_t.astype(np.int16)
        projected = SparseFeatures(
            np.ascontiguousarray(idx_t),
            np.ascontiguousarray(v.T),
            projector.projected_dim,
            ell_axis=-2,
        )
        if hasattr(dataset.shards, "prefetch"):
            # Lazy-upload ShardDict: register the HOST planes and let the
            # data-plane pipeline ship them asynchronously (the coordinate-
            # descent loop prefetches coordinate k+1's shard during
            # coordinate k's solve) instead of paying the transfer
            # synchronously inside prepare.
            dataset.shards[new_name] = projected
        else:
            # Plain-dict datasets have no lazy materialization — upload now.
            dataset.shards[new_name] = dataclasses.replace(
                projected,
                indices=jnp.asarray(projected.indices),
                values=jnp.asarray(projected.values),
            )
    else:
        dataset.shards[new_name] = projector.project_features(
            dataset.shards[shard], entity_rows
        )
    re_dataset.config = dataclasses.replace(re_dataset.config, feature_shard=new_name)
    return ProjectedShard(new_name, projector)

"""GameEstimator: the spark.ml-style fit() entry of the GAME layer.

Counterpart of photon-api estimators/GameEstimator.scala:54-773:
  * validates coordinate configurations against the update sequence
    (validateInput);
  * builds per-coordinate training datasets ONCE and reuses them across every
    optimization configuration (prepareTrainingDatasets:453-557 — here:
    entity-blocked RandomEffectDatasets + projected shards + normalization
    contexts);
  * builds the validation dataset and EvaluationSuite
    (prepareValidationDatasetAndEvaluators:567, default evaluator per task
    :614-625);
  * for each GameOptimizationConfiguration runs coordinate descent via the
    Coordinate objects (train:698-753), warm-starting each configuration from
    the previous one's model (fit:214-230);
  * returns (model, config, evaluation) triples for model selection by the
    driver.

Coordinate objects are cached across the sweep keyed by their *static*
configuration (everything but the regularization weight, which is a traced
scalar) so a reg-weight sweep reuses the same compiled XLA programs — the
TPU version of the reference's single mutable opt problem reused across the
sweep (ModelTraining.scala:165-213).
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import time
from typing import Dict, List, Mapping, Optional, Sequence, Set, Tuple

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.containers import SparseFeatures
from photon_ml_tpu.data.game_dataset import (
    FixedEffectDataConfig,
    GameDataset,
    RandomEffectDataConfig,
    RandomEffectDataset,
    build_random_effect_dataset,
)
from photon_ml_tpu.data.stats import summarize
from photon_ml_tpu.evaluation.suite import (
    EvaluationResults,
    EvaluationSuite,
    EvaluatorType,
    default_evaluator_for_task,
)
from photon_ml_tpu.game.coordinate import FixedEffectCoordinate, RandomEffectCoordinate
from photon_ml_tpu.game.coordinate_descent import run_coordinate_descent
from photon_ml_tpu.game.model import GameModel
from photon_ml_tpu.game.projector import project_shard
from photon_ml_tpu.ops.normalization import NormalizationContext, from_feature_stats
from photon_ml_tpu.optimize.config import CoordinateOptimizationConfig
from photon_ml_tpu.transformers.game_transformer import (
    CoordinateScoringSpec,
    GameTransformer,
    PreparedCoordinateData,
    coordinate_margins,
    prefetch_fixed_effect_shards,
    prepare_coordinate_data,
)
from photon_ml_tpu.types import NormalizationType, TaskType
from photon_ml_tpu.utils import telemetry
from photon_ml_tpu.utils.observability import (
    CheckpointEvent,
    CoordinateUpdateEvent,
    EventEmitter,
    SweepConfigEvent,
    TimingRegistry,
    TrainingFinishEvent,
    TrainingStartEvent,
    stage_scope,
    stage_timer,
)

logger = logging.getLogger(__name__)

GameOptimizationConfiguration = Mapping[str, CoordinateOptimizationConfig]

# The prepare-stage breakdown keys reported in `fit_timing` (VERDICT r05
# "Next round" #1): host RE dataset builds, projection, feature statistics,
# bucketed pack, device uploads, program construction/compile, and the
# residual host glue. In a synchronous run they tile `prepare_s`; in a
# pipelined run stages record where the work happens, so overlapped stages
# can sum past the wall they were hidden behind. The schema itself lives
# in utils/contracts.py (re-exported here for the existing importers).
from photon_ml_tpu.utils.contracts import (
    PREPARE_STAGES,
    ROBUSTNESS_CLEAN_ZERO_KEYS,
)


from photon_ml_tpu.optimize.config import static_config_key as _static_config_key


@dataclasses.dataclass
class GameResult:
    """One (GameModel, configuration, evaluation) triple
    (GameEstimator.fit's Seq element, GameEstimator.scala:169-172)."""

    model: GameModel
    config: Dict[str, CoordinateOptimizationConfig]
    evaluation: Optional[EvaluationResults]
    best_model: GameModel
    timing: Dict[str, float]


@dataclasses.dataclass
class _PreparedCoordinate:
    """Training-time artifacts for one coordinate, reused across configs."""

    data_config: object
    original_shard: str
    shard: str  # projected shard name for REs
    norm: Optional[NormalizationContext]
    re_dataset: Optional[RandomEffectDataset] = None
    projector: Optional[object] = None


class GameEstimator:
    """fit(data, validation, configs) -> [GameResult] (GameEstimator.scala:54).

    `coordinate_data_configs` is an ORDERED mapping coordinate id ->
    FixedEffectDataConfig | RandomEffectDataConfig; its order is the
    coordinate update sequence unless `update_sequence` overrides it.
    """

    def __init__(
        self,
        task: TaskType,
        coordinate_data_configs: Mapping[str, object],
        *,
        update_sequence: Optional[Sequence[str]] = None,
        coordinate_descent_iterations: int = 1,
        normalization: NormalizationType = NormalizationType.NONE,
        validation_evaluators: Optional[Sequence[EvaluatorType]] = None,
        locked_coordinates: Optional[Set[str]] = None,
        intercept_indices: Optional[Mapping[str, int]] = None,
        seed: int = 0,
        checkpoint_dir: Optional[str] = None,
        pipeline: Optional[bool] = None,
        event_emitter: Optional[EventEmitter] = None,
    ):
        self.task = task
        self.data_configs = dict(coordinate_data_configs)
        self.update_sequence = list(update_sequence or self.data_configs.keys())
        unknown = [c for c in self.update_sequence if c not in self.data_configs]
        if unknown:
            raise ValueError(f"update sequence names unknown coordinates {unknown}")
        missing = [c for c in self.data_configs if c not in self.update_sequence]
        if missing:
            raise ValueError(f"coordinates missing from update sequence {missing}")
        self.cd_iterations = coordinate_descent_iterations
        self.normalization = normalization
        self.validation_evaluators = list(validation_evaluators or [])
        self.locked = set(locked_coordinates or ())
        self.intercept_indices = dict(intercept_indices or {})
        self.seed = seed
        # Outer-loop checkpoint root (SURVEY §5.3); each optimization
        # configuration in the sweep checkpoints under config-<i>/.
        self.checkpoint_dir = checkpoint_dir
        # Host data-plane pipelining: None = auto (PHOTON_PIPELINE env, else
        # effective host parallelism > 1); True/False forces. A pipelined
        # fit is bitwise-identical to a synchronous one — the pipeline only
        # moves WHEN host builds/uploads run (tests/test_pipeline.py).
        self.pipeline = pipeline
        # Lifecycle event bus (ISSUE 11 satellite): library callers get
        # the same start/coordinate/sweep/checkpoint/finish record as CLI
        # jobs — register a telemetry journal_listener (or any listener)
        # on this emitter. None keeps fit() emission-free.
        self.event_emitter = event_emitter
        # Per-stage prepare walls (PREPARE_STAGES) accumulated across
        # prepare() + coordinate construction; surfaced via `fit_timing`.
        self.timing_registry = TimingRegistry()
        self._prepared: Optional[Dict[str, _PreparedCoordinate]] = None
        self._prepared_dataset: Optional[GameDataset] = None
        self._coordinate_cache: Dict[Tuple, object] = {}

    # ------------------------------------------------------------------ prep

    @contextlib.contextmanager
    def _exclusive_stage(self, name: str):
        """Like stage_timer, but attributes only the block's wall NOT
        already recorded to the nested `pack`/`upload` stages (a projector
        block that faults a synchronous ShardDict upload must not count
        the same seconds twice — the sync-run breakdown tiles prepare_s).
        Must run inside an open stage_scope on this registry."""
        reg = self.timing_registry
        t0 = time.perf_counter()
        nested0 = reg.get("pack") + reg.get("upload")
        try:
            yield
        finally:
            elapsed = time.perf_counter() - t0
            nested = reg.get("pack") + reg.get("upload") - nested0
            reg.record(name, max(0.0, elapsed - nested))

    def _norm_for_shard(
        self,
        dataset: GameDataset,
        shard: str,
        *,
        intercept_shard: Optional[str] = None,
        projected: bool = False,
    ) -> Optional[NormalizationContext]:
        """`intercept_shard` is the ORIGINAL shard name users configure
        intercepts under; `shard` may be a projected view (the RANDOM
        projector's dense space, where the global intercept column is mixed
        away, so shift-based normalization is not expressible — factor-only
        types are safe: a constant column gets factor 1 via the zero-variance
        guard)."""
        if self.normalization == NormalizationType.NONE:
            return None
        intercept = self.intercept_indices.get(intercept_shard or shard)
        if projected:
            if self.normalization == NormalizationType.STANDARDIZATION:
                raise ValueError(
                    "STANDARDIZATION is not supported on randomly-projected "
                    "shards (the intercept column is mixed into every "
                    "projected dimension); use a factor-only normalization "
                    "type, INDEX_MAP or IDENTITY projection"
                )
            intercept = None
        # Stats need a full pass over the entries, so a device transfer is
        # unavoidable — but make it a TRANSIENT copy (freed after the
        # summary) rather than ShardDict's cached materialization, which
        # would pin the raw ELL in HBM for a training run that then uses
        # only the bucketed/projected layouts.
        feats = dataset.peek_shard(shard) if hasattr(dataset, "peek_shard") else dataset.shards[shard]
        if isinstance(feats, SparseFeatures) and not isinstance(
            feats.indices, jnp.ndarray
        ):
            feats = dataclasses.replace(
                feats,
                indices=jnp.asarray(feats.indices),
                values=jnp.asarray(feats.values),
            )
        stats = summarize(feats, intercept_index=intercept)
        return from_feature_stats(
            self.normalization,
            mean=stats.mean,
            variance=stats.variance,
            max_abs=stats.max_abs,
            intercept_index=intercept,
        )

    def _norm_for_projected_re(self, dataset: GameDataset, original_shard: str, ps):
        """Normalization for a projected random-effect coordinate.

        INDEX_MAP compaction projects the GLOBAL context (computed on the
        original shard) into every entity's local slots — the reference's
        per-entity projected NormalizationContexts
        (IndexMapProjectorRDD.scala:133), so STANDARDIZATION works on
        projected shards. RANDOM projection cannot carry an affine
        per-feature transform through the Gaussian mix; factor-only types
        fall back to statistics of the projected (dense) space.
        """
        from photon_ml_tpu.game.projector import IndexMapProjector
        from photon_ml_tpu.ops.normalization import project_normalization

        if self.normalization == NormalizationType.NONE:
            return None
        if isinstance(ps.projector, IndexMapProjector):
            stats = getattr(ps.projector, "original_stats", None)
            if stats is not None:
                # Fused auxiliary pass (device assembly): the summary was
                # computed in the SAME device program as the projector key
                # sort — identical ops to summarize(), no second sweep.
                intercept = self.intercept_indices.get(original_shard)
                global_norm = from_feature_stats(
                    self.normalization,
                    mean=stats.mean,
                    variance=stats.variance,
                    max_abs=stats.max_abs,
                    intercept_index=intercept,
                )
            else:
                global_norm = self._norm_for_shard(dataset, original_shard)
            return project_normalization(global_norm, ps.projector.slot_tables)
        return self._norm_for_shard(
            dataset, ps.shard_name, intercept_shard=original_shard, projected=True
        )

    def prepare(self, dataset: GameDataset) -> Dict[str, _PreparedCoordinate]:
        """Build per-coordinate datasets/projections/normalizations once
        (prepareTrainingDatasets + prepareNormalizationContextWrappers).
        Bound to the first dataset seen — an estimator instance trains one
        dataset (as in the reference, where datasets are fit() arguments but
        coordinates cache RDD views).

        When the host data-plane pipeline is enabled (see `pipeline` in
        __init__), the entity-grouping builds of later random-effect
        coordinates run on a small worker pool, overlapping the current
        coordinate's projector/statistics work — the single-host stand-in
        for the reference's executor-parallel RandomEffectDataset
        construction (RandomEffectDataset.scala:229-438). Build ORDER of
        consumption is unchanged, so results are bitwise-identical to the
        synchronous path.
        """
        if self._prepared is not None:
            if dataset is not self._prepared_dataset:
                raise ValueError(
                    "This GameEstimator already prepared a different training "
                    "dataset; create a new estimator per training dataset"
                )
            return self._prepared
        self._prepared_dataset = dataset

        from photon_ml_tpu.data.pipeline import (
            effective_host_parallelism,
            pipeline_enabled,
        )

        re_futures: Dict[str, object] = {}
        pending_re: List[str] = []
        pool = None
        prepared: Dict[str, _PreparedCoordinate] = {}
        try:
            with stage_scope(self.timing_registry):
                if pipeline_enabled(self.pipeline):
                    from concurrent.futures import ThreadPoolExecutor

                    re_cids = [
                        cid
                        for cid in self.update_sequence
                        if isinstance(
                            self.data_configs[cid], RandomEffectDataConfig
                        )
                    ]
                    if len(re_cids) > 1:
                        workers = max(
                            1, min(4, effective_host_parallelism() - 1)
                        )
                        pool = ThreadPoolExecutor(
                            max_workers=workers,
                            thread_name_prefix="photon-prepare",
                        )
                        # Rolling submission, not queue-everything: at most
                        # `workers + 1` block layouts exist at once (the one
                        # being consumed plus the in-flight builds) — a
                        # completed layout is GB-scale at MovieLens-20M, so
                        # finished-but-unconsumed results must not pile up.
                        pending_re = list(re_cids)
                        reg = self.timing_registry
                        span_h = telemetry.span_handoff()

                        def _build_in_scope(cfg_re):
                            # Stage scopes are thread-local: hand the
                            # spawning fit's registry to the worker so its
                            # re_build wall lands in THIS fit's breakdown
                            # (and its re_build span under the fit span).
                            with stage_scope(reg), telemetry.adopt_span(
                                span_h
                            ):
                                return build_random_effect_dataset(
                                    dataset, cfg_re
                                )

                        def _submit_re() -> None:
                            while pending_re and len(re_futures) <= workers:
                                nxt = pending_re.pop(0)
                                re_futures[nxt] = pool.submit(
                                    _build_in_scope, self.data_configs[nxt]
                                )

                        _submit_re()
                for cid in self.update_sequence:
                    cfg = self.data_configs[cid]
                    if isinstance(cfg, RandomEffectDataConfig):
                        fut = re_futures.pop(cid, None)
                        if fut is not None:
                            try:
                                red = fut.result()
                            except Exception:
                                # A failed producer thread must not kill the
                                # fit: rebuild synchronously on this thread
                                # (the pipeline moves only WHEN work runs, so
                                # the fallback result is identical).
                                from photon_ml_tpu.utils import faults

                                logger.warning(
                                    "background build of coordinate %r "
                                    "failed; rebuilding synchronously",
                                    cid,
                                    exc_info=True,
                                )
                                faults.COUNTERS.increment(
                                    "fallback_sync_builds"
                                )
                                red = build_random_effect_dataset(dataset, cfg)
                        else:
                            red = build_random_effect_dataset(dataset, cfg)
                        if pending_re:
                            _submit_re()
                        original_shard = cfg.feature_shard
                        with self._exclusive_stage("projector"):
                            ps = project_shard(
                                dataset,
                                red,
                                cfg.projector_type,
                                projected_dim=cfg.projected_dim,
                                seed=self.seed,
                                # Fused pass: a device-built index-map
                                # projector folds the feature summary into
                                # its key-sort sweep when normalization
                                # will need it.
                                want_stats=(
                                    self.normalization
                                    != NormalizationType.NONE
                                ),
                            )
                        with stage_timer("stats"):
                            if ps.shard_name != original_shard:
                                norm = self._norm_for_projected_re(
                                    dataset, original_shard, ps
                                )
                            else:
                                norm = self._norm_for_shard(dataset, original_shard)
                        prepared[cid] = _PreparedCoordinate(
                            cfg, original_shard, ps.shard_name, norm, red, ps.projector
                        )
                        logger.info(
                            "coordinate %s: %d entities, %d active / %d passive "
                            "samples, projected dim %d",
                            cid,
                            red.num_entities,
                            red.num_active_samples,
                            red.num_passive_samples,
                            ps.projector.projected_dim,
                        )
                    elif isinstance(cfg, FixedEffectDataConfig):
                        with stage_timer("stats"):
                            norm = self._norm_for_shard(dataset, cfg.feature_shard)
                        prepared[cid] = _PreparedCoordinate(
                            cfg, cfg.feature_shard, cfg.feature_shard, norm
                        )
                    else:
                        raise TypeError(f"unknown data config for {cid}: {type(cfg)}")
        finally:
            if pool is not None:
                pool.shutdown(wait=True)
        self._prepared = prepared
        return prepared

    # ----------------------------------------------------------- coordinates

    def _coordinate_for(
        self,
        dataset: GameDataset,
        cid: str,
        prep: _PreparedCoordinate,
        opt_config: CoordinateOptimizationConfig,
    ):
        """CoordinateFactory.build (CoordinateFactory.scala:51) with a cache
        keyed by the static parts of the config — the reg weight is traced, so
        sweep steps share compiled programs.

        Construction is where the data-plane pack join, the packed-layout /
        ELL device uploads, and the program construction happen; the first
        two record their own stages, and the remainder of the construction
        wall is attributed to `compile` (dispatch decisions + jit/program
        building)."""
        key = (cid, _static_config_key(opt_config))
        coord = self._coordinate_cache.get(key)
        if coord is None:
            with stage_scope(self.timing_registry), self._exclusive_stage(
                "compile"
            ):
                # Coordinates are constructed with the weight zeroed so the
                # baked-in config carries no sweep-step value (the real
                # weight is a traced argument to every train call).
                static_cfg = dataclasses.replace(opt_config, reg_weight=0.0)
                if prep.re_dataset is not None:
                    coord = RandomEffectCoordinate(
                        dataset, prep.re_dataset, static_cfg, self.task, prep.norm
                    )
                else:
                    coord = FixedEffectCoordinate(
                        dataset, prep.shard, static_cfg, self.task, prep.norm
                    )
            self._coordinate_cache[key] = coord
        return coord

    # ------------------------------------------------------------ validation

    def _make_transformer(self, model: GameModel) -> GameTransformer:
        specs = self.scoring_specs()
        return GameTransformer(model, specs, self.task, pipeline=self.pipeline)

    def scoring_specs(self) -> Dict[str, CoordinateScoringSpec]:
        """Scoring metadata for the trained coordinates (consumed by
        GameTransformer and by model save)."""
        if self._prepared is None:
            raise RuntimeError("fit()/prepare() must run first")
        specs = {}
        for cid, prep in self._prepared.items():
            if prep.re_dataset is not None:
                specs[cid] = CoordinateScoringSpec(
                    shard=prep.original_shard,
                    norm=prep.norm,
                    random_effect_type=prep.re_dataset.config.random_effect_type,
                    entity_index=prep.re_dataset.entity_index,
                    projector=prep.projector,
                )
            else:
                specs[cid] = CoordinateScoringSpec(shard=prep.shard, norm=prep.norm)
        return specs

    def training_prepared(self) -> Dict[str, "PreparedCoordinateData"]:
        """Scoring-prep views of the TRAINING dataset, reusing the arrays
        prepare() already built — the projected shard registered on the
        dataset and each RandomEffectDataset's per-sample entity rows.
        Scoring/evaluating the training dataset with GameTransformer must
        pass this instead of letting transform() re-run the projector and
        entity resolution over data fit() already resolved (the reference's
        transform():150-263 rebuilds them; its scoring of training data
        reuses the training RDD views the same way)."""
        if self._prepared is None:
            raise RuntimeError("fit()/prepare() must run first")
        out: Dict[str, PreparedCoordinateData] = {}
        for cid, prep in self._prepared.items():
            if prep.re_dataset is not None:
                out[cid] = PreparedCoordinateData(
                    self._prepared_dataset.shards[prep.shard],
                    prep.re_dataset.sample_entity_rows,
                )
            else:
                # Prefer the trained coordinate's features (bucketed layout
                # or bf16-stored matrix): scoring through them avoids
                # materializing the raw ELL on device when training never
                # did (ShardDict lazy upload). All sweep entries of a cid
                # share the same feature representation, so any cache hit
                # serves (training_features is the public accessor).
                feats = next(
                    (
                        coord.training_features
                        for key, coord in self._coordinate_cache.items()
                        if isinstance(key, tuple) and key and key[0] == cid
                    ),
                    None,
                )
                if feats is None:
                    feats = self._prepared_dataset.shards[prep.shard]
                out[cid] = PreparedCoordinateData(feats, None)
        return out

    def _validation_suite(self, validation: GameDataset) -> EvaluationSuite:
        evaluators = self.validation_evaluators or [
            default_evaluator_for_task(self.task)
        ]
        return EvaluationSuite(
            evaluators,
            validation.labels,
            validation.weights,
            id_tag_values=validation.id_tags,
        )

    # ------------------------------------------------------------------- fit

    def fit(
        self,
        data: GameDataset,
        validation_data: Optional[GameDataset],
        opt_configs: Sequence[GameOptimizationConfiguration],
        *,
        initial_model: Optional[GameModel] = None,
    ) -> List[GameResult]:
        """Train one GameModel per optimization configuration
        (GameEstimator.fit:169-230), warm-starting successive configurations.

        `initial_model` seeds the first configuration (the driver's warm-start
        path, GameTrainingDriver.scala:370-378) and must contain every locked
        coordinate's model.

        The whole fit runs under a root `fit` trace span (so a traced run's
        spans cover the full wall), and when an `event_emitter` was given,
        start/sweep/coordinate/checkpoint/finish lifecycle events flow
        through it — the same record cli/train jobs get (ISSUE 11).
        """
        emit = self.event_emitter.send if self.event_emitter is not None else None
        # The adaptive-runtime gate (ISSUE 14): install a plan when
        # PHOTON_PLAN/PHOTON_PLAN_PROFILE ask for one and none is ambient
        # (CLI drivers install earlier so ingest is planned too) — OWNED:
        # a plan this fit installed is uninstalled on every exit path, so
        # library callers re-fitting under a changed env never silently
        # reuse a stale plan (the journal/tracer owned-slot discipline).
        from photon_ml_tpu import planner

        plan_owned = planner.current_plan() is None
        installed = planner.ensure_ambient_plan()
        try:
            with telemetry.span("fit", num_configs=len(opt_configs)):
                if emit is not None:
                    emit(TrainingStartEvent(num_samples=int(data.num_samples)))
                results = self._fit(
                    data, validation_data, opt_configs, initial_model=initial_model
                )
                if emit is not None:
                    best_eval = (
                        select_best_result(results)[1].evaluation
                        if results
                        else None
                    )
                    emit(
                        TrainingFinishEvent(
                            num_configs=len(results),
                            best_metric=(
                                None
                                if best_eval is None
                                else float(best_eval.primary_value)
                            ),
                        )
                    )
                return results
        finally:
            if plan_owned and installed is not None:
                planner.uninstall_plan()

    def _on_cd_event(self, etype: str, **fields) -> None:
        """run_coordinate_descent's event hook -> typed bus events
        (listener failures are isolated by EventEmitter.send)."""
        if self.event_emitter is None:
            return
        if etype == "coordinate":
            self.event_emitter.send(CoordinateUpdateEvent(**fields))
        elif etype == "checkpoint":
            self.event_emitter.send(CheckpointEvent(**fields))

    def _fit(
        self,
        data: GameDataset,
        validation_data: Optional[GameDataset],
        opt_configs: Sequence[GameOptimizationConfiguration],
        *,
        initial_model: Optional[GameModel] = None,
    ) -> List[GameResult]:
        if not opt_configs:
            raise ValueError("at least one optimization configuration required")
        from photon_ml_tpu import planner
        from photon_ml_tpu.data.pipeline import pipeline_enabled

        pipelined = pipeline_enabled(self.pipeline)
        # Stage breakdown (prepare = host-side dataset/coordinate builds,
        # solve = coordinate descent + validation): exposed as
        # `self.fit_timing` so drivers/benchmarks report where fit wall
        # goes without instrumenting internals. `prepare_s` additionally
        # splits into the PREPARE_STAGES keys (+ `other`, the residual
        # glue) recorded by the data-plane functions themselves.
        t0 = time.perf_counter()
        stage_base = dict(self.timing_registry.sections)
        # Per-fit note evidence: the placement/layout notes describe THIS
        # fit's decisions (a second fit on cached packs legitimately
        # reports "none" — it packed nothing), never a previous fit's.
        # Stage WALLS are delta'd against stage_base instead; notes have
        # no delta, so they reset.
        self.timing_registry.clear_notes(
            "pack_path", "re_path", "sparse_layout"
        )
        # Snapshot the pod-scale robustness counters so fit_timing reports
        # THIS fit's events (the process-wide counters are cumulative).
        from photon_ml_tpu.utils import faults as _faults

        robustness_base = {
            k: _faults.COUNTERS.get(k) for k in ROBUSTNESS_CLEAN_ZERO_KEYS
        }
        prepared = self.prepare(data)
        for cfgs in opt_configs:
            missing = [c for c in self.update_sequence if c not in cfgs and c not in self.locked]
            if missing:
                raise ValueError(f"optimization config missing coordinates {missing}")

        suite = self._validation_suite(validation_data) if validation_data is not None else None
        specs = self.scoring_specs()

        # One-time host prep of the validation dataset per coordinate
        # (projection + entity-row resolution) reused across every CD step;
        # attributed to the `projector` stage (it is projection +
        # entity-row resolution over the validation sample axis).
        val_prep = None
        if validation_data is not None:
            with stage_scope(self.timing_registry):
                # Prefetch INSIDE the scope: AsyncUploader captures the
                # submitter's registry at submit time, so these uploads'
                # walls land in the breakdown's `upload` stage.
                prefetch_fixed_effect_shards(
                    specs, self.update_sequence, validation_data, self.pipeline
                )
                with self._exclusive_stage("projector"):
                    val_prep = {
                        cid: prepare_coordinate_data(specs[cid], validation_data)
                        for cid in self.update_sequence
                    }

        self.fit_timing = {"prepare_s": time.perf_counter() - t0, "solve_s": 0.0}

        results: List[GameResult] = []
        prev_model: Optional[GameModel] = initial_model
        diverged_steps = 0
        collective_bytes = 0
        sharding_infos: Dict[str, dict] = {}
        default_cfg = CoordinateOptimizationConfig()
        for ci, cfgs in enumerate(opt_configs):
            if self.event_emitter is not None:
                self.event_emitter.send(
                    SweepConfigEvent(index=ci, total=len(opt_configs))
                )
            t_coord = time.perf_counter()
            coordinates = {
                cid: self._coordinate_for(
                    data, cid, prepared[cid], cfgs.get(cid, default_cfg)
                )
                for cid in self.update_sequence
            }
            self.fit_timing["prepare_s"] += time.perf_counter() - t_coord
            if ci == 0:
                # The sharding decision each coordinate trains under
                # (entity axis size, rows per shard, collective bytes) —
                # recorded once per fit; it is a property of the dataset
                # layout, not the optimization configuration.
                for cid, coord in coordinates.items():
                    info = getattr(coord, "sharding_info", None)
                    if info is not None:
                        sharding_infos[cid] = info()
            t_solve = time.perf_counter()
            if ci == 0:
                # Every fixed-effect coordinate that wanted the ingest's
                # host-COO stash has consumed it by now (its pack decision
                # is cached on the dataset); shards that feed only
                # random-effect coordinates never pop theirs — release them
                # so the triplets don't pin host RAM for the rest of fit.
                # The validation dataset never trains, so its stash has no
                # consumer at all.
                getattr(data, "release_stash", lambda: None)()
                if validation_data is not None:
                    getattr(validation_data, "release_stash", lambda: None)()
            reg_weights = {cid: cfgs[cid].reg_weight for cid in cfgs}

            validation_scorer = None
            if validation_data is not None:
                def validation_scorer(cid, model):
                    return coordinate_margins(specs[cid], model, val_prep[cid])

            # Pipelined: keep the stage scope open across the solve so the
            # prefetched uploads (which run DURING coordinate descent, on
            # background threads) land in the `upload` stage — the
            # breakdown must show overlapped transfers even though no
            # prepare wall waited on them. Synchronous runs keep the scope
            # closed: solve-time uploads are solve work there, and the
            # stage keys must tile prepare_s exactly.
            solve_scope = (
                stage_scope(self.timing_registry)
                if pipelined
                else contextlib.nullcontext()
            )
            with solve_scope:
                cd = run_coordinate_descent(
                    coordinates,
                    self.cd_iterations,
                    initial_models=prev_model,
                    locked_coordinates=self.locked or None,
                    validation_scorer=validation_scorer,
                    validation_suite=suite,
                    validation_offsets=(
                        validation_data.offsets
                        if validation_data is not None
                        else None
                    ),
                    reg_weights=reg_weights,
                    seed=self.seed + ci,
                    checkpoint_dir=(
                        None
                        if self.checkpoint_dir is None
                        else f"{self.checkpoint_dir}/config-{ci}"
                    ),
                    # Overlap coordinate k+1's device-shard upload with the
                    # solve of coordinate k (ShardDict.prefetch on a
                    # background thread) — the stage the reference hides
                    # inside executor-parallel dataset construction.
                    prefetch=pipelined,
                    on_event=(
                        self._on_cd_event
                        if self.event_emitter is not None
                        else None
                    ),
                )
            evaluation = None
            if validation_data is not None and suite is not None:
                transformer = self._make_transformer(cd.model)
                evaluation = transformer.evaluate(validation_data, suite, val_prep)
            results.append(
                GameResult(
                    model=cd.model,
                    config=dict(cfgs),
                    evaluation=evaluation,
                    best_model=cd.best_model,
                    timing=cd.timing,
                )
            )
            prev_model = cd.model
            diverged_steps += cd.diverged_steps
            collective_bytes += cd.collective_bytes
            self.fit_timing["solve_s"] += time.perf_counter() - t_solve
            logger.info(
                "configuration %d/%d trained%s",
                ci + 1,
                len(opt_configs),
                f": {evaluation.results}" if evaluation else "",
            )
        # Finalize the per-stage prepare breakdown: deltas of the timing
        # registry over this fit call. In a synchronous run the stages +
        # `other` tile `prepare_s`; in a pipelined run overlapped stages
        # record where they ran, so their sum can exceed the wall they hid
        # behind (that excess IS the overlap win).
        stages = {
            k: self.timing_registry.get(k) - stage_base.get(k, 0.0)
            for k in PREPARE_STAGES
        }
        stages["other"] = max(
            0.0, self.fit_timing["prepare_s"] - sum(stages.values())
        )
        self.fit_timing.update(stages)
        # Pack placement split (nested inside the `pack` stage, so NOT part
        # of the tiling sum above): where the bucketed placement pass
        # actually ran, plus which implementation ran it. The keys are
        # always present — the bench e2e contract fails loudly on their
        # absence like the stage keys — and `pack_path` is "none" when no
        # pack engaged this fit.
        self.fit_timing["pack_device_s"] = self.timing_registry.get(
            "pack_device"
        ) - stage_base.get("pack_device", 0.0)
        self.fit_timing["pack_host_s"] = self.timing_registry.get(
            "pack_host"
        ) - stage_base.get("pack_host", 0.0)
        self.fit_timing["pack_path"] = (
            self.timing_registry.get_note("pack_path") or "none"
        )
        # RE-assembly placement split (nested inside the `re_build` stage,
        # so NOT part of the tiling sum): where the entity-block build ran
        # (device_assemble vs the host loops). Keys always present —
        # `re_path` is "none" when no random-effect coordinate was built.
        self.fit_timing["re_device_s"] = self.timing_registry.get(
            "re_device"
        ) - stage_base.get("re_device", 0.0)
        self.fit_timing["re_host_s"] = self.timing_registry.get(
            "re_host"
        ) - stage_base.get("re_host", 0.0)
        self.fit_timing["re_path"] = (
            self.timing_registry.get_note("re_path") or "none"
        )
        # Robustness counter: coordinate updates rejected by the divergence
        # guard across every configuration of this fit (0 on a clean fit —
        # nonzero in a bench artifact is a loud regression signal).
        self.fit_timing["diverged_steps"] = diverged_steps
        # Pod-scale robustness counters for THIS fit (ISSUE 10): collective
        # re-dispatches, shard-staging retries, failed promotions, watchdog
        # trips — all keys always present and all-zero on a clean fit (the
        # bench clean-run contract enforces it).
        self.fit_timing["robustness"] = {
            k: _faults.COUNTERS.get(k) - robustness_base[k]
            for k in ROBUSTNESS_CLEAN_ZERO_KEYS
        }
        # The pod-scale sharding decision as proper JSON keys (ISSUE 7):
        # always present — `entity_sharded` False with axis_size 1 on the
        # single-device path — so the bench e2e contract can fail loudly on
        # absence rather than ship an artifact that silently lost it.
        # The adaptive-runtime plan block (ISSUE 14): always present —
        # inactive ({"active": False, ...}) on an unplanned fit — so the
        # bench e2e contract can fail loudly on absence, and an auditor
        # can tell "planner off" from "block lost".
        self.fit_timing["plan"] = planner.plan_block()
        re_infos = [i for i in sharding_infos.values() if i is not None]
        self.fit_timing["sharding"] = {
            "entity_sharded": any(i["entity_sharded"] for i in re_infos),
            "axis_size": max(
                [i["axis_size"] for i in re_infos], default=1
            ),
            "rows_per_shard": {
                cid: i["rows_per_shard"] for cid, i in sharding_infos.items()
            },
            "collective_bytes_per_sweep": sum(
                i["collective_bytes_per_sweep"] for i in re_infos
            ),
            # Actually moved across the whole fit (every accepted sweep of
            # every configuration) — 0 on the replicated path.
            "collective_bytes_total": int(collective_bytes),
        }
        return results

    # -------------------------------------------------------------- sweeps

    def sweep_executor(
        self,
        data: GameDataset,
        validation_data: GameDataset,
        base_config: GameOptimizationConfiguration,
        tuned_ids: Optional[Sequence[str]] = None,
        *,
        mode: Optional[str] = None,
        warm_start: bool = True,
        max_stack: Optional[int] = None,
        shard_groups: Optional[int] = None,
        on_event=None,
    ):
        """The batched trial executor for hyperparameter sweeps (ISSUE 12):
        wires this estimator's prepared coordinates, validation scorers and
        shard-group builder into a `hyperparameter.sweep.SweepExecutor`.

        `base_config` fixes every coordinate's optimizer statics (and the
        reg weight of untuned coordinates); `tuned_ids` (default: every
        coordinate) names the coordinates whose reg weight the candidate
        columns drive, in column order. The executor's `evaluate_batch` is
        the `BatchEvaluationFunction` the searchers' `find_batched` calls;
        `finalize()` cold-refits the winner (bitwise-equal to a standalone
        fit of the winning config). The trial VALUE is the validation
        suite's primary metric of each trial's final model — the same
        definition in every evaluation mode."""
        from photon_ml_tpu.evaluation.suite import better_than
        from photon_ml_tpu.hyperparameter.sweep import SweepExecutor
        from photon_ml_tpu.transformers.game_transformer import (
            _fe_margins,
            _re_margins,
        )

        if validation_data is None:
            raise ValueError(
                "sweep_executor needs validation data — the trial value is "
                "the validation suite's primary metric"
            )
        if self.locked:
            raise ValueError(
                "hyperparameter sweeps retrain every coordinate; locked "
                "coordinates are not supported"
            )
        missing = [c for c in self.update_sequence if c not in base_config]
        if missing:
            raise ValueError(f"base configuration missing coordinates {missing}")
        prepared = self.prepare(data)
        coordinates = {
            cid: self._coordinate_for(data, cid, prepared[cid], base_config[cid])
            for cid in self.update_sequence
        }
        suite = self._validation_suite(validation_data)
        specs = self.scoring_specs()
        with stage_scope(self.timing_registry):
            prefetch_fixed_effect_shards(
                specs, self.update_sequence, validation_data, self.pipeline
            )
            with self._exclusive_stage("projector"):
                val_prep = {
                    cid: prepare_coordinate_data(specs[cid], validation_data)
                    for cid in self.update_sequence
                }
        # Traceable per-coordinate validation scorers: model ARRAYS ->
        # margins through the same jitted programs the serial validation
        # path dispatches (`coordinate_margins`' replicated branches), so
        # the stacked program can compute them in-trace.
        trial_scorers = {}
        for cid in self.update_sequence:
            spec, vp = specs[cid], val_prep[cid]
            if spec.is_random_effect:
                def scorer(arrays, _f=vp.features, _r=vp.entity_rows, _n=spec.norm):
                    return _re_margins(_f, _r, arrays["m"], _n)
            else:
                def scorer(arrays, _f=vp.features, _n=spec.norm):
                    return _fe_margins(_f, arrays["w"], _n)
            trial_scorers[cid] = scorer
        return SweepExecutor(
            coordinates,
            list(tuned_ids) if tuned_ids is not None else list(self.update_sequence),
            self.cd_iterations,
            task=self.task,
            base_reg_weights={
                cid: base_config[cid].reg_weight for cid in self.update_sequence
            },
            validation_suite=suite,
            validation_offsets=validation_data.offsets,
            num_validation_samples=validation_data.num_samples,
            trial_scorers=trial_scorers,
            maximize=better_than(suite.primary, 1.0, 0.0),
            seed=self.seed,
            mode=mode,
            warm_start=warm_start,
            max_stack=max_stack,
            shard_groups=shard_groups,
            group_builder=self._sweep_group_builder(data, base_config),
            on_event=on_event,
        )

    def _sweep_group_builder(self, data: GameDataset, base_config):
        """Shard-group coordinate factory: `build(devices)` clones this
        estimator's prepared coordinates onto a device group so one trial's
        full serial fit runs there concurrently with the other groups'.
        Single-device groups are plain device_put clones (bitwise-equal
        programs on another chip); multi-device groups replicate the sample
        data over a group mesh and row-shard the RE coefficient store —
        the PR 7 entity-sharded ring-collective sweep inside the group."""

        def build(devices):
            import jax

            from photon_ml_tpu.data.game_dataset import EntityBlocks

            prepared = self._prepared
            if prepared is None:
                raise RuntimeError("prepare() must run before group builds")
            multi = len(devices) > 1
            if multi:
                from photon_ml_tpu.parallel.mesh import (
                    make_mesh,
                    replicated,
                    shard_random_effect_dataset,
                )

                mesh = make_mesh(devices)
                target = rep = replicated(mesh)
                # Only the RE ENTITY axis shards (the PR 7 ring-collective
                # sweep, bitwise-equal to replicated) — what shard groups
                # buy is the row-sharded coefficient store for fits whose
                # RE matrix exceeds one device.
                # replicate_sample_rows: the group's SAMPLE axis stays
                # replicated (see ds_g below), and batch-sharding
                # sample_entity_rows would demand mesh-divisible sample
                # counts the sweep never promised.
                put_red = lambda red: dataclasses.replace(
                    shard_random_effect_dataset(
                        red, mesh, replicate_sample_rows=True
                    ),
                    feature_mask=put(red.feature_mask),
                )
            else:
                target = devices[0]

                def put_red(red):
                    buckets = []
                    for b in red.buckets:
                        nb = EntityBlocks.__new__(EntityBlocks)
                        nb.gather = put(b.gather)
                        nb.mask = put(b.mask)
                        nb.entity_rows = put(b.entity_rows)
                        buckets.append(nb)
                    return dataclasses.replace(
                        red,
                        buckets=buckets,
                        sample_entity_rows=put(red.sample_entity_rows),
                        feature_mask=put(red.feature_mask),
                    )

            put = lambda a: None if a is None else jax.device_put(a, target)

            def put_feat(f):
                if isinstance(f, SparseFeatures):
                    return dataclasses.replace(
                        f, indices=put(f.indices), values=put(f.values)
                    )
                return put(f)

            # SAMPLE data stays replicated inside a multi-device group
            # (committed to the one device of a single-device group): a
            # batch-sharded fixed-effect solve would reorder the gradient
            # all-reduce and break the bitwise-parity contract.
            ds_g = GameDataset(
                shards={
                    name: put_feat(data.shards[name])
                    for name in {p.shard for p in prepared.values()}
                },
                labels=put(data.labels),
                offsets=put(data.offsets),
                weights=put(data.weights),
                id_tags=data.id_tags,
            )

            coords = {}
            for cid in self.update_sequence:
                prep = prepared[cid]
                static_cfg = dataclasses.replace(
                    base_config[cid], reg_weight=0.0
                )
                # Norm contexts are NamedTuple pytrees: device_put moves
                # their factor/shift arrays with the group's data.
                norm_g = (
                    None
                    if prep.norm is None
                    else jax.device_put(prep.norm, target)
                )
                if prep.re_dataset is not None:
                    coord = RandomEffectCoordinate(
                        ds_g, put_red(prep.re_dataset), static_cfg,
                        self.task, norm_g,
                    )
                    if multi:
                        # The ring-gather scoring path emits SAMPLE-sharded
                        # margins; left alone they propagate sample
                        # sharding into the next fixed-effect solve, whose
                        # partitioned gradient reduction would break the
                        # bitwise contract. Re-replicating is an exact
                        # all-gather (same bits), so the group fit keeps
                        # every residual replicated while the coefficient
                        # store stays row-sharded.
                        _orig_score = coord.score
                        coord.score = lambda m, _s=_orig_score, _r=rep: (
                            jax.device_put(_s(m), _r)
                        )
                    coords[cid] = coord
                else:
                    coords[cid] = FixedEffectCoordinate(
                        ds_g, prep.shard, static_cfg, self.task, norm_g
                    )
            return coords

        return build

    # ---------------------------------------------------------- run profile

    def run_profile(self) -> Dict[str, object]:
        """The machine-readable run profile of the LAST fit (ISSUE 11):
        stage breakdown, ingest breakdown, dispatch decisions, bucket
        shapes, device topology, roofline annotation, and a metrics
        snapshot — the artifact the adaptive-runtime planner consumes.
        Persist with `telemetry.write_profile(path, est.run_profile())`;
        consumers re-read it through `telemetry.read_profile` (loud
        missing-key contract)."""
        if not hasattr(self, "fit_timing"):
            raise RuntimeError("run_profile() needs a completed fit()")
        ft = dict(self.fit_timing)
        stages = {k: round(float(ft[k]), 4) for k in (*PREPARE_STAGES, "other")}
        stages["prepare_s"] = round(float(ft["prepare_s"]), 4)
        stages["solve_s"] = round(float(ft["solve_s"]), 4)
        # Every runtime decision this fit took — the knobs the Spark-ML
        # performance study shows dominate end-to-end cost, recorded so a
        # planner (or a human) can audit WHY this run ran the way it did.
        from photon_ml_tpu.data.pipeline import pipeline_enabled

        dispatch = {
            "pack_path": ft["pack_path"],
            "re_path": ft["re_path"],
            "sharding": dict(ft["sharding"]),
            "pipeline": bool(pipeline_enabled(self.pipeline)),
            # The level-1 sparse layout this fit packed ("none" when no
            # sparse shard packed) — the evidence the planner's
            # sparse_layout rule adopts next run.
            "layout": self.timing_registry.get_note("sparse_layout")
            or "none",
        }
        bucket_shapes: Dict[str, object] = {}
        for cid, prep in (self._prepared or {}).items():
            if prep.re_dataset is not None:
                bucket_shapes[cid] = [
                    [b.num_entities, b.capacity]
                    for b in prep.re_dataset.buckets
                ]
        ingest = dict(
            getattr(self._prepared_dataset, "ingest_timing", None) or {}
        )
        profile = telemetry.build_profile(
            "fit",
            wall_s=float(ft["prepare_s"]) + float(ft["solve_s"]),
            stages=stages,
            dispatch=dispatch,
            bucket_shapes=bucket_shapes,
            fit_timing=ft,
            ingest=ingest,
        )
        # The plan block rides the profile too (ISSUE 14) so plan
        # decisions round-trip through write_profile/read_profile —
        # deliberately NOT a PROFILE_*_KEYS contract key: r06-era
        # profiles (pre-planner) must keep loading for the cold start.
        profile["plan"] = dict(ft["plan"])
        return profile


def select_best_result(
    results: Sequence[GameResult],
) -> Tuple[int, GameResult]:
    """Pick the configuration whose validation metric is best
    (GameTrainingDriver.selectModels:683-710); falls back to the last result
    when no validation ran."""
    best_i = len(results) - 1
    best: Optional[EvaluationResults] = None
    for i, r in enumerate(results):
        if r.evaluation is not None and r.evaluation.better_than(best):
            best, best_i = r.evaluation, i
    return best_i, results[best_i]

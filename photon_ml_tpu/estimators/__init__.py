from photon_ml_tpu.estimators.game_estimator import (  # noqa: F401
    GameEstimator,
    GameResult,
)

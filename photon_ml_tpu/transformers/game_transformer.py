"""GameTransformer: batch scoring of a GAME model over a GameDataset.

Counterpart of photon-api transformers/GameTransformer.scala:39-318 and the
model scoring paths it drives (GameModel.scala:99-110,
FixedEffectModel.score — broadcast + mapValues dot products;
RandomEffectModel.score — re-key by REId + join, RandomEffectModel.scala:239+).

TPU translation: scoring a dataset is one jitted program per coordinate —
fixed effects are a (sharded) matvec, random effects a coefficient-row gather
plus batched dot products; the per-coordinate score RDD join becomes an
elementwise sum because every coordinate scores the same static sample axis.

The transformer also owns the *data plumbing* that scoring a NEW dataset
needs (which the reference rebuilds inside transform():150-263):
  * mapping each sample's entity key to a coefficient row through the
    training-time entity index (unseen entities -> the pinned zero row);
  * projecting the random-effect feature shard through the training-time
    projector (scoring happens in projected space — same math as training,
    avoiding RandomEffectModelInProjectedSpace back-projection);
  * folding normalization into effective coefficients.

That plumbing is host-side and dataset-bound, so it is factored into
`prepare_coordinate_data` and done ONCE per (coordinate, dataset) — repeated
scoring of the same dataset (the coordinate-descent validation loop) reuses
the prepared features/entity rows.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.containers import Features, LabeledData, SparseFeatures
from photon_ml_tpu.data.game_dataset import GameDataset
from photon_ml_tpu.evaluation.suite import EvaluationResults, EvaluationSuite
from photon_ml_tpu.game.model import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
    random_effect_margins,
)
from photon_ml_tpu.ops import objective
from photon_ml_tpu.ops.losses import mean_for_task
from photon_ml_tpu.ops.normalization import NormalizationContext
from photon_ml_tpu.types import TaskType

Array = jax.Array


@dataclasses.dataclass
class CoordinateScoringSpec:
    """Everything needed to score one coordinate on a fresh dataset.

    `shard` is the ORIGINAL feature-shard name as it appears in incoming
    datasets; `projector`/`entity_index` are the training-time artifacts for
    random-effect coordinates (None for fixed effects).
    """

    shard: str
    norm: Optional[NormalizationContext] = None
    random_effect_type: Optional[str] = None
    entity_index: Optional[Dict[object, int]] = None
    projector: Optional[object] = None

    @property
    def is_random_effect(self) -> bool:
        return self.random_effect_type is not None


@dataclasses.dataclass
class PreparedCoordinateData:
    """One coordinate's scoring view of one dataset: (projected) features +
    per-sample entity rows (None for fixed effects)."""

    features: Features
    entity_rows: Optional[Array]


def entity_rows_for_dataset(
    dataset: GameDataset, spec: CoordinateScoringSpec
) -> np.ndarray:
    """Per-sample coefficient-row indices through the training entity index;
    unseen entities get the pinned zero row (the reference's prior-model
    scoring of new entities)."""
    keys = dataset.id_tags[spec.random_effect_type]
    index = spec.entity_index
    unseen = len(index)
    # Entity ids are strings in persisted artifacts (REId = String,
    # Types.scala:9-25) but may be ints in in-memory datasets; coerce lookup
    # keys to the index's key type so reloaded models resolve entities.
    coerce = (
        index
        and isinstance(next(iter(index)), str)
        and keys.dtype.kind not in "USO"
    )
    # Ingest-factorized columns: resolve the small value table through the
    # index and gather — no n_samples sort at all.
    ct = getattr(dataset, "tag_codes", {}).get(spec.random_effect_type)
    if ct is not None:
        codes, tbl = ct
        tbl_rows = np.fromiter(
            (index.get(k, unseen) for k in tbl.tolist()),
            np.int64,
            count=len(tbl),
        )
        return tbl_rows[codes]
    # Dict-lookup the UNIQUE keys only (entities repeat ~n/E times), then
    # scatter through the inverse — the per-row Python loop was the last
    # O(n) interpreter cost in the scoring path. np.unique needs orderable
    # keys (it sorts); hand-built object-dtype tags with mixed types keep
    # the hash-based per-row path.
    try:
        uniq, inv = np.unique(keys, return_inverse=True)
    except TypeError:
        return np.fromiter(
            (
                index.get(str(k) if coerce else k, unseen)
                for k in keys.tolist()
            ),
            np.int64,
            count=len(keys),
        )
    uniq_rows = np.fromiter(
        (
            index.get(str(k) if coerce else k, unseen)
            for k in uniq.tolist()
        ),
        np.int64,
        count=len(uniq),
    )
    return uniq_rows[inv]


def prefetch_fixed_effect_shards(
    specs: Mapping[str, CoordinateScoringSpec],
    coordinate_ids,
    dataset: GameDataset,
    pipeline: Optional[bool] = None,
) -> None:
    """Kick the async upload of every fixed-effect shard (ShardDict
    prefetch, double-buffered) so the transfers overlap the host-side
    entity-row resolution and projection of the random-effect coordinates
    instead of each faulting synchronously in sequence. Random-effect
    shards are NOT prefetched: their scoring view is the projected shard
    `prepare_coordinate_data` builds/uploads itself — prefetching the raw
    ELL would ship bytes scoring never reads. No-op when the host
    data-plane pipeline is off (`pipeline` override, else data/pipeline.py
    gating) — the single switch that must keep forced-synchronous runs
    thread-free."""
    from photon_ml_tpu.data.pipeline import pipeline_enabled

    if not pipeline_enabled(pipeline) or not hasattr(dataset.shards, "prefetch"):
        return
    for cid in coordinate_ids:
        if not specs[cid].is_random_effect:
            dataset.shards.prefetch(specs[cid].shard)


def prepare_coordinate_data(
    spec: CoordinateScoringSpec, dataset: GameDataset
) -> PreparedCoordinateData:
    """Host-side, once per (coordinate, dataset): resolve entity rows and run
    the projector. Everything downstream is pure device compute."""
    if not spec.is_random_effect:
        return PreparedCoordinateData(dataset.shards[spec.shard], None)
    rows = entity_rows_for_dataset(dataset, spec)
    host_planes = getattr(dataset, "host_ell", {}).get(spec.shard)
    if spec.projector is not None and host_planes is not None:
        # Project from ingest's host planes: the raw ELL never ships to
        # the device (ShardDict lazy upload) — only the projected shard
        # does, inside project_features.
        feats = (
            dataset.peek_shard(spec.shard)
            if hasattr(dataset, "peek_shard")
            else dataset.shards[spec.shard]
        )
        feats = spec.projector.project_features(
            feats, rows, host_planes=host_planes
        )
    else:
        feats = dataset.shards[spec.shard]
        if spec.projector is not None:
            feats = spec.projector.project_features(feats, rows)
    return PreparedCoordinateData(feats, jnp.asarray(rows, jnp.int32))


@jax.jit
def _re_margins(features: Features, entity_rows: Array, matrix: Array, norm) -> Array:
    return random_effect_margins(features, entity_rows, matrix, norm)


def _entity_sharded_mesh(matrix):
    """The 1-D mesh a row-sharded coefficient matrix lives on, if any."""
    from photon_ml_tpu.parallel.mesh import leading_axis_mesh

    return leading_axis_mesh(matrix, require_divisible=True)


# Dense batches up to this many rows score sharded matrices through the psum
# broadcast-gather; beyond it (dataset-scale scoring) the replicated (N, D)
# gathered block would cost more HBM than the ring rotation it avoids.
_BCAST_SCORING_MAX_ROWS = 4096


def dense_margins(features: Array, w: Array, norm) -> Array:
    """Row-stable dense margins: multiply-broadcast + per-row reduction
    instead of the matvec `features @ w`. The matvec's CPU/TPU lowering picks
    blocking by the BATCH dimension, so the same row can score differently at
    different batch sizes (observed 2e-6 drift on CPU between a 7-row and a
    padded 16-row call); the per-row reduction's within-row order is fixed
    regardless of how many rows ride along. That batch-size invariance is
    what lets the online serving engine score padded power-of-two buckets
    bitwise-identically to this offline path (serving/engine.py), and makes
    a request's score independent of which micro-batch it lands in. Margins
    are bandwidth-bound (one multiply-add per X element), so giving up the
    matvec costs little. jit-traceable; shared by `_fe_margins` and the
    serving engine's fused program — keep both on this one code path."""
    w_eff, shift = objective.margin_params(w, norm)
    return jnp.sum(features * w_eff, axis=-1) + shift


@jax.jit
def _fe_margins(features: Features, w: Array, norm) -> Array:
    # `features` may be an ELL SparseFeatures (either layout), a dense
    # matrix, or the trained coordinate's BucketedSparseFeatures
    # (training_prepared's preference) — all three expose the logical
    # (n_rows, dim) via .shape, and compute_margins handles each. Dense
    # matrices take the row-stable path (see `dense_margins`); the sparse
    # layouts' gather + per-row-K reductions are already batch-invariant.
    if isinstance(features, (jax.Array, np.ndarray)):
        return dense_margins(features, w, norm)
    n = features.shape[0]
    zeros = jnp.zeros((n,), w.dtype)
    return objective.compute_margins(w, LabeledData(features, zeros, zeros, zeros), norm)


def coordinate_margins(
    spec: CoordinateScoringSpec, model, prepared: PreparedCoordinateData
) -> Array:
    """Score one coordinate's model over prepared data."""
    if spec.is_random_effect:
        assert isinstance(model, RandomEffectModel)
        matrix = model.coefficients_matrix
        mesh = _entity_sharded_mesh(matrix)
        from photon_ml_tpu.ops.normalization import PerEntityNormalization

        if mesh is not None and not isinstance(spec.norm, PerEntityNormalization):
            # Mesh-trained row-sharded matrix: the full (E+1, D) matrix is
            # never replicated on one device (the whole point of the
            # entity-sharded store). Dense small batches take the psum
            # broadcast-gather (one collective of N*D floats — the serving
            # engine's dispatch, bitwise-equal to the replicated branch);
            # sparse or dataset-scale sample axes keep the ring, whose wire
            # cost is independent of N.
            from photon_ml_tpu.game.model import (
                random_effect_margins_bcast,
                random_effect_margins_sharded,
            )

            dense = isinstance(prepared.features, (jax.Array, np.ndarray))
            if dense and prepared.entity_rows.shape[0] <= _BCAST_SCORING_MAX_ROWS:
                return random_effect_margins_bcast(
                    prepared.features, prepared.entity_rows, matrix, spec.norm, mesh
                )
            return random_effect_margins_sharded(
                prepared.features, prepared.entity_rows, matrix, spec.norm, mesh
            )
        return _re_margins(prepared.features, prepared.entity_rows, matrix, spec.norm)
    assert isinstance(model, FixedEffectModel)
    return _fe_margins(prepared.features, model.coefficients.means, spec.norm)


@dataclasses.dataclass
class TransformResult:
    """ModelDataScores equivalent: raw summed margins (incl. offsets) plus the
    task-link mean response (ScoredGameDatum fields)."""

    scores: Array
    means: Array
    per_coordinate: Dict[str, Array]


class GameTransformer:
    """Scores GameDatasets with a trained GAME model (GameTransformer.scala).

    `specs` must cover every coordinate of the model; built by GameEstimator
    (training) or reconstructed from a model store (scoring driver).
    """

    def __init__(
        self,
        model: GameModel,
        specs: Mapping[str, CoordinateScoringSpec],
        task: TaskType,
        *,
        pipeline: Optional[bool] = None,
    ):
        missing = [c for c in model.coordinate_ids if c not in specs]
        if missing:
            raise ValueError(f"No scoring spec for coordinates {missing}")
        self.model = model
        self.specs = dict(specs)
        self.task = task
        # Host data-plane pipelining override (see GameEstimator.pipeline);
        # None = the data/pipeline.py env/auto gate.
        self.pipeline = pipeline

    def prepare(self, dataset: GameDataset) -> Dict[str, PreparedCoordinateData]:
        """One-time host prep of `dataset` for every coordinate; pass the
        result to transform() when scoring the same dataset repeatedly.

        When the host data-plane pipeline is enabled, fixed-effect shard
        uploads start asynchronously first so they overlap the
        random-effect host prep (see `prefetch_fixed_effect_shards`)."""
        prefetch_fixed_effect_shards(
            self.specs, self.model.coordinate_ids, dataset, self.pipeline
        )
        return {
            cid: prepare_coordinate_data(self.specs[cid], dataset)
            for cid in self.model.coordinate_ids
        }

    def score_coordinate(
        self,
        cid: str,
        dataset: GameDataset,
        prepared: Optional[PreparedCoordinateData] = None,
    ) -> Array:
        spec = self.specs[cid]
        if prepared is None:
            prepared = prepare_coordinate_data(spec, dataset)
        return coordinate_margins(spec, self.model[cid], prepared)

    def transform(
        self,
        dataset: GameDataset,
        prepared: Optional[Dict[str, PreparedCoordinateData]] = None,
    ) -> TransformResult:
        """GameTransformer.transform:150 / scoreGameDataset:263 — sum of
        coordinate scores + offsets, and the link-function mean."""
        if prepared is None:
            prepared = self.prepare(dataset)
        per_coordinate = {
            cid: coordinate_margins(self.specs[cid], self.model[cid], prepared[cid])
            for cid in self.model.coordinate_ids
        }
        total = dataset.offsets
        for s in per_coordinate.values():
            total = total + s
        means = mean_for_task(self.task, total)
        return TransformResult(scores=total, means=means, per_coordinate=per_coordinate)

    def evaluate(
        self,
        dataset: GameDataset,
        suite: EvaluationSuite,
        prepared: Optional[Dict[str, PreparedCoordinateData]] = None,
    ) -> EvaluationResults:
        """Optional validation path of the transformer (GameTransformer.scala
        logValidationMetrics)."""
        return suite.evaluate(self.transform(dataset, prepared).scores)

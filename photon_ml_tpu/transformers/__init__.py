from photon_ml_tpu.transformers.game_transformer import (  # noqa: F401
    CoordinateScoringSpec,
    GameTransformer,
    PreparedCoordinateData,
    TransformResult,
    coordinate_margins,
    prepare_coordinate_data,
)

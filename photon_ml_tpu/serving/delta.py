"""Delta bundles: ship ONLY what an incremental fit changed to a live engine.

The serving half of the ISSUE 16 continuous-refresh loop. A full model
swap re-uploads every coordinate; after an incremental fit
(game/incremental.py) almost all of that traffic is bytes the device
already holds. `build_delta_bundle` diffs two fit states BITWISE into the
minimal payload — changed/added random-effect rows and changed
fixed-effect planes — and `apply_delta` flips a live engine onto it
through the SAME reshard staging machinery every other live mutation
uses (`MeshReshardOrchestrator._stage_and_commit`): double-buffered
staging under the `shard_upload` fault site, compatibility check,
pre-warm, `reshard_commit` fault site, atomic flip, drain, retire. A
failure anywhere before the flip rolls back to the old generation —
which never stopped serving — and journals `delta_rollback`.

Row placement: new entities interleave into the sorted-unique entity
index, so carried rows can MOVE even though their floats don't change.
The bundle therefore carries, per coordinate, both the changed rows
(values that cross the host->device wire) and a carry map (old row ->
new row) applied as a device-side gather — upload bytes stay
proportional to the churn, not the matrix. When the index is unchanged
the carry map is the identity and the apply is a pure functional
`.at[rows].set` on the resident matrix (per-shard on entity-sharded
coordinates). Entity-sharded growth must fit the existing mesh padding;
past it, the apply refuses loudly — grow through a reshard instead.

Provenance: every committed apply updates the live bundle's lineage
block IN PLACE (origin -> "incremental", deltas_applied += 1,
last_delta_source/ts) — surfaced by cli/serve in serving-summary.json —
and journals `delta_apply`.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.incremental import FitState, grow_random_effect_model
from photon_ml_tpu.game.model import FixedEffectModel, RandomEffectModel
from photon_ml_tpu.serving.bundle import (
    ServingBundle,
    ServingCoordinate,
    TwoTierEntityStore,
    _stage_shard,
)
from photon_ml_tpu.utils import faults, telemetry
from photon_ml_tpu.utils.contracts import DELTA_BUNDLE_KEYS

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class CoordinateDelta:
    """One coordinate's minimal update payload.

    Fixed effects: `plane` is the full new (dim,) weight plane (FE planes
    are tiny — shipping whole is already minimal). Random effects:
    `rows`/`values` are the changed/added coefficient rows in NEW-index
    row space, `carry_old`/`carry_new` map every carried row's old
    position to its new one (identity maps are stored as None), and
    `entity_index`/`logical_rows` are the coordinate's new host indexes.
    """

    cid: str
    plane: Optional[np.ndarray] = None
    rows: Optional[np.ndarray] = None
    values: Optional[np.ndarray] = None
    carry_old: Optional[np.ndarray] = None
    carry_new: Optional[np.ndarray] = None
    entity_index: Optional[Dict[object, int]] = None
    logical_rows: Optional[int] = None

    @property
    def is_random_effect(self) -> bool:
        return self.plane is None

    @property
    def nbytes(self) -> int:
        if self.plane is not None:
            return int(self.plane.nbytes)
        return int(self.values.nbytes)

    @property
    def n_rows(self) -> int:
        return 0 if self.rows is None else int(len(self.rows))


@dataclasses.dataclass(frozen=True)
class DeltaBundle:
    """The minimal refresh payload between two fits (manifest keys pinned
    by contracts.DELTA_BUNDLE_KEYS)."""

    source: str
    mode: str
    coordinates: Dict[str, CoordinateDelta]
    delta_rows: int
    total_rows: int

    @property
    def nbytes(self) -> int:
        return sum(d.nbytes for d in self.coordinates.values())

    @property
    def is_empty(self) -> bool:
        return not self.coordinates

    def manifest(self) -> Dict[str, object]:
        """DELTA_BUNDLE_KEYS-shaped summary for journals and CLI output."""
        out = {
            "source": self.source,
            "mode": self.mode,
            "coordinates": {
                cid: {
                    "kind": "re" if d.is_random_effect else "fe",
                    "rows": d.n_rows,
                }
                for cid, d in self.coordinates.items()
            },
            "delta_rows": int(self.delta_rows),
            "total_rows": int(self.total_rows),
            "bytes": int(self.nbytes),
        }
        assert tuple(out) == DELTA_BUNDLE_KEYS
        return out


def build_delta_bundle(
    prev: FitState, new: FitState, *, source: str, mode: str = "delta",
    delta_rows: int = 0, total_rows: int = 0,
) -> DeltaBundle:
    """Bitwise-diff two fit states into the minimal update payload.

    Trusting the diff to be bitwise is what makes the payload honest: a
    coordinate the incremental fit carried over contributes NOTHING (its
    floats are the same objects), a changed random-effect coordinate
    contributes exactly its churned + new rows, and carried rows that
    merely MOVED (index re-sort) ride the carry map, not the wire."""
    coords: Dict[str, CoordinateDelta] = {}
    for cid in new.model.coordinate_ids:
        pm, nm = prev.model[cid], new.model[cid]
        if isinstance(nm, FixedEffectModel):
            new_plane = np.ascontiguousarray(
                np.asarray(nm.coefficients.means), np.float32
            )
            old_plane = np.asarray(pm.coefficients.means, np.float32)
            if new_plane.shape == old_plane.shape and np.array_equal(
                new_plane, old_plane
            ):
                continue
            coords[cid] = CoordinateDelta(cid, plane=new_plane)
            continue
        if not isinstance(nm, RandomEffectModel):
            raise TypeError(f"unknown model type {type(nm)} for {cid!r}")
        prev_idx = prev.entity_indices[cid]
        new_idx = new.entity_indices[cid]
        # Compare in NEW row space: grow the previous matrix (key-mapped
        # carry, zero rows for new entities) and keep rows that differ.
        grown = (
            pm
            if prev_idx == new_idx
            else grow_random_effect_model(pm, prev_idx, new_idx)
        )
        e_new = len(new_idx)
        new_mat = np.asarray(nm.coefficients_matrix)[: e_new + 1]
        old_mat = np.asarray(grown.coefficients_matrix)[: e_new + 1]
        changed = np.nonzero(np.any(new_mat != old_mat, axis=1))[0]
        # Brand-new entities whose solve happened to stay zero still need
        # their index entry; the row payload covers value changes only.
        if changed.size == 0 and prev_idx == new_idx:
            continue
        carry_old = carry_new = None
        if prev_idx != new_idx:
            shared = [k for k in new_idx if k in prev_idx]
            carry_old = np.fromiter(
                (prev_idx[k] for k in shared), np.int64, len(shared)
            )
            carry_new = np.fromiter(
                (new_idx[k] for k in shared), np.int64, len(shared)
            )
            if np.array_equal(carry_old, carry_new):
                carry_old = carry_new = None  # pure append: no moves
        coords[cid] = CoordinateDelta(
            cid,
            rows=changed.astype(np.int64),
            values=np.ascontiguousarray(new_mat[changed], np.float32),
            carry_old=carry_old,
            carry_new=carry_new,
            entity_index=dict(new_idx),
            logical_rows=e_new + 1,
        )
    return DeltaBundle(
        source, mode, coords, int(delta_rows), int(total_rows)
    )


def _apply_re_delta(
    c: ServingCoordinate, d: CoordinateDelta, staged_stores: List
) -> ServingCoordinate:
    """Stage one random-effect coordinate's new generation from its
    resident state + the delta rows, per storage mode. Functional updates
    only: in-flight batches keep scoring their captured params snapshot."""
    vals = jnp.asarray(d.values)
    rows = jnp.asarray(d.rows)
    if c.store is not None:
        # Two-tier: the cold matrix is host RAM — rebuild it host-side
        # (carry + scatter) and stage a fresh store; the old store closes
        # on retire (or on rollback via staged_stores).
        old_cold = c.store.cold_matrix
        if d.carry_old is None:
            new_cold = np.zeros((d.logical_rows, old_cold.shape[1]), np.float32)
            new_cold[: old_cold.shape[0]] = old_cold
        else:
            new_cold = np.zeros((d.logical_rows, old_cold.shape[1]), np.float32)
            new_cold[d.carry_new] = old_cold[d.carry_old]
        new_cold[d.rows] = d.values
        new_store = _stage_shard(
            f"{d.cid} (delta two-tier rebuild)",
            lambda: TwoTierEntityStore(new_cold, c.store.capacity),
        )
        staged_stores.append(new_store)
        return ServingCoordinate(
            d.cid,
            c.shard,
            new_store.snapshot(),
            norm=c.norm,
            random_effect_type=c.random_effect_type,
            entity_index=d.entity_index,
            logical_rows=d.logical_rows,
            store=new_store,
        )
    if c.mesh is not None:
        # Entity-sharded: growth must fit the existing mesh padding and
        # carried rows must keep their positions — per-device row blocks
        # are placement, and placement changes go through reshard().
        physical = int(c.params.shape[0])
        if d.logical_rows > physical:
            raise ValueError(
                f"coordinate {d.cid!r}: delta grows logical rows to "
                f"{d.logical_rows} past the mesh-padded {physical} — "
                "reshard to a larger padding first, then apply"
            )
        if d.carry_old is not None:
            raise ValueError(
                f"coordinate {d.cid!r}: delta re-sorts carried entity rows; "
                "an entity-sharded matrix's row placement changes through "
                "reshard(), not a delta apply"
            )
        ndev = int(c.mesh.devices.size)
        rows_per = physical // ndev
        shard_of = d.rows // rows_per
        params = c.params
        for k in np.unique(shard_of):
            m = shard_of == int(k)
            r_k, v_k = rows[np.nonzero(m)[0]], vals[np.nonzero(m)[0]]
            params = _stage_shard(
                f"{d.cid} shard {int(k)} (delta rows)",
                lambda p=params, r=r_k, v=v_k: p.at[r].set(v),
            )
        return ServingCoordinate(
            d.cid,
            c.shard,
            params,
            norm=c.norm,
            random_effect_type=c.random_effect_type,
            entity_index=d.entity_index,
            mesh=c.mesh,
            logical_rows=d.logical_rows,
            shard_health=c.shard_health,
        )
    # Replicated single-tier: one shard, one staged functional update.
    old_params = c.params
    old_rows = int(old_params.shape[0])

    def stage():
        if d.carry_old is None:
            base = (
                old_params
                if d.logical_rows == old_rows
                else jnp.pad(
                    old_params, ((0, d.logical_rows - old_rows), (0, 0))
                )
            )
        else:
            base = (
                jnp.zeros((d.logical_rows, old_params.shape[1]), jnp.float32)
                .at[jnp.asarray(d.carry_new)]
                .set(old_params[jnp.asarray(d.carry_old)])
            )
        return base.at[rows].set(vals)

    params = _stage_shard(f"{d.cid} (delta rows)", stage)
    from photon_ml_tpu.serving.bundle import ShardHealth

    return ServingCoordinate(
        d.cid,
        c.shard,
        params,
        norm=c.norm,
        random_effect_type=c.random_effect_type,
        entity_index=d.entity_index,
        shard_health=ShardHealth(1, d.logical_rows),
    )


def apply_delta(
    engine, delta: DeltaBundle, *, drain_timeout_s: float = 30.0
) -> Dict[str, object]:
    """Flip a live engine onto a delta bundle — an in-place generation
    flip through the reshard stage->pre-warm->commit->rollback primitive
    (kind="delta"). Zero failed requests: the old generation serves every
    in-flight and concurrent request until the atomic flip, and keeps
    serving if anything fails before it. An empty bundle commits nothing
    and returns immediately."""
    orch = engine.reshard_orchestrator
    if delta.is_empty:
        return {
            "version": engine._state.version,
            "committed": False,
            "delta_rows_staged": 0,
            "restaged_bytes": 0,
        }
    with engine.bundle_manager.mutex:
        old_state = engine._state
        old_bundle = old_state.bundle
        missing = [c for c in delta.coordinates if c not in old_bundle.coordinates]
        if missing:
            raise ValueError(
                f"delta bundle targets unknown coordinates {missing!r}"
            )
        staged_stores: List[TwoTierEntityStore] = []
        close_stores = tuple(
            old_bundle.coordinates[cid].store
            for cid, d in delta.coordinates.items()
            if d.is_random_effect
            and old_bundle.coordinates[cid].store is not None
        )

        def build_new_coords() -> Tuple[Dict[str, ServingCoordinate], int]:
            new_coords = dict(old_bundle.coordinates)
            for cid, d in delta.coordinates.items():
                c = old_bundle.coordinates[cid]
                with telemetry.span("delta_stage", coordinate=cid):
                    if d.is_random_effect:
                        new_coords[cid] = _apply_re_delta(c, d, staged_stores)
                    else:
                        plane = d.plane
                        params = _stage_shard(
                            f"{cid} (delta fixed-effect plane)",
                            lambda p=plane: jnp.asarray(p, jnp.float32),
                        )
                        new_coords[cid] = ServingCoordinate(
                            cid, c.shard, params, norm=c.norm
                        )
            return new_coords, delta.nbytes

        info = orch._stage_and_commit(
            old_state,
            None,
            build_new_coords,
            close_stores=close_stores,
            kind="delta",
            drain_timeout_s=drain_timeout_s,
            on_rollback=lambda: [s.close() for s in staged_stores],
        )
        n_rows = sum(d.n_rows for d in delta.coordinates.values())
        faults.COUNTERS.increment("delta_applies")
        if n_rows:
            faults.COUNTERS.increment("delta_rows_staged", n_rows)
        live = engine._state.bundle
        live.provenance["origin"] = "incremental"
        live.provenance["deltas_applied"] = (
            int(live.provenance.get("deltas_applied", 0)) + 1
        )
        live.provenance["last_delta_source"] = delta.source
        live.provenance["last_delta_ts"] = time.time()
        telemetry.emit_event(
            "delta_apply",
            version=info["version"],
            coordinates=sorted(delta.coordinates),
            rows=int(n_rows),
            bytes=int(delta.nbytes),
            source=delta.source,
        )
        logger.info(
            "delta bundle applied: generation %d -> %d (%d rows, %d bytes, "
            "source %s)",
            info["previous_version"],
            info["version"],
            n_rows,
            delta.nbytes,
            delta.source,
        )
        info["delta_rows_staged"] = int(n_rows)
        return info


def apply_delta_for_tenant(
    registry, name: str, delta: DeltaBundle, *, drain_timeout_s: float = 30.0
) -> Dict[str, object]:
    """Per-tenant refresh: flip ONE tenant's engine onto a delta bundle.
    Tenant engines share the fleet's device mutex through their bundle
    managers, so the flip serializes with every other tenant's dispatch
    exactly like any other live mutation — and touches no other tenant's
    generation."""
    tenant = registry.tenant(name)
    return apply_delta(tenant.engine, delta, drain_timeout_s=drain_timeout_s)

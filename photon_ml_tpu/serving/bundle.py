"""Serving bundles: a GAME model staged into device memory exactly once.

The offline scoring path (cli/score.py) re-stages the model per job: load
the Avro artifact, build host matrices, upload shards, score, exit. An
online engine cannot pay that per request — Snap ML's serving result
(PAPERS.md) is precisely that keeping model state pinned in accelerator
memory across requests is where the latency win lives. A `ServingBundle`
is that pinned state:

  * per fixed-effect coordinate: the effective weight vector, one device
    array, uploaded at load;
  * per random-effect coordinate: the dense `(n_entities + 1, dim)`
    coefficient matrix (row `n_entities` is the pinned zero row — GLMix
    cold-start semantics: an unknown entity scores with the fixed effects
    only) plus a host-side entity-id -> row hash index;
  * optionally the feature index maps, so requests can arrive as
    (name, term) -> value dicts and be resolved to column indices host-side.

Bundles are built from a persisted model artifact (`from_artifact` /
`load_bundle` — the production path, original feature space, no
projector/normalization needed) or directly from an in-memory trained
model (`from_model` — tests and co-located train+serve; normalization
passes through to the same margin algebra the transformer uses, but
projected random-effect coordinates are rejected: serving scores in
original space, so export through `model_bridge.artifact_from_game_model`
first, which back-projects).
"""

from __future__ import annotations

import dataclasses
import glob
import logging
import os
import threading
import time
from typing import Dict, List, Mapping, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)

from photon_ml_tpu.data.index_map import IndexMap, feature_key
from photon_ml_tpu.utils import faults, telemetry
from photon_ml_tpu.utils.knobs import get_knob
from photon_ml_tpu.game.model import FixedEffectModel, GameModel, RandomEffectModel
from photon_ml_tpu.io.model_store import GameModelArtifact
from photon_ml_tpu.transformers.game_transformer import CoordinateScoringSpec
from photon_ml_tpu.types import TaskType

Array = jax.Array

# Request feature payload for one shard: a dense (dim,) row, or a sparse
# (indices, values) pair, or a {feature_key: value} mapping resolved through
# the bundle's index maps at encode time.
ShardFeatures = Union[np.ndarray, Tuple[np.ndarray, np.ndarray]]


@dataclasses.dataclass
class ScoreRequest:
    """One scoring request: per-shard features + per-RE-type entity ids.

    `features[shard]` is a dense (dim,) float row or an (indices, values)
    sparse pair (duplicate indices accumulate, matching `pack_csr_to_ell`).
    A shard absent from the mapping scores as an all-zero row. Entity ids
    missing for a random-effect type are cold starts by definition.

    `deadline_ms` is the request's latency budget, counted from submission
    to the micro-batcher: a request still queued past its budget is failed
    with `DeadlineExceeded` before wasting a device slot, and batch
    assembly never co-batches an expired request. None defers to the
    batcher's `default_deadline_ms` (which may also be None: no deadline).
    """

    features: Dict[str, ShardFeatures] = dataclasses.field(default_factory=dict)
    entity_ids: Dict[str, object] = dataclasses.field(default_factory=dict)
    offset: float = 0.0
    uid: Optional[str] = None
    deadline_ms: Optional[float] = None


def default_provenance(origin: str = "full_fit") -> Dict[str, object]:
    """A fresh bundle lineage block (contracts.BUNDLE_PROVENANCE_KEYS):
    where the bundle came from ("full_fit" | "artifact" | "incremental")
    and how many delta applies it has absorbed. Stamped by the builders,
    updated IN PLACE by serving/delta.apply_delta at each committed flip,
    and surfaced by cli/serve in serving-summary.json."""
    return {
        "origin": origin,
        "generation": 0,
        "deltas_applied": 0,
        "last_delta_source": None,
        "last_delta_ts": None,
    }


def _shard_upload_policy():
    """Bounded retry for per-shard model staging/restage: 1 +
    PHOTON_SHARD_UPLOAD_RETRIES attempts under the standard backoff."""
    return faults.bounded_policy(int(get_knob("PHOTON_SHARD_UPLOAD_RETRIES")))


def _stage_shard(label: str, fn):
    """One per-shard staging step under the `shard_upload` fault site
    (counted in COUNTERS["shard_upload_retries"]). Exhausted retries
    propagate: at bundle build time that fails the build (a hot-swap's
    builder failure rides the existing BundleManager rollback — the old
    bundle never stops serving); at shard RESTAGE time the shard simply
    stays lost and the engine keeps serving its entities FE-only."""

    def attempt():
        faults.fault_point("shard_upload")
        return fn()

    return faults.retry(
        attempt,
        _shard_upload_policy(),
        label=f"shard staging {label}",
        counter="shard_upload_retries",
    )


class ShardHealth:
    """Per-shard health of one random-effect coordinate's device-resident
    coefficient rows (ISSUE 10 shard-loss degradation).

    A "shard" is one device's contiguous row block of the (padded)
    coefficient matrix on the entity-sharded path, or the whole matrix
    (one shard) on the replicated path. Marking a shard LOST makes the
    engine resolve every request row in its range to the pinned zero row
    at lookup time — bitwise FE-only answers for exactly those entities,
    the same degradation tier as a circuit-open but scoped to one shard —
    while every other shard keeps serving full-fidelity. Recovery
    (`ServingBundle.restage_shard`) re-uploads ONLY the lost shard's rows.

    Thread-safe: lookups snapshot the lost set under the lock; the mask
    math itself runs lock-free on the snapshot.
    """

    def __init__(self, n_shards: int, rows_per_shard: int):
        self.n_shards = int(n_shards)
        self.rows_per_shard = int(rows_per_shard)
        self._lock = threading.Lock()
        self._lost: set = set()
        # Observed per-shard request load (rows resolved into each shard's
        # range at lookup time, cold starts excluded) — the telemetry a
        # reshard/rebalance plan reads to name the overloaded shard.
        self._loads = [0] * self.n_shards

    def _check(self, idx: int) -> int:
        idx = int(idx)
        if not 0 <= idx < self.n_shards:
            raise ValueError(
                f"shard index {idx} out of range (n_shards={self.n_shards})"
            )
        return idx

    def row_range(self, idx: int) -> Tuple[int, int]:
        idx = self._check(idx)
        lo = idx * self.rows_per_shard
        return lo, lo + self.rows_per_shard

    def mark_lost(self, idx: int) -> None:
        with self._lock:
            self._lost.add(self._check(idx))

    def mark_ok(self, idx: int) -> None:
        with self._lock:
            self._lost.discard(self._check(idx))

    @property
    def lost(self) -> Tuple[int, ...]:
        with self._lock:
            return tuple(sorted(self._lost))

    @property
    def any_lost(self) -> bool:
        with self._lock:
            return bool(self._lost)

    @property
    def loads(self) -> Tuple[int, ...]:
        with self._lock:
            return tuple(self._loads)

    def record_loads(self, rows: np.ndarray, unseen_row: int) -> None:
        """Count one lookup's rows into their shards' load counters
        (rows at the pinned zero row are cold starts, not shard load)."""
        rows = np.asarray(rows, np.int64)
        rows = rows[rows != int(unseen_row)]
        if not len(rows):
            return
        shard_of = np.clip(rows // self.rows_per_shard, 0, self.n_shards - 1)
        counts = np.bincount(shard_of, minlength=self.n_shards)
        with self._lock:
            for i in range(self.n_shards):
                self._loads[i] += int(counts[i])

    def lost_mask(self, rows: np.ndarray) -> np.ndarray:
        """Bool mask over `rows` of those living in a LOST shard."""
        with self._lock:
            lost = tuple(self._lost)
        if not lost:
            return np.zeros(len(rows), bool)
        shard_of = np.asarray(rows, np.int64) // self.rows_per_shard
        mask = np.zeros(len(rows), bool)
        for idx in lost:
            mask |= shard_of == idx
        return mask


class TwoTierEntityStore:
    """Two-tier random-effect row store: HBM-resident HOT set + host-RAM
    COLD tier with asynchronous promotion (the Snap ML device/host memory
    hierarchy, PAPERS.md, applied to serving coefficients).

    The hot tier is a pinned `(capacity + 1, dim)` device matrix (slot
    `capacity` is the pinned zero row — unknown entities and padding gather
    it). The cold tier is the FULL `(E + 1, dim)` float32 matrix in host
    RAM. A lookup resolves each logical coefficient row to either its hot
    slot or, on a hot miss, copies the row out of the cold tier into the
    request's override buffer — the request still scores BITWISE-identically
    to a single-tier bundle (the override row IS the matrix row; see
    `game.model.gathered_row_margins`) — and schedules the row for async
    promotion into the hot set (LRU eviction under the capacity bound).
    Rows absent from both tiers fall through to the pinned zero row, the
    existing cold-start miss tier.

    Consistency: the (hot matrix, row->slot index) pair is read and
    published under one lock, and promotions build a NEW device matrix
    (functional `.at[].set`), so an in-flight batch's captured snapshot can
    never be remapped under it. The promotion worker is a short-lived
    thread (`photon-serving-promote`, joined by `close()`/`drain()`), so a
    released bundle leaks nothing.
    """

    def __init__(
        self,
        cold_matrix: np.ndarray,
        hot_rows: int,
        preload_rows: Optional[Sequence[int]] = None,
    ):
        self._cold = np.ascontiguousarray(cold_matrix, dtype=np.float32)
        self.n_rows = int(self._cold.shape[0])  # logical E + 1
        self.dim = int(self._cold.shape[1])
        cap = max(0, min(int(hot_rows), self.n_rows - 1))
        self.capacity = cap
        self.zero_slot = cap
        self._lock = threading.Lock()
        # Deterministic preload: the first `capacity` logical rows by
        # default, or an explicit measured-hotness row list (the hot-row
        # rebalance path, serving/reshard.py) — deduped, pinned-row
        # excluded, truncated to capacity; unfilled slots stay empty and
        # are the first LRU victims.
        if preload_rows is None:
            preload = list(range(cap))
        else:
            seen: set = set()
            preload = []
            for r in preload_rows:
                r = int(r)
                if 0 <= r < self.n_rows - 1 and r not in seen:
                    seen.add(r)
                    preload.append(r)
                if len(preload) >= cap:
                    break
        self.preloaded_rows: Tuple[int, ...] = tuple(preload)
        hot = np.zeros((cap + 1, self.dim), np.float32)
        if preload:
            hot[: len(preload)] = self._cold[preload]
        self._hot = jnp.asarray(hot)
        self._slot_of_row: Dict[int, int] = {
            r: s for s, r in enumerate(preload)
        }
        self._row_of_slot: List[Optional[int]] = list(preload) + [None] * (
            cap - len(preload)
        )
        self._tick = 0
        self._last_used = [0] * cap
        self._pending: Dict[int, bool] = {}
        self._worker: Optional[threading.Thread] = None
        self._closed = False
        self.hot_hits = 0
        self.cold_hits = 0
        self.promotions = 0
        self.evictions = 0
        self.promote_failures = 0
        # row -> times it was promoted into the hot set: the observed-
        # hotness signal a rebalance plan consumes (promotion_stats()).
        self._promote_count: Dict[int, int] = {}

    @property
    def cold_matrix(self) -> np.ndarray:
        """The full host-RAM coefficient matrix (the rebalance path
        restages a new store over the SAME host rows — no copy)."""
        return self._cold

    def promotion_stats(self) -> Dict[int, int]:
        """Observed promotions per logical row — the telemetry feeding the
        hot-row rebalance plan (serving/reshard.plan_rebalance)."""
        with self._lock:
            return dict(self._promote_count)

    @property
    def hot_nbytes(self) -> int:
        """Device-resident bytes of the hot tier (the HBM-budget term)."""
        return (self.capacity + 1) * self.dim * 4

    @property
    def hot_fraction(self) -> float:
        return self.capacity / max(1, self.n_rows - 1)

    def snapshot(self) -> Array:
        with self._lock:
            return self._hot

    def lookup(
        self, rows: np.ndarray, bucket: int
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, Array]:
        """Resolve logical rows -> (hot slots, override rows, override
        flags, hot-matrix snapshot), all padded to `bucket`. Cold-tier hits
        carry their row in the override buffer (flag set) and are queued
        for async promotion. The slot/snapshot pair is captured under one
        lock so a concurrent promotion can never remap an in-flight batch.
        """
        n = len(rows)
        slots = np.full(bucket, self.zero_slot, np.int32)
        ovr = np.zeros((bucket, self.dim), np.float32)
        flags = np.zeros(bucket, bool)
        with self._lock:
            self._tick += 1
            tick = self._tick
            for i in range(n):
                r = int(rows[i])
                if r >= self.n_rows - 1:
                    continue  # unseen -> pinned zero slot
                s = self._slot_of_row.get(r)
                if s is not None:
                    slots[i] = s
                    self._last_used[s] = tick
                    self.hot_hits += 1
                else:
                    ovr[i] = self._cold[r]
                    flags[i] = True
                    self.cold_hits += 1
                    if self.capacity and not self._closed:
                        self._pending.setdefault(r, True)
            snapshot = self._hot
            # Kick the worker whenever ANYTHING is pending — not only when
            # this lookup queued a new row: a row enqueued in the window
            # where the previous worker had decided to exit but still
            # reported is_alive() would otherwise never be promoted (no
            # later lookup of it re-queues, so no restart ever fires).
            if self._pending and not self._closed:
                self._maybe_start_worker_locked()
        return slots, ovr, flags, snapshot

    def _maybe_start_worker_locked(self) -> None:
        if self._worker is None or not self._worker.is_alive():
            # Parent the promotion worker's spans under the lookup that
            # queued the promotions (the stage-registry handoff pattern).
            self._span_h = telemetry.span_handoff()
            self._worker = threading.Thread(
                target=self._promote_pending,
                name="photon-serving-promote",
                daemon=True,
            )
            self._worker.start()

    def _promote_pending(self) -> None:
        with telemetry.adopt_span(getattr(self, "_span_h", None)):
            self._promote_pending_inner()

    def _promote_pending_inner(self) -> None:
        while True:
            with self._lock:
                if self._closed or not self._pending:
                    return
                batch = list(self._pending)[: max(1, self.capacity)]
                idx: List[int] = []
                srcs: List[int] = []
                for r in batch:
                    self._pending.pop(r, None)
                    if r in self._slot_of_row:
                        continue
                    s = self._lru_slot_locked()
                    old = self._row_of_slot[s]
                    if old is not None:
                        del self._slot_of_row[old]
                        self.evictions += 1
                    self._row_of_slot[s] = r
                    self._slot_of_row[r] = s
                    self._last_used[s] = self._tick
                    self.promotions += 1
                    self._promote_count[r] = self._promote_count.get(r, 0) + 1
                    idx.append(s)
                    srcs.append(r)
                if idx:
                    # Functional update INSIDE the critical section: the new
                    # (matrix, index) pair publishes atomically; snapshots
                    # already handed out keep their own immutable matrix.
                    try:
                        faults.fault_point("promote")
                        with telemetry.span("promote_rows", rows=len(idx)):
                            self._hot = self._hot.at[
                                jnp.asarray(idx, jnp.int32)
                            ].set(jnp.asarray(self._cold[srcs]))
                    except BaseException as exc:  # noqa: BLE001 - see below
                        # Roll the index back — lookups must keep resolving
                        # these rows through the cold tier, never to a hot
                        # slot that was not actually written.
                        for s, r in zip(idx, srcs):
                            self._slot_of_row.pop(r, None)
                            self._row_of_slot[s] = None
                            self.promotions -= 1
                            n_p = self._promote_count.get(r, 0) - 1
                            if n_p > 0:
                                self._promote_count[r] = n_p
                            else:
                                self._promote_count.pop(r, None)
                        self.promote_failures += len(idx)
                        faults.COUNTERS.increment(
                            "promote_failures", len(idx)
                        )
                        if faults.is_device_error(exc):
                            # Transient/injected (the `promote` fault
                            # site): the rows simply STAY COLD — counted,
                            # never fatal, never a lost request (cold rows
                            # keep scoring bitwise through the per-request
                            # override buffers); the worker lives on and a
                            # later touch re-queues the promotion.
                            logger.warning(
                                "promotion of %d row(s) failed (%s); rows "
                                "stay cold",
                                len(idx),
                                exc,
                            )
                            continue
                        # Non-transient (e.g. runtime tearing down): stop
                        # promoting for good.
                        self._closed = True
                        return

    def _lru_slot_locked(self) -> int:
        return int(np.argmin(self._last_used))

    def drain(self, timeout_s: float = 30.0) -> None:
        """Block until every queued promotion applied (tests/metrics)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                w = self._worker
                busy = bool(self._pending) and not self._closed
                if busy:
                    self._maybe_start_worker_locked()
                    w = self._worker
            if w is not None and w.is_alive():
                w.join(timeout=0.2)
            elif not busy:
                return

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._pending.clear()
            w = self._worker
        if w is not None and w is not threading.current_thread():
            w.join(timeout=10)

    def metrics(self) -> Dict[str, object]:
        with self._lock:
            return {
                "hot_rows": self.capacity,
                "hot_fraction": round(self.hot_fraction, 6),
                "hot_tier_hits": self.hot_hits,
                "cold_tier_hits": self.cold_hits,
                "promotions": self.promotions,
                "evictions": self.evictions,
                "promote_failures": self.promote_failures,
                "pending_promotions": len(self._pending),
            }


@dataclasses.dataclass
class ServingCoordinate:
    """One coordinate's device-resident serving state.

    Random-effect coordinates come in three storage modes:
      * single-tier (default): `params` is the full (E + 1, dim) matrix on
        one device;
      * entity-sharded: `mesh` set, `params` row-sharded over it (rows
        padded to a mesh multiple — `logical_rows` keeps the true E + 1);
      * two-tier: `store` set, `params` is the initial hot-tier matrix and
        batches score against per-batch store snapshots.
    """

    cid: str
    shard: str
    params: Array  # (dim,) fixed-effect weights or (E + 1, dim) RE matrix
    norm: Optional[object] = None
    random_effect_type: Optional[str] = None
    entity_index: Optional[Mapping[object, int]] = None
    mesh: Optional[object] = None  # jax.sharding.Mesh when row-sharded
    logical_rows: Optional[int] = None  # E + 1 when params rows are padded
    store: Optional[TwoTierEntityStore] = None
    # Per-shard loss tracking for device-resident matrices (ISSUE 10):
    # requests resolving into a LOST shard's row range degrade to the
    # pinned zero row until the shard is restaged.
    shard_health: Optional[ShardHealth] = None
    # Precision-ladder rung (ISSUE 20): "f32" (bitwise), or a quantized
    # plane — "bf16" (params are bfloat16 rows) / "int8" (params are int8
    # rows dequantized by the per-row `scales` inside the bucket
    # program). Quantized coordinates retain `host_f32`, the ORIGINAL
    # float32 rows in host RAM: the bitwise restore source, and what any
    # further ladder step quantizes from (never the lossy plane).
    tier: str = "f32"
    scales: Optional[Array] = None  # (E + 1,) f32, int8 tier only
    host_f32: Optional[np.ndarray] = None

    @property
    def is_random_effect(self) -> bool:
        return self.random_effect_type is not None

    @property
    def dim(self) -> int:
        return int(self.params.shape[-1])

    @property
    def unseen_row(self) -> int:
        """The pinned zero row unknown entities gather (cold start) — the
        LOGICAL row: mesh-padded and two-tier matrices keep extra physical
        rows past it (all zero / the hot tier), never exposed to lookups."""
        if self.logical_rows is not None:
            return int(self.logical_rows) - 1
        return int(self.params.shape[0]) - 1

    def device_nbytes(self) -> int:
        """Device-resident bytes of this coordinate's model state (the hot
        tier only for two-tier coordinates — the cold tier is host RAM;
        itemsize-aware, so a bf16 plane charges half and an int8 plane a
        quarter + its f32 scale vector; the retained `host_f32` restore
        copy is host RAM and charges nothing)."""
        if self.store is not None:
            return self.store.hot_nbytes
        nb = int(self.params.size) * self.params.dtype.itemsize
        if self.scales is not None:
            nb += int(self.scales.size) * self.scales.dtype.itemsize
        return nb

    def device_nbytes_per_shard(self) -> int:
        """Peak bytes on any ONE device: sharded matrices divide over the
        mesh; everything else is resident whole."""
        nb = self.device_nbytes()
        if self.mesh is not None:
            return nb // int(self.mesh.devices.size)
        return nb

    def lookup_rows(self, entity_ids: Sequence[object]) -> Tuple[np.ndarray, int]:
        """Resolve entity ids to coefficient rows; id None or unknown ->
        the pinned zero row. Returns (rows, cold_start_count). Same key
        coercion as the offline `entity_rows_for_dataset`: persisted
        artifacts key entities by string, in-memory models may key by int."""
        index = self.entity_index or {}
        unseen = self.unseen_row
        coerce = bool(index) and isinstance(next(iter(index)), str)
        rows = np.empty(len(entity_ids), np.int32)
        cold = 0
        for i, eid in enumerate(entity_ids):
            if eid is None:
                rows[i] = unseen
                cold += 1
                continue
            if coerce and not isinstance(eid, str):
                eid = str(eid)
            row = index.get(eid, unseen)
            rows[i] = row
            cold += row == unseen
        return rows, cold


@dataclasses.dataclass
class ServingBundle:
    """Device-pinned GAME model + the host indexes serving needs."""

    task: TaskType
    coordinates: Dict[str, ServingCoordinate]
    index_maps: Optional[Mapping[str, IndexMap]] = None
    # Load-time accounting: bytes shipped to the device and the wall it took
    # (exactly once — the engine never re-uploads model state per request).
    upload_bytes: int = 0
    upload_s: float = 0.0
    # Set by release(): the hot-swap drain freed this bundle's device state.
    released: bool = False
    # Lineage block (contracts.BUNDLE_PROVENANCE_KEYS order) — see
    # `default_provenance`.
    provenance: Dict[str, object] = dataclasses.field(
        default_factory=default_provenance
    )

    @property
    def coordinate_ids(self) -> List[str]:
        return list(self.coordinates.keys())

    def release(self, close_stores: bool = True) -> None:
        """Drop this bundle's device-resident state (hot-swap retirement).

        Drops the coordinate references rather than calling .delete() on
        the arrays: `from_model` stages without copying when the trained
        model's arrays are already device-resident f32, so a hard delete
        here could free buffers a live GameModel still reads. CPython
        refcounting frees the device memory the moment the last reference
        dies — for the production artifact path (host-built matrices owned
        solely by the bundle) that is immediately. Scoring a released
        bundle raises; release is idempotent. Two-tier stores close their
        promotion worker here so a retired bundle leaks no thread —
        `close_stores=False` skips that for a retirement whose stores were
        CARRIED OVER into a successor bundle (the host-tier demotion path:
        the successor owns them now and closes them at its own release)."""
        if close_stores:
            for c in self.coordinates.values():
                if getattr(c, "store", None) is not None:
                    c.store.close()
        self.coordinates = {}
        self.index_maps = None
        self.released = True

    def device_bytes(self) -> int:
        """Total device-resident model bytes across every coordinate (the
        cold tier of two-tier stores is host RAM and excluded)."""
        return sum(c.device_nbytes() for c in self.coordinates.values())

    def device_bytes_per_shard(self) -> int:
        """Peak model bytes on any ONE device — the number an HBM budget
        must bound: entity-sharded matrices divide over their mesh, so a
        sharded swap is charged per shard, not per total."""
        return sum(
            c.device_nbytes_per_shard() for c in self.coordinates.values()
        )

    # ------------------------------------------------- shard loss / recovery

    def mark_shard_lost(self, cid: str, shard_index: int) -> Tuple[int, int]:
        """Record one coefficient shard as LOST (a failed refresh, a dead
        device's rows). The serving engine keeps answering: requests whose
        entity row falls in the returned [lo, hi) range resolve to the
        pinned zero row — bitwise FE-only for exactly those entities —
        until `restage_shard` recovers it. Returns the lost row range."""
        c = self.coordinates[cid]
        if c.shard_health is None:
            raise ValueError(
                f"coordinate {cid!r} has no device-resident shard tracking "
                "(fixed-effect or two-tier coordinate)"
            )
        c.shard_health.mark_lost(shard_index)
        logger.warning(
            "serving shard lost: %s shard %d (rows %s) — its entities "
            "degrade to pinned-zero-row answers until restaged",
            cid,
            shard_index,
            c.shard_health.row_range(shard_index),
        )
        return c.shard_health.row_range(shard_index)

    def restage_shard(
        self, cid: str, shard_index: int, rows: Optional[np.ndarray] = None
    ) -> int:
        """Recover ONE lost shard: re-upload only its row block (never the
        whole matrix), under the `shard_upload` fault site + bounded retry.
        `rows` is the host source for the block (the model artifact / a
        replica); None re-reads the resident device block — the refresh
        case where the data is intact but was marked stale/lost. Returns
        the bytes restaged; a terminal failure leaves the shard lost (the
        engine keeps serving degraded) and re-raises.

        Memory shape: only the shard's rows cross the host->device wire,
        and the functional `.at[].set` keeps every OTHER device's chunk
        untouched — the transient device cost is ~2 chunks on the
        affected devices (old + new generation, the same double-buffer
        envelope the BundleManager swap budget already charges), never a
        replica. In-flight batches keep scoring their captured params
        snapshot, which is why the update must stay functional (a
        donating in-place write would invalidate their buffers)."""
        c = self.coordinates[cid]
        if c.shard_health is None:
            raise ValueError(
                f"coordinate {cid!r} has no device-resident shard tracking"
            )
        lo, hi = c.shard_health.row_range(shard_index)
        if rows is None:
            rows = np.asarray(c.params[lo:hi])
        rows = np.ascontiguousarray(rows, np.float32)
        if rows.shape != (hi - lo, c.dim):
            raise ValueError(
                f"restage rows shape {rows.shape} != shard shape "
                f"{(hi - lo, c.dim)}"
            )

        def upload():
            new = c.params.at[lo:hi].set(jnp.asarray(rows))
            jax.block_until_ready(new)
            return new

        c.params = _stage_shard(f"{cid} shard {shard_index} restage", upload)
        c.shard_health.mark_ok(shard_index)
        logger.info(
            "serving shard restaged: %s shard %d (%d bytes)",
            cid,
            shard_index,
            rows.nbytes,
        )
        return int(rows.nbytes)

    def shard_dims(self) -> Dict[str, int]:
        """Feature width per shard consumed by any coordinate."""
        dims: Dict[str, int] = {}
        for c in self.coordinates.values():
            dims[c.shard] = c.dim
        return dims

    def encode_request(
        self,
        features: Mapping[str, Union[ShardFeatures, Mapping[str, float]]],
        *,
        entity_ids: Optional[Mapping[str, object]] = None,
        offset: float = 0.0,
        uid: Optional[str] = None,
    ) -> ScoreRequest:
        """Build a ScoreRequest, resolving {feature_key: value} mappings
        through the bundle's index maps (unknown features are dropped, as
        the offline ingest drops features outside the training index)."""
        enc: Dict[str, ShardFeatures] = {}
        for shard, payload in features.items():
            if isinstance(payload, Mapping):
                if self.index_maps is None or shard not in self.index_maps:
                    raise ValueError(
                        f"no index map for shard {shard!r}: named-feature "
                        "requests need a bundle loaded with index maps"
                    )
                imap = self.index_maps[shard]
                idx: List[int] = []
                vals: List[float] = []
                for key, v in payload.items():
                    j = imap.get_index(key)
                    if j >= 0:
                        idx.append(j)
                        vals.append(float(v))
                enc[shard] = (
                    np.asarray(idx, np.int32),
                    np.asarray(vals, np.float32),
                )
            else:
                enc[shard] = payload
        return ScoreRequest(
            features=enc,
            entity_ids=dict(entity_ids or {}),
            offset=float(offset),
            uid=uid,
        )

    # ------------------------------------------------------------- builders

    @classmethod
    def from_model(
        cls,
        model: GameModel,
        specs: Mapping[str, CoordinateScoringSpec],
        task: TaskType,
        *,
        index_maps: Optional[Mapping[str, IndexMap]] = None,
        mesh=None,
        hot_rows: Optional[Union[int, Mapping[str, int]]] = None,
        origin: str = "full_fit",
    ) -> "ServingBundle":
        """Stage an in-memory (model, specs) pair. Projected random-effect
        coordinates are rejected — serving scores in original feature space
        (export via model_bridge.artifact_from_game_model, which
        back-projects, then `from_artifact`).

        Pod-scale staging knobs (per random-effect coordinate, mutually
        exclusive):
          * `mesh`: stage the RE coefficient matrix ROW-SHARDED over the
            mesh's entity axis (rows padded to a mesh multiple) — per-device
            model state is total/n_devices, which is what breaks the
            one-HBM ceiling. A matrix that is ALREADY row-sharded (a
            mesh-trained model) keeps its sharding without any `mesh`
            argument — training's sharding decision flows into serving.
          * `hot_rows` (int, or {cid: int}): stage a two-tier store — an
            HBM hot set of that many rows plus the full matrix in host RAM
            (`TwoTierEntityStore`), with async promotion and the pinned
            zero row as the final miss tier.
        Both knobs preserve bitwise scoring parity with the single-tier
        replicated bundle (tests/test_serving_two_tier.py)."""
        from photon_ml_tpu.ops.normalization import PerEntityNormalization
        from photon_ml_tpu.parallel.mesh import (
            leading_axis_mesh,
            matrix_row_sharding,
            pad_rows_for_mesh,
        )

        t0 = time.perf_counter()
        coords: Dict[str, ServingCoordinate] = {}
        nbytes = 0
        for cid in model.coordinate_ids:
            spec = specs[cid]
            m = model[cid]
            if isinstance(m, FixedEffectModel):
                params = _stage_shard(
                    f"{cid} (fixed-effect plane)",
                    lambda: jnp.asarray(m.coefficients.means, jnp.float32),
                )
                coords[cid] = ServingCoordinate(
                    cid, spec.shard, params, norm=spec.norm
                )
            elif isinstance(m, RandomEffectModel):
                if spec.projector is not None:
                    raise ValueError(
                        f"coordinate {cid!r} is trained in projected space; "
                        "serving bundles score in original space — export "
                        "the artifact (model_bridge.artifact_from_game_model) "
                        "and build the bundle from it"
                    )
                matrix = m.coefficients_matrix
                logical = m.num_entities + 1
                hr = (
                    hot_rows.get(cid)
                    if isinstance(hot_rows, Mapping)
                    else hot_rows
                )
                coord_mesh = mesh if mesh is not None else leading_axis_mesh(
                    matrix, require_divisible=True
                )
                if hr is not None and coord_mesh is not None:
                    # Explicit mesh OR a mesh-trained matrix whose sharding
                    # would be adopted: silently pulling a row-sharded
                    # store whole into host RAM to build a hot set would
                    # quietly break the "training's sharding flows into
                    # serving" guarantee — refuse and make the operator
                    # pick one.
                    raise ValueError(
                        f"coordinate {cid!r}: hot_rows and mesh staging are "
                        "mutually exclusive (a two-tier hot set is already "
                        "the small-memory option); the matrix is "
                        f"{'explicitly' if mesh is not None else 'already'} "
                        "mesh-sharded"
                    )
                if (hr is not None or coord_mesh is not None) and isinstance(
                    spec.norm, PerEntityNormalization
                ):
                    raise ValueError(
                        f"coordinate {cid!r}: per-entity normalization tables "
                        "are entity-sized and not sharded/tiered — stage "
                        "single-tier"
                    )
                if hr is not None:
                    # Two-tier: hot set in HBM, full matrix in host RAM.
                    if matrix.shape[0] > logical:
                        matrix = matrix[:logical]
                    store = _stage_shard(
                        f"{cid} (two-tier hot set)",
                        lambda: TwoTierEntityStore(np.asarray(matrix), hr),
                    )
                    coords[cid] = ServingCoordinate(
                        cid,
                        spec.shard,
                        store.snapshot(),
                        norm=spec.norm,
                        random_effect_type=spec.random_effect_type,
                        entity_index=dict(spec.entity_index or {}),
                        logical_rows=logical,
                        store=store,
                    )
                elif coord_mesh is not None:
                    # Entity-sharded: rows padded to the mesh multiple stay
                    # (or become) row-sharded; rows past logical are inert
                    # zeros, never exposed (unseen_row is the LOGICAL one).
                    n_rows = pad_rows_for_mesh(
                        max(int(matrix.shape[0]), logical), coord_mesh
                    )
                    if matrix.shape[0] != n_rows:
                        matrix = jnp.pad(
                            jnp.asarray(matrix, jnp.float32),
                            ((0, n_rows - matrix.shape[0]), (0, 0)),
                        )
                    params = _stage_shard(
                        f"{cid} (row-sharded matrix)",
                        lambda: jax.device_put(
                            jnp.asarray(matrix, jnp.float32),
                            matrix_row_sharding(coord_mesh),
                        ),
                    )
                    ndev_c = int(coord_mesh.devices.size)
                    coords[cid] = ServingCoordinate(
                        cid,
                        spec.shard,
                        params,
                        norm=spec.norm,
                        random_effect_type=spec.random_effect_type,
                        entity_index=dict(spec.entity_index or {}),
                        mesh=coord_mesh,
                        logical_rows=logical,
                        shard_health=ShardHealth(ndev_c, n_rows // ndev_c),
                    )
                else:
                    # Mesh-padded matrices carry inert all-zero rows past
                    # the logical E + 1; slice them off so unseen_row is
                    # the pinned zero row and the replicated gather exact.
                    if matrix.shape[0] > logical:
                        matrix = matrix[:logical]
                    params = _stage_shard(
                        f"{cid} (replicated matrix)",
                        lambda: jnp.asarray(matrix, jnp.float32),
                    )
                    coords[cid] = ServingCoordinate(
                        cid,
                        spec.shard,
                        params,
                        norm=spec.norm,
                        random_effect_type=spec.random_effect_type,
                        entity_index=dict(spec.entity_index or {}),
                        shard_health=ShardHealth(1, int(params.shape[0])),
                    )
            else:
                raise TypeError(f"unknown model type {type(m)} for {cid!r}")
            nbytes += coords[cid].device_nbytes()
        # One blocking upload at load: everything after this is pinned.
        jax.block_until_ready([c.params for c in coords.values()])
        return cls(
            task=task,
            coordinates=coords,
            index_maps=index_maps,
            upload_bytes=int(nbytes),
            upload_s=time.perf_counter() - t0,
            provenance=default_provenance(origin),
        )

    @classmethod
    def from_artifact(
        cls,
        artifact: GameModelArtifact,
        *,
        index_maps: Optional[Mapping[str, IndexMap]] = None,
        mesh=None,
        hot_rows: Optional[Union[int, Mapping[str, int]]] = None,
    ) -> "ServingBundle":
        """The production path: persisted artifact (original feature space,
        string entity ids) -> pinned bundle. `mesh`/`hot_rows` as in
        `from_model`."""
        from photon_ml_tpu.io.model_bridge import game_model_from_artifact

        model, specs = game_model_from_artifact(artifact)
        return cls.from_model(
            model,
            specs,
            artifact.task,
            index_maps=index_maps,
            mesh=mesh,
            hot_rows=hot_rows,
            origin="artifact",
        )


def demote_bundle_to_host_tier(
    bundle: ServingBundle, hot_rows: int = 0
) -> ServingBundle:
    """Rebuild `bundle` with every single-tier random-effect matrix demoted
    to a TwoTierEntityStore: `hot_rows` rows stay pinned in HBM (0 = none —
    every lookup rides the per-request override buffers) and the full
    matrix moves to host RAM. The multi-tenant registry's HBM-pressure
    eviction engine (ISSUE 15): a cold tenant demoted this way keeps
    answering BITWISE — the override row IS the matrix row (see
    TwoTierEntityStore) — it just pays a host copy per request instead of
    pinning (E + 1) * dim floats of HBM.

    Fixed-effect coordinates are carried over by reference (their planes
    are tiny and shared — releasing the OLD bundle only drops its dict,
    never the arrays the new bundle still holds). Entity-sharded
    coordinates refuse: their rows already divide over the mesh, and
    pulling a sharded store whole into host RAM would silently change the
    placement story (reshard first, then demote).
    """
    coords: Dict[str, ServingCoordinate] = {}
    for cid, c in bundle.coordinates.items():
        if not c.is_random_effect or c.store is not None:
            # FE planes and already-demoted stores carry over unchanged.
            coords[cid] = c
            continue
        if c.mesh is not None:
            raise ValueError(
                f"coordinate {cid!r} is entity-sharded over a mesh; "
                "demotion to the host tier only applies to replicated "
                "single-tier matrices"
            )
        logical = c.unseen_row + 1
        if c.host_f32 is not None:
            # Quantized coordinate (ISSUE 20): the host tier is built from
            # the retained ORIGINAL f32 rows, never the lossy plane — a
            # tenant demoted off the ladder's last quantized rung answers
            # bitwise vs. its pre-quantization self again.
            host = np.asarray(c.host_f32[:logical], np.float32)
        else:
            host = np.asarray(c.params[:logical], np.float32)
        store = TwoTierEntityStore(host, int(hot_rows))
        coords[cid] = ServingCoordinate(
            cid,
            c.shard,
            store.snapshot(),
            norm=c.norm,
            random_effect_type=c.random_effect_type,
            entity_index=c.entity_index,
            logical_rows=logical,
            store=store,
        )
    out = ServingBundle(
        task=bundle.task,
        coordinates=coords,
        index_maps=bundle.index_maps,
        upload_bytes=sum(c.device_nbytes() for c in coords.values()),
        upload_s=0.0,
    )
    return out


def promote_bundle_from_host_tier(bundle: ServingBundle) -> ServingBundle:
    """The exact inverse of `demote_bundle_to_host_tier`: rebuild every
    two-tier coordinate as a single-tier device-resident matrix from the
    store's host-RAM cold tier. BITWISE — the cold matrix IS the
    original float32 rows (the two-tier store scores overrides straight
    out of it), so a demote/restore round trip answers identically at
    every step. The autopilot's HBM restore ladder (ISSUE 19): a cold
    tenant demoted under pressure moves back up when headroom returns.
    Single-tier and fixed-effect coordinates carry over by reference;
    the OLD bundle still owns its stores (release them with the bundle,
    close_stores=True, once the new generation serves)."""
    coords: Dict[str, ServingCoordinate] = {}
    for cid, c in bundle.coordinates.items():
        if c.store is None:
            coords[cid] = c
            continue
        full = jnp.asarray(c.store.cold_matrix)
        coords[cid] = ServingCoordinate(
            cid,
            c.shard,
            full,
            norm=c.norm,
            random_effect_type=c.random_effect_type,
            entity_index=c.entity_index,
        )
    return ServingBundle(
        task=bundle.task,
        coordinates=coords,
        index_maps=bundle.index_maps,
        upload_bytes=sum(c.device_nbytes() for c in coords.values()),
        upload_s=0.0,
    )


# The precision ladder's rung order (ISSUE 20), best fidelity first. The
# host tier is deliberately NOT a rung here: it is the PR 15 whole-bundle
# demotion (bitwise, host-RAM latency) that the ladder falls through to
# once int8 cannot relieve pressure.
PRECISION_LADDER = ("f32", "bf16", "int8")


def _quantize_rows(host: np.ndarray, tier: str):
    """Quantize one coordinate's (E + 1, dim) f32 rows to `tier`.

    Returns (plane, scales, max_rel_err): the device plane, the per-row
    f32 dequant scales (None for bf16 — its dequant is a pure dtype
    widen), and the worst relative round-trip error against the f32 rows
    (max |dequant - host| / max |host|, the number the per-tenant
    `tier_quant_error` histogram records and the int8 error ceiling
    judges). int8 is per-row symmetric: scale = max|row| / 127, zero rows
    pinned to scale 1.0 so the zero cold-start row stays exactly zero.
    """
    denom = float(np.max(np.abs(host))) or 1.0
    if tier == "bf16":
        plane = jnp.asarray(host, jnp.bfloat16)
        deq = np.asarray(plane.astype(jnp.float32))
        return plane, None, float(np.max(np.abs(deq - host))) / denom
    if tier != "int8":
        raise ValueError(f"unknown quantized tier {tier!r}")
    row_max = np.max(np.abs(host), axis=1)
    scales = np.where(row_max > 0.0, row_max / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(host / scales[:, None]), -127, 127).astype(np.int8)
    deq = q.astype(np.float32) * scales[:, None]
    return (
        jnp.asarray(q),
        jnp.asarray(scales),
        float(np.max(np.abs(deq - host))) / denom,
    )


def quantize_bundle_rows(
    bundle: ServingBundle, tier: str
) -> Tuple[ServingBundle, Dict[str, float]]:
    """Rebuild `bundle` with every replicated random-effect matrix on the
    `tier` rung ("bf16" or "int8") — the precision ladder's demotion
    build (ISSUE 20), run inside the `quantize_stage` fault site by
    `TenantRegistry.demote_tier`. Always quantizes from the ORIGINAL f32
    rows (the retained `host_f32` for an already-quantized coordinate),
    never re-quantizes a lossy plane, so walking bf16 -> int8 costs one
    rounding, not two. Returns (new bundle, {cid: max relative round-trip
    error}) — the evidence the transition journals and the int8 ceiling
    gate judges BEFORE anything commits.

    Fixed-effect planes carry over by reference (quantizing them would
    change every answer for ~nothing: they are (dim,) vectors, not
    (E + 1, dim) matrices). Two-tier coordinates carry over too — they
    already stopped pinning their matrix, the ladder's rung BELOW int8.
    Entity-sharded coordinates refuse loudly, like the host-tier builder:
    reshard to a replicated layout first."""
    if tier not in PRECISION_LADDER or tier == "f32":
        raise ValueError(
            f"quantized tier must be one of {PRECISION_LADDER[1:]}, "
            f"got {tier!r}"
        )
    coords: Dict[str, ServingCoordinate] = {}
    errors: Dict[str, float] = {}
    for cid, c in bundle.coordinates.items():
        if not c.is_random_effect or c.store is not None:
            coords[cid] = c
            continue
        if c.mesh is not None:
            raise ValueError(
                f"coordinate {cid!r} is entity-sharded over a mesh; "
                "precision-tier quantization only applies to replicated "
                "single-tier matrices (reshard first)"
            )
        if c.tier == tier:
            coords[cid] = c
            continue
        logical = c.unseen_row + 1
        host = (
            np.asarray(c.host_f32[:logical], np.float32)
            if c.host_f32 is not None
            else np.asarray(c.params[:logical], np.float32)
        )
        plane, scales, err = _quantize_rows(host, tier)
        errors[cid] = err
        coords[cid] = ServingCoordinate(
            cid,
            c.shard,
            plane,
            norm=c.norm,
            random_effect_type=c.random_effect_type,
            entity_index=c.entity_index,
            shard_health=c.shard_health,
            tier=tier,
            scales=scales,
            host_f32=host,
        )
    out = ServingBundle(
        task=bundle.task,
        coordinates=coords,
        index_maps=bundle.index_maps,
        upload_bytes=sum(c.device_nbytes() for c in coords.values()),
        upload_s=0.0,
    )
    return out, errors


def restore_bundle_precision(bundle: ServingBundle) -> ServingBundle:
    """The exact inverse of `quantize_bundle_rows`: rebuild every
    quantized coordinate as a full-precision f32 matrix from its retained
    `host_f32` rows — BITWISE vs. the pre-quantization generation (the
    retained copy IS the original rows; quantization never touched it).
    The `tier_restore` fault-site build run by
    `TenantRegistry.restore_tier`. Un-quantized coordinates carry over by
    reference."""
    coords: Dict[str, ServingCoordinate] = {}
    for cid, c in bundle.coordinates.items():
        if c.tier == "f32" or c.host_f32 is None:
            coords[cid] = c
            continue
        coords[cid] = ServingCoordinate(
            cid,
            c.shard,
            jnp.asarray(c.host_f32),
            norm=c.norm,
            random_effect_type=c.random_effect_type,
            entity_index=c.entity_index,
            shard_health=c.shard_health,
        )
    return ServingBundle(
        task=bundle.task,
        coordinates=coords,
        index_maps=bundle.index_maps,
        upload_bytes=sum(c.device_nbytes() for c in coords.values()),
        upload_s=0.0,
    )


def serving_entity_mesh():
    """Env-gated serving mesh: PHOTON_SERVING_ENTITY_SHARD=1 stages RE
    matrices row-sharded over all local devices (no-op on one device)."""
    if not get_knob("PHOTON_SERVING_ENTITY_SHARD"):
        return None
    if len(jax.devices()) < 2:
        logger.warning(
            "PHOTON_SERVING_ENTITY_SHARD set with a single device; staging "
            "replicated"
        )
        return None
    from photon_ml_tpu.parallel.mesh import make_mesh

    return make_mesh()


def serving_hot_rows() -> Optional[int]:
    """Env-gated two-tier hot-set size (PHOTON_SERVING_HOT_ROWS)."""
    rows = int(get_knob("PHOTON_SERVING_HOT_ROWS"))
    return rows if rows > 0 else None


def load_bundle(
    model_dir: str,
    *,
    index_maps: Optional[Mapping[str, IndexMap]] = None,
    mesh=None,
    hot_rows: Optional[Union[int, Mapping[str, int]]] = None,
) -> ServingBundle:
    """Load a model directory (the training driver's layout) into a serving
    bundle. Index maps default to the JSON maps saved beside the model
    (`<model_dir>/feature-indexes/<shard>.json`), mirroring cli/score.py.
    `mesh`/`hot_rows` default to the env knobs (PHOTON_SERVING_ENTITY_SHARD,
    PHOTON_SERVING_HOT_ROWS) so `cli.serve` picks the pod-scale staging up
    without new flags."""
    from photon_ml_tpu.io import model_store

    if mesh is None:
        mesh = serving_entity_mesh()
    if hot_rows is None:
        hot_rows = serving_hot_rows()
    if index_maps is None:
        index_dir = os.path.join(model_dir, "feature-indexes")
        index_maps = {
            os.path.splitext(os.path.basename(p))[0]: IndexMap.load(p)
            for p in sorted(glob.glob(os.path.join(index_dir, "*.json")))
        }
        if not index_maps:
            raise FileNotFoundError(
                f"no feature index maps under {index_dir}; pass index_maps "
                "explicitly (e.g. resolved from an off-heap store)"
            )
    artifact = model_store.load_game_model(model_dir, index_maps)
    return ServingBundle.from_artifact(
        artifact, index_maps=index_maps, mesh=mesh, hot_rows=hot_rows
    )


def request_from_record(
    bundle: ServingBundle,
    record: Mapping[str, object],
    shard_configs: Mapping[str, object],
    *,
    uid_field: str = "uid",
    offset_field: str = "offset",
) -> ScoreRequest:
    """Reference-shaped Avro record (name/term/value feature bags + id
    fields) -> ScoreRequest. `shard_configs` maps each shard to its
    FeatureShardConfig (bag list + intercept), as parsed from the
    feature-shard DSL — the same config offline ingest applies, so a
    replayed record builds the same feature row."""
    features: Dict[str, Dict[str, float]] = {}
    for shard, cfg in shard_configs.items():
        fmap: Dict[str, float] = {}
        for bag in cfg.feature_bags:
            for ntv in record.get(bag) or ():
                key = feature_key(ntv.get("name", ""), ntv.get("term", "") or "")
                # Duplicate (name, term) entries accumulate, as ingest does.
                fmap[key] = fmap.get(key, 0.0) + float(ntv["value"])
        if getattr(cfg, "has_intercept", False):
            from photon_ml_tpu.data.index_map import INTERCEPT_KEY

            fmap[INTERCEPT_KEY] = fmap.get(INTERCEPT_KEY, 0.0) + 1.0
        features[shard] = fmap
    # Id-tag resolution mirrors offline ingest EXACTLY (io/avro_data.py:
    # direct record field, "map.key" dotted path, metadataMap fallback,
    # and a missing id resolving to the string "" — which ingest treats as
    # a trainable entity key, NOT a cold start). A replayed record must
    # gather the same coefficient row the dataset reader would have.
    def _tag(tag: str) -> str:
        v = record.get(tag)
        field, _, map_key = tag.partition(".")
        if v is None and map_key:
            inner = record.get(field)
            if isinstance(inner, Mapping):
                v = inner.get(map_key)
        if v is None:
            meta = record.get("metadataMap")
            v = meta.get(tag, "") if isinstance(meta, Mapping) else ""
        return str(v)

    entity_ids = {
        c.random_effect_type: _tag(c.random_effect_type)
        for c in bundle.coordinates.values()
        if c.is_random_effect
    }
    uid = record.get(uid_field)
    return bundle.encode_request(
        features,
        entity_ids=entity_ids,
        offset=float(record.get(offset_field) or 0.0),
        uid=None if uid is None else str(uid),
    )

"""Serving bundles: a GAME model staged into device memory exactly once.

The offline scoring path (cli/score.py) re-stages the model per job: load
the Avro artifact, build host matrices, upload shards, score, exit. An
online engine cannot pay that per request — Snap ML's serving result
(PAPERS.md) is precisely that keeping model state pinned in accelerator
memory across requests is where the latency win lives. A `ServingBundle`
is that pinned state:

  * per fixed-effect coordinate: the effective weight vector, one device
    array, uploaded at load;
  * per random-effect coordinate: the dense `(n_entities + 1, dim)`
    coefficient matrix (row `n_entities` is the pinned zero row — GLMix
    cold-start semantics: an unknown entity scores with the fixed effects
    only) plus a host-side entity-id -> row hash index;
  * optionally the feature index maps, so requests can arrive as
    (name, term) -> value dicts and be resolved to column indices host-side.

Bundles are built from a persisted model artifact (`from_artifact` /
`load_bundle` — the production path, original feature space, no
projector/normalization needed) or directly from an in-memory trained
model (`from_model` — tests and co-located train+serve; normalization
passes through to the same margin algebra the transformer uses, but
projected random-effect coordinates are rejected: serving scores in
original space, so export through `model_bridge.artifact_from_game_model`
first, which back-projects).
"""

from __future__ import annotations

import dataclasses
import glob
import os
import time
from typing import Dict, List, Mapping, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.index_map import IndexMap, feature_key
from photon_ml_tpu.game.model import FixedEffectModel, GameModel, RandomEffectModel
from photon_ml_tpu.io.model_store import GameModelArtifact
from photon_ml_tpu.transformers.game_transformer import CoordinateScoringSpec
from photon_ml_tpu.types import TaskType

Array = jax.Array

# Request feature payload for one shard: a dense (dim,) row, or a sparse
# (indices, values) pair, or a {feature_key: value} mapping resolved through
# the bundle's index maps at encode time.
ShardFeatures = Union[np.ndarray, Tuple[np.ndarray, np.ndarray]]


@dataclasses.dataclass
class ScoreRequest:
    """One scoring request: per-shard features + per-RE-type entity ids.

    `features[shard]` is a dense (dim,) float row or an (indices, values)
    sparse pair (duplicate indices accumulate, matching `pack_csr_to_ell`).
    A shard absent from the mapping scores as an all-zero row. Entity ids
    missing for a random-effect type are cold starts by definition.

    `deadline_ms` is the request's latency budget, counted from submission
    to the micro-batcher: a request still queued past its budget is failed
    with `DeadlineExceeded` before wasting a device slot, and batch
    assembly never co-batches an expired request. None defers to the
    batcher's `default_deadline_ms` (which may also be None: no deadline).
    """

    features: Dict[str, ShardFeatures] = dataclasses.field(default_factory=dict)
    entity_ids: Dict[str, object] = dataclasses.field(default_factory=dict)
    offset: float = 0.0
    uid: Optional[str] = None
    deadline_ms: Optional[float] = None


@dataclasses.dataclass
class ServingCoordinate:
    """One coordinate's device-resident serving state."""

    cid: str
    shard: str
    params: Array  # (dim,) fixed-effect weights or (E + 1, dim) RE matrix
    norm: Optional[object] = None
    random_effect_type: Optional[str] = None
    entity_index: Optional[Mapping[object, int]] = None

    @property
    def is_random_effect(self) -> bool:
        return self.random_effect_type is not None

    @property
    def dim(self) -> int:
        return int(self.params.shape[-1])

    @property
    def unseen_row(self) -> int:
        """The pinned zero row unknown entities gather (cold start)."""
        return int(self.params.shape[0]) - 1

    def lookup_rows(self, entity_ids: Sequence[object]) -> Tuple[np.ndarray, int]:
        """Resolve entity ids to coefficient rows; id None or unknown ->
        the pinned zero row. Returns (rows, cold_start_count). Same key
        coercion as the offline `entity_rows_for_dataset`: persisted
        artifacts key entities by string, in-memory models may key by int."""
        index = self.entity_index or {}
        unseen = self.unseen_row
        coerce = bool(index) and isinstance(next(iter(index)), str)
        rows = np.empty(len(entity_ids), np.int32)
        cold = 0
        for i, eid in enumerate(entity_ids):
            if eid is None:
                rows[i] = unseen
                cold += 1
                continue
            if coerce and not isinstance(eid, str):
                eid = str(eid)
            row = index.get(eid, unseen)
            rows[i] = row
            cold += row == unseen
        return rows, cold


@dataclasses.dataclass
class ServingBundle:
    """Device-pinned GAME model + the host indexes serving needs."""

    task: TaskType
    coordinates: Dict[str, ServingCoordinate]
    index_maps: Optional[Mapping[str, IndexMap]] = None
    # Load-time accounting: bytes shipped to the device and the wall it took
    # (exactly once — the engine never re-uploads model state per request).
    upload_bytes: int = 0
    upload_s: float = 0.0
    # Set by release(): the hot-swap drain freed this bundle's device state.
    released: bool = False

    @property
    def coordinate_ids(self) -> List[str]:
        return list(self.coordinates.keys())

    def release(self) -> None:
        """Drop this bundle's device-resident state (hot-swap retirement).

        Drops the coordinate references rather than calling .delete() on
        the arrays: `from_model` stages without copying when the trained
        model's arrays are already device-resident f32, so a hard delete
        here could free buffers a live GameModel still reads. CPython
        refcounting frees the device memory the moment the last reference
        dies — for the production artifact path (host-built matrices owned
        solely by the bundle) that is immediately. Scoring a released
        bundle raises; release is idempotent."""
        self.coordinates = {}
        self.index_maps = None
        self.released = True

    def shard_dims(self) -> Dict[str, int]:
        """Feature width per shard consumed by any coordinate."""
        dims: Dict[str, int] = {}
        for c in self.coordinates.values():
            dims[c.shard] = c.dim
        return dims

    def encode_request(
        self,
        features: Mapping[str, Union[ShardFeatures, Mapping[str, float]]],
        *,
        entity_ids: Optional[Mapping[str, object]] = None,
        offset: float = 0.0,
        uid: Optional[str] = None,
    ) -> ScoreRequest:
        """Build a ScoreRequest, resolving {feature_key: value} mappings
        through the bundle's index maps (unknown features are dropped, as
        the offline ingest drops features outside the training index)."""
        enc: Dict[str, ShardFeatures] = {}
        for shard, payload in features.items():
            if isinstance(payload, Mapping):
                if self.index_maps is None or shard not in self.index_maps:
                    raise ValueError(
                        f"no index map for shard {shard!r}: named-feature "
                        "requests need a bundle loaded with index maps"
                    )
                imap = self.index_maps[shard]
                idx: List[int] = []
                vals: List[float] = []
                for key, v in payload.items():
                    j = imap.get_index(key)
                    if j >= 0:
                        idx.append(j)
                        vals.append(float(v))
                enc[shard] = (
                    np.asarray(idx, np.int32),
                    np.asarray(vals, np.float32),
                )
            else:
                enc[shard] = payload
        return ScoreRequest(
            features=enc,
            entity_ids=dict(entity_ids or {}),
            offset=float(offset),
            uid=uid,
        )

    # ------------------------------------------------------------- builders

    @classmethod
    def from_model(
        cls,
        model: GameModel,
        specs: Mapping[str, CoordinateScoringSpec],
        task: TaskType,
        *,
        index_maps: Optional[Mapping[str, IndexMap]] = None,
    ) -> "ServingBundle":
        """Stage an in-memory (model, specs) pair. Projected random-effect
        coordinates are rejected — serving scores in original feature space
        (export via model_bridge.artifact_from_game_model, which
        back-projects, then `from_artifact`)."""
        t0 = time.perf_counter()
        coords: Dict[str, ServingCoordinate] = {}
        nbytes = 0
        for cid in model.coordinate_ids:
            spec = specs[cid]
            m = model[cid]
            if isinstance(m, FixedEffectModel):
                params = jnp.asarray(m.coefficients.means, jnp.float32)
                coords[cid] = ServingCoordinate(
                    cid, spec.shard, params, norm=spec.norm
                )
            elif isinstance(m, RandomEffectModel):
                if spec.projector is not None:
                    raise ValueError(
                        f"coordinate {cid!r} is trained in projected space; "
                        "serving bundles score in original space — export "
                        "the artifact (model_bridge.artifact_from_game_model) "
                        "and build the bundle from it"
                    )
                matrix = m.coefficients_matrix
                # Mesh-padded matrices carry inert all-zero rows past the
                # logical E + 1; slice them off so unseen_row is the pinned
                # zero row and the replicated gather is exact.
                logical = m.num_entities + 1
                if matrix.shape[0] > logical:
                    matrix = matrix[:logical]
                params = jnp.asarray(matrix, jnp.float32)
                coords[cid] = ServingCoordinate(
                    cid,
                    spec.shard,
                    params,
                    norm=spec.norm,
                    random_effect_type=spec.random_effect_type,
                    entity_index=dict(spec.entity_index or {}),
                )
            else:
                raise TypeError(f"unknown model type {type(m)} for {cid!r}")
            nbytes += coords[cid].params.size * coords[cid].params.dtype.itemsize
        # One blocking upload at load: everything after this is pinned.
        jax.block_until_ready([c.params for c in coords.values()])
        return cls(
            task=task,
            coordinates=coords,
            index_maps=index_maps,
            upload_bytes=int(nbytes),
            upload_s=time.perf_counter() - t0,
        )

    @classmethod
    def from_artifact(
        cls,
        artifact: GameModelArtifact,
        *,
        index_maps: Optional[Mapping[str, IndexMap]] = None,
    ) -> "ServingBundle":
        """The production path: persisted artifact (original feature space,
        string entity ids) -> pinned bundle."""
        from photon_ml_tpu.io.model_bridge import game_model_from_artifact

        model, specs = game_model_from_artifact(artifact)
        return cls.from_model(model, specs, artifact.task, index_maps=index_maps)


def load_bundle(
    model_dir: str,
    *,
    index_maps: Optional[Mapping[str, IndexMap]] = None,
) -> ServingBundle:
    """Load a model directory (the training driver's layout) into a serving
    bundle. Index maps default to the JSON maps saved beside the model
    (`<model_dir>/feature-indexes/<shard>.json`), mirroring cli/score.py."""
    from photon_ml_tpu.io import model_store

    if index_maps is None:
        index_dir = os.path.join(model_dir, "feature-indexes")
        index_maps = {
            os.path.splitext(os.path.basename(p))[0]: IndexMap.load(p)
            for p in sorted(glob.glob(os.path.join(index_dir, "*.json")))
        }
        if not index_maps:
            raise FileNotFoundError(
                f"no feature index maps under {index_dir}; pass index_maps "
                "explicitly (e.g. resolved from an off-heap store)"
            )
    artifact = model_store.load_game_model(model_dir, index_maps)
    return ServingBundle.from_artifact(artifact, index_maps=index_maps)


def request_from_record(
    bundle: ServingBundle,
    record: Mapping[str, object],
    shard_configs: Mapping[str, object],
    *,
    uid_field: str = "uid",
    offset_field: str = "offset",
) -> ScoreRequest:
    """Reference-shaped Avro record (name/term/value feature bags + id
    fields) -> ScoreRequest. `shard_configs` maps each shard to its
    FeatureShardConfig (bag list + intercept), as parsed from the
    feature-shard DSL — the same config offline ingest applies, so a
    replayed record builds the same feature row."""
    features: Dict[str, Dict[str, float]] = {}
    for shard, cfg in shard_configs.items():
        fmap: Dict[str, float] = {}
        for bag in cfg.feature_bags:
            for ntv in record.get(bag) or ():
                key = feature_key(ntv.get("name", ""), ntv.get("term", "") or "")
                # Duplicate (name, term) entries accumulate, as ingest does.
                fmap[key] = fmap.get(key, 0.0) + float(ntv["value"])
        if getattr(cfg, "has_intercept", False):
            from photon_ml_tpu.data.index_map import INTERCEPT_KEY

            fmap[INTERCEPT_KEY] = fmap.get(INTERCEPT_KEY, 0.0) + 1.0
        features[shard] = fmap
    # Id-tag resolution mirrors offline ingest EXACTLY (io/avro_data.py:
    # direct record field, "map.key" dotted path, metadataMap fallback,
    # and a missing id resolving to the string "" — which ingest treats as
    # a trainable entity key, NOT a cold start). A replayed record must
    # gather the same coefficient row the dataset reader would have.
    def _tag(tag: str) -> str:
        v = record.get(tag)
        field, _, map_key = tag.partition(".")
        if v is None and map_key:
            inner = record.get(field)
            if isinstance(inner, Mapping):
                v = inner.get(map_key)
        if v is None:
            meta = record.get("metadataMap")
            v = meta.get(tag, "") if isinstance(meta, Mapping) else ""
        return str(v)

    entity_ids = {
        c.random_effect_type: _tag(c.random_effect_type)
        for c in bundle.coordinates.values()
        if c.is_random_effect
    }
    uid = record.get(uid_field)
    return bundle.encode_request(
        features,
        entity_ids=entity_ids,
        offset=float(record.get(offset_field) or 0.0),
        uid=None if uid is None else str(uid),
    )

"""Online scoring engine: jitted padded-bucket programs over a pinned bundle.

Design constraints (the DrJAX lesson from PAPERS.md — fixed, jit-stable
program shapes — applied to a serving hot path):

  * The compile set is BOUNDED and declared up front: one XLA program per
    power-of-two bucket size up to `max_batch`. A batch of n requests pads
    to the smallest bucket >= n; after `warmup()` has compiled every
    bucket, a request stream of arbitrary batch sizes triggers ZERO new
    compiles (`recompiles_after_warmup` in metrics, asserted in tests).
  * One device round trip per batch: pack host-side, upload the request
    buffers, dispatch one fused program (all coordinates + link function),
    fetch (scores, means) together.
  * Bitwise offline parity: the fused program reuses the transformer's own
    margin kernels (`dense_margins`, `random_effect_margins`) and sums
    coordinates in the same order, and those kernels are batch-size
    invariant (see dense_margins' docstring) — so a request scores
    bitwise-identically to `GameTransformer.transform` on the same row,
    whatever bucket it pads into. That also makes scores independent of
    micro-batch composition, which is what lets the batcher degrade to
    per-request dispatch under faults without changing any answer.
  * Cold start: entities absent from the bundle's hash index gather the
    pinned zero row, i.e. score with the fixed effects (+ offset) only —
    GLMix's prior-model semantics for unseen entities. Counted per lookup
    and surfaced per request.
  * Request buffers are donated to the program on accelerator backends
    (they are per-batch scratch; donation lets XLA reuse the HBM). Model
    planes are never donated — they are the bundle's pinned state.

Fault sites: `lookup` (entity-row resolution) and `score` (device
dispatch), via utils/faults.py. The engine itself raises; degradation
policy (retry, per-request fallback) lives in the batcher so direct
callers keep raw failure semantics.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.model import random_effect_margins
from photon_ml_tpu.ops.losses import mean_for_task
from photon_ml_tpu.serving.bundle import ScoreRequest, ServingBundle
from photon_ml_tpu.transformers.game_transformer import dense_margins
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.utils import faults
from photon_ml_tpu.utils.observability import TimingRegistry, stage_scope, stage_timer

Array = jax.Array


@dataclasses.dataclass
class ScoreResult:
    """One answered request: raw summed margin + link-function mean
    (ScoredGameDatum fields), plus cold-start accounting."""

    score: float
    mean: float
    uid: Optional[str] = None
    cold_start: bool = False  # any random-effect lookup fell back
    n_cold: int = 0  # how many of the request's RE lookups fell back


def _score_program(offsets, shard_feats, rows, params, norms, *, kinds, shards, task):
    """The fused per-bucket program: offsets + per-coordinate margins (same
    kernels and summation order as GameTransformer.transform) + link mean.

    Request features arrive as ONE buffer per shard (`shard_feats`), with
    coordinates resolving their shard by the static `shards` tuple — never
    as a per-coordinate tuple, which would pass the same device array
    twice when two coordinates share a shard and make buffer donation
    alias one buffer to two parameters (undefined on accelerators)."""
    total = offsets
    for k, kind in enumerate(kinds):
        feats = shard_feats[shards[k]]
        if kind == "fe":
            total = total + dense_margins(feats, params[k], norms[k])
        else:
            total = total + random_effect_margins(
                feats, rows[k], params[k], norms[k]
            )
    return total, mean_for_task(task, total)


def _bucket_sizes(max_batch: int) -> Tuple[int, ...]:
    sizes = []
    b = 1
    while b < max_batch:
        sizes.append(b)
        b <<= 1
    sizes.append(max_batch)
    return tuple(sizes)


class ServingEngine:
    """Scores request batches against a pinned `ServingBundle`.

    Thread-safety: `score_batch` may be called from any thread (the
    batcher's flush thread, a caller's worker pool); metrics updates are
    lock-protected. One engine owns one private jit cache, so `compiles`
    counts exactly this engine's XLA programs.
    """

    def __init__(
        self,
        bundle: ServingBundle,
        *,
        max_batch: int = 256,
        task: Optional[TaskType] = None,
    ):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.bundle = bundle
        self.task = task or bundle.task
        self.max_batch = int(max_batch)
        self.buckets = _bucket_sizes(self.max_batch)
        self._kinds = tuple(
            "re" if bundle.coordinates[cid].is_random_effect else "fe"
            for cid in bundle.coordinate_ids
        )
        self._coords = [bundle.coordinates[cid] for cid in bundle.coordinate_ids]
        self._coord_shards = tuple(c.shard for c in self._coords)
        self._shard_dims = bundle.shard_dims()
        # Per-engine jit instance = private compile cache, so _cache_size()
        # is an honest XLA-compile counter for THIS engine. jit caches key
        # on the underlying callable, and wrappers over the same module
        # function SHARE entries — a fresh per-engine trampoline keeps this
        # engine's count isolated from every other engine in the process.
        def _engine_score_program(*args, **kwargs):
            return _score_program(*args, **kwargs)

        donate = () if jax.default_backend() == "cpu" else (0, 1, 2)
        self._jit = jax.jit(
            _engine_score_program,
            static_argnames=("kinds", "shards", "task"),
            donate_argnums=donate,
        )
        self.stages = TimingRegistry()
        self._lock = threading.Lock()
        self._requests = 0
        self._batches = 0
        self._lookups = 0
        self._cold_lookups = 0
        self._slots_total = 0
        self._slots_padded = 0
        self._warmup_compiles: Optional[int] = None
        self._dispatched_buckets: set = set()
        self._t_first: Optional[float] = None
        self._t_last: Optional[float] = None
        self._batchers: List[object] = []
        self._closed = False

    # ----------------------------------------------------------- lifecycle

    def batcher(self, **kwargs) -> "MicroBatcher":  # noqa: F821
        """Create a MicroBatcher bound to this engine; `close()` joins it."""
        if self._closed:
            # close() already ran and will never revisit _batchers — a
            # batcher created now would leak its flush thread.
            raise RuntimeError("ServingEngine is closed")
        from photon_ml_tpu.serving.batcher import MicroBatcher

        b = MicroBatcher(self, **kwargs)
        self._batchers.append(b)
        return b

    def close(self) -> None:
        """Shut down every batcher created via `batcher()` (joining their
        flush threads). Idempotent. The bundle stays usable — model planes
        are plain device arrays owned by the bundle, not the engine."""
        if self._closed:
            return
        self._closed = True
        for b in self._batchers:
            b.close()

    def __enter__(self) -> "ServingEngine":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    # ------------------------------------------------------------- scoring

    def bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        return self.max_batch

    def warmup(self) -> int:
        """Compile every declared bucket (inert all-cold zero batches that
        do not count toward request metrics). Returns the compile count;
        afterwards `recompiles_after_warmup` tracks cache misses — zero for
        any request stream whose batches fit max_batch."""
        t0 = time.perf_counter()
        for b in self.buckets:
            # inject=False: warmup is not the request path — an armed
            # lookup/score fault must fire on (and be counted against)
            # real traffic, not kill engine bring-up.
            self._dispatch(self._pack([], b, inject=False), inject=False)
        # Warmup wall (mostly XLA compiles) is recorded under its own stage
        # key; no ambient scope is open here, so the inner serve_pack/
        # serve_score timers stay warmup-free.
        self.stages.record("serve_warmup", time.perf_counter() - t0)
        compiles = self.compiles
        with self._lock:
            self._warmup_compiles = compiles
        return compiles

    def score_batch(self, requests: Sequence[ScoreRequest]) -> List[ScoreResult]:
        """Score one micro-batch: pad to the bucket, one device round trip.
        Batches larger than max_batch split internally."""
        if not requests:
            return []
        if len(requests) > self.max_batch:
            out: List[ScoreResult] = []
            for lo in range(0, len(requests), self.max_batch):
                out.extend(self.score_batch(requests[lo : lo + self.max_batch]))
            return out
        n = len(requests)
        bucket = self.bucket_for(n)
        with stage_scope(self.stages):
            packed = self._pack(requests, bucket)
            scores, means = self._dispatch(packed)
        flags = packed["cold_flags"]
        results = [
            ScoreResult(
                score=float(scores[i]),
                mean=float(means[i]),
                uid=requests[i].uid,
                cold_start=bool(flags[i].any()),
                n_cold=int(flags[i].sum()),
            )
            for i in range(n)
        ]
        now = time.monotonic()
        with self._lock:
            self._requests += n
            self._batches += 1
            self._lookups += int(flags.size)
            self._cold_lookups += int(flags.sum())
            self._slots_total += bucket
            self._slots_padded += bucket - n
            if self._t_first is None:
                self._t_first = now
            self._t_last = now
        return results

    # ------------------------------------------------------------ internals

    def _pack(
        self, requests: Sequence[ScoreRequest], bucket: int, *, inject: bool = True
    ) -> dict:
        """Host-side batch assembly: per-shard dense buffers, per-RE-coordinate
        entity rows (padding slots gather the pinned zero row), offsets."""
        n = len(requests)
        with stage_timer("serve_pack"):
            buffers = {
                s: np.zeros((bucket, d), np.float32)
                for s, d in self._shard_dims.items()
            }
            offsets = np.zeros(bucket, np.float32)
            for i, r in enumerate(requests):
                offsets[i] = r.offset
                for s, payload in r.features.items():
                    buf = buffers.get(s)
                    if buf is None:
                        continue
                    if isinstance(payload, tuple):
                        idx, vals = payload
                        np.add.at(buf[i], np.asarray(idx, np.int64), vals)
                    else:
                        buf[i, :] = payload
        with stage_timer("serve_lookup"):
            if inject:
                faults.fault_point("lookup")
            re_coords = [c for c in self._coords if c.is_random_effect]
            cold_flags = np.zeros((n, len(re_coords)), bool)
            rows_by_cid: Dict[str, np.ndarray] = {}
            for k, c in enumerate(re_coords):
                ids = [r.entity_ids.get(c.random_effect_type) for r in requests]
                rows, _ = c.lookup_rows(ids)
                cold_flags[:, k] = rows == c.unseen_row
                padded = np.full(bucket, c.unseen_row, np.int32)
                padded[:n] = rows
                rows_by_cid[c.cid] = padded
        return {
            "bucket": bucket,
            "buffers": buffers,
            "offsets": offsets,
            "rows_by_cid": rows_by_cid,
            "cold_flags": cold_flags,
        }

    def _dispatch(
        self, packed: dict, *, inject: bool = True
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Upload request buffers, run the fused program, fetch both outputs
        in one transfer."""
        with stage_timer("serve_score"):
            if inject:
                faults.fault_point("score")
            dev_buffers = {
                s: jnp.asarray(b) for s, b in packed["buffers"].items()
            }
            rows = tuple(
                jnp.asarray(packed["rows_by_cid"][c.cid])
                if c.is_random_effect
                else None
                for c in self._coords
            )
            params = tuple(c.params for c in self._coords)
            norms = tuple(c.norm for c in self._coords)
            total, means = self._jit(
                jnp.asarray(packed["offsets"]),
                dev_buffers,
                rows,
                params,
                norms,
                kinds=self._kinds,
                shards=self._coord_shards,
                task=self.task,
            )
            host_total, host_means = jax.device_get((total, means))
        with self._lock:
            self._dispatched_buckets.add(packed["bucket"])
        return np.asarray(host_total), np.asarray(host_means)

    # -------------------------------------------------------------- metrics

    @property
    def compiles(self) -> int:
        """XLA programs compiled by THIS engine: the jit wrapper's cache
        size (an honest compile count), falling back to the number of
        distinct bucket shapes dispatched if the private cache API ever
        goes away (same value whenever each bucket is one program)."""
        try:
            return int(self._jit._cache_size())
        except AttributeError:
            with self._lock:
                return len(self._dispatched_buckets)

    @property
    def recompiles_after_warmup(self) -> Optional[int]:
        """Compiles since warmup(), or None when warmup never ran — a 0
        here must MEAN zero hot-path compiles, not 'nobody measured'; an
        un-warmed engine compiling on live traffic has no baseline to
        count from, and None trips the bench's missing-key contract."""
        with self._lock:
            base = self._warmup_compiles
        return None if base is None else max(0, self.compiles - base)

    def metrics(self) -> Dict[str, object]:
        """Engine-side counters; the batcher's metrics() merges these with
        request latency percentiles."""
        compiles = self.compiles  # before the lock: the fallback path locks
        with self._lock:
            lookups = self._lookups
            cold = self._cold_lookups
            slots = self._slots_total
            padded = self._slots_padded
            elapsed = (
                (self._t_last - self._t_first)
                if self._t_first is not None and self._t_last > self._t_first
                else 0.0
            )
            out = {
                "requests": self._requests,
                "batches": self._batches,
                "cold_start_lookups": cold,
                "cold_start_fraction": (cold / lookups) if lookups else 0.0,
                "padding_waste": (padded / slots) if slots else 0.0,
                "compiles": compiles,
                "recompiles_after_warmup": (
                    None
                    if self._warmup_compiles is None
                    else max(0, compiles - self._warmup_compiles)
                ),
                "upload_bytes": self.bundle.upload_bytes,
                "upload_s": round(self.bundle.upload_s, 4),
                "engine_qps": (
                    round(self._requests / elapsed, 1) if elapsed > 0 else None
                ),
            }
        out["stage_walls_s"] = {
            k: round(v, 4) for k, v in sorted(self.stages.sections.items())
        }
        return out

"""Online scoring engine: jitted padded-bucket programs over a pinned bundle.

Design constraints (the DrJAX lesson from PAPERS.md — fixed, jit-stable
program shapes — applied to a serving hot path):

  * The compile set is BOUNDED and declared up front: one XLA program per
    power-of-two bucket size up to `max_batch`. A batch of n requests pads
    to the smallest bucket >= n; after `warmup()` has compiled every
    bucket, a request stream of arbitrary batch sizes triggers ZERO new
    compiles (`recompiles_after_warmup` in metrics, asserted in tests).
  * One device round trip per batch: pack host-side, upload the request
    buffers, dispatch one fused program (all coordinates + link function),
    fetch (scores, means) together.
  * Bitwise offline parity: the fused program reuses the transformer's own
    margin kernels (`dense_margins`, `random_effect_margins`) and sums
    coordinates in the same order, and those kernels are batch-size
    invariant (see dense_margins' docstring) — so a request scores
    bitwise-identically to `GameTransformer.transform` on the same row,
    whatever bucket it pads into. That also makes scores independent of
    micro-batch composition, which is what lets the batcher degrade to
    per-request dispatch under faults without changing any answer.
  * Cold start: entities absent from the bundle's hash index gather the
    pinned zero row, i.e. score with the fixed effects (+ offset) only —
    GLMix's prior-model semantics for unseen entities. Counted per lookup
    and surfaced per request.
  * Request buffers are donated to the program on accelerator backends
    (they are per-batch scratch; donation lets XLA reuse the HBM). Model
    planes are never donated — they are the bundle's pinned state.

Lifecycle tier (serving/lifecycle.py) additions on top of PR 4:

  * The bundle is no longer construction-pinned: every batch snapshots an
    immutable `_EngineState` (bundle + derived coordinate metadata), and a
    `BundleManager.swap()` flips that snapshot atomically between batches
    — in-flight batches finish on the generation they started on, which
    the per-state in-flight counter drains before the old bundle is
    released.
  * `score_batch_fe_only` is the circuit-open degradation tier: every
    random-effect lookup is forced to the pinned zero row and no fault
    site fires in the path, so it keeps answering (bitwise-equal to
    FE-only `GameTransformer` output) while the full path is broken.
  * `health` (STARTING/READY/DEGRADED/DRAINING/CLOSED) and `breaker` (the
    circuit over the lookup/score fault sites) surface through
    `metrics()`.

Fault sites: `lookup` (entity-row resolution) and `score` (device
dispatch), via utils/faults.py. The engine itself raises; degradation
policy (retry, per-request fallback, circuit routing) lives in the batcher
so direct callers keep raw failure semantics.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.model import random_effect_margins
from photon_ml_tpu.ops.losses import mean_for_task
from photon_ml_tpu.serving.bundle import ScoreRequest, ServingBundle, ServingCoordinate
from photon_ml_tpu.serving.lifecycle import (
    BundleManager,
    CircuitBreaker,
    HealthStateMachine,
    ServingState,
)
from photon_ml_tpu.transformers.game_transformer import dense_margins
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.utils import faults, telemetry
from photon_ml_tpu.utils.observability import TimingRegistry, stage_scope, stage_timer
from photon_ml_tpu.utils.watchdog import Watchdog, watchdog_ms

Array = jax.Array


@dataclasses.dataclass
class ScoreResult:
    """One answered request: raw summed margin + link-function mean
    (ScoredGameDatum fields), plus cold-start accounting. `fe_only` marks
    an answer produced by the circuit-open fixed-effect-only tier (the
    score is the FE-only score, NOT the full-model one)."""

    score: float
    mean: float
    uid: Optional[str] = None
    cold_start: bool = False  # any random-effect lookup fell back
    n_cold: int = 0  # how many of the request's RE lookups fell back
    fe_only: bool = False
    # How many of the fallbacks were shard-loss degradations (the row is
    # RESIDENT in the artifact but its shard is marked LOST on this
    # server) — distinct from genuine cold starts, which no replica could
    # answer. A multi-host merge prefers the answer with the fewest.
    n_lost: int = 0


@dataclasses.dataclass
class _EngineState:
    """One bundle generation's scoring state. Immutable after build except
    `active` (in-flight batch count, guarded by the engine lock) — the
    swap drain waits on it before releasing the generation's bundle.

    `kinds` name each coordinate's storage mode and pick its margin kernel:
    "fe" (weight vector), "re" (single-tier matrix), "re_sh" (row-sharded
    matrix over `meshes[k]` — the fused program becomes a pjit program over
    the mesh), "re2" (two-tier hot/cold store), "re_bf16"/"re_i8"
    (precision-ladder quantized planes, dequantized inside the fused
    program — ISSUE 20)."""

    bundle: ServingBundle
    coords: List[ServingCoordinate]
    kinds: Tuple[str, ...]
    coord_shards: Tuple[str, ...]
    shard_dims: Dict[str, int]
    meshes: Tuple[Optional[object], ...] = ()
    version: int = 0
    active: int = 0


def _score_program(
    offsets,
    shard_feats,
    rows,
    overrides,
    params,
    norms,
    *,
    kinds,
    shards,
    meshes,
    task,
):
    """The fused per-bucket program: offsets + per-coordinate margins (same
    kernels and summation order as GameTransformer.transform) + link mean.

    Request features arrive as ONE buffer per shard (`shard_feats`), with
    coordinates resolving their shard by the static `shards` tuple — never
    as a per-coordinate tuple, which would pass the same device array
    twice when two coordinates share a shard and make buffer donation
    alias one buffer to two parameters (undefined on accelerators).

    Storage-mode kernels, all BITWISE-equal to the single-tier path:
      * "re_sh": the row-sharded matrix is read via the psum
        broadcast-gather (exact row movement over the mesh —
        game.model.random_effect_margins_bcast) so no device materializes
        the full (E + 1, D) matrix;
      * "re2": rows resolve against the hot-tier snapshot, with cold-tier
        hits overridden by the rows the pack stage copied out of host RAM
        (`overrides[k]` = (values, flags)) — the override row IS the
        matrix row, so the margin is unchanged."""
    from photon_ml_tpu.game.model import (
        _random_effect_margins_bcast_impl,
        gathered_row_margins,
    )

    total = offsets
    for k, kind in enumerate(kinds):
        feats = shard_feats[shards[k]]
        if kind == "fe":
            total = total + dense_margins(feats, params[k], norms[k])
        elif kind == "re_sh":
            total = total + _random_effect_margins_bcast_impl(
                feats, rows[k], params[k], norms[k], mesh=meshes[k]
            )
        elif kind == "re2":
            ovr_vals, ovr_flags = overrides[k]
            w = params[k][rows[k]]
            w = jnp.where(ovr_flags[:, None], ovr_vals, w)
            total = total + gathered_row_margins(feats, w, norms[k])
        elif kind == "re_bf16":
            # Quantized rung (ISSUE 20): the gathered bf16 rows widen to
            # f32 INSIDE the fused program — one extra cast on (B, dim)
            # request rows, never a host-side dequant of the full matrix.
            w = params[k][rows[k]].astype(jnp.float32)
            total = total + gathered_row_margins(feats, w, norms[k])
        elif kind == "re_i8":
            # int8 rung: params[k] is (int8 plane, per-row f32 scales);
            # dequant is fused per gathered row — widen + one broadcast
            # multiply by the row's symmetric scale.
            plane, scales = params[k]
            w = plane[rows[k]].astype(jnp.float32) * scales[rows[k]][:, None]
            total = total + gathered_row_margins(feats, w, norms[k])
        else:
            total = total + random_effect_margins(
                feats, rows[k], params[k], norms[k]
            )
    return total, mean_for_task(task, total)


def _bucket_sizes(max_batch: int) -> Tuple[int, ...]:
    sizes = []
    b = 1
    while b < max_batch:
        sizes.append(b)
        b <<= 1
    sizes.append(max_batch)
    return tuple(sizes)


class ServingEngine:
    """Scores request batches against a swappable pinned `ServingBundle`.

    Thread-safety: `score_batch` may be called from any thread (the
    batcher's flush thread, a caller's worker pool); metrics updates are
    lock-protected, and each batch runs against one atomic state snapshot.
    One engine owns one private jit cache, so `compiles` counts exactly
    this engine's XLA programs.
    """

    def __init__(
        self,
        bundle: ServingBundle,
        *,
        max_batch: Optional[int] = None,
        task: Optional[TaskType] = None,
        circuit_threshold: int = 5,
        circuit_probe_interval_s: float = 1.0,
        watchdog_ms_override: Optional[float] = None,
        inject_faults: bool = True,
        device_mutex: Optional[threading.Lock] = None,
    ):
        # The compiled-bucket ceiling is a PLANNED quantity (ISSUE 14):
        # an explicit argument wins (the operator/test said so); None
        # defers to the installed plan's serving_max_batch (observed-p95
        # batch size rounded up) and falls back to the pre-planner
        # default. The bucket SET is the power-of-two ladder up to it.
        if max_batch is None:
            from photon_ml_tpu import planner

            max_batch = int(planner.planned_value("serving_max_batch"))
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.task = task or bundle.task
        self.max_batch = int(max_batch)
        self.buckets = _bucket_sizes(self.max_batch)
        # Per-engine jit instance = private compile cache, so _cache_size()
        # is an honest XLA-compile counter for THIS engine. jit caches key
        # on the underlying callable, and wrappers over the same module
        # function SHARE entries — a fresh per-engine trampoline keeps this
        # engine's count isolated from every other engine in the process.
        def _engine_score_program(*args, **kwargs):
            return _score_program(*args, **kwargs)

        # Donate the per-batch request scratch (offsets, shard buffers,
        # rows, two-tier overrides) — never the model planes.
        donate = () if jax.default_backend() == "cpu" else (0, 1, 2, 3)
        self._jit = jax.jit(
            _engine_score_program,
            static_argnames=("kinds", "shards", "meshes", "task"),
            donate_argnums=donate,
        )
        self.stages = TimingRegistry()
        # Condition, not Lock: the hot-swap drain waits on per-state
        # in-flight counts reaching zero (notified by score_batch exits).
        self._lock = threading.Condition()
        # Multi-device program dispatches serialize on this mutex: two
        # host threads concurrently launching collective programs over
        # overlapping device sets (live traffic + a reshard's pre-warm of
        # the NEW mesh's pjit programs) can deadlock the runtime's
        # participant rendezvous — the warm path and the score path must
        # interleave, never overlap. Uncontended cost: one lock hop per
        # batch. The multi-tenant registry (serving/tenancy.py) passes
        # ONE shared mutex to every tenant engine for the same reason:
        # N tenant flush threads dispatching collective programs over the
        # same fleet must interleave across engines too.
        self._device_mutex = (
            device_mutex if device_mutex is not None else threading.Lock()
        )
        # Per-engine fault-injection gate (ISSUE 15): the process-global
        # fault plan fires at this engine's lookup/score sites only when
        # True. The multi-tenant chaos drills use it to CONFINE an armed
        # plan to one tenant's dispatches — the isolation proof needs
        # deterministic targeting, and site invocation counters are
        # process-wide. Production engines leave it True (an unarmed
        # fault_point is a free no-op).
        self.inject_faults = bool(inject_faults)
        self._state = self._build_state(bundle, version=0)
        self.health = HealthStateMachine()
        self.breaker = CircuitBreaker(
            threshold=circuit_threshold,
            probe_interval_s=circuit_probe_interval_s,
            on_open=lambda: self.health.add_degraded("circuit_open"),
            on_close=lambda: self.health.clear_degraded("circuit_open"),
        )
        self._bundle_manager: Optional[BundleManager] = None
        self._reshard_orchestrator = None
        self._requests = 0
        self._batches = 0
        self._lookups = 0
        self._cold_lookups = 0
        self._slots_total = 0
        self._slots_padded = 0
        self._fe_only_requests = 0
        self._shard_loss_fallbacks = 0
        # Hang watchdog around live-traffic dispatches (PHOTON_WATCHDOG_MS,
        # constructor override for tests; 0 = off). Warmup and the FE-only
        # degradation tier are exempt: compiles legitimately exceed a
        # serving deadline, and the degraded tier must keep answering —
        # warm up BEFORE arming a tight deadline on live traffic.
        self._watchdog_ms = (
            float(watchdog_ms()) if watchdog_ms_override is None
            else float(watchdog_ms_override)
        )
        self._watchdog = Watchdog(on_trip=self._on_watchdog_trip)
        self._hang_seen = False
        self._warmup_compiles: Optional[int] = None
        self._dispatched_buckets: set = set()
        self._t_first: Optional[float] = None
        self._t_last: Optional[float] = None
        self._batchers: List[object] = []
        self._closed = False

    # ----------------------------------------------------------- lifecycle

    @property
    def bundle(self) -> ServingBundle:
        """The ACTIVE bundle generation (swappable; snapshot per batch)."""
        return self._state.bundle

    @property
    def bundle_version(self) -> int:
        return self._state.version

    @property
    def bundle_manager(self) -> BundleManager:
        """The engine's hot-swap manager (created on first use)."""
        with self._lock:
            if self._bundle_manager is None:
                self._bundle_manager = BundleManager(self)
            return self._bundle_manager

    @property
    def reshard_orchestrator(self):
        """The engine's live mesh-elasticity orchestrator (created on
        first use; serving/reshard.py): shrink/grow the coefficient shard
        layout or rebalance the two-tier hot set under live traffic,
        serialized with bundle hot-swaps on the manager's mutex."""
        manager = self.bundle_manager  # created first: shares its mutex
        with self._lock:
            if self._reshard_orchestrator is None:
                from photon_ml_tpu.serving.reshard import (
                    MeshReshardOrchestrator,
                )

                self._reshard_orchestrator = MeshReshardOrchestrator(self)
            return self._reshard_orchestrator

    def batcher(self, **kwargs) -> "MicroBatcher":  # noqa: F821
        """Create a MicroBatcher bound to this engine; `close()` joins it."""
        if self._closed:
            # close() already ran and will never revisit _batchers — a
            # batcher created now would leak its flush thread.
            raise RuntimeError("ServingEngine is closed")
        from photon_ml_tpu.serving.batcher import MicroBatcher

        b = MicroBatcher(self, **kwargs)
        self._batchers.append(b)
        return b

    def close(self) -> None:
        """Graceful drain-on-shutdown: DRAINING while every batcher created
        via `batcher()` answers its pending futures and joins its flush
        thread, then CLOSED. Idempotent. The bundle stays usable — model
        planes are plain device arrays owned by the bundle, not the
        engine."""
        if self._closed:
            return
        self._closed = True
        self.health.begin_drain()
        for b in self._batchers:
            b.close()
        self._watchdog.close()
        self.health.close()

    def _on_watchdog_trip(self, label: str) -> None:
        """A device dispatch blew its deadline — fired FROM the monitor
        thread while the dispatch may still be stuck, so a hung-forever
        device flips health immediately; the next successful dispatch
        clears the reason."""
        self._hang_seen = True
        self.health.add_degraded("device_hang")

    # --------------------------------------------------- shard loss/recovery

    def mark_shard_lost(self, cid: str, shard_index: int) -> Tuple[int, int]:
        """Record one coefficient shard LOST (see ServingBundle): its
        entities degrade to bitwise FE-only pinned-zero-row answers, the
        engine stays up, health reports DEGRADED with the shard named."""
        rng = self._state.bundle.mark_shard_lost(cid, shard_index)
        self.health.add_degraded(f"shard_loss:{cid}/{shard_index}")
        telemetry.emit_event("shard_loss", coordinate=cid, shard_index=shard_index)
        return rng

    def restage_shard(
        self, cid: str, shard_index: int, rows=None
    ) -> int:
        """Recover one lost shard (re-uploads ONLY its rows, under the
        `shard_upload` fault site); clears the shard's degraded reason on
        success. A terminal staging failure re-raises and the shard stays
        lost — the engine keeps serving its entities FE-only."""
        nbytes = self._state.bundle.restage_shard(cid, shard_index, rows=rows)
        self.health.clear_degraded(f"shard_loss:{cid}/{shard_index}")
        telemetry.emit_event(
            "shard_restage", coordinate=cid, shard_index=shard_index, bytes=nbytes
        )
        return nbytes

    def _on_batcher_unhealthy(self, exc: BaseException) -> None:
        """A batcher's flush thread died (serving/batcher.py failed all its
        pending futures); the engine is degraded until operators replace
        the batcher — this reason never self-clears."""
        self.health.add_degraded(f"batcher_unhealthy: {exc!r}")

    def __enter__(self) -> "ServingEngine":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    # ------------------------------------------------------- state plumbing

    def _build_state(self, bundle: ServingBundle, *, version: int) -> _EngineState:
        if bundle.released:
            raise RuntimeError("cannot serve a released bundle")
        coords = [bundle.coordinates[cid] for cid in bundle.coordinate_ids]

        def _kind(c: ServingCoordinate) -> str:
            if not c.is_random_effect:
                return "fe"
            if getattr(c, "store", None) is not None:
                return "re2"
            if getattr(c, "mesh", None) is not None:
                return "re_sh"
            tier = getattr(c, "tier", "f32")
            if tier == "bf16":
                return "re_bf16"
            if tier == "int8":
                return "re_i8"
            return "re"

        return _EngineState(
            bundle=bundle,
            coords=coords,
            kinds=tuple(_kind(c) for c in coords),
            coord_shards=tuple(c.shard for c in coords),
            shard_dims=bundle.shard_dims(),
            meshes=tuple(getattr(c, "mesh", None) for c in coords),
            version=version,
        )

    def _warm_state(self, state: _EngineState) -> None:
        """Compile every bucket program for `state`'s parameter shapes
        (inert all-cold zero batches; no fault sites, no request metrics).
        Used by warmup() on the live state and by the hot-swap staging on
        the NEXT state — so the atomic flip compiles nothing."""
        for b in self.buckets:
            self._dispatch(
                self._pack([], b, state, inject=False), state, inject=False
            )

    def _commit_state(
        self, new_state: _EngineState, *, baseline_bump: int = 0
    ) -> _EngineState:
        """The hot-swap flip: one assignment under the lock. The warmup
        baseline grows by exactly the programs STAGING compiled
        (`baseline_bump`) — never reset to the current total, which would
        silently absorb any pre-swap hot-path recompiles and wipe the
        regression signal recompiles_after_warmup exists to carry."""
        with self._lock:
            old = self._state
            self._state = new_state
            if self._warmup_compiles is not None:
                self._warmup_compiles += max(0, baseline_bump)
        return old

    def _drain_state(self, state: _EngineState, *, timeout_s: float) -> bool:
        """Wait until no in-flight batch still scores on `state`."""
        deadline = time.monotonic() + timeout_s
        with self._lock:
            while state.active > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._lock.wait(timeout=remaining)
        return True

    # ------------------------------------------------------------- scoring

    def bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        return self.max_batch

    def warmup(self) -> int:
        """Compile every declared bucket (inert all-cold zero batches that
        do not count toward request metrics). Returns the compile count;
        afterwards `recompiles_after_warmup` tracks cache misses — zero for
        any request stream whose batches fit max_batch. Transitions the
        health machine STARTING -> READY."""
        t0 = time.perf_counter()
        # inject=False inside _warm_state: warmup is not the request path —
        # an armed lookup/score fault must fire on (and be counted against)
        # real traffic, not kill engine bring-up.
        self._warm_state(self._state)
        # Warmup wall (mostly XLA compiles) is recorded under its own stage
        # key; no ambient scope is open here, so the inner serve_pack/
        # serve_score timers stay warmup-free.
        self.stages.record("serve_warmup", time.perf_counter() - t0)
        compiles = self.compiles
        with self._lock:
            self._warmup_compiles = compiles
        self.health.mark_ready()
        return compiles

    def score_batch(
        self, requests: Sequence[ScoreRequest], *, fe_only: bool = False
    ) -> List[ScoreResult]:
        """Score one micro-batch: pad to the bucket, one device round trip.
        Batches larger than max_batch split internally. `fe_only=True` is
        the circuit-open tier: every RE lookup forced to the pinned zero
        row, no fault sites in the path."""
        if not requests:
            return []
        if len(requests) > self.max_batch:
            out: List[ScoreResult] = []
            for lo in range(0, len(requests), self.max_batch):
                out.extend(
                    self.score_batch(
                        requests[lo : lo + self.max_batch], fe_only=fe_only
                    )
                )
            return out
        n = len(requests)
        bucket = self.bucket_for(n)
        with self._lock:
            st = self._state
            st.active += 1
        try:
            with stage_scope(self.stages):
                packed = self._pack(
                    requests, bucket, st, inject=not fe_only, fe_only=fe_only
                )
                scores, means = self._dispatch(packed, st, inject=not fe_only)
        finally:
            with self._lock:
                st.active -= 1
                self._lock.notify_all()
        flags = packed["cold_flags"]
        lflags = packed["lost_flags"]
        results = [
            ScoreResult(
                score=float(scores[i]),
                mean=float(means[i]),
                uid=requests[i].uid,
                cold_start=bool(flags[i].any()),
                n_cold=int(flags[i].sum()),
                fe_only=fe_only,
                n_lost=int(lflags[i].sum()),
            )
            for i in range(n)
        ]
        now = time.monotonic()
        with self._lock:
            self._requests += n
            self._batches += 1
            if fe_only:
                # FE-only answers are forced cold by construction; keeping
                # them out of the lookup counters preserves
                # cold_start_fraction's meaning (unknown entities on the
                # HEALTHY path).
                self._fe_only_requests += n
            else:
                self._lookups += int(flags.size)
                self._cold_lookups += int(flags.sum())
            self._slots_total += bucket
            self._slots_padded += bucket - n
            if self._t_first is None:
                self._t_first = now
            self._t_last = now
        if self.health.state is ServingState.STARTING:
            self.health.mark_ready()  # serving without explicit warmup()
        return results

    def score_batch_fe_only(
        self, requests: Sequence[ScoreRequest]
    ) -> List[ScoreResult]:
        """The circuit-open degradation tier: score with fixed effects (+
        offset) only, bitwise-equal to FE-only GameTransformer output via
        the pinned zero-row path. No fault site fires here — this tier
        must keep answering precisely when the full path is broken."""
        return self.score_batch(requests, fe_only=True)

    # ------------------------------------------------------------ internals

    def _pack(
        self,
        requests: Sequence[ScoreRequest],
        bucket: int,
        state: _EngineState,
        *,
        inject: bool = True,
        fe_only: bool = False,
    ) -> dict:
        """Host-side batch assembly: per-shard dense buffers, per-RE-coordinate
        entity rows (padding slots gather the pinned zero row), offsets."""
        n = len(requests)
        with stage_timer("serve_pack"):
            buffers = {
                s: np.zeros((bucket, d), np.float32)
                for s, d in state.shard_dims.items()
            }
            offsets = np.zeros(bucket, np.float32)
            for i, r in enumerate(requests):
                offsets[i] = r.offset
                for s, payload in r.features.items():
                    buf = buffers.get(s)
                    if buf is None:
                        continue
                    if isinstance(payload, tuple):
                        idx, vals = payload
                        np.add.at(buf[i], np.asarray(idx, np.int64), vals)
                    else:
                        buf[i, :] = payload
        with stage_timer("serve_lookup"):
            if inject and self.inject_faults:
                faults.fault_point("lookup")
            re_coords = [c for c in state.coords if c.is_random_effect]
            cold_flags = np.zeros((n, len(re_coords)), bool)
            # Which cold flags are shard-loss fallbacks (resident row,
            # LOST shard) rather than genuinely unseen entities — kept
            # separate so ScoreResult.n_lost can tell a degraded answer
            # from one nobody could improve on.
            lost_flags = np.zeros((n, len(re_coords)), bool)
            rows_by_cid: Dict[str, np.ndarray] = {}
            # Two-tier coordinates: per-batch override buffers (cold-tier
            # rows copied from host RAM) + the hot-matrix snapshot captured
            # ATOMICALLY with the slot resolution — a concurrent promotion
            # can then never remap an in-flight batch (the snapshot matrix
            # is immutable; promotions build a new one).
            overrides_by_cid: Dict[str, tuple] = {}
            tier_params: Dict[str, Array] = {}
            for k, c in enumerate(re_coords):
                store = getattr(c, "store", None)
                if fe_only:
                    # Every slot gathers the pinned zero row: the margin
                    # contribution is exactly +0.0, i.e. FE-only scoring
                    # without touching the (possibly failing) index path.
                    if store is not None:
                        rows_by_cid[c.cid] = np.full(
                            bucket, store.zero_slot, np.int32
                        )
                        overrides_by_cid[c.cid] = (
                            np.zeros((bucket, c.dim), np.float32),
                            np.zeros(bucket, bool),
                        )
                        tier_params[c.cid] = store.snapshot()
                    else:
                        rows_by_cid[c.cid] = np.full(
                            bucket, c.unseen_row, np.int32
                        )
                    continue
                ids = [r.entity_ids.get(c.random_effect_type) for r in requests]
                rows, _ = c.lookup_rows(ids)
                sh = getattr(c, "shard_health", None)
                if sh is not None:
                    # Per-shard load telemetry (cold starts excluded) —
                    # what a reshard/rebalance plan reads to name the
                    # overloaded shard.
                    sh.record_loads(rows[:n], c.unseen_row)
                if sh is not None and sh.any_lost:
                    # Shard-loss degradation: rows living in a LOST shard
                    # resolve to the pinned zero row — bitwise FE-only for
                    # exactly those entities; every other row keeps
                    # full-fidelity answers.
                    # Rows ALREADY at the pinned zero row (cold starts)
                    # are excluded: they were FE-only by design, and
                    # counting them would report cold-start traffic as
                    # shard-loss degradation.
                    lost = sh.lost_mask(rows) & (rows != c.unseen_row)
                    if lost.any():
                        lost_flags[:, k] = lost
                        rows = np.where(lost, c.unseen_row, rows).astype(
                            np.int32
                        )
                        n_lost = int(lost.sum())
                        faults.COUNTERS.increment(
                            "shard_loss_fallbacks", n_lost
                        )
                        with self._lock:
                            self._shard_loss_fallbacks += n_lost
                cold_flags[:, k] = rows == c.unseen_row
                if store is not None:
                    slots, ovr, flags, snapshot = store.lookup(rows, bucket)
                    rows_by_cid[c.cid] = slots
                    overrides_by_cid[c.cid] = (ovr, flags)
                    tier_params[c.cid] = snapshot
                else:
                    padded = np.full(bucket, c.unseen_row, np.int32)
                    padded[:n] = rows
                    rows_by_cid[c.cid] = padded
        return {
            "bucket": bucket,
            "buffers": buffers,
            "offsets": offsets,
            "rows_by_cid": rows_by_cid,
            "overrides_by_cid": overrides_by_cid,
            "tier_params": tier_params,
            "cold_flags": cold_flags,
            "lost_flags": lost_flags,
        }

    def _dispatch(
        self, packed: dict, state: _EngineState, *, inject: bool = True
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Upload request buffers, run the fused program, fetch both outputs
        in one transfer."""
        with stage_timer("serve_score"):
            if inject and self.inject_faults:
                faults.fault_point("score")
            # Hang watchdog (live traffic only — warmup/FE-only exempt):
            # the guard wraps upload + fused program + fetch; an
            # over-deadline dispatch raises a typed DeviceHang that the
            # batcher's breaker counts toward circuit-open FE-only routing.
            wd_ms = self._watchdog_ms if inject else 0.0
            with self._watchdog.guard(
                wd_ms, f"serving dispatch (bucket {packed['bucket']})"
            ):
                out = self._dispatch_device(packed, state)
            if wd_ms > 0 and self._hang_seen:
                # A GUARDED dispatch finished inside its deadline: the
                # device answered again, so the hang degradation
                # self-clears (an unguarded FE-only dispatch proves
                # nothing about the full path).
                self._hang_seen = False
                self.health.clear_degraded("device_hang")
        host_total, host_means = out
        with self._lock:
            self._dispatched_buckets.add(packed["bucket"])
        return np.asarray(host_total), np.asarray(host_means)

    def _dispatch_device(
        self, packed: dict, state: _EngineState
    ) -> Tuple[np.ndarray, np.ndarray]:
        with self._device_mutex:
            return self._dispatch_device_locked(packed, state)

    def _dispatch_device_locked(
        self, packed: dict, state: _EngineState
    ) -> Tuple[np.ndarray, np.ndarray]:
        dev_buffers = {
            s: jnp.asarray(b) for s, b in packed["buffers"].items()
        }
        rows = tuple(
            jnp.asarray(packed["rows_by_cid"][c.cid])
            if c.is_random_effect
            else None
            for c in state.coords
        )
        overrides = tuple(
            (
                jnp.asarray(packed["overrides_by_cid"][c.cid][0]),
                jnp.asarray(packed["overrides_by_cid"][c.cid][1]),
            )
            if c.is_random_effect
            and c.cid in packed["overrides_by_cid"]
            else None
            for c in state.coords
        )
        # Two-tier coordinates score against the hot-matrix snapshot
        # the pack stage captured with the slots; int8 coordinates pass
        # (plane, per-row scales) so the program's fused dequant gathers
        # both; everyone else serves the bundle's pinned planes.
        params = tuple(
            (c.params, c.scales)
            if state.kinds[k] == "re_i8"
            else packed["tier_params"].get(c.cid, c.params)
            for k, c in enumerate(state.coords)
        )
        norms = tuple(c.norm for c in state.coords)
        total, means = self._jit(
            jnp.asarray(packed["offsets"]),
            dev_buffers,
            rows,
            overrides,
            params,
            norms,
            kinds=state.kinds,
            shards=state.coord_shards,
            meshes=state.meshes,
            task=self.task,
        )
        return jax.device_get((total, means))

    # -------------------------------------------------------------- metrics

    def warmup_buffer_bytes(self, state: Optional[_EngineState] = None) -> int:
        """Peak per-batch transient request-buffer bytes (largest bucket):
        offsets + per-shard feature buffers + per-RE rows + two-tier
        override buffers + both outputs. This is what a hot-swap's
        pre-warm allocates BESIDE the two resident bundle generations, so
        BundleManager charges it against the HBM budget."""
        st = state if state is not None else self._state
        b = self.max_batch
        total = b * 4  # offsets
        total += sum(b * d * 4 for d in st.shard_dims.values())
        for k, c in enumerate(st.coords):
            if c.is_random_effect:
                total += b * 4  # rows
                if st.kinds[k] == "re2":
                    total += b * (c.dim * 4 + 1)  # override values + flags
        total += 2 * b * 4  # (scores, means)
        return total

    def _sharding_metrics(self, state: _EngineState) -> Dict[str, object]:
        """The serving sharding decision as proper JSON keys (the
        serving-summary/bench contract): mesh axis size, peak coefficient
        rows resident per shard, two-tier hot-set fraction, and the
        analytic collective bytes one max_batch bucket moves."""
        from photon_ml_tpu.parallel.mesh import bcast_gather_wire_bytes

        sharded = False
        axis = 1
        rows_per_shard = 0
        hot_fraction = 1.0
        wire = 0
        shards_lost = 0
        for k, c in enumerate(state.coords):
            kind = state.kinds[k]
            sh = getattr(c, "shard_health", None)
            if sh is not None:
                shards_lost += len(sh.lost)
            if kind == "re_sh":
                sharded = True
                ndev = int(c.mesh.devices.size)
                axis = max(axis, ndev)
                rows_per_shard = max(
                    rows_per_shard, int(c.params.shape[0]) // ndev
                )
                wire += bcast_gather_wire_bytes(c.mesh, self.max_batch, c.dim)
            elif kind == "re2":
                hot_fraction = min(hot_fraction, c.store.hot_fraction)
                rows_per_shard = max(rows_per_shard, c.store.capacity + 1)
            elif kind == "re":
                rows_per_shard = max(rows_per_shard, int(c.params.shape[0]))
        # Explicit keys (immune to schema-tuple reorders), checked against
        # the shared schema so the producer cannot drift from what
        # bench/serve assert on.
        from photon_ml_tpu.utils.contracts import SERVING_SHARDING_KEYS

        with self._lock:
            loss_fallbacks = self._shard_loss_fallbacks
        out = {
            "entity_sharded": sharded,
            "axis_size": axis,
            "rows_per_shard": rows_per_shard,
            "hot_set_fraction": round(hot_fraction, 6),
            "all_to_all_bytes_per_batch": wire,
            "shards_lost": shards_lost,
            "shard_loss_fallbacks": loss_fallbacks,
        }
        assert set(out) == set(SERVING_SHARDING_KEYS), (
            "serving sharding block drifted from utils/contracts."
            "SERVING_SHARDING_KEYS"
        )
        return out

    @property
    def compiles(self) -> int:
        """XLA programs compiled by THIS engine: the jit wrapper's cache
        size (an honest compile count), falling back to the number of
        distinct bucket shapes dispatched if the private cache API ever
        goes away (same value whenever each bucket is one program)."""
        try:
            return int(self._jit._cache_size())
        except AttributeError:
            with self._lock:
                return len(self._dispatched_buckets)

    @property
    def recompiles_after_warmup(self) -> Optional[int]:
        """Compiles since warmup(), or None when warmup never ran — a 0
        here must MEAN zero hot-path compiles, not 'nobody measured'; an
        un-warmed engine compiling on live traffic has no baseline to
        count from, and None trips the bench's missing-key contract."""
        with self._lock:
            base = self._warmup_compiles
        return None if base is None else max(0, self.compiles - base)

    def metrics(self) -> Dict[str, object]:
        """Engine-side counters; the batcher's metrics() merges these with
        request latency percentiles. Includes the lifecycle tier: health
        state (+ degraded reasons), circuit snapshot, bundle version and
        swap counters."""
        compiles = self.compiles  # before the lock: the fallback path locks
        manager = self._bundle_manager
        with self._lock:
            st = self._state
            lookups = self._lookups
            cold = self._cold_lookups
            slots = self._slots_total
            padded = self._slots_padded
            elapsed = (
                (self._t_last - self._t_first)
                if self._t_first is not None and self._t_last > self._t_first
                else 0.0
            )
            out = {
                "requests": self._requests,
                "batches": self._batches,
                "cold_start_lookups": cold,
                "cold_start_fraction": (cold / lookups) if lookups else 0.0,
                "padding_waste": (padded / slots) if slots else 0.0,
                "compiles": compiles,
                "recompiles_after_warmup": (
                    None
                    if self._warmup_compiles is None
                    else max(0, compiles - self._warmup_compiles)
                ),
                "fe_only_requests": self._fe_only_requests,
                "bundle_version": st.version,
                "upload_bytes": st.bundle.upload_bytes,
                "upload_s": round(st.bundle.upload_s, 4),
                "engine_qps": (
                    round(self._requests / elapsed, 1) if elapsed > 0 else None
                ),
            }
        # Pod-scale accounting: the sharding decision this bundle serves
        # under + the two-tier store counters (all keys always present —
        # 0/False on a single-tier replicated bundle — so the bench/summary
        # missing-key contract can be loud).
        out["sharding"] = self._sharding_metrics(st)
        tier = {
            "hot_tier_hits": 0,
            "cold_tier_hits": 0,
            "promotions": 0,
            "evictions": 0,
            "promote_failures": 0,
            "pending_promotions": 0,
        }
        for c in st.coords:
            store = getattr(c, "store", None)
            if store is not None:
                sm = store.metrics()
                for key in tier:
                    tier[key] += int(sm[key])
        out.update(tier)
        health = self.health.snapshot()
        out["state"] = health["state"]
        out["degraded_reasons"] = health["degraded_reasons"]
        out.update(self.breaker.snapshot())
        out["bundle_swaps"] = manager.swaps if manager is not None else 0
        out["bundle_swap_rollbacks"] = (
            manager.rollbacks if manager is not None else 0
        )
        orch = self._reshard_orchestrator
        out["bundle_reshards"] = orch.reshards if orch is not None else 0
        out["bundle_rebalances"] = orch.rebalances if orch is not None else 0
        out["bundle_deltas"] = orch.deltas if orch is not None else 0
        out["bundle_reshard_rollbacks"] = (
            orch.rollbacks if orch is not None else 0
        )
        out["stage_walls_s"] = {
            k: round(v, 4) for k, v in sorted(self.stages.sections.items())
        }
        return out

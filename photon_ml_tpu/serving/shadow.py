"""Shadow deployment & online evaluation (ISSUE 18).

Photon ML gated every deployment on OFFLINE validators (photon-lib
evaluation/, GameTrainingDriver's validation gate): a candidate model had
to beat the incumbent on a held-out set before it shipped. This module
takes that gate ONLINE on the serving platform itself: a challenger
bundle registers as a **shadow tenant** on the multi-tenant registry
(ISSUE 15), receives mirrored champion traffic co-batched with the
champion — the shadow rides the same `_cobatch_program` device dispatch,
so shadow scoring costs marginal device time, not a second fleet (the
Snap ML concurrent-stages thesis) — and its answers are NEVER returned
to clients: the champion's future resolves exactly as today, bitwise.

Both tenants' scores stream into windowed label joins feeding the exact
jitted `EvaluationSuite` metric programs (`resolve_metric_fn`, ISSUE 12)
through `StreamingWindowEvaluator` — one metric program shared by
offline and online evaluation, so a regression tolerance means the same
thing in both worlds — plus per-tenant score-drift and calibration
histograms in the telemetry registry (ISSUE 11).

The decision loop keeps control-theory hygiene: a verdict needs
`min_windows` CONSECUTIVE windows agreeing (all healthy promotes, all
regressed rejects — the mixed band in between is hysteresis and holds),
an optional cooldown delays actuation past transients, and every verdict
is a journaled `shadow_verdict` event carrying its evidence. Verdicts
drive the EXISTING actuators: promote flips the challenger to champion
through the BundleManager stage->pre-warm->commit->drain generation flip
(`swap`), and reject tears the shadow tenant down with zero champion
impact (`TenantRegistry.remove`).

Failure domain: `shadow_mirror` / `label_join` / `shadow_promote` fault
sites make the loop chaos-injectable. A mirror or join failure degrades
to champion-only serving — counted, NEVER a failed client request — and
a promotion failure (or a SIGKILL mid-promotion) leaves the champion
serving its old generation bitwise, because the flip is the same atomic
commit every hot-swap uses.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from concurrent.futures import Future
from typing import Deque, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from photon_ml_tpu.evaluation.suite import (
    EvaluatorType,
    StreamingWindowEvaluator,
    default_evaluator_for_task,
    regression,
)
from photon_ml_tpu.serving.bundle import ScoreRequest, ServingBundle
from photon_ml_tpu.serving.engine import ScoreResult
from photon_ml_tpu.serving.tenancy import TenantRegistry
from photon_ml_tpu.utils import faults, telemetry
from photon_ml_tpu.utils.contracts import SHADOW_BLOCK_KEYS
from photon_ml_tpu.utils.knobs import get_knob

logger = logging.getLogger(__name__)

# One joined evaluation row: champion/challenger raw scores feed the
# metric programs (same quantity offline evaluation scores), the
# link-function means feed the drift/calibration histograms.
_Row = Tuple[float, float, float, float, float, float]


class ShadowController:
    """Mirror champion traffic to a shadow challenger, evaluate both
    online, and actuate promote/reject with zero champion impact.

    The controller OWNS the challenger: it admits the bundle as a shadow
    tenant at construction and tears it down (releasing the bundle) on a
    reject verdict, a failed promotion, or `close()` before any verdict.
    A successful promotion transfers bundle ownership to the champion's
    engine (the swap releases the old champion generation instead).

    `auto_actuate=True` (serving default) lets the decision worker drive
    the actuators itself; `auto_actuate=False` (the refresh gate mode,
    cli/refresh) records the verdict for `wait_for_verdict()` and leaves
    promotion to the caller — rejection ALWAYS tears the shadow down in
    both modes, because a regressed challenger must never keep riding
    the fleet.

    Knob-deferred parameters (explicit argument wins, None defers):
    PHOTON_SHADOW_MIN_WINDOWS / PHOTON_SHADOW_REGRESSION_TOL /
    PHOTON_SHADOW_COOLDOWN_S / PHOTON_SHADOW_MIRROR_FRACTION.
    """

    def __init__(
        self,
        registry: TenantRegistry,
        champion: str,
        challenger: str,
        challenger_bundle: Union[ServingBundle, object],
        *,
        evaluator_types: Optional[Sequence[EvaluatorType]] = None,
        window_size: int = 64,
        min_windows: Optional[int] = None,
        regression_tol: Optional[float] = None,
        cooldown_s: Optional[float] = None,
        mirror_fraction: Optional[float] = None,
        auto_actuate: bool = True,
        max_pending_joins: int = 4096,
        max_pending: Optional[int] = None,
        deadline_ms: Optional[float] = None,
    ):
        if window_size < 1:
            raise ValueError(f"window_size must be >= 1, got {window_size}")
        self._registry = registry
        self._champion = champion
        self._challenger = challenger
        self._window_size = int(window_size)
        self._min_windows = int(
            get_knob("PHOTON_SHADOW_MIN_WINDOWS")
            if min_windows is None
            else min_windows
        )
        self._regression_tol = float(
            get_knob("PHOTON_SHADOW_REGRESSION_TOL")
            if regression_tol is None
            else regression_tol
        )
        self._cooldown_s = float(
            get_knob("PHOTON_SHADOW_COOLDOWN_S")
            if cooldown_s is None
            else cooldown_s
        )
        self._mirror_fraction = float(
            get_knob("PHOTON_SHADOW_MIRROR_FRACTION")
            if mirror_fraction is None
            else mirror_fraction
        )
        if self._min_windows < 1:
            raise ValueError(
                f"min_windows must be >= 1, got {self._min_windows}"
            )
        if not 0.0 < self._mirror_fraction <= 1.0:
            raise ValueError(
                "mirror_fraction must be in (0, 1], got "
                f"{self._mirror_fraction}"
            )
        self._auto_actuate = bool(auto_actuate)
        self._max_pending_joins = int(max_pending_joins)

        champ_engine = registry.tenant(champion).engine
        ets = (
            list(evaluator_types)
            if evaluator_types
            else [default_evaluator_for_task(champ_engine.task)]
        )
        self._evaluator = StreamingWindowEvaluator(ets)

        # Joined-row state, all guarded by _cond. Callbacks (which run on
        # registry/batcher threads) only touch dicts/deques here — device
        # work happens exclusively on the decision worker.
        self._cond = threading.Condition()
        self._pending: Dict[str, Dict[str, Optional[ScoreResult]]] = {}
        self._labels: Dict[str, Tuple[float, float]] = {}
        self._rows: Deque[_Row] = collections.deque()
        self._evaluating = False
        self._history: List[bool] = []
        self._last_metrics: Tuple[Optional[float], Optional[float]] = (
            None,
            None,
        )
        self._credit = 0.0
        self._mirrored = 0
        self._mirror_failures = 0
        self._label_join_failures = 0
        self._status = "observing"
        self._verdict: Optional[str] = None
        self._verdict_event = threading.Event()
        self._closed = False
        self._error: Optional[BaseException] = None
        self._started = time.monotonic()
        self._promoted_version: Optional[int] = None

        # Admit the challenger as a shadow tenant. Same signature class
        # as the champion (entity counts are NOT in the co-batch
        # signature) -> mirrored traffic rides the champion's co-batched
        # device dispatch at marginal cost.
        registry.admit(
            challenger,
            challenger_bundle,
            max_pending=max_pending,
            deadline_ms=deadline_ms,
        )
        telemetry.emit_event(
            "shadow_start",
            champion=champion,
            challenger=challenger,
            window_size=self._window_size,
            min_windows=self._min_windows,
            mirror_fraction=self._mirror_fraction,
        )
        self._worker = threading.Thread(
            target=self._run,
            name=f"photon-shadow-{challenger}-eval",
            daemon=True,
        )
        self._worker.start()

    # ---------------------------------------------------------- mirroring

    @property
    def status(self) -> str:
        with self._cond:
            return self._status

    @property
    def verdict(self) -> Optional[str]:
        with self._cond:
            return self._verdict

    def mirror(
        self, request: ScoreRequest, champion_future: "Future[ScoreResult]"
    ) -> bool:
        """Mirror one champion request to the challenger. Returns whether
        the request was mirrored — False means champion-only (fraction
        gate, a mirror fault, shed by the shadow's quota, or the
        controller past its observation phase) and is NEVER an error: the
        champion's future is untouched either way."""
        uid = request.uid
        if uid is None:
            # No join key -> no evaluation row; mirroring would spend
            # device time on a score nothing can consume.
            return False
        with self._cond:
            if self._status != "observing" or self._closed:
                return False
            # Deterministic credit accumulator (no RNG): at fraction f,
            # exactly every (1/f)th eligible request mirrors.
            self._credit += self._mirror_fraction
            if self._credit < 1.0:
                return False
            self._credit -= 1.0
            self._pending[uid] = {"champion": None, "challenger": None}
            self._evict_stale_joins_locked()
        try:
            faults.fault_point("shadow_mirror")
            shadow_future = self._registry.submit(
                self._challenger, request, block=False
            )
        except BaseException as exc:  # noqa: BLE001 - degrade, never fail
            with self._cond:
                self._pending.pop(uid, None)
                self._mirror_failures += 1
            faults.COUNTERS.increment("shadow_mirror_failures")
            logger.warning(
                "shadow mirror for %r degraded to champion-only: %s",
                uid,
                exc,
            )
            return False
        telemetry.METRICS.increment("shadow_mirrored_requests")
        with self._cond:
            self._mirrored += 1
        champion_future.add_done_callback(
            lambda f, _u=uid: self._on_result("champion", _u, f)
        )
        shadow_future.add_done_callback(
            lambda f, _u=uid: self._on_result("challenger", _u, f)
        )
        return True

    def record_label(self, uid: str, label: float, weight: float = 1.0) -> bool:
        """Join one label into the evaluation stream. A `label_join`
        fault drops the label (counted) — the champion path is untouched
        by construction, because labels only feed the shadow windows."""
        try:
            faults.fault_point("label_join")
        except faults.InjectedFault as exc:
            with self._cond:
                self._label_join_failures += 1
            faults.COUNTERS.increment("label_join_failures")
            logger.warning("label join for %r dropped: %s", uid, exc)
            return False
        with self._cond:
            if self._closed:
                return False
            self._labels[uid] = (float(label), float(weight))
            self._maybe_complete_locked(uid)
            # Bound the label side of the join the same way as pending
            # score pairs: an unmatched label that would grow memory
            # forever is a failed join, counted as one.
            while len(self._labels) > self._max_pending_joins:
                stale = next(iter(self._labels))
                del self._labels[stale]
                self._label_join_failures += 1
                faults.COUNTERS.increment("label_join_failures")
        return True

    def _on_result(self, role: str, uid: str, fut: Future) -> None:
        try:
            exc = fut.exception()
        except BaseException as cancelled:  # noqa: BLE001 - cancelled future
            exc = cancelled
        if exc is not None:
            # A failed champion request never evaluates (nothing was
            # served); a failed MIRRORED request degrades that request to
            # champion-only — counted as a mirror failure.
            with self._cond:
                dropped = self._pending.pop(uid, None) is not None
                if dropped and role == "challenger":
                    self._mirror_failures += 1
            if dropped and role == "challenger":
                faults.COUNTERS.increment("shadow_mirror_failures")
            return
        result = fut.result()
        with self._cond:
            ent = self._pending.get(uid)
            if ent is None:
                return
            ent[role] = result
            self._maybe_complete_locked(uid)

    def _maybe_complete_locked(self, uid: str) -> None:
        ent = self._pending.get(uid)
        if ent is None or ent["champion"] is None or ent["challenger"] is None:
            return
        lab = self._labels.get(uid)
        if lab is None:
            return
        champ, chall = ent["champion"], ent["challenger"]
        del self._pending[uid]
        del self._labels[uid]
        self._rows.append(
            (champ.score, champ.mean, chall.score, chall.mean, lab[0], lab[1])
        )
        self._cond.notify_all()

    def _evict_stale_joins_locked(self) -> None:
        # Bounded join state: a pair whose label (or score) never arrives
        # must not grow memory forever. Eviction IS a failed join.
        while len(self._pending) > self._max_pending_joins:
            stale = next(iter(self._pending))
            del self._pending[stale]
            self._labels.pop(stale, None)
            self._label_join_failures += 1
            faults.COUNTERS.increment("label_join_failures")

    # ------------------------------------------------------ decision loop

    def _run(self) -> None:
        try:
            while True:
                with self._cond:
                    while (
                        not self._closed
                        and self._status == "observing"
                        and len(self._rows) < self._window_size
                    ):
                        self._cond.wait(timeout=0.05)
                    if self._closed or self._status != "observing":
                        return
                    rows = [
                        self._rows.popleft()
                        for _ in range(self._window_size)
                    ]
                    self._evaluating = True
                try:
                    self._evaluate_window(rows)
                finally:
                    with self._cond:
                        self._evaluating = False
        except BaseException as exc:  # noqa: BLE001 - surfaced via summary
            logger.exception("shadow decision worker died")
            with self._cond:
                self._error = exc
                self._verdict_event.set()

    def _evaluate_window(self, rows: Sequence[_Row]) -> None:
        arr = np.asarray(rows, np.float32)
        c_scores, c_means = arr[:, 0], arr[:, 1]
        s_scores, s_means = arr[:, 2], arr[:, 3]
        labels, weights = arr[:, 4], arr[:, 5]
        res_c = self._evaluator.evaluate_window(c_scores, labels, weights)
        res_s = self._evaluator.evaluate_window(s_scores, labels, weights)
        c_val, s_val = res_c.primary_value, res_s.primary_value
        for cm, sm, lb in zip(c_means, s_means, labels):
            telemetry.METRICS.observe(
                "shadow_score_drift", abs(float(cm) - float(sm))
            )
            telemetry.METRICS.observe(
                "shadow_calibration_champion", abs(float(cm) - float(lb))
            )
            telemetry.METRICS.observe(
                "shadow_calibration_challenger", abs(float(sm) - float(lb))
            )
        telemetry.METRICS.increment("shadow_windows")
        reg = regression(self._evaluator.primary, s_val, c_val)
        healthy = reg <= self._regression_tol
        with self._cond:
            self._history.append(healthy)
            self._last_metrics = (c_val, s_val)
            window_index = len(self._history)
        telemetry.emit_event(
            "shadow_window",
            champion=self._champion,
            challenger=self._challenger,
            window=window_index,
            rows=len(rows),
            champion_metric=c_val,
            challenger_metric=s_val,
            evaluator=str(self._evaluator.primary),
            healthy=healthy,
        )
        decision = self._check_verdict()
        if decision is None:
            return
        with self._cond:
            self._verdict = decision
        telemetry.emit_event(
            "shadow_verdict",
            champion=self._champion,
            challenger=self._challenger,
            decision=decision,
            windows=window_index,
            champion_metric=c_val,
            challenger_metric=s_val,
            evaluator=str(self._evaluator.primary),
            reason=(
                f"last {self._min_windows} window(s) all "
                f"{'healthy' if decision == 'promote' else 'regressed'} "
                f"(tol={self._regression_tol}, "
                f"evaluator={self._evaluator.primary})"
            ),
        )
        if decision == "reject":
            # Rejection always actuates: a regressed challenger must not
            # keep riding the fleet while a caller deliberates.
            self._teardown_rejected(
                f"regression verdict after {window_index} window(s)"
            )
        elif self._auto_actuate:
            self.promote(raise_on_failure=False)
        else:
            with self._cond:
                self._status = "promote_ready"
        self._verdict_event.set()

    def _check_verdict(self) -> Optional[str]:
        with self._cond:
            if self._cooldown_s > 0.0 and (
                time.monotonic() - self._started < self._cooldown_s
            ):
                return None
            if len(self._history) < self._min_windows:
                return None
            recent = self._history[-self._min_windows :]
        if all(recent):
            return "promote"
        if not any(recent):
            return "reject"
        return None  # mixed evidence: the hysteresis band holds

    def drain(self, timeout_s: float = 60.0) -> Optional[str]:
        """Wait (bounded) for the evaluation worker to digest every
        already-joined FULL window — and, when that produces a verdict,
        for its actuation to finish. Returns the verdict (or None if the
        backlog drained without one). A fast replay outruns the async
        worker (the first metric compile alone can cost more than the
        whole replay), so callers that want `summary()` to reflect
        everything they fed in call this first; with no verdict pending
        it returns as soon as fewer than `window_size` joined rows
        remain, never the full timeout."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._verdict_event.is_set():
                break
            with self._cond:
                idle = not self._evaluating and (
                    self._closed
                    or self._status != "observing"
                    or len(self._rows) < self._window_size
                )
            if idle:
                break
            time.sleep(0.02)
        with self._cond:
            if self._error is not None:
                raise RuntimeError(
                    "shadow decision worker died"
                ) from self._error
            return self._verdict

    def wait_for_verdict(self, timeout_s: Optional[float] = None) -> Optional[str]:
        """Block until a verdict fires (or the worker dies). Returns the
        decision ("promote" | "reject") or None on timeout."""
        self._verdict_event.wait(timeout=timeout_s)
        with self._cond:
            if self._error is not None:
                raise RuntimeError(
                    "shadow decision worker died"
                ) from self._error
            return self._verdict

    # ----------------------------------------------------------- actuators

    def promote(self, *, raise_on_failure: bool = True) -> Optional[Dict[str, object]]:
        """Flip the challenger to champion: drain + retire the shadow
        tenant (keeping its warm bundle), then commit that bundle into
        the champion's engine through the BundleManager's atomic
        stage->pre-warm->commit->drain generation flip. A failure at any
        point — including an armed `shadow_promote` fault that exhausts
        its retries — leaves the champion serving its OLD generation
        bitwise and tears the challenger down (a failed promotion is a
        rollback, counted and journaled as one)."""
        with self._cond:
            if self._status not in ("observing", "promote_ready"):
                raise RuntimeError(
                    f"cannot promote from status {self._status!r}"
                )
            self._status = "promoting"
        champ_engine = self._registry.tenant(self._champion).engine
        chall_bundle = self._registry.tenant(self._challenger).engine._state.bundle
        try:
            # Retire the shadow tenant FIRST (drains mirrored in-flight
            # work); its bundle stays alive and warm for the flip.
            self._registry.remove(self._challenger, release_bundle=False)
            # Transient shadow_promote faults get the bounded retry
            # policy; exhaustion aborts BEFORE the swap ever stages.
            faults.retry(
                lambda: faults.fault_point("shadow_promote"),
                label="shadow promotion",
            )
            info = champ_engine.bundle_manager.swap(
                chall_bundle, release_old=True
            )
        except BaseException as exc:  # noqa: BLE001 - champion keeps serving
            if not chall_bundle.released:
                try:
                    chall_bundle.release()
                except Exception:  # noqa: BLE001 - teardown best-effort
                    pass
            faults.COUNTERS.increment("shadow_rollbacks")
            telemetry.emit_event(
                "shadow_rollback",
                champion=self._champion,
                challenger=self._challenger,
                reason=f"promotion failed: {exc}",
            )
            with self._cond:
                self._status = "rejected"
            logger.warning(
                "shadow promotion of %r failed; champion %r keeps serving "
                "its old generation: %s",
                self._challenger,
                self._champion,
                exc,
            )
            if raise_on_failure:
                raise
            return None
        telemetry.METRICS.increment("shadow_promotions")
        telemetry.emit_event(
            "shadow_promote",
            champion=self._champion,
            challenger=self._challenger,
            version=info["version"],
        )
        with self._cond:
            self._status = "promoted"
            self._promoted_version = int(info["version"])
        logger.info(
            "shadow challenger %r promoted to champion %r (generation %s)",
            self._challenger,
            self._champion,
            info["version"],
        )
        return info

    def _teardown_rejected(self, reason: str) -> None:
        try:
            self._registry.remove(self._challenger, release_bundle=True)
        except KeyError:
            pass  # already retired
        faults.COUNTERS.increment("shadow_rollbacks")
        telemetry.emit_event(
            "shadow_rollback",
            champion=self._champion,
            challenger=self._challenger,
            reason=reason,
        )
        with self._cond:
            self._status = "rejected"
        logger.info(
            "shadow challenger %r rejected and torn down (%s); champion "
            "%r unaffected",
            self._challenger,
            reason,
            self._champion,
        )

    # ------------------------------------------------------------ lifecycle

    def summary(self) -> Dict[str, object]:
        """The serving-summary shadow block — zips SHADOW_BLOCK_KEYS
        exactly, every key always present so absence is loud."""
        champ_engine = self._registry.tenant(self._champion).engine
        drift = telemetry.METRICS.histogram("shadow_score_drift")
        with self._cond:
            c_val, s_val = self._last_metrics
            block = dict(
                zip(
                    SHADOW_BLOCK_KEYS,
                    (
                        self._champion,
                        self._challenger,
                        self._status,
                        len(self._history),
                        self._mirrored,
                        self._mirror_failures,
                        self._label_join_failures,
                        c_val,
                        s_val,
                        str(self._evaluator.primary),
                        None if drift is None else drift.quantile(0.5),
                        int(champ_engine._state.version),
                    ),
                )
            )
        assert set(block) == set(SHADOW_BLOCK_KEYS)
        return block

    def close(self) -> None:
        """Stop the decision loop and tear down an un-promoted shadow
        tenant WITHOUT a verdict (no rollback counter, no verdict event —
        close is the no-opinion exit; reject/promote speak through their
        own events). Idempotent; joins the worker."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._worker.join(timeout=30.0)
        still_admitted = True
        try:
            self._registry.tenant(self._challenger)
        except KeyError:
            still_admitted = False
        if still_admitted:
            try:
                self._registry.remove(self._challenger, release_bundle=True)
            except KeyError:
                pass
        with self._cond:
            if self._status == "observing":
                self._status = "closed"

    def __enter__(self) -> "ShadowController":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

"""Deadline micro-batching: coalesce single requests into engine batches.

Latency/throughput tradeoff of every online scorer: dispatching each
request alone wastes the accelerator (a bucket-1 program per request);
waiting for a full batch starves low-traffic periods. The batcher flushes
the pending queue when EITHER `max_batch` requests are waiting (throughput
bound) or the OLDEST pending request has waited `max_wait_ms`
(tail-latency bound) — the standard deadline policy.

Production-traffic hardening (serving/lifecycle.py types):

* ADMISSION CONTROL — the pending queue is bounded by `max_pending`; a
  submit against a full queue is shed with a typed `Overloaded` rejection
  (counted per batcher and in COUNTERS["serving_shed_requests"]), never an
  unbounded backlog. Closed-loop clients (replay drivers, `score()`)
  can pass `block=True` to wait for space instead — backpressure, bounded
  by the flush loop's progress. The `admit` fault site fires per submit:
  an armed fault sheds deterministically (chaos-testable admission).
* DEADLINE ENFORCEMENT — each request carries a deadline budget
  (`ScoreRequest.deadline_ms`, falling back to the batcher's
  `default_deadline_ms`). A request still queued past its budget is failed
  with `DeadlineExceeded` at batch-assembly time, BEFORE wasting a device
  slot — an expired request is never co-batched. The budget check
  subtracts a decaying max of recent batch service time: a request whose
  answer could only arrive past its deadline is failed up front too, so
  admitted-request tail latency stays under the configured deadline even
  at sustained overload (a stale estimate decays on dispatch-less expiry
  rounds, so a one-off spike can never wedge the queue shut).
* CIRCUIT ROUTING — the engine's breaker counts consecutive device-class
  failures that survived the bounded retry policy; once OPEN, batches are
  routed to the engine's fixed-effect-only tier (bitwise-equal to FE-only
  GameTransformer output) instead of failing, with half-open probing to
  recover the full path.
* FLUSH-THREAD DEATH — an exception escaping the flush loop no longer
  leaves every pending and future submit() hanging: all pending futures
  are failed with the error, the batcher is marked unhealthy (a
  `BatcherUnhealthy` on later submits, a permanent DEGRADED reason on the
  engine's health machine), and `close()` stays joinable.

Failure domain (utils/faults.py): the engine's `lookup`/`score` fault
points surface transient failures mid-batch. The batcher DEGRADES instead
of dying: ANY failed batch re-dispatches per request — transient failures
get the bounded retry policy; a non-transient error (one malformed
request poisoning the pack) fails only the offending request's future,
never its co-batched neighbors. One poisoned buffer or transient device
error costs latency, not availability — and because the engine's kernels
are batch-size invariant, the degraded answers are bitwise-identical to
the batched ones (tests/test_serving.py asserts this under injected
faults). Each degradation increments the per-batcher `degraded_batches`
metric and the process-wide COUNTERS["serving_degraded_batches"], zero on
clean runs by construction.

Observability: per-request wall latency is recorded at completion into a
BOUNDED tracker (utils/telemetry.LatencyStats — a mergeable fixed-bucket
histogram plus a small reservoir for exact small-run percentiles; the
former unbounded sample list grew without bound under sustained traffic,
ISSUE 11 satellite). `metrics()` reports p50/p95/p99 (exact while the
run fits the reservoir, within one log-bucket width beyond it), qps,
shed/deadline-miss/fe-only counts, and the engine's counters in one
snapshot — the serving counterpart of PR 1's fit_timing stage breakdown.
Queue wait, batch size and latency also feed the process metrics
registry, and each dispatched batch opens a `serving_batch` trace span
carrying queue-wait and deadline-budget attribution.

Generation changes are transparent here: a bundle hot-swap OR a live
mesh reshard (serving/reshard.py) flips the engine's state between
batches — a batch claimed before the flip scores (and drains) on the
generation it started on, one claimed after scores on the new one, and
because both generations answer bitwise-identically the batcher never
has to know a flip happened. During a reshard's pre-warm the engine's
device mutex briefly serializes dispatches; the added queue wait rides
the same decaying service-tail estimate deadline enforcement already
uses.

The flush thread is named `photon-serving-flush` and MUST be joined via
`close()` (or the engine's close, or context-manager exit) — the test
suite's thread-leak fixture asserts no such thread survives a test.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from concurrent.futures import Future
from typing import Deque, Dict, Iterable, List, Optional, Tuple

from photon_ml_tpu.serving.bundle import ScoreRequest
from photon_ml_tpu.serving.engine import ScoreResult, ServingEngine
from photon_ml_tpu.serving.lifecycle import (
    BatcherUnhealthy,
    DeadlineExceeded,
    Overloaded,
)
from photon_ml_tpu.utils import faults, telemetry

logger = logging.getLogger(__name__)

# One queued request: (request, future, submit time, absolute expiry or None).
_Pending = Tuple[ScoreRequest, Future, float, Optional[float]]


class MicroBatcher:
    """Bounded queue + flush thread in front of a ServingEngine.

    `submit()` returns a Future[ScoreResult]; `score()` is the blocking
    convenience (backpressured, never shed). Use as a context manager or
    call `close()` — close drains the queue (pending requests are still
    answered) and joins the flush thread.
    """

    def __init__(
        self,
        engine: ServingEngine,
        *,
        max_batch: Optional[int] = None,
        max_wait_ms: Optional[float] = None,
        max_pending: Optional[int] = None,
        default_deadline_ms: Optional[float] = None,
        latency_reservoir: int = 4096,
        thread_name: Optional[str] = None,
        metric_labels: Optional[Dict[str, str]] = None,
    ):
        # The partial-batch flush wait is a PLANNED quantity (ISSUE 14):
        # an explicit argument wins; None defers to the installed plan's
        # serving_max_wait_ms (observed-latency rule) and falls back to
        # the pre-planner default.
        if max_wait_ms is None:
            from photon_ml_tpu import planner

            max_wait_ms = float(planner.planned_value("serving_max_wait_ms"))
        self.engine = engine
        self.max_batch = int(
            engine.max_batch if max_batch is None else max_batch
        )
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {self.max_batch}")
        if self.max_batch > engine.max_batch:
            raise ValueError(
                f"max_batch {self.max_batch} exceeds the engine's declared "
                f"bucket ceiling {engine.max_batch} (would recompile)"
            )
        # Admission bound: a few batches' worth by default — deep enough to
        # ride a burst, shallow enough that queueing delay stays within a
        # small multiple of the batch service time (shed, don't backlog).
        self.max_pending = int(
            max(4 * self.max_batch, 64) if max_pending is None else max_pending
        )
        if self.max_pending < 1:
            raise ValueError(
                f"max_pending must be >= 1, got {self.max_pending}"
            )
        self.default_deadline_ms = (
            None if default_deadline_ms is None else float(default_deadline_ms)
        )
        self.max_wait_s = float(max_wait_ms) / 1e3
        self._pending: Deque[_Pending] = collections.deque()
        self._cv = threading.Condition()
        self._stop = False
        self._unhealthy: Optional[BaseException] = None
        # Bounded latency accounting (ISSUE 11 satellite): the mergeable
        # fixed-bucket histogram + a `latency_reservoir`-sample reservoir
        # replace the unbounded per-request list — memory stays O(1) in
        # request count under sustained traffic, percentiles stay exact
        # for small runs and within one bucket width beyond.
        self._latency = telemetry.LatencyStats(reservoir=latency_reservoir)
        # Per-batcher batch-size percentiles: the planner's bucket-
        # ceiling evidence (the process-global serving_batch_size
        # histogram mixes every batcher in the process).
        self._batch_sizes = telemetry.LatencyStats(reservoir=latency_reservoir)
        self._completed = 0
        self._failed = 0
        self._shed = 0
        self._deadline_missed = 0
        # Decaying MAX of batch service time (claim -> answers), subtracted
        # from a request's remaining budget at claim: a request that cannot
        # FINISH inside its deadline is failed up front, not co-batched
        # into an answer that arrives past its budget anyway. A decaying
        # max (not a mean) because the contract is about the admitted
        # TAIL: the p99 request pays the p99 service time.
        self._service_tail_s = 0.0
        self._fe_only = 0  # requests answered by the circuit-open FE tier
        self._degraded = 0  # THIS batcher's degraded batches (the global
        # faults counter aggregates process-wide and would cross-contaminate
        # metrics when several engines serve in one process)
        self._t_first_submit: Optional[float] = None
        self._t_last_done: Optional[float] = None
        # Per-tenant attribution (ISSUE 15): a batcher serving one tenant
        # of a multi-tenant registry carries that tenant's metric labels
        # — every process-global robustness counter it bumps (shed,
        # deadline, degraded, FE-only, flush death) lands in both the
        # aggregate and the tenant's labeled sub-count, whatever thread
        # fires it. None (the single-tenant default) keeps increments
        # unlabeled, bit-for-bit the pre-tenancy behavior.
        self._metric_labels = (
            tuple(sorted((k, str(v)) for k, v in metric_labels.items()))
            if metric_labels
            else None
        )
        self._thread = threading.Thread(
            target=self._flush_loop,
            name=thread_name or "photon-serving-flush",
            daemon=True,
        )
        self._thread.start()

    # ------------------------------------------------------------ lifecycle

    @property
    def closed(self) -> bool:
        return self._stop

    @property
    def healthy(self) -> bool:
        return self._unhealthy is None

    def close(self) -> None:
        """Drain pending requests, stop and JOIN the flush thread."""
        with self._cv:
            if self._stop:
                return
            self._stop = True
            self._cv.notify_all()
        self._thread.join()

    def __enter__(self) -> "MicroBatcher":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    # -------------------------------------------------------------- scoring

    def submit(
        self,
        request: ScoreRequest,
        *,
        block: bool = False,
        deadline_ms: Optional[float] = None,
    ) -> "Future[ScoreResult]":
        """Enqueue one request. Raises `Overloaded` when the bounded queue
        is full (`block=True` waits for space instead — replay/closed-loop
        backpressure), `BatcherUnhealthy` after a flush-thread death,
        RuntimeError after close. `deadline_ms` overrides the request's
        own budget and the batcher default."""
        fut: "Future[ScoreResult]" = Future()
        now = time.monotonic()
        budget_ms = (
            deadline_ms
            if deadline_ms is not None
            else (
                request.deadline_ms
                if request.deadline_ms is not None
                else self.default_deadline_ms
            )
        )
        expiry = None if budget_ms is None else now + budget_ms / 1e3
        with self._cv:
            first_pass = True
            while True:
                if self._stop:
                    raise RuntimeError("MicroBatcher is closed")
                if self._unhealthy is not None:
                    raise BatcherUnhealthy(
                        f"flush thread died: {self._unhealthy!r}"
                    ) from self._unhealthy
                if first_pass:
                    # AFTER the closed/unhealthy checks: an armed admit
                    # fault simulates admission failing for a live batcher
                    # — it must never mask the typed closed/unhealthy
                    # rejections (nor count sheds for requests that would
                    # have been refused regardless). Once per submit. The
                    # engine's per-tenant injection gate applies: a chaos
                    # drill arming `admit` must target one tenant's
                    # admissions, not every batcher in the process.
                    first_pass = False
                    try:
                        if getattr(self.engine, "inject_faults", True):
                            faults.fault_point("admit")
                    except faults.InjectedFault as exc:
                        self._shed += 1
                        faults.COUNTERS.increment(
                            "serving_shed_requests",
                            labels=self._metric_labels,
                        )
                        raise Overloaded(
                            f"admission fault injected: {exc}"
                        ) from exc
                if len(self._pending) < self.max_pending:
                    break
                if not block:
                    self._shed += 1
                    faults.COUNTERS.increment(
                        "serving_shed_requests", labels=self._metric_labels
                    )
                    raise Overloaded(
                        f"pending queue full ({self.max_pending} requests); "
                        "shed by admission control"
                    )
                self._cv.wait()
            if self._t_first_submit is None:
                self._t_first_submit = now
            self._pending.append((request, fut, now, expiry))
            self._cv.notify_all()
        return fut

    def score(self, request: ScoreRequest) -> ScoreResult:
        return self.submit(request, block=True).result()

    def score_all(self, requests: Iterable[ScoreRequest]) -> List[ScoreResult]:
        """Replay helper: submit a stream (backpressured, never shed), wait
        for every result in order."""
        futures = [self.submit(r, block=True) for r in requests]
        return [f.result() for f in futures]

    # ----------------------------------------------------------- flush loop

    def _flush_loop(self) -> None:
        # Satellite hardening: an exception escaping the loop used to kill
        # the thread silently — every pending and future submit() then hung
        # forever. Now: fail ALL pending futures with the error, mark the
        # batcher unhealthy (typed rejections on later submits + a
        # permanent DEGRADED reason on the engine), stay joinable.
        try:
            if self._metric_labels is not None:
                # The tenant attribution scope lives for the thread's
                # whole life: everything the dispatch path fires from
                # HERE — including watchdog guards, whose trips are
                # recorded by the MONITOR thread with the labels captured
                # at arm time — lands in this tenant's sub-counts.
                with telemetry.metric_label_scope(
                    **dict(self._metric_labels)
                ):
                    self._flush_loop_inner()
                return
            self._flush_loop_inner()
        except BaseException as exc:  # noqa: BLE001 - terminal thread guard
            logger.error("serving flush thread died: %r", exc)
            faults.COUNTERS.increment(
                "serving_flush_thread_failures", labels=self._metric_labels
            )
            with self._cv:
                self._unhealthy = exc
                doomed = list(self._pending)
                self._pending.clear()
                self._failed += len(doomed)
                self._cv.notify_all()  # wake blocked submitters
            for _, fut, _, _ in doomed:
                if fut.set_running_or_notify_cancel():
                    fut.set_exception(exc)
            try:
                self.engine._on_batcher_unhealthy(exc)
            except Exception:  # noqa: BLE001 - health is best-effort here
                pass

    def _flush_loop_inner(self) -> None:
        while True:
            with self._cv:
                while not self._stop and not self._ripe_locked():
                    self._cv.wait(timeout=self._wait_timeout_locked())
                if self._stop and not self._pending:
                    return
                # Transition each future to RUNNING as it is claimed; a
                # client-cancelled future is dropped HERE — once running it
                # can no longer be cancelled, so the completion paths'
                # set_result/set_exception cannot race a cancel and blow
                # InvalidStateError through the flush thread. Requests past
                # their deadline budget are failed HERE, before a device
                # slot is assembled for them — never co-batched.
                batch: List[_Pending] = []
                expired: List[Future] = []
                now = time.monotonic()
                horizon = now + self._service_tail_s  # when answers would land
                while len(batch) < self.max_batch and self._pending:
                    item = self._pending.popleft()
                    if item[3] is not None and horizon >= item[3]:
                        if item[1].set_running_or_notify_cancel():
                            expired.append(item[1])
                        continue
                    if item[1].set_running_or_notify_cancel():
                        batch.append(item)
                telemetry.METRICS.set_gauge(
                    "serving_pending_depth", len(self._pending)
                )
                if expired:
                    self._deadline_missed += len(expired)
                    self._failed += len(expired)
                    if not batch:
                        # Everything expired and nothing dispatched: a
                        # stale/spiked service-tail estimate could otherwise
                        # pre-fail every short-budget request FOREVER (no
                        # dispatch -> no new measurement). Decay it so the
                        # batcher re-probes the true service time.
                        self._service_tail_s *= 0.5
                self._cv.notify_all()  # queue space freed: wake submitters
            for fut in expired:
                faults.COUNTERS.increment(
                    "serving_deadline_misses", labels=self._metric_labels
                )
                fut.set_exception(
                    DeadlineExceeded(
                        "request expired in queue before batch assembly"
                    )
                )
            if batch:
                try:
                    self._dispatch(batch)
                except BaseException as exc:
                    # The claimed batch is no longer in _pending — fail its
                    # futures HERE before the terminal guard handles the
                    # queued remainder, or they would hang unanswered.
                    # Mark unhealthy FIRST: once any client observes its
                    # future fail with the thread-death error, later
                    # submits must already be typed-rejected — not race
                    # the terminal guard a few frames up the unwind.
                    with self._cv:
                        self._unhealthy = exc
                        self._failed += sum(
                            1 for _, f, _, _ in batch if not f.done()
                        )
                    for _, fut, _, _ in batch:
                        if not fut.done():
                            fut.set_exception(exc)
                    raise

    def _ripe_locked(self) -> bool:
        if not self._pending:
            return False
        if len(self._pending) >= self.max_batch:
            return True
        front = self._pending[0]
        now = time.monotonic()
        if front[3] is not None and now >= front[3]:
            return True  # expired head: claim promptly to fail it
        return (now - front[2]) >= self.max_wait_s

    def _wait_timeout_locked(self) -> Optional[float]:
        if not self._pending:
            return None  # sleep until a submit/close notifies
        front = self._pending[0]
        wake = front[2] + self.max_wait_s
        if front[3] is not None:
            wake = min(wake, front[3])
        return max(0.0, wake - time.monotonic())

    def _update_service_tail(self, wall_s: float) -> None:
        with self._cv:
            self._service_tail_s = max(wall_s, 0.9 * self._service_tail_s)

    def _dispatch(self, batch: List[_Pending]) -> None:
        # Request-path telemetry (ISSUE 11): queue wait per claimed
        # request, batch size, and one `serving_batch` span carrying the
        # queue-wait and remaining-deadline-budget attribution — the
        # engine's serve_pack/serve_lookup/serve_score stage spans nest
        # under it, so a traced replay shows queue-wait -> assembly ->
        # device dispatch -> harvest per batch.
        now = time.monotonic()
        waits_ms = [(now - t0) * 1e3 for _, _, t0, _ in batch]
        for w in waits_ms:
            telemetry.METRICS.observe("serving_queue_wait_ms", w)
        telemetry.METRICS.observe("serving_batch_size", len(batch))
        self._batch_sizes.record(float(len(batch)))
        budgets = [(e - now) * 1e3 for _, _, _, e in batch if e is not None]
        with telemetry.span(
            "serving_batch",
            size=len(batch),
            queue_wait_ms_max=round(max(waits_ms), 3),
            deadline_budget_ms_min=(
                round(min(budgets), 3) if budgets else None
            ),
        ):
            self._dispatch_batch(batch)

    def _dispatch_batch(self, batch: List[_Pending]) -> None:
        requests = [r for r, _, _, _ in batch]
        t_d = time.monotonic()
        breaker = self.engine.breaker
        permit = breaker.acquire()
        if permit is None:
            # Circuit OPEN (and no probe due): degrade the whole batch to
            # the fixed-effect-only tier — answers, not errors.
            self._dispatch_fe_only(batch)
            return
        try:
            results = self.engine.score_batch(requests)
        except faults.DeviceHang:
            # A batch-level watchdog trip is UNAMBIGUOUS device evidence
            # (unlike a poisoned pack): feed the breaker directly and
            # answer the WHOLE batch FE-only — re-probing a wedged device
            # once per co-batched request would stall the flush thread
            # for many watchdog periods while the queue blows deadlines.
            breaker.on_failure(permit)
            faults.COUNTERS.increment(
                "serving_degraded_batches", labels=self._metric_labels
            )
            with self._cv:
                self._degraded += 1
            logger.warning(
                "batch of %d hit the dispatch watchdog; answering FE-only",
                len(requests),
            )
            self._dispatch_fe_only(batch)
            return
        except BaseException as exc:  # noqa: BLE001 - isolated below
            # ANY mid-batch failure degrades to per-request dispatch:
            # transient faults (injected, device blip) get the bounded
            # retry policy inside the fallback, while a non-transient error
            # (one malformed request poisoning the pack) re-raises
            # immediately there and fails ONLY the offending request's
            # future — co-batched healthy requests still get answers.
            # Batch-size-invariant kernels keep the degraded scores
            # bitwise-identical to what the batch would have produced. The
            # batch-level failure is INCONCLUSIVE for the breaker (one bad
            # request poisons a pack too): the permit is returned and each
            # per-request outcome is judged individually.
            breaker.on_abandon(permit)
            faults.COUNTERS.increment(
                "serving_degraded_batches", labels=self._metric_labels
            )
            with self._cv:
                self._degraded += 1
            logger.warning(
                "batch of %d degraded to per-request dispatch: %s",
                len(requests),
                exc,
            )
            self._dispatch_degraded(batch)
            return
        breaker.on_success(permit)
        now = time.monotonic()
        self._update_service_tail(now - t_d)
        for (_, fut, t0, _), res in zip(batch, results):
            self._complete(fut, res, now - t0)

    def _dispatch_degraded(self, batch: List[_Pending]) -> None:
        breaker = self.engine.breaker
        for req, fut, t0, _ in batch:
            permit = breaker.acquire()
            if permit is None:
                # The circuit opened mid-loop (this batch supplied the last
                # consecutive failures): remaining requests get FE-only
                # answers instead of piling more errors on a dead device.
                self._dispatch_fe_only([(req, fut, t0, None)])
                continue
            try:
                res = faults.retry(
                    lambda req=req: self.engine.score_batch([req])[0],
                    label="serving per-request fallback",
                )
            except BaseException as exc:  # noqa: BLE001 - surfaced via future
                if faults.is_device_error(exc):
                    # Survived the bounded retry policy and still looks
                    # like the device: evidence toward opening the circuit.
                    breaker.on_failure(permit)
                else:
                    breaker.on_abandon(permit)  # the request's fault, not the device's
                if isinstance(exc, faults.DeviceHang):
                    # A watchdog-tripped dispatch that outlived its bounded
                    # retries still ANSWERS: the hang contract (ISSUE 10)
                    # is a DEGRADED health transition + FE-only answers,
                    # never a stuck-or-failed future — the FE-only tier
                    # has no watchdog (it must work while the full path is
                    # wedged).
                    self._dispatch_fe_only([(req, fut, t0, None)])
                    continue
                with self._cv:
                    self._failed += 1
                fut.set_exception(exc)
                continue
            breaker.on_success(permit)
            self._complete(fut, res, time.monotonic() - t0)

    def _dispatch_fe_only(self, batch: List[_Pending]) -> None:
        """Circuit-open tier: fixed-effect-only answers via the pinned
        zero-row path (no fault sites fire — this must work while the full
        path is down)."""
        requests = [r for r, _, _, _ in batch]
        try:
            results = self.engine.score_batch_fe_only(requests)
        except BaseException as exc:  # noqa: BLE001 - surfaced via futures
            logger.error("FE-only degradation tier failed: %r", exc)
            with self._cv:
                self._failed += len(batch)
            for _, fut, _, _ in batch:
                fut.set_exception(exc)
            return
        with self._cv:
            self._fe_only += len(batch)
        faults.COUNTERS.increment(
            "serving_fe_only_requests", len(batch), labels=self._metric_labels
        )
        now = time.monotonic()
        for (_, fut, t0, _), res in zip(batch, results):
            self._complete(fut, res, now - t0)

    def _complete(self, fut: Future, res: ScoreResult, wall_s: float) -> None:
        self._latency.record(wall_s * 1e3)
        telemetry.METRICS.observe("serving_latency_ms", wall_s * 1e3)
        with self._cv:
            self._completed += 1
            self._t_last_done = time.monotonic()
        fut.set_result(res)

    # -------------------------------------------------------------- metrics

    def metrics(self) -> Dict[str, object]:
        """One snapshot: request latency percentiles + qps + admission/
        deadline/circuit accounting + the engine's counters. Keys are the
        serving_online bench contract."""
        with self._cv:
            completed = self._completed
            failed = self._failed
            degraded = self._degraded
            shed = self._shed
            deadline_missed = self._deadline_missed
            fe_only = self._fe_only
            unhealthy = self._unhealthy
            t0, t1 = self._t_first_submit, self._t_last_done
        out: Dict[str, object] = {
            "completed": completed,
            "failed": failed,
            "degraded_batches": degraded,
            "shed": shed,
            "deadline_missed": deadline_missed,
            "fe_only_answers": fe_only,
            "max_pending": self.max_pending,
            "unhealthy": None if unhealthy is None else repr(unhealthy),
        }
        if self._latency.count:
            # Exact while the run still fits the reservoir; histogram
            # quantile (one log-bucket accuracy) under sustained traffic.
            out.update(
                p50_ms=round(float(self._latency.percentile(50.0)), 4),
                p95_ms=round(float(self._latency.percentile(95.0)), 4),
                p99_ms=round(float(self._latency.percentile(99.0)), 4),
            )
        else:
            out.update(p50_ms=None, p95_ms=None, p99_ms=None)
        # The observed-batch-size percentile the planner's serving bucket
        # rule consumes (serve profiles carry metrics(), so this is the
        # rule's REAL production evidence, not a fixture-only key).
        out["batch_size_p95"] = (
            round(float(self._batch_sizes.percentile(95.0)), 2)
            if self._batch_sizes.count
            else None
        )
        wall = (t1 - t0) if (t0 is not None and t1 is not None and t1 > t0) else 0.0
        out["qps"] = round(completed / wall, 1) if wall > 0 else None
        out.update(self.engine.metrics())
        return out

"""Deadline micro-batching: coalesce single requests into engine batches.

Latency/throughput tradeoff of every online scorer: dispatching each
request alone wastes the accelerator (a bucket-1 program per request);
waiting for a full batch starves low-traffic periods. The batcher flushes
the pending queue when EITHER `max_batch` requests are waiting (throughput
bound) or the OLDEST pending request has waited `max_wait_ms`
(tail-latency bound) — the standard deadline policy.

Failure domain (utils/faults.py): the engine's `lookup`/`score` fault
points surface transient failures mid-batch. The batcher DEGRADES instead
of dying: ANY failed batch re-dispatches per request — transient failures
get the bounded retry policy; a non-transient error (one malformed
request poisoning the pack) fails only the offending request's future,
never its co-batched neighbors. One poisoned buffer or transient device
error costs latency, not availability — and because the engine's kernels
are batch-size invariant, the degraded answers are bitwise-identical to
the batched ones (tests/test_serving.py asserts this under injected
faults). Each degradation increments the per-batcher `degraded_batches`
metric and the process-wide COUNTERS["serving_degraded_batches"], zero on
clean runs by construction.

Observability: per-request wall latency is recorded at completion;
`metrics()` reports p50/p95/p99, qps, and the engine's counters (cold-start
fraction, padding waste, recompiles) in one snapshot — the serving
counterpart of PR 1's fit_timing stage breakdown.

The flush thread is named `photon-serving-flush` and MUST be joined via
`close()` (or the engine's close, or context-manager exit) — the test
suite's thread-leak fixture asserts no such thread survives a test.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from concurrent.futures import Future
from typing import Deque, Dict, Iterable, List, Optional, Tuple

import numpy as np

from photon_ml_tpu.serving.bundle import ScoreRequest
from photon_ml_tpu.serving.engine import ScoreResult, ServingEngine
from photon_ml_tpu.utils import faults

logger = logging.getLogger(__name__)


class MicroBatcher:
    """Queue + flush thread in front of a ServingEngine.

    `submit()` returns a Future[ScoreResult]; `score()` is the blocking
    convenience. Use as a context manager or call `close()` — close drains
    the queue (pending requests are still answered) and joins the flush
    thread.
    """

    def __init__(
        self,
        engine: ServingEngine,
        *,
        max_batch: Optional[int] = None,
        max_wait_ms: float = 2.0,
        latency_window: int = 1 << 20,
    ):
        self.engine = engine
        self.max_batch = int(
            engine.max_batch if max_batch is None else max_batch
        )
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {self.max_batch}")
        if self.max_batch > engine.max_batch:
            raise ValueError(
                f"max_batch {self.max_batch} exceeds the engine's declared "
                f"bucket ceiling {engine.max_batch} (would recompile)"
            )
        self.max_wait_s = float(max_wait_ms) / 1e3
        self._pending: Deque[Tuple[ScoreRequest, Future, float]] = (
            collections.deque()
        )
        self._cv = threading.Condition()
        self._stop = False
        self._latencies_ms: Deque[float] = collections.deque(maxlen=latency_window)
        self._completed = 0
        self._failed = 0
        self._degraded = 0  # THIS batcher's degraded batches (the global
        # faults counter aggregates process-wide and would cross-contaminate
        # metrics when several engines serve in one process)
        self._t_first_submit: Optional[float] = None
        self._t_last_done: Optional[float] = None
        self._thread = threading.Thread(
            target=self._flush_loop, name="photon-serving-flush", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------ lifecycle

    @property
    def closed(self) -> bool:
        return self._stop

    def close(self) -> None:
        """Drain pending requests, stop and JOIN the flush thread."""
        with self._cv:
            if self._stop:
                return
            self._stop = True
            self._cv.notify_all()
        self._thread.join()

    def __enter__(self) -> "MicroBatcher":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    # -------------------------------------------------------------- scoring

    def submit(self, request: ScoreRequest) -> "Future[ScoreResult]":
        fut: "Future[ScoreResult]" = Future()
        now = time.monotonic()
        with self._cv:
            if self._stop:
                raise RuntimeError("MicroBatcher is closed")
            if self._t_first_submit is None:
                self._t_first_submit = now
            self._pending.append((request, fut, now))
            self._cv.notify_all()
        return fut

    def score(self, request: ScoreRequest) -> ScoreResult:
        return self.submit(request).result()

    def score_all(self, requests: Iterable[ScoreRequest]) -> List[ScoreResult]:
        """Replay helper: submit a stream, wait for every result in order."""
        futures = [self.submit(r) for r in requests]
        return [f.result() for f in futures]

    # ----------------------------------------------------------- flush loop

    def _flush_loop(self) -> None:
        while True:
            with self._cv:
                while not self._stop and not self._ripe_locked():
                    self._cv.wait(timeout=self._wait_timeout_locked())
                if self._stop and not self._pending:
                    return
                # Transition each future to RUNNING as it is claimed; a
                # client-cancelled future is dropped HERE — once running it
                # can no longer be cancelled, so the completion paths'
                # set_result/set_exception cannot race a cancel and blow
                # InvalidStateError through the flush thread.
                batch = []
                while len(batch) < self.max_batch and self._pending:
                    item = self._pending.popleft()
                    if item[1].set_running_or_notify_cancel():
                        batch.append(item)
            if batch:
                self._dispatch(batch)

    def _ripe_locked(self) -> bool:
        if not self._pending:
            return False
        if len(self._pending) >= self.max_batch:
            return True
        oldest = self._pending[0][2]
        return (time.monotonic() - oldest) >= self.max_wait_s

    def _wait_timeout_locked(self) -> Optional[float]:
        if not self._pending:
            return None  # sleep until a submit/close notifies
        oldest = self._pending[0][2]
        return max(0.0, oldest + self.max_wait_s - time.monotonic())

    def _dispatch(self, batch: List[Tuple[ScoreRequest, Future, float]]) -> None:
        requests = [r for r, _, _ in batch]
        try:
            results = self.engine.score_batch(requests)
        except BaseException as exc:  # noqa: BLE001 - isolated below
            # ANY mid-batch failure degrades to per-request dispatch:
            # transient faults (injected, device blip) get the bounded
            # retry policy inside the fallback, while a non-transient error
            # (one malformed request poisoning the pack) re-raises
            # immediately there and fails ONLY the offending request's
            # future — co-batched healthy requests still get answers.
            # Batch-size-invariant kernels keep the degraded scores
            # bitwise-identical to what the batch would have produced.
            faults.COUNTERS.increment("serving_degraded_batches")
            with self._cv:
                self._degraded += 1
            logger.warning(
                "batch of %d degraded to per-request dispatch: %s",
                len(requests),
                exc,
            )
            self._dispatch_degraded(batch)
            return
        now = time.monotonic()
        for (_, fut, t0), res in zip(batch, results):
            self._complete(fut, res, now - t0)

    def _dispatch_degraded(
        self, batch: List[Tuple[ScoreRequest, Future, float]]
    ) -> None:
        for req, fut, t0 in batch:
            try:
                res = faults.retry(
                    lambda req=req: self.engine.score_batch([req])[0],
                    label="serving per-request fallback",
                )
            except BaseException as exc:  # noqa: BLE001 - surfaced via future
                with self._cv:
                    self._failed += 1
                fut.set_exception(exc)
                continue
            self._complete(fut, res, time.monotonic() - t0)

    def _complete(self, fut: Future, res: ScoreResult, wall_s: float) -> None:
        with self._cv:
            self._latencies_ms.append(wall_s * 1e3)
            self._completed += 1
            self._t_last_done = time.monotonic()
        fut.set_result(res)

    # -------------------------------------------------------------- metrics

    def metrics(self) -> Dict[str, object]:
        """One snapshot: request latency percentiles + qps + the engine's
        counters. Keys are the serving_online bench contract."""
        with self._cv:
            lat = np.asarray(self._latencies_ms, np.float64)
            completed = self._completed
            failed = self._failed
            degraded = self._degraded
            t0, t1 = self._t_first_submit, self._t_last_done
        out: Dict[str, object] = {
            "completed": completed,
            "failed": failed,
            "degraded_batches": degraded,
        }
        if lat.size:
            p50, p95, p99 = np.percentile(lat, [50.0, 95.0, 99.0])
            out.update(
                p50_ms=round(float(p50), 4),
                p95_ms=round(float(p95), 4),
                p99_ms=round(float(p99), 4),
            )
        else:
            out.update(p50_ms=None, p95_ms=None, p99_ms=None)
        wall = (t1 - t0) if (t0 is not None and t1 is not None and t1 > t0) else 0.0
        out["qps"] = round(completed / wall, 1) if wall > 0 else None
        out.update(self.engine.metrics())
        return out

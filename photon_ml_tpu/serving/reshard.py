"""Live mesh elasticity: reshard a READY serving engine under traffic.

PR 10 made the mesh shape survivable OFFLINE: elastic per-shard
checkpoints reassemble onto any device count, and a lost serving shard
degrades to pinned-zero answers until restaged. This module is the LIVE
half (the ROADMAP "Elastic mesh" item): take an engine from an n-shard to
an m-shard coefficient layout — shrink onto survivors after a device
loss, regrow when capacity returns, or re-place observed-hot rows —
without failing a single in-flight request. Spark gets this from dynamic
allocation + shuffle refetch (executors leave and join, lost map output
re-fetches); our pjit mesh has fixed program shapes, so elasticity is an
explicit generation flip:

  1. PLAN — `plan_reshard` computes the row-movement plan from the old
     and new shard maps: which contiguous row segments of each
     random-effect coefficient matrix land on a different device under
     the new layout. Only those rows need to cross the host<->device
     wire; the plan's moved_rows/moved_bytes are the honest accounting
     the journal records.
  2. STAGE — every new shard's row block uploads on its own
     `photon-reshard-stage<k>` worker under the `reshard_stage` fault
     site with bounded retries (PHOTON_RESHARD_RETRIES, counted in
     `reshard_retries`), DOUBLE-BUFFERED beside the live generation: the
     old bundle never stops serving while the new one stages.
  3. PRE-WARM — every bucket pjit program compiles against the new
     layout's parameter shapes/meshes before the flip, so live traffic
     never waits on XLA.
  4. FLIP — the `reshard_commit` fault site, then the same atomic
     `_commit_state` the BundleManager hot-swap uses: in-flight batches
     finish on the generation they started on, the drain waits them out,
     and only then is the old generation's device state dropped.

Any failure at any step ROLLS BACK: the flip never happened, the old
generation kept answering, the staged arrays drop their references,
`reshard_rollbacks` counts it, and the error propagates — zero failed
requests by construction (tests/test_elastic_mesh.py injects failures at
every step and proves it).

`plan_rebalance` / `rebalance` close the telemetry->placement loop: the
`TwoTierEntityStore`'s observed promotion stats name the rows the cold
tier keeps paying for; the rebalance stages a NEW store whose hot tier
preloads exactly those rows and flips it through the same
stage/warm/commit/rollback machinery. Bitwise-neutral by construction —
hot vs cold placement never changes an answer, only its cost.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.serving.bundle import (
    ServingBundle,
    ServingCoordinate,
    ShardHealth,
    TwoTierEntityStore,
)
from photon_ml_tpu.serving.lifecycle import SwapIncompatible
from photon_ml_tpu.utils import faults, telemetry
from photon_ml_tpu.utils.knobs import get_knob

logger = logging.getLogger(__name__)


def _reshard_policy():
    """Bounded retry for per-shard reshard staging: 1 +
    PHOTON_RESHARD_RETRIES attempts under the standard backoff."""
    return faults.bounded_policy(int(get_knob("PHOTON_RESHARD_RETRIES")))


# ------------------------------------------------------------------ planning


@dataclasses.dataclass(frozen=True)
class ShardSegment:
    """One contiguous row range of a NEW shard's block: rows
    [row_lo, row_hi) sourced from old shard `source_shard` (-1 = padding
    zeros that exist only in the new layout). `moves` says whether the
    segment's bytes must cross the wire — the old and new owning devices
    differ."""

    row_lo: int
    row_hi: int
    source_shard: int
    moves: bool

    @property
    def rows(self) -> int:
        return self.row_hi - self.row_lo


@dataclasses.dataclass(frozen=True)
class CoordinateReshardPlan:
    """The row-movement plan for ONE random-effect coordinate."""

    cid: str
    old_shards: int
    new_shards: int
    logical_rows: int  # E + 1 (the pinned zero row included)
    padded_rows: int  # rows in the NEW layout (mesh multiple)
    dim: int
    # Per NEW shard: the ordered segments tiling its row block.
    segments: Tuple[Tuple[ShardSegment, ...], ...]
    moved_rows: int
    moved_bytes: int
    # Observed per-OLD-shard request load (ShardHealth counters) — names
    # the overloaded shard for operators reading the plan.
    shard_loads: Tuple[int, ...]


@dataclasses.dataclass(frozen=True)
class ReshardPlan:
    old_shards: int
    new_shards: int
    coordinates: Tuple[CoordinateReshardPlan, ...]

    @property
    def moved_rows(self) -> int:
        return sum(c.moved_rows for c in self.coordinates)

    @property
    def moved_bytes(self) -> int:
        return sum(c.moved_bytes for c in self.coordinates)


def _coord_devices(coord: ServingCoordinate) -> List[object]:
    """The per-shard device list of a coordinate's CURRENT layout."""
    if coord.mesh is not None:
        return list(np.asarray(coord.mesh.devices).flat)
    try:
        return [sorted(coord.params.devices(), key=str)[0]]
    except Exception:  # noqa: BLE001 - uncommitted arrays: any device
        return [jax.devices()[0]]


def _mesh_devices(new_mesh) -> List[object]:
    if new_mesh is None:
        return [jax.devices()[0]]
    return list(np.asarray(new_mesh.devices).flat)


def plan_coordinate_reshard(
    coord: ServingCoordinate, new_mesh
) -> CoordinateReshardPlan:
    """Compute one coordinate's row movement from its current shard map to
    the `new_mesh` layout (None = replicated single-shard). A row MOVES
    when the device owning it under the new layout differs from the one
    holding it now; padding rows (at or past the logical E + 1) are zeros
    on both sides and never move."""
    from photon_ml_tpu.parallel.mesh import pad_rows_for_mesh

    if coord.shard_health is None:
        raise ValueError(
            f"coordinate {coord.cid!r} has no device-resident shard "
            "tracking (fixed-effect or two-tier coordinate)"
        )
    if getattr(coord, "tier", "f32") != "f32":
        # ISSUE 20: the movement plan assumes f32 row planes (4-byte
        # rows, params-only staging); a quantized plane carries scales
        # alongside. Restore full precision first, then reshard.
        raise ValueError(
            f"coordinate {coord.cid!r} is quantized to "
            f"{coord.tier!r} — resharding requires full-precision rows "
            "(restore_bundle_precision first)"
        )
    old_devs = _coord_devices(coord)
    new_devs = _mesh_devices(new_mesh)
    n_old, n_new = len(old_devs), len(new_devs)
    logical = coord.unseen_row + 1
    rows_per_old = coord.shard_health.rows_per_shard
    padded = (
        pad_rows_for_mesh(logical, new_mesh) if new_mesh is not None else logical
    )
    rows_per_new = padded // n_new
    old_rows_total = n_old * rows_per_old
    segments: List[Tuple[ShardSegment, ...]] = []
    moved = 0
    for k in range(n_new):
        lo, hi = k * rows_per_new, (k + 1) * rows_per_new
        segs: List[ShardSegment] = []
        r = lo
        while r < hi:
            if r >= old_rows_total:
                segs.append(ShardSegment(r, hi, -1, False))
                break
            j = r // rows_per_old
            seg_hi = min(hi, (j + 1) * rows_per_old, old_rows_total)
            moves = old_devs[j] is not new_devs[k]
            segs.append(ShardSegment(r, seg_hi, j, moves))
            if moves:
                # Only LOGICAL rows move; old-layout padding is zeros.
                moved += max(0, min(seg_hi, logical) - min(r, logical))
            r = seg_hi
        segments.append(tuple(segs))
    return CoordinateReshardPlan(
        cid=coord.cid,
        old_shards=n_old,
        new_shards=n_new,
        logical_rows=logical,
        padded_rows=padded,
        dim=coord.dim,
        segments=tuple(segments),
        moved_rows=moved,
        moved_bytes=moved * coord.dim * 4,
        shard_loads=coord.shard_health.loads,
    )


def plan_reshard(bundle: ServingBundle, new_mesh) -> ReshardPlan:
    """The bundle-wide row-movement plan: every shard-tracked
    random-effect coordinate (replicated or entity-sharded) replans onto
    `new_mesh`; fixed-effect planes and two-tier stores are not
    mesh-sharded and carry over untouched."""
    plans = [
        plan_coordinate_reshard(c, new_mesh)
        for c in bundle.coordinates.values()
        if c.is_random_effect and c.store is None and c.shard_health is not None
    ]
    if not plans:
        raise ValueError(
            "bundle has no shard-tracked random-effect coordinate to "
            "reshard (two-tier stores rebalance instead; see rebalance())"
        )
    return ReshardPlan(
        old_shards=max(p.old_shards for p in plans),
        new_shards=plans[0].new_shards,
        coordinates=tuple(plans),
    )


def plan_rebalance(
    coord: ServingCoordinate, *, min_promotions: Optional[int] = None
) -> Tuple[int, ...]:
    """Hot rows a rebalance should preload, from the two-tier store's
    observed promotion stats: rows promoted at least
    `min_promotions` times (PHOTON_REBALANCE_MIN_PROMOTIONS), hottest
    first, truncated to the hot-set capacity. Empty = nothing earned a
    move yet."""
    store = coord.store
    if store is None:
        raise ValueError(
            f"coordinate {coord.cid!r} has no two-tier store — only "
            "two-tier coordinates carry the promotion stats a rebalance "
            "plan reads"
        )
    floor = (
        int(get_knob("PHOTON_REBALANCE_MIN_PROMOTIONS"))
        if min_promotions is None
        else int(min_promotions)
    )
    stats = store.promotion_stats()
    hot = sorted(
        (r for r, n in stats.items() if n >= max(1, floor)),
        key=lambda r: (-stats[r], r),
    )
    return tuple(hot[: store.capacity])


# ------------------------------------------------------------- orchestrator


class MeshReshardOrchestrator:
    """Takes a live ServingEngine between mesh layouts with the
    BundleManager's staging/flip/rollback discipline extended to
    mesh-shape changes. One orchestrator per engine (created lazily via
    `engine.reshard_orchestrator`); reshard/rebalance serialize on the
    same mutex as bundle hot-swaps, so a push and a reshard order
    cleanly instead of racing the engine state."""

    def __init__(self, engine):
        self.engine = engine
        self._reshards = 0
        self._rebalances = 0
        self._deltas = 0
        self._rollbacks = 0

    # Public counters (read by engine.metrics()).
    @property
    def reshards(self) -> int:
        return self._reshards

    @property
    def rebalances(self) -> int:
        return self._rebalances

    @property
    def deltas(self) -> int:
        return self._deltas

    @property
    def rollbacks(self) -> int:
        return self._rollbacks

    # ------------------------------------------------------------- reshard

    def reshard(
        self,
        new_mesh=None,
        *,
        drain_timeout_s: float = 30.0,
        plan: Optional[ReshardPlan] = None,
    ) -> Dict[str, object]:
        """Move the engine's shard-tracked coefficient matrices onto
        `new_mesh` (None = replicated single-shard) under live traffic.

        Sequence: plan -> `reshard_start` journal event -> per-shard
        staged uploads of each new shard's row block (parallel
        `photon-reshard-stage<k>` workers, `reshard_stage` fault site,
        PHOTON_RESHARD_RETRIES bounded retries) double-buffered beside
        the serving generation -> compatibility check -> pre-warm every
        bucket program for the new layout -> `reshard_commit` fault site
        -> atomic flip -> drain in-flight batches -> retire the old
        generation. ANY failure before the flip rolls back: the old
        generation never stopped serving, staged arrays are dropped,
        `reshard_rollbacks` counts it, `reshard_rollback` journals it,
        and the error propagates."""
        engine = self.engine
        with engine.bundle_manager.mutex:
            old_state = engine._state
            old_bundle = old_state.bundle
            if plan is None:
                plan = plan_reshard(old_bundle, new_mesh)
            telemetry.emit_event(
                "reshard_start",
                old_shards=plan.old_shards,
                new_shards=plan.new_shards,
                moved_rows=plan.moved_rows,
                moved_bytes=plan.moved_bytes,
            )
            plan_by_cid = {p.cid: p for p in plan.coordinates}

            def build_new_coords():
                staged_bytes = 0
                new_coords: Dict[str, ServingCoordinate] = {}
                for cid in old_bundle.coordinate_ids:
                    c = old_bundle.coordinates[cid]
                    cplan = plan_by_cid.get(cid)
                    if cplan is None:
                        # FE planes and two-tier stores are not
                        # mesh-sharded: the SAME coordinate object serves
                        # both generations (never released at retire).
                        new_coords[cid] = c
                        continue
                    params, nbytes = self._stage_resharded_params(
                        c, cplan, new_mesh
                    )
                    staged_bytes += nbytes
                    new_coords[cid] = ServingCoordinate(
                        cid,
                        c.shard,
                        params,
                        norm=c.norm,
                        random_effect_type=c.random_effect_type,
                        entity_index=c.entity_index,
                        mesh=new_mesh if cplan.new_shards > 1 else None,
                        logical_rows=cplan.logical_rows,
                        shard_health=ShardHealth(
                            cplan.new_shards,
                            cplan.padded_rows // cplan.new_shards,
                        ),
                    )
                return new_coords, staged_bytes

            return self._stage_and_commit(
                old_state,
                plan,
                build_new_coords,
                close_stores=(),
                kind="reshard",
                drain_timeout_s=drain_timeout_s,
            )

    def _stage_resharded_params(
        self, coord: ServingCoordinate, cplan: CoordinateReshardPlan, new_mesh
    ):
        """Stage one coordinate's matrix in the NEW layout, double-buffered
        beside the live generation.

        The old matrix is read PER SURVIVING SHARD BUFFER
        (`addressable_shards` — plain device->host copies, exactly how the
        elastic checkpoint reads a sharded matrix), deliberately never
        through a cross-device slice/gather program: staging runs beside
        live traffic, and a second thread launching collective programs
        over the same devices can deadlock the runtime's participant
        rendezvous (the same hazard the engine's device mutex closes for
        the pre-warm). Each new shard's row block then uploads to its
        device on a `photon-reshard-stage<k>` worker under the
        `reshard_stage` fault site + bounded retries (single-device
        transfers — no collective in the whole staging phase). The WIRE
        accounting is the plan's: only segments whose owning device
        changes count as restaged bytes — a same-device segment's hop is
        device-local. Returns (new params array, bytes moved across the
        wire)."""
        from photon_ml_tpu.parallel.mesh import matrix_row_sharding

        new_devs = _mesh_devices(new_mesh)
        n_new = cplan.new_shards
        rows_per_new = cplan.padded_rows // n_new
        dim = cplan.dim
        logical = cplan.logical_rows
        old_rows = int(coord.params.shape[0])
        # Host bounce of the old matrix, assembled from per-shard device
        # buffers (the surviving replicas), truncated to the new layout's
        # rows — the same transient envelope `_load_sharded_model` pays.
        host = np.zeros((max(cplan.padded_rows, old_rows), dim), np.float32)
        if coord.mesh is not None:
            for s in coord.params.addressable_shards:
                start = int(s.index[0].start or 0)
                block = np.asarray(s.data, np.float32)
                host[start : start + block.shape[0]] = block
        else:
            host[:old_rows] = np.asarray(coord.params, np.float32)
        host[logical:] = 0.0  # old-layout padding never leaks forward
        policy = _reshard_policy()
        bufs: List[Optional[jax.Array]] = [None] * n_new
        errors: List[BaseException] = []
        err_lock = threading.Lock()
        span_h = telemetry.span_handoff()

        def _stage_one(k: int) -> None:
            try:
                lo = k * rows_per_new
                hi = lo + rows_per_new
                block = host[lo:hi]

                def attempt():
                    faults.fault_point("reshard_stage")
                    buf = jax.device_put(jnp.asarray(block), new_devs[k])
                    jax.block_until_ready(buf)
                    return buf

                with telemetry.adopt_span(span_h), telemetry.span(
                    "reshard_stage", coordinate=cplan.cid, shard=k
                ):
                    bufs[k] = faults.retry(
                        attempt,
                        policy,
                        label=f"reshard staging {cplan.cid} shard {k}",
                        counter="reshard_retries",
                    )
            except BaseException as exc:  # noqa: BLE001 - joined below
                with err_lock:
                    errors.append(exc)

        threads = [
            threading.Thread(
                target=_stage_one,
                args=(k,),
                name=f"photon-reshard-stage{k}",
                daemon=True,
            )
            for k in range(n_new)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        if n_new == 1:
            params = bufs[0]
        else:
            params = jax.make_array_from_single_device_arrays(
                (cplan.padded_rows, dim),
                matrix_row_sharding(new_mesh),
                bufs,
            )
        # The wire accounting is the PLAN's — one source of truth for the
        # moved-segment arithmetic (plan_coordinate_reshard), never a
        # second copy here that could drift.
        return params, cplan.moved_bytes

    # ----------------------------------------------------------- rebalance

    def rebalance(
        self,
        cid: str,
        *,
        min_promotions: Optional[int] = None,
        drain_timeout_s: float = 30.0,
    ) -> Dict[str, object]:
        """Re-place a two-tier coordinate's hot set from its OBSERVED
        promotion stats: rows the cold tier kept promoting become the
        new store's preload, staged and flipped through the same
        double-buffer/commit/rollback machinery as a mesh reshard
        (shard count unchanged — the movement is tier placement).
        Bitwise-neutral: hot vs cold placement never changes a score.
        Returns {"rebalanced_rows": 0, ...} without flipping anything
        when no row has earned a move yet."""
        engine = self.engine
        with engine.bundle_manager.mutex:
            old_state = engine._state
            old_bundle = old_state.bundle
            c = old_bundle.coordinates[cid]
            hot_rows = plan_rebalance(c, min_promotions=min_promotions)
            old_store = c.store
            if not hot_rows:
                return {
                    "rebalanced_rows": 0,
                    "version": old_state.version,
                    "committed": False,
                }
            moved_bytes = len(hot_rows) * c.dim * 4
            telemetry.emit_event(
                "reshard_start",
                old_shards=1,
                new_shards=1,
                moved_rows=len(hot_rows),
                moved_bytes=moved_bytes,
            )
            staged_stores: List[TwoTierEntityStore] = []

            def build_new_coords():
                def attempt():
                    faults.fault_point("reshard_stage")
                    return TwoTierEntityStore(
                        old_store.cold_matrix,
                        old_store.capacity,
                        preload_rows=hot_rows,
                    )

                with telemetry.span(
                    "reshard_stage", coordinate=cid, shard=0
                ):
                    new_store = faults.retry(
                        attempt,
                        _reshard_policy(),
                        label=f"rebalance staging {cid}",
                        counter="reshard_retries",
                    )
                staged_stores.append(new_store)
                new_coords = dict(old_bundle.coordinates)
                new_coords[cid] = ServingCoordinate(
                    cid,
                    c.shard,
                    new_store.snapshot(),
                    norm=c.norm,
                    random_effect_type=c.random_effect_type,
                    entity_index=c.entity_index,
                    logical_rows=c.logical_rows,
                    store=new_store,
                )
                return new_coords, moved_bytes

            info = self._stage_and_commit(
                old_state,
                None,
                build_new_coords,
                close_stores=(old_store,),
                kind="rebalance",
                drain_timeout_s=drain_timeout_s,
                on_rollback=lambda: [s.close() for s in staged_stores],
            )
            faults.COUNTERS.increment("rebalanced_rows", len(hot_rows))
            info["rebalanced_rows"] = len(hot_rows)
            info["preloaded_rows"] = list(staged_stores[0].preloaded_rows)
            return info

    # ------------------------------------------------------------ internals

    def _stage_and_commit(
        self,
        old_state,
        plan,
        build_new_coords,
        *,
        close_stores: Sequence[TwoTierEntityStore],
        kind: str,
        drain_timeout_s: float,
        on_rollback=None,
    ) -> Dict[str, object]:
        """The ONE staging/flip/rollback sequence reshard(), rebalance()
        and the delta-bundle apply (serving/delta.py) all run (a fix to
        the flip discipline lands once): `build_new_coords()` stages the
        new generation's coordinates double-buffered and returns (coords,
        restaged_bytes); then compatibility check -> pre-warm
        (compile-count delta feeds the warmup baseline) ->
        `reshard_commit` fault site -> atomic flip -> drain -> retire.
        `kind` ("reshard" | "rebalance" | "delta") selects which commit
        counter the flip lands in and which rollback event a failure
        journals. ANY failure before the flip runs `on_rollback` (close
        staged stores), counts/journals the rollback, and re-raises — the
        old generation never stopped serving."""
        engine = self.engine
        old_bundle = old_state.bundle
        t0 = time.perf_counter()
        try:
            new_coords, restaged_bytes = build_new_coords()
            new_bundle = ServingBundle(
                task=old_bundle.task,
                coordinates=new_coords,
                index_maps=old_bundle.index_maps,
                upload_bytes=restaged_bytes,
                upload_s=time.perf_counter() - t0,
                provenance=dict(old_bundle.provenance),
            )
            new_state = engine._build_state(
                new_bundle, version=old_state.version + 1
            )
            self._check_compatible(old_state, new_state)
            compiles_before = engine.compiles
            engine._warm_state(new_state)
            staging_compiles = engine.compiles - compiles_before
            faults.fault_point("reshard_commit")
            stage_s = time.perf_counter() - t0
        except BaseException as exc:
            if on_rollback is not None:
                try:
                    on_rollback()
                except Exception:  # noqa: BLE001 - rollback best-effort
                    pass
            self._roll_back(plan, exc, kind=kind, version=old_state.version)
            raise
        return self._commit(
            old_state,
            new_state,
            plan,
            staging_compiles=staging_compiles,
            stage_s=stage_s,
            restaged_bytes=restaged_bytes,
            drain_timeout_s=drain_timeout_s,
            close_stores=close_stores,
            kind=kind,
        )

    def _roll_back(
        self, plan, exc: BaseException, *, kind: str = "reshard", version: int = 0
    ) -> None:
        self._rollbacks += 1
        if kind == "delta":
            faults.COUNTERS.increment("delta_rollbacks")
            telemetry.emit_event(
                "delta_rollback", version=version, reason=repr(exc)
            )
        else:
            faults.COUNTERS.increment("reshard_rollbacks")
            telemetry.emit_event(
                "reshard_rollback",
                old_shards=plan.old_shards if plan is not None else 1,
                new_shards=plan.new_shards if plan is not None else 1,
                reason=repr(exc),
            )
        logger.warning(
            "live %s rolled back (%s); the old generation never "
            "stopped serving",
            kind,
            exc,
        )

    def _commit(
        self,
        old_state,
        new_state,
        plan,
        *,
        staging_compiles: int,
        stage_s: float,
        restaged_bytes: int,
        drain_timeout_s: float,
        close_stores: Sequence[TwoTierEntityStore],
        kind: str = "reshard",
    ) -> Dict[str, object]:
        engine = self.engine
        engine._commit_state(new_state, baseline_bump=staging_compiles)
        if kind == "rebalance":
            self._rebalances += 1
        elif kind == "delta":
            self._deltas += 1
        else:
            self._reshards += 1
        new_state.bundle.provenance["generation"] = new_state.version
        telemetry.emit_event(
            "reshard_commit",
            old_shards=plan.old_shards if plan is not None else 1,
            new_shards=plan.new_shards if plan is not None else 1,
            version=new_state.version,
            restaged_bytes=restaged_bytes,
        )
        telemetry.METRICS.set_gauge(
            "serving_bundle_generation", new_state.version
        )
        drained = engine._drain_state(old_state, timeout_s=drain_timeout_s)
        if not drained:
            logger.warning(
                "old generation %d still has in-flight batches after "
                "%.1fs; leaving its device state allocated",
                old_state.version,
                drain_timeout_s,
            )
        else:
            self._retire(old_state.bundle, new_state.bundle, close_stores)
        logger.info(
            "live %s committed: generation %d -> %d (%d bytes restaged "
            "in %.3fs)",
            kind,
            old_state.version,
            new_state.version,
            restaged_bytes,
            stage_s,
        )
        return {
            "version": new_state.version,
            "previous_version": old_state.version,
            "old_shards": plan.old_shards if plan is not None else 1,
            "new_shards": plan.new_shards if plan is not None else 1,
            "moved_rows": plan.moved_rows if plan is not None else 0,
            "moved_bytes": plan.moved_bytes if plan is not None else 0,
            "restaged_bytes": int(restaged_bytes),
            "stage_s": round(stage_s, 4),
            "old_released": bool(drained),
            "committed": True,
        }

    @staticmethod
    def _retire(
        old_bundle: ServingBundle,
        new_bundle: ServingBundle,
        close_stores,
    ) -> None:
        """Retire the OLD generation by turning its bundle OBJECT into a
        live view of the new one — NOT by `release()`-gutting it: callers
        that captured the bundle at load time keep working against the
        CURRENT generation (the CLI's lazy replay stream encodes requests
        through that handle mid-replay, and its teardown `release()` must
        close the LIVE generation's stores, not a husk). Coordinates the
        new generation reuses (FE planes, untouched two-tier stores)
        carry over untouched; only explicitly replaced stores close, and
        replaced coefficient matrices free when their last reference (the
        old generation's former dict) drops here."""
        for store in close_stores:
            store.close()
        old_bundle.coordinates = dict(new_bundle.coordinates)
        old_bundle.index_maps = new_bundle.index_maps
        old_bundle.upload_bytes = new_bundle.upload_bytes
        old_bundle.upload_s = new_bundle.upload_s
        old_bundle.provenance = new_bundle.provenance

    @staticmethod
    def _check_compatible(old_state, new_state) -> None:
        """A reshard may change each coordinate's STORAGE MODE (replicated
        <-> entity-sharded, different mesh) — that is the whole point —
        but never the coordinate structure the request path is built
        around: ids and order, feature shards, and feature dims must
        match, and a coordinate cannot change between fixed-effect /
        two-tier and shard-tracked kinds mid-flip."""
        if [c.cid for c in old_state.coords] != [
            c.cid for c in new_state.coords
        ]:
            raise SwapIncompatible(
                "resharded bundle's coordinate ids differ from the engine's"
            )
        if old_state.coord_shards != new_state.coord_shards:
            raise SwapIncompatible(
                "resharded bundle maps coordinates to different feature "
                "shards"
            )
        if old_state.shard_dims != new_state.shard_dims:
            raise SwapIncompatible(
                f"resharded bundle's shard dims {new_state.shard_dims} "
                f"differ from the engine's {old_state.shard_dims}"
            )
        for ok, nk in zip(old_state.kinds, new_state.kinds):
            mesh_kinds = ("re", "re_sh")
            if ok != nk and not (ok in mesh_kinds and nk in mesh_kinds):
                raise SwapIncompatible(
                    f"reshard cannot change a coordinate's storage kind "
                    f"{ok} -> {nk} (only replicated <-> entity-sharded)"
                )

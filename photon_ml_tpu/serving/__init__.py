"""Online serving: device-resident GAME model bundles + low-latency scoring.

A deliberate extension beyond the reference (which only scores offline via
GameScoringDriver): `bundle.py` pins a trained model's weight planes in
device memory once, `engine.py` answers scoring requests through a bounded
set of jit-compiled padded-bucket programs, and `batcher.py` coalesces
single requests into deadline micro-batches. See PARITY.md "Online serving".
"""

from photon_ml_tpu.serving.batcher import MicroBatcher
from photon_ml_tpu.serving.bundle import (
    ScoreRequest,
    ServingBundle,
    ServingCoordinate,
    load_bundle,
)
from photon_ml_tpu.serving.engine import ScoreResult, ServingEngine

__all__ = [
    "MicroBatcher",
    "ScoreRequest",
    "ScoreResult",
    "ServingBundle",
    "ServingCoordinate",
    "ServingEngine",
    "load_bundle",
]

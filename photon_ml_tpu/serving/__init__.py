"""Online serving: device-resident GAME model bundles + low-latency scoring.

A deliberate extension beyond the reference (which only scores offline via
GameScoringDriver): `bundle.py` pins a trained model's weight planes in
device memory once, `engine.py` answers scoring requests through a bounded
set of jit-compiled padded-bucket programs, and `batcher.py` coalesces
single requests into deadline micro-batches. `lifecycle.py` is the
management tier that keeps it serving under fire: admission control
(typed `Overloaded` shedding), per-request deadline budgets
(`DeadlineExceeded`), a circuit breaker that degrades a persistently
faulting device to fixed-effect-only answers, versioned atomic bundle
hot-swap (`BundleManager`), and the STARTING → READY ⇄ DEGRADED →
DRAINING → CLOSED health machine. `tenancy.py` generalizes the stack to
N named tenants sharing one device fleet (`TenantRegistry`): per-tenant
admission quotas and deadlines, weighted-fair cross-tenant co-batching
(bitwise-equal to solo dispatch), fully per-tenant failure domains, and
HBM-pressure demotion of cold tenants' RE rows to the host tier. See
PARITY.md "Online serving", "Serving failure semantics" and
"Multi-tenant serving".
"""

from photon_ml_tpu.serving.batcher import MicroBatcher
from photon_ml_tpu.serving.bundle import (
    ScoreRequest,
    ServingBundle,
    ServingCoordinate,
    ShardHealth,
    TwoTierEntityStore,
    demote_bundle_to_host_tier,
    load_bundle,
)
from photon_ml_tpu.serving.tenancy import Tenant, TenantRegistry
from photon_ml_tpu.utils.faults import DeviceHang
from photon_ml_tpu.serving.engine import ScoreResult, ServingEngine
from photon_ml_tpu.serving.reshard import (
    MeshReshardOrchestrator,
    ReshardPlan,
    plan_rebalance,
    plan_reshard,
)
from photon_ml_tpu.serving.lifecycle import (
    BatcherUnhealthy,
    BundleManager,
    CircuitBreaker,
    CircuitState,
    DeadlineExceeded,
    HbmBudgetExceeded,
    HealthStateMachine,
    Overloaded,
    ServingState,
    SwapIncompatible,
)

__all__ = [
    "BatcherUnhealthy",
    "BundleManager",
    "CircuitBreaker",
    "CircuitState",
    "DeadlineExceeded",
    "DeviceHang",
    "HbmBudgetExceeded",
    "HealthStateMachine",
    "MeshReshardOrchestrator",
    "MicroBatcher",
    "Overloaded",
    "ReshardPlan",
    "plan_rebalance",
    "plan_reshard",
    "ScoreRequest",
    "ScoreResult",
    "ServingBundle",
    "ServingCoordinate",
    "ServingEngine",
    "ServingState",
    "ShardHealth",
    "SwapIncompatible",
    "Tenant",
    "TenantRegistry",
    "TwoTierEntityStore",
    "demote_bundle_to_host_tier",
    "load_bundle",
]

"""Multi-tenant serving: N isolated model bundles on one device fleet.

Photon ML serves one GAME model per Spark job, and every isolation
property — memory, admission, failure blast radius — comes free from the
one-job-per-model deployment. The TPU engine runs N models IN ONE
PROCESS on one device fleet, so everything Spark's job boundary gave for
free must be enforced here explicitly. `TenantRegistry` is that layer —
the generalization of `BundleManager` from "a model server" to "a
serving platform" (the ROADMAP's multi-tenant open item):

* **Per-tenant admission quotas and deadline budgets.** Each tenant owns
  a bounded pending count (`PHOTON_TENANT_MAX_PENDING` default); a
  submit past it sheds with a typed `Overloaded` NAMING the tenant —
  one tenant's overload is its own typed rejection, never a shared-queue
  backlog that starves its neighbors (the Spark-ML performance study's
  finding that contention knobs dominate tail latency, PAPERS.md,
  applied as per-tenant bounds instead of one shared queue). Deadlines
  default per tenant and enforce at claim time exactly like the
  single-tenant micro-batcher: an expired request is failed before it
  wastes a device slot.

* **Weighted-fair cross-tenant batch assembly.** The registry's one
  dispatch thread (`photon-tenant-dispatch`) claims up to `max_batch`
  requests per round, splitting slots across backlogged tenants in
  proportion to their weights (every backlogged tenant gets at least
  one slot — weighted fairness, not starvation), then CO-BATCHES
  compatible tenants' requests into ONE device dispatch: requests from
  different bundles share a padded bucket, each slot gathering ITS
  tenant's parameters (fixed-effect planes via a stacked per-slot row
  gather, random-effect rows via a per-tenant gather + exact where-
  select). Both kernels reuse the engine's margin code paths
  (`dense_margins`, `gathered_row_margins`), so a co-batched slice is
  BITWISE-equal to dispatching that tenant alone — the same invariance
  argument that lets the micro-batcher degrade to per-request dispatch
  without changing an answer. Co-batch eligibility is structural (all
  coordinates "fe"/"re", no normalization, same task and dims, no lost
  shards); anything else — demoted tenants, sharded/two-tier stores,
  open circuits — dispatches SOLO through the tenant's own hardened
  micro-batcher, which already owns the retry/FE-only/deadline policy.

* **Fully per-tenant failure domains.** Every tenant owns a complete
  `ServingEngine`: its own health machine, circuit breaker, watchdog,
  jit cache, and flush thread (`photon-tenant-<name>-flush`). One
  tenant's open circuit or `DeviceHang` routes only ITS requests to the
  FE-only tier; a chaos drill confines an armed fault plan to one
  tenant via the engine's `inject_faults` gate (site invocation
  counters are process-global, so deterministic targeting needs a
  per-engine gate). The process-global serving robustness counters are
  additionally scoped per tenant via telemetry metric labels — the
  aggregate stays, and each tenant's clean-run zero contract is its own
  labeled sub-count.

* **HBM-pressure eviction of cold tenants.** Admission charges every
  tenant's per-shard device bytes against the fleet budget
  (`PHOTON_TENANT_HBM_FRACTION` of the device limit). When tenant N+1
  does not fit, the registry DEMOTES the coldest (least-recently-
  active) tenant's random-effect rows to the host tier — the
  `TwoTierEntityStore` as cross-tenant eviction engine
  (`bundle.demote_bundle_to_host_tier`): the demoted tenant keeps
  answering BITWISE through per-request override rows (Snap ML's
  hierarchical host/device memory management, PAPERS.md, arbitrating
  HBM across tenants), it just stops pinning its matrix. Admission may
  demote, never fail, a READY tenant; only a fleet that cannot fit even
  after demoting every candidate refuses with `HbmBudgetExceeded`.

* **Precision-tier graceful degradation (ISSUE 20).** With
  `PHOTON_TIER_LADDER` on, the pressure valve (and the autopilot's
  hbm rules) walks a tenant DOWN a ladder instead of leaping to the
  host tier: f32 -> bf16 -> int8 -> host (`demote_tier`), each quantize
  rung halving/quartering the pinned RE bytes via planes dequantized
  INSIDE the bucket programs, and each step the same stage->pre-warm->
  commit->drain generation flip as a hot-swap. Quantization always
  reads the retained ORIGINAL f32 rows, so `restore_tier` walks back up
  and the final f32 step — and any host-tier round trip — is BITWISE
  vs. the pre-demotion self. A quantized tenant answers under the
  CHARACTERIZED contract (contracts.TIER_TOLERANCES), not the bitwise
  one; that trade is opt-in, journaled (`tier_demote`/`tier_restore`
  with evidence), error-histogrammed per tenant, and refused outright
  when int8's measured error would exceed the configured ceiling.

Fault sites: `tenant_admit` (staging a tenant onto the fleet — bounded
retry, an exhausted failure leaves the registry unchanged),
`tenant_evict` (the demotion build — bounded retry, a terminal failure
rolls back and the tenant keeps serving its device-resident
generation), and `quantize_stage`/`tier_restore` (the ladder builds —
same rollback story, counted in `tier_rollbacks`). Journal events
`tenant_admit`/`tenant_evict`/`tenant_degraded`/`tier_demote`/
`tier_restore` record the platform's lifecycle per tenant.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from concurrent.futures import Future
from typing import Deque, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.model import gathered_row_margins
from photon_ml_tpu.ops.losses import mean_for_task
from photon_ml_tpu.serving.bundle import (
    PRECISION_LADDER,
    ScoreRequest,
    ServingBundle,
    demote_bundle_to_host_tier,
    promote_bundle_from_host_tier,
    quantize_bundle_rows,
    restore_bundle_precision,
)
from photon_ml_tpu.serving.engine import (
    ScoreResult,
    ServingEngine,
    _bucket_sizes,
)
from photon_ml_tpu.serving.lifecycle import (
    BatcherUnhealthy,
    DeadlineExceeded,
    HbmBudgetExceeded,
    Overloaded,
    _bundle_device_bytes,
    device_memory_budget_bytes,
)
from photon_ml_tpu.transformers.game_transformer import dense_margins
from photon_ml_tpu.utils import faults, telemetry
from photon_ml_tpu.utils.contracts import TENANT_BLOCK_KEYS, TIER_BLOCK_KEYS
from photon_ml_tpu.utils.knobs import get_knob
from photon_ml_tpu.utils.watchdog import Watchdog, watchdog_ms

logger = logging.getLogger(__name__)

# One queued request: (request, future, submit time, absolute expiry or None)
# — the micro-batcher's pending shape, kept per tenant.
_Pending = Tuple[ScoreRequest, Future, float, Optional[float]]


class TierErrorCeilingExceeded(RuntimeError):
    """An int8 quantization's measured round-trip error exceeded
    PHOTON_TIER_INT8_ERROR_CEILING: the build is discarded BEFORE commit,
    the tenant stays on its current rung, and ladder walkers fall through
    to the (bitwise) host tier for pressure relief instead of serving
    answers outside the characterized tolerance."""


def _cobatch_program(offsets, tids, feats, rows, params, *, kinds, task):
    """The fused cross-tenant bucket program: one device dispatch scoring
    a padded bucket whose slots belong to DIFFERENT tenants' bundles.

    Per coordinate position k (eligibility guarantees every tenant in the
    group shares the (kind, dim) structure and carries no normalization):

      * "fe": the group's weight vectors stack to (T, dim) and each slot
        gathers ITS tenant's row — `dense_margins` on gathered (B, dim)
        rows runs the identical multiply + per-row reduce the solo engine
        runs on the broadcast (dim,) vector, so the slice is bitwise the
        solo answer (stack/gather move bits, never arithmetic).
      * "re": each tenant's (E_t + 1, dim) matrix is gathered at its OWN
        per-slot rows (foreign slots point at that tenant's pinned zero
        row, keeping every gather in bounds), then an exact `where`
        select by tenant id picks each slot's true row — a select, not a
        sum, so no foreign zero ever touches the arithmetic. The margin
        is `gathered_row_margins`, the shared tail that already keeps the
        two-tier and entity-sharded paths bitwise-equal to the
        replicated one.

    Padding slots carry tenant id 0 and pinned zero rows; their outputs
    are discarded and — both kernels being batch-size invariant — never
    influence a real slot."""
    total = offsets
    for k, kind in enumerate(kinds):
        f = feats[k]
        if kind == "fe":
            w = jnp.stack(params[k])[tids]
            total = total + dense_margins(f, w, None)
        else:
            w = params[k][0][rows[k][0]]
            for t in range(1, len(params[k])):
                w = jnp.where(
                    (tids == t)[:, None], params[k][t][rows[k][t]], w
                )
            total = total + gathered_row_margins(f, w, None)
    return total, mean_for_task(task, total)


class Tenant:
    """One named tenant's complete serving stack: its pinned bundle, its
    OWN engine (health/circuit/watchdog/jit cache), its own micro-batcher
    (the solo/fallback dispatch path, `photon-tenant-<name>-flush`), its
    admission quota and deadline default, and its registry-side queue for
    the co-batched fast path."""

    def __init__(
        self,
        name: str,
        engine: ServingEngine,
        batcher,
        *,
        quota: int,
        deadline_ms: Optional[float],
        weight: float,
        order: int,
    ):
        self.name = name
        self.engine = engine
        self.batcher = batcher
        self.quota = int(quota)
        self.deadline_ms = deadline_ms
        self.weight = float(weight)
        self.order = int(order)  # admission order: the stable group index
        self.queue: Deque[_Pending] = collections.deque()
        self.in_flight = 0  # both paths: submitted, not yet resolved
        self.demoted = False
        self.draining = False  # remove() in progress: refuse new submits
        self.last_active = time.monotonic()
        self.completed = 0
        self.failed = 0
        self.shed = 0
        self.deadline_missed = 0
        self.cobatched = 0  # requests answered by the co-batched fast path
        self.cobatch_degraded = 0  # co-batches this tenant degraded out of
        self.latency = telemetry.LatencyStats()
        self._seen_reasons: Tuple[str, ...] = ()
        # Precision-ladder bookkeeping (ISSUE 20): the tenant's current
        # rung ("f32"/"bf16"/"int8" — the host rung keeps the last
        # quantized rung beside demoted=True), per-tenant transition
        # tallies, and the worst quantization error ever measured (None
        # until the first quantization) — the metrics() tier sub-block.
        self.tier = "f32"
        self.tier_demotions = 0
        self.tier_restores = 0
        self.tier_rollbacks = 0
        self.quant_error_max: Optional[float] = None

    @property
    def bundle(self) -> ServingBundle:
        return self.engine.bundle

    def device_bytes(self) -> int:
        return _bundle_device_bytes(self.engine._state.bundle)

    def can_demote(self) -> bool:
        """Whether HBM-pressure eviction may pick this tenant: not
        already demoted, and no entity-sharded coordinate (a mesh-sharded
        matrix already divides over the fleet — pulling it whole into
        host RAM would change the placement story, and
        demote_bundle_to_host_tier refuses it loudly)."""
        if self.demoted:
            return False
        st = self.engine._state
        return all(kind != "re_sh" for kind in st.kinds)

    def can_quantize(self) -> bool:
        """Whether a precision-ladder step down may pick this tenant: not
        demoted, not already on the last quantized rung, no entity-
        sharded coordinate (quantize_bundle_rows refuses it loudly), and
        at least one replicated RE matrix left to shrink — an all-FE or
        all-two-tier tenant frees nothing by quantizing."""
        if self.demoted or self.tier == PRECISION_LADDER[-1]:
            return False
        st = self.engine._state
        if any(kind == "re_sh" for kind in st.kinds):
            return False
        return any(kind in ("re", "re_bf16") for kind in st.kinds)

    def signature(self) -> Optional[tuple]:
        """The co-batch compatibility key, or None when this tenant must
        dispatch solo: every coordinate "fe"/"re" (replicated single-tier
        — two-tier and mesh-sharded stores gather differently), no
        normalization (norm algebra folds per tenant and would break the
        shared-kernel bitwise argument), no lost shards (the solo path
        owns the pinned-zero remap), and not demoted."""
        if self.demoted:
            return None
        st = self.engine._state
        for k, c in enumerate(st.coords):
            if st.kinds[k] not in ("fe", "re"):
                return None
            if c.norm is not None:
                return None
            sh = getattr(c, "shard_health", None)
            if sh is not None and sh.any_lost:
                return None
        return (
            self.engine.task,
            st.kinds,
            tuple(c.dim for c in st.coords),
        )


class TenantRegistry:
    """N named tenants sharing one device fleet, with per-tenant
    isolation enforced in-process (see module doc). `admit()` stages a
    tenant, `submit(name, request)` routes one request, `close()` drains
    and joins every worker. One registry per fleet; tenant engines share
    ONE device mutex so concurrent multi-device dispatches interleave
    instead of deadlocking the collective rendezvous."""

    def __init__(
        self,
        *,
        max_batch: Optional[int] = None,
        max_wait_ms: Optional[float] = None,
        hbm_budget_bytes: Optional[int] = None,
        watchdog_ms_override: Optional[float] = None,
    ):
        # Both batching quantities are PLANNED (ISSUE 14): explicit
        # arguments win, None defers to the installed plan and then the
        # pre-planner defaults — the same deferral the engine/batcher use.
        from photon_ml_tpu import planner

        if max_batch is None:
            max_batch = int(planner.planned_value("serving_max_batch"))
        if max_wait_ms is None:
            max_wait_ms = float(planner.planned_value("serving_max_wait_ms"))
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.buckets = _bucket_sizes(self.max_batch)
        self._hbm_budget_override = hbm_budget_bytes
        self._watchdog_ms = (
            float(watchdog_ms()) if watchdog_ms_override is None
            else float(watchdog_ms_override)
        )
        self._watchdog = Watchdog()
        self._cv = threading.Condition()
        self._tenants: Dict[str, Tenant] = {}
        self._order = 0
        self._rr = 0  # weighted-fair rotation cursor
        self._stop = False
        self._unhealthy: Optional[BaseException] = None
        self._service_tail_s = 0.0
        self._cobatch_dispatches = 0
        self._cobatch_compiles = 0
        # ONE device mutex across every tenant engine: N flush threads
        # dispatching (possibly collective) programs over one fleet must
        # interleave, never overlap (the ISSUE 13 rendezvous deadlock,
        # now cross-engine).
        self._device_mutex = threading.Lock()

        # Private jit instance (the engine's per-instance trampoline
        # discipline): _cobatch_compiles honestly counts THIS registry's
        # cross-tenant programs.
        def _registry_cobatch_program(*args, **kwargs):
            return _cobatch_program(*args, **kwargs)

        donate = () if jax.default_backend() == "cpu" else (0, 1, 2, 3)
        self._jit = jax.jit(
            _registry_cobatch_program,
            static_argnames=("kinds", "task"),
            donate_argnums=donate,
        )
        self._thread = threading.Thread(
            target=self._dispatch_loop,
            name="photon-tenant-dispatch",
            daemon=True,
        )
        self._thread.start()

    # ------------------------------------------------------------ admission

    def _fleet_budget(self) -> Optional[int]:
        if self._hbm_budget_override is not None:
            return int(self._hbm_budget_override)
        budget = device_memory_budget_bytes()
        if budget is None:
            return None
        return int(budget * float(get_knob("PHOTON_TENANT_HBM_FRACTION")))

    def admit(
        self,
        name: str,
        bundle,
        *,
        max_pending: Optional[int] = None,
        deadline_ms: Optional[float] = None,
        weight: float = 1.0,
        inject_faults: bool = True,
        warm: bool = True,
        watchdog_ms_override: Optional[float] = None,
    ) -> Tenant:
        """Stage `bundle` (a ServingBundle or zero-arg builder) as tenant
        `name`. The fleet HBM budget is enforced BEFORE the new engine
        pins anything beyond the staged bundle: while over budget, the
        coldest demotable tenant's RE rows demote to the host tier
        (`tenant_evict` path — the tenant keeps answering bitwise;
        entity-sharded tenants are never victims); only a fleet that
        cannot fit after demoting every candidate refuses. Staging runs
        under the `tenant_admit` fault site with the bounded retry
        policy; ANY failure (staging exhausted, engine bring-up) leaves
        the registry without the new tenant — nothing staged stays
        pinned, though demotions already made to fit it are kept (a
        demoted tenant keeps answering bitwise from the host tier).
        `inject_faults=False` excludes this tenant's dispatches from an
        armed fault plan (chaos-drill targeting); `watchdog_ms_override`
        arms a per-tenant dispatch deadline."""
        with self._cv:
            if self._stop:
                raise RuntimeError("TenantRegistry is closed")
            if name in self._tenants:
                raise ValueError(f"tenant {name!r} already admitted")
        builder = bundle if callable(bundle) else None

        def _stage():
            faults.fault_point("tenant_admit")
            return builder() if builder is not None else bundle

        with telemetry.metric_label_scope(tenant=name):
            staged = faults.retry(_stage, label=f"tenant {name} admission")
        if getattr(staged, "released", False):
            raise ValueError(f"tenant {name!r} bundle is already released")

        # HBM pressure: demote, never fail, resident tenants to fit the
        # newcomer; refuse only when no demotion can free enough. With
        # PHOTON_TIER_LADDER on (ISSUE 20), each relief step walks the
        # coldest steppable tenant ONE precision rung down (quantize-in-
        # place before host-tier demotion); off keeps the PR 15 all-or-
        # nothing host demotion and the bitwise contract.
        ladder = bool(get_knob("PHOTON_TIER_LADDER"))
        demoted: List[str] = []
        need = _bundle_device_bytes(staged)
        budget = self._fleet_budget()
        try:
            while budget is not None:
                with self._cv:
                    have = sum(
                        t.device_bytes() for t in self._tenants.values()
                    )
                    victims = sorted(
                        (
                            t
                            for t in self._tenants.values()
                            if t.can_demote()
                            or (ladder and t.can_quantize())
                        ),
                        key=lambda t: (t.last_active, t.order),
                    )
                if have + need <= budget:
                    break
                if not victims:
                    raise HbmBudgetExceeded(
                        f"admitting tenant {name!r} needs {need} bytes "
                        f"beside {have} resident bytes (budget {budget}); "
                        "every demotable resident tenant is already on "
                        "the host tier"
                    )
                # Quantize-in-place is tried before host-tier demotion
                # FLEET-WIDE: the coldest quantizable tenant steps a rung
                # down first, even when an already-int8 tenant is colder
                # — otherwise the valve would walk one tenant straight
                # through to host while its neighbors still had lossless-
                # er rungs to give.
                victim = next(
                    (t for t in victims if ladder and t.can_quantize()),
                    victims[0],
                )
                if ladder and victim.can_quantize():
                    try:
                        self.demote_tier(
                            victim.name, reason="hbm_pressure"
                        )
                    except TierErrorCeilingExceeded:
                        # int8 would answer outside the characterized
                        # tolerance: fall through to the bitwise host
                        # tier for this victim's relief instead.
                        self.demote(victim.name, reason="hbm_pressure")
                else:
                    self.demote(victim.name, reason="hbm_pressure")
                demoted.append(victim.name)
        except BaseException:
            if builder is not None and staged is not None:
                try:
                    staged.release()
                except Exception:  # noqa: BLE001 - rollback best-effort
                    pass
            raise

        engine = None
        try:
            engine = ServingEngine(
                staged,
                max_batch=self.max_batch,
                inject_faults=inject_faults,
                device_mutex=self._device_mutex,
                watchdog_ms_override=watchdog_ms_override,
            )
            if warm:
                engine.warmup()
            quota = (
                int(get_knob("PHOTON_TENANT_MAX_PENDING"))
                if max_pending is None
                else int(max_pending)
            )
            batcher = engine.batcher(
                max_wait_ms=self.max_wait_s * 1e3,
                max_pending=quota,
                default_deadline_ms=deadline_ms,
                thread_name=f"photon-tenant-{name}-flush",
                metric_labels={"tenant": name},
            )
        except BaseException:
            # Engine bring-up failed (compile error, OOM at the budget
            # edge): the tenant is NOT admitted, so nothing may stay
            # pinned or threaded — close the half-built engine (joins
            # its watchdog/batchers) and release a builder-staged bundle
            # (a caller-owned prebuilt bundle stays the caller's).
            # Demotions already performed to make room are KEPT: demoted
            # tenants answer bitwise from the host tier, and re-promoting
            # them on this error path would thrash HBM for no request.
            if engine is not None:
                try:
                    engine.close()
                except Exception:  # noqa: BLE001 - rollback best-effort
                    pass
            if builder is not None:
                try:
                    staged.release()
                except Exception:  # noqa: BLE001 - rollback best-effort
                    pass
            raise
        with self._cv:
            t = Tenant(
                name,
                engine,
                batcher,
                quota=quota,
                deadline_ms=deadline_ms,
                weight=weight,
                order=self._order,
            )
            self._order += 1
            self._tenants[name] = t
        telemetry.emit_event(
            "tenant_admit",
            tenant=name,
            device_bytes=int(need),
            demoted_tenants=demoted,
        )
        logger.info(
            "tenant %r admitted: %.2f MB device-resident%s",
            name,
            need / 1e6,
            f" (demoted {demoted} to the host tier)" if demoted else "",
        )
        return t

    def demote(self, name: str, *, hot_rows: int = 0, reason: str = "manual") -> int:
        """Demote tenant `name`'s random-effect rows to the host tier
        (TwoTierEntityStore, `hot_rows` rows kept in HBM). The tenant
        keeps answering BITWISE throughout — the new generation pre-warms
        before the atomic flip, in-flight batches drain on the old one —
        and a terminal `tenant_evict` failure rolls back with the old
        generation still serving. Returns the device bytes freed."""
        t = self._tenant(name)
        if t.demoted:
            return 0
        # Serialize with hot-swaps on the engine's own swap mutex — a
        # model push and a demotion must order, not race, the state flip.
        with t.engine.bundle_manager.mutex:
            old_state = t.engine._state
            old_bytes = _bundle_device_bytes(old_state.bundle)

            def _build():
                faults.fault_point("tenant_evict")
                return demote_bundle_to_host_tier(
                    old_state.bundle, hot_rows=hot_rows
                )

            with telemetry.metric_label_scope(tenant=name):
                demoted_bundle = faults.retry(
                    _build, label=f"tenant {name} demotion"
                )
                new_state = t.engine._build_state(
                    demoted_bundle, version=old_state.version + 1
                )
                # Pre-warm the demoted generation's bucket programs (the
                # kinds changed re -> re2, so these ARE new programs) so
                # the flip compiles nothing on live traffic; the compile
                # delta bumps the warmup baseline like a hot-swap's.
                before = t.engine.compiles
                t.engine._warm_state(new_state)
                t.engine._commit_state(
                    new_state, baseline_bump=t.engine.compiles - before
                )
                t.demoted = True
                t.engine._drain_state(old_state, timeout_s=30.0)
                # close_stores=False: any store-bearing coordinate was
                # carried over INTO the demoted bundle, which owns it now.
                old_state.bundle.release(close_stores=False)
                faults.COUNTERS.increment("tenant_demotions")
        freed = old_bytes - _bundle_device_bytes(demoted_bundle)
        telemetry.emit_event(
            "tenant_evict",
            tenant=name,
            reason=reason,
            freed_bytes=int(freed),
            hot_rows=int(hot_rows),
        )
        logger.info(
            "tenant %r demoted to the host tier (%s): %.2f MB HBM freed",
            name,
            reason,
            freed / 1e6,
        )
        return int(freed)

    def restore(self, name: str, *, reason: str = "manual") -> int:
        """Promote a demoted tenant's random-effect rows back to full
        HBM residency (the exact inverse of `demote` — the rebuilt
        single-tier matrices come bitwise from the two-tier store's cold
        tier). Same discipline as demotion: serialized with hot-swaps on
        the engine's swap mutex, the restored generation pre-warms before
        the atomic flip, in-flight batches drain on the old one. The
        autopilot's HBM-ladder restore actuator (ISSUE 19). Returns the
        device bytes the restore re-pinned (0 if not demoted)."""
        t = self._tenant(name)
        if not t.demoted:
            return 0
        with t.engine.bundle_manager.mutex:
            old_state = t.engine._state
            old_bytes = _bundle_device_bytes(old_state.bundle)

            def _build():
                return promote_bundle_from_host_tier(old_state.bundle)

            with telemetry.metric_label_scope(tenant=name):
                restored_bundle = faults.retry(
                    _build, label=f"tenant {name} restore"
                )
                new_state = t.engine._build_state(
                    restored_bundle, version=old_state.version + 1
                )
                # The kinds changed back re2 -> re: these are new bucket
                # programs — pre-warm so the flip compiles nothing on
                # live traffic (the demotion's own discipline, inverted).
                before = t.engine.compiles
                t.engine._warm_state(new_state)
                t.engine._commit_state(
                    new_state, baseline_bump=t.engine.compiles - before
                )
                t.demoted = False
                # The cold tier holds the ORIGINAL f32 rows (a quantized
                # tenant's host demotion was built from its retained
                # host_f32 copy), so a host restore always lands on the
                # full-precision rung — quantized rungs are only
                # re-entered by a new demote_tier() (ISSUE 20).
                t.tier = "f32"
                t.engine._drain_state(old_state, timeout_s=30.0)
                # close_stores=True: the restored generation owns plain
                # device matrices — the old bundle's two-tier stores (and
                # their promotion workers) retire with it.
                old_state.bundle.release(close_stores=True)
                faults.COUNTERS.increment("tenant_restores")
        repinned = _bundle_device_bytes(restored_bundle) - old_bytes
        telemetry.emit_event(
            "tenant_restore",
            tenant=name,
            reason=reason,
            device_bytes=int(repinned),
        )
        logger.info(
            "tenant %r restored to HBM residency (%s): %.2f MB re-pinned",
            name,
            reason,
            repinned / 1e6,
        )
        return int(repinned)

    # ------------------------------------------------------ precision ladder

    def demote_tier(
        self, name: str, *, to: Optional[str] = None, reason: str = "manual"
    ) -> int:
        """Walk tenant `name` DOWN the precision ladder (ISSUE 20):
        f32 -> bf16 -> int8 -> host, one rung per call by default, or to
        the named rung `to` ("bf16"/"int8"/"host"). Each quantize step is
        the same stage->pre-warm->commit->drain generation flip as a
        hot-swap, under the `quantize_stage` fault site with the bounded
        retry policy — a terminal mid-quantize failure (or SIGKILL)
        leaves the OLD generation serving and counts `tier_rollbacks`.
        An int8 step whose measured round-trip error exceeds
        PHOTON_TIER_INT8_ERROR_CEILING raises `TierErrorCeilingExceeded`
        before commit (when walking past it to "host", the ceiling trip
        falls through to the bitwise host tier instead). The host rung
        delegates to `demote()` — the PR 15 whole-bundle host demotion,
        built from the retained ORIGINAL f32 rows, never a lossy plane.
        Returns total device bytes freed."""
        t = self._tenant(name)
        ladder = (*PRECISION_LADDER, "host")
        if to is not None and to not in ladder[1:]:
            raise ValueError(
                f"unknown precision rung {to!r} (ladder: {ladder[1:]})"
            )
        if t.demoted:
            return 0
        idx = ladder.index(t.tier)
        tgt = idx + 1 if to is None else ladder.index(to)
        if tgt <= idx:
            return 0
        freed = 0
        for rung in ladder[idx + 1 : tgt + 1]:
            if rung == "host":
                freed += self.demote(name, reason=reason)
                continue
            try:
                freed += self._quantize_step(t, rung, reason)
            except TierErrorCeilingExceeded:
                if tgt > ladder.index(rung):
                    # Walking past int8 anyway: the host rung below is
                    # bitwise — skip the refused rung, keep descending.
                    continue
                raise
        return int(freed)

    def restore_tier(
        self, name: str, *, to: str = "f32", reason: str = "manual"
    ) -> int:
        """Walk tenant `name` back UP the ladder toward `to` (default all
        the way to f32): host -> int8 -> bf16 -> f32, under the existing
        demote/restore discipline per step. The host rung delegates to
        `restore()`; quantized rungs rebuild under the `tier_restore`
        fault site — the final step to f32 is BITWISE (rebuilt from the
        retained original rows), intermediate re-quantizations
        (int8 -> bf16) re-round the same originals. Returns total device
        bytes re-pinned."""
        t = self._tenant(name)
        ladder = (*PRECISION_LADDER, "host")
        if to not in PRECISION_LADDER:
            raise ValueError(
                f"unknown precision rung {to!r} (ladder: {PRECISION_LADDER})"
            )
        repinned = 0
        if t.demoted:
            repinned += self.restore(name, reason=reason)
        tgt = ladder.index(to)
        while ladder.index(t.tier) > tgt:
            repinned += self._restore_step(
                t, ladder[ladder.index(t.tier) - 1], reason
            )
        return int(repinned)

    def _quantize_step(self, t: Tenant, rung: str, reason: str) -> int:
        """One committed rung down: quantize, pre-warm, flip, drain.
        Serialized with hot-swaps on the engine's swap mutex, like
        `demote()` — a model push and a ladder step must order, never
        race, the state flip."""
        from_tier = t.tier
        with t.engine.bundle_manager.mutex:
            old_state = t.engine._state
            old_bytes = _bundle_device_bytes(old_state.bundle)

            def _build():
                faults.fault_point("quantize_stage")
                return quantize_bundle_rows(old_state.bundle, rung)

            with telemetry.metric_label_scope(tenant=t.name):
                try:
                    new_bundle, errors = faults.retry(
                        _build, label=f"tenant {t.name} {rung} quantization"
                    )
                except BaseException:
                    # Retry exhausted mid-stage: nothing committed, the
                    # old generation never stopped serving.
                    t.tier_rollbacks += 1
                    faults.COUNTERS.increment("tier_rollbacks")
                    raise
                err_max = max(errors.values(), default=0.0)
                ceiling = float(
                    get_knob("PHOTON_TIER_INT8_ERROR_CEILING")
                )
                if rung == "int8" and err_max > ceiling:
                    new_bundle.release(close_stores=False)
                    t.tier_rollbacks += 1
                    faults.COUNTERS.increment("tier_rollbacks")
                    raise TierErrorCeilingExceeded(
                        f"tenant {t.name!r}: int8 round-trip error "
                        f"{err_max:.4g} exceeds the "
                        f"PHOTON_TIER_INT8_ERROR_CEILING of {ceiling}; "
                        f"staying at {from_tier!r}"
                    )
                for err in errors.values():
                    # Ambient tenant label: the per-tenant quantization-
                    # error histogram the characterized contract audits.
                    telemetry.METRICS.observe("tier_quant_error", err)
                new_state = t.engine._build_state(
                    new_bundle, version=old_state.version + 1
                )
                # The kinds changed re -> re_bf16/re_i8: new bucket
                # programs — pre-warm so the flip compiles nothing on
                # live traffic (the demotion's own discipline).
                before = t.engine.compiles
                t.engine._warm_state(new_state)
                t.engine._commit_state(
                    new_state, baseline_bump=t.engine.compiles - before
                )
                t.tier = rung
                t.tier_demotions += 1
                t.quant_error_max = max(t.quant_error_max or 0.0, err_max)
                t.engine._drain_state(old_state, timeout_s=30.0)
                old_state.bundle.release(close_stores=False)
                faults.COUNTERS.increment("tier_demotions")
        freed = old_bytes - _bundle_device_bytes(new_bundle)
        telemetry.emit_event(
            "tier_demote",
            tenant=t.name,
            from_tier=from_tier,
            to_tier=rung,
            reason=reason,
            freed_bytes=int(freed),
            evidence={
                "quant_error_max": err_max,
                "quantized_coordinates": len(errors),
            },
        )
        logger.info(
            "tenant %r stepped down the precision ladder %s -> %s (%s): "
            "%.2f MB HBM freed, worst round-trip error %.4g",
            t.name,
            from_tier,
            rung,
            reason,
            freed / 1e6,
            err_max,
        )
        return int(freed)

    def _restore_step(self, t: Tenant, rung: str, reason: str) -> int:
        """One committed rung up: rebuild toward `rung` from the retained
        original rows, pre-warm, flip, drain — under the `tier_restore`
        fault site. The step to "f32" is bitwise; int8 -> bf16 re-rounds
        the same originals (never the int8 plane)."""
        from_tier = t.tier
        with t.engine.bundle_manager.mutex:
            old_state = t.engine._state
            old_bytes = _bundle_device_bytes(old_state.bundle)

            def _build():
                faults.fault_point("tier_restore")
                if rung == "f32":
                    return restore_bundle_precision(old_state.bundle), {}
                return quantize_bundle_rows(old_state.bundle, rung)

            with telemetry.metric_label_scope(tenant=t.name):
                try:
                    new_bundle, errors = faults.retry(
                        _build, label=f"tenant {t.name} {rung} restore"
                    )
                except BaseException:
                    t.tier_rollbacks += 1
                    faults.COUNTERS.increment("tier_rollbacks")
                    raise
                for err in errors.values():
                    telemetry.METRICS.observe("tier_quant_error", err)
                new_state = t.engine._build_state(
                    new_bundle, version=old_state.version + 1
                )
                before = t.engine.compiles
                t.engine._warm_state(new_state)
                t.engine._commit_state(
                    new_state, baseline_bump=t.engine.compiles - before
                )
                t.tier = rung
                t.tier_restores += 1
                if errors:
                    t.quant_error_max = max(
                        t.quant_error_max or 0.0, max(errors.values())
                    )
                t.engine._drain_state(old_state, timeout_s=30.0)
                old_state.bundle.release(close_stores=False)
                faults.COUNTERS.increment("tier_restores")
        repinned = _bundle_device_bytes(new_bundle) - old_bytes
        telemetry.emit_event(
            "tier_restore",
            tenant=t.name,
            from_tier=from_tier,
            to_tier=rung,
            reason=reason,
            repinned_bytes=int(repinned),
            evidence={"quantized_coordinates": len(errors)},
        )
        logger.info(
            "tenant %r stepped up the precision ladder %s -> %s (%s): "
            "%.2f MB re-pinned",
            t.name,
            from_tier,
            rung,
            reason,
            repinned / 1e6,
        )
        return int(repinned)

    def retune(self, *, max_wait_ms: Optional[float] = None) -> Dict[str, float]:
        """Live-adjust the micro-batching flush wait (the autopilot's
        batch/wait retune actuator, ISSUE 19). Only the WAIT is mutable
        online: the bucket ladder is compiled state — changing max_batch
        live would recompile every program, which is a reshard-class
        action, not a retune. Returns the displaced values so a rollback
        can restore them."""
        with self._cv:
            prev = {"max_wait_ms": self.max_wait_s * 1e3}
            if max_wait_ms is not None:
                if max_wait_ms < 0:
                    raise ValueError("max_wait_ms must be >= 0")
                self.max_wait_s = float(max_wait_ms) / 1e3
                self._cv.notify_all()
        return prev

    # -------------------------------------------------------------- scoring

    def _tenant(self, name: str) -> Tenant:
        with self._cv:
            t = self._tenants.get(name)
        if t is None:
            raise KeyError(
                f"unknown tenant {name!r} (admitted: "
                f"{sorted(self._tenants)})"
            )
        return t

    def submit(
        self,
        name: str,
        request: ScoreRequest,
        *,
        block: bool = False,
        deadline_ms: Optional[float] = None,
    ) -> "Future[ScoreResult]":
        """Enqueue one request for tenant `name`. Sheds with a typed
        `Overloaded` NAMING the tenant once its quota is full
        (`block=True` backpressures instead); deadline budget defaults
        per request, then per tenant. Co-batch-eligible tenants ride the
        registry's weighted-fair cross-tenant dispatch; everyone else
        goes straight to their own micro-batcher."""
        t = self._tenant(name)
        fut: "Future[ScoreResult]" = Future()
        now = time.monotonic()
        budget_ms = (
            deadline_ms
            if deadline_ms is not None
            else (
                request.deadline_ms
                if request.deadline_ms is not None
                else t.deadline_ms
            )
        )
        expiry = None if budget_ms is None else now + budget_ms / 1e3
        with telemetry.metric_label_scope(tenant=name):
            eligible = t.signature() is not None
            with self._cv:
                first_pass = True
                while True:
                    if self._stop:
                        raise RuntimeError("TenantRegistry is closed")
                    if self._unhealthy is not None:
                        raise BatcherUnhealthy(
                            f"tenant dispatch thread died: "
                            f"{self._unhealthy!r}"
                        ) from self._unhealthy
                    if t.draining:
                        # remove() is draining this tenant: refuse loudly
                        # instead of racing the teardown (ISSUE 18 — a
                        # retired shadow tenant must never accept traffic).
                        raise KeyError(
                            f"tenant {name!r} is being removed; no new "
                            "submits accepted while it drains"
                        )
                    if first_pass and eligible:
                        # One admission fault per submit, after the
                        # closed/unhealthy checks (the micro-batcher fires
                        # its own site for the direct path). Gated per
                        # tenant so a chaos plan targets one tenant's
                        # admissions.
                        first_pass = False
                        try:
                            if t.engine.inject_faults:
                                faults.fault_point("admit")
                        except faults.InjectedFault as exc:
                            t.shed += 1
                            faults.COUNTERS.increment(
                                "serving_shed_requests"
                            )
                            raise Overloaded(
                                f"admission fault injected: {exc}",
                                tenant=name,
                            ) from exc
                    if t.in_flight < t.quota:
                        break
                    if not block:
                        t.shed += 1
                        faults.COUNTERS.increment("serving_shed_requests")
                        raise Overloaded(
                            f"tenant {name!r} pending quota full "
                            f"({t.quota} requests); shed by per-tenant "
                            "admission control",
                            tenant=name,
                        )
                    self._cv.wait()
                t.in_flight += 1
                t.last_active = now
                if eligible:
                    t.queue.append((request, fut, now, expiry))
                    self._cv.notify_all()
            if not eligible:
                self._submit_direct(t, request, fut, now, expiry, block)
        return fut

    def score(self, name: str, request: ScoreRequest) -> ScoreResult:
        return self.submit(name, request, block=True).result()

    def _submit_direct(
        self,
        t: Tenant,
        request: ScoreRequest,
        fut: Future,
        t0: float,
        expiry: Optional[float],
        block: bool,
    ) -> None:
        """Route one request straight to the tenant's own micro-batcher
        (solo path: demoted / sharded / normalized tenants), chaining its
        future to the registry's so accounting stays uniform."""
        remaining = None
        if expiry is not None:
            remaining = max(0.0, (expiry - time.monotonic()) * 1e3)
        try:
            inner = t.batcher.submit(
                request, block=block, deadline_ms=remaining
            )
        except Overloaded as exc:
            self._resolve(
                t, fut, None, t0,
                error=Overloaded(str(exc), tenant=t.name),
            )
            return
        except BaseException as exc:  # noqa: BLE001 - surfaced via future
            self._resolve(t, fut, None, t0, error=exc)
            return
        self._chain(t, fut, inner, t0)

    def _chain(self, t: Tenant, fut: Future, inner: Future, t0: float) -> None:
        def _done(inner_fut: Future) -> None:
            exc = inner_fut.exception()
            if exc is not None:
                if isinstance(exc, Overloaded) and exc.tenant is None:
                    exc = Overloaded(str(exc), tenant=t.name)
                elif isinstance(exc, DeadlineExceeded) and exc.tenant is None:
                    exc = DeadlineExceeded(str(exc), tenant=t.name)
                self._resolve(t, fut, None, t0, error=exc)
            else:
                self._resolve(t, fut, inner_fut.result(), t0)

        inner.add_done_callback(_done)

    def _resolve(
        self,
        t: Tenant,
        fut: Future,
        result: Optional[ScoreResult],
        t0: float,
        *,
        error: Optional[BaseException] = None,
        cobatched: bool = False,
    ) -> None:
        """The one completion path for every route: per-tenant latency +
        counters, in-flight release (wakes blocked submitters), future
        resolution."""
        wall_ms = (time.monotonic() - t0) * 1e3
        with self._cv:
            t.in_flight -= 1
            if error is None:
                t.completed += 1
                t.latency.record(wall_ms)
                if cobatched:
                    t.cobatched += 1
            else:
                if isinstance(error, DeadlineExceeded):
                    t.deadline_missed += 1
                elif isinstance(error, Overloaded):
                    t.shed += 1
                t.failed += 1
            self._cv.notify_all()
        self._note_health(t)
        if fut.done():
            return
        if error is None:
            # Labeled observe (ISSUE 19): the aggregate series is
            # unchanged; the per-tenant sub-histogram is what the
            # autopilot's p95 retune rule reads.
            telemetry.METRICS.observe(
                "serving_latency_ms", wall_ms, labels=(("tenant", t.name),)
            )
            fut.set_result(result)
        else:
            fut.set_exception(error)

    def _note_health(self, t: Tenant) -> None:
        """Journal newly-appeared per-tenant degradation reasons (the
        `tenant_degraded` event): the per-tenant isolation story needs
        WHICH tenant degraded on the record, not just a health flip."""
        reasons = tuple(t.engine.health.degraded_reasons)
        if reasons and reasons != t._seen_reasons:
            new = [r for r in reasons if r not in t._seen_reasons]
            if new:
                telemetry.emit_event(
                    "tenant_degraded", tenant=t.name, reasons=list(new)
                )
        t._seen_reasons = reasons

    # --------------------------------------------------------- dispatch loop

    def _dispatch_loop(self) -> None:
        try:
            self._dispatch_loop_inner()
        except BaseException as exc:  # noqa: BLE001 - terminal thread guard
            logger.error("tenant dispatch thread died: %r", exc)
            faults.COUNTERS.increment("serving_flush_thread_failures")
            with self._cv:
                self._unhealthy = exc
                doomed: List[Tuple[Tenant, _Pending]] = []
                for t in self._tenants.values():
                    while t.queue:
                        doomed.append((t, t.queue.popleft()))
                self._cv.notify_all()
            for t, (_, fut, t0, _) in doomed:
                if fut.set_running_or_notify_cancel():
                    self._resolve(t, fut, None, t0, error=exc)
            for t in self._tenants.values():
                t.engine.health.add_degraded(
                    f"tenant_dispatch_dead: {exc!r}"
                )

    def _dispatch_loop_inner(self) -> None:
        while True:
            with self._cv:
                while not self._stop and not self._ripe_locked():
                    self._cv.wait(timeout=self._wait_timeout_locked())
                if self._stop and not any(
                    t.queue for t in self._tenants.values()
                ):
                    return
                claimed, expired = self._claim_locked()
                self._cv.notify_all()
            for t, fut, t0 in expired:
                with telemetry.metric_label_scope(tenant=t.name):
                    faults.COUNTERS.increment("serving_deadline_misses")
                self._resolve(
                    t, fut, None, t0,
                    error=DeadlineExceeded(
                        "request expired in the tenant queue before "
                        "batch assembly",
                        tenant=t.name,
                    ),
                )
            if not claimed:
                continue
            # Partition by co-batch signature; each partition is one
            # device dispatch (a tenant whose signature changed since
            # submit re-routes through its own batcher inside).
            groups: Dict[tuple, List[Tuple[Tenant, _Pending]]] = {}
            stale: List[Tuple[Tenant, _Pending]] = []
            for t, item in claimed:
                sig = t.signature()
                if sig is None:
                    stale.append((t, item))
                else:
                    groups.setdefault(sig, []).append((t, item))
            for t, item in stale:
                self._fallback(t, [item])
            for sig, items in groups.items():
                self._dispatch_cobatch(sig, items)

    def _ripe_locked(self) -> bool:
        now = time.monotonic()
        pending = 0
        for t in self._tenants.values():
            if not t.queue:
                continue
            pending += len(t.queue)
            front = t.queue[0]
            if front[3] is not None and now >= front[3]:
                return True  # expired head: claim promptly to fail it
            if (now - front[2]) >= self.max_wait_s:
                return True
        return pending >= self.max_batch

    def _wait_timeout_locked(self) -> Optional[float]:
        wake: Optional[float] = None
        for t in self._tenants.values():
            if not t.queue:
                continue
            front = t.queue[0]
            w = front[2] + self.max_wait_s
            if front[3] is not None:
                w = min(w, front[3])
            wake = w if wake is None else min(wake, w)
        if wake is None:
            return None
        return max(0.0, wake - time.monotonic())

    def _claim_locked(self):
        """Weighted-fair claim: up to max_batch slots split across
        backlogged tenants proportionally to weight (each gets at least
        one), rotation-started so equal-weight tenants alternate who
        claims first; leftover slots round-robin. Expired and cancelled
        requests are filtered here, before a slot is assembled for them."""
        now = time.monotonic()
        horizon = now + self._service_tail_s
        backlogged = [t for t in self._tenants.values() if t.queue]
        claimed: List[Tuple[Tenant, _Pending]] = []
        expired: List[Tuple[Tenant, Future, float]] = []
        if not backlogged:
            return claimed, expired
        start = self._rr % len(backlogged)
        self._rr += 1
        order = backlogged[start:] + backlogged[:start]
        slots = self.max_batch
        total_w = sum(t.weight for t in order) or 1.0

        def _take(t: Tenant, n: int) -> int:
            took = 0
            while took < n and t.queue:
                item = t.queue.popleft()
                claim = item[1].set_running_or_notify_cancel()
                if not claim:
                    # Client-cancelled while queued: the future resolves
                    # itself, but the admission slot must be released
                    # HERE — _resolve never runs for a cancelled future,
                    # and a leaked in_flight count would wedge the
                    # tenant's quota shut forever.
                    t.in_flight -= 1
                    continue
                if item[3] is not None and horizon >= item[3]:
                    expired.append((t, item[1], item[2]))
                    continue
                claimed.append((t, item))
                took += 1
            return took

        for t in order:
            if slots <= 0:
                break
            share = max(1, int(self.max_batch * t.weight / total_w))
            slots -= _take(t, min(share, slots))
        while slots > 0:
            progressed = False
            for t in order:
                if slots <= 0:
                    break
                got = _take(t, 1)
                slots -= got
                progressed = progressed or bool(got)
            if not progressed:
                break
        if expired and not claimed:
            # Same decay rule as the micro-batcher: an expiry round with
            # no dispatch must re-probe the true service time, or a
            # one-off spike pre-fails short-budget requests forever.
            self._service_tail_s *= 0.5
        return claimed, expired

    def _fallback(
        self, t: Tenant, items: Sequence[_Pending], *, degraded: bool = False
    ) -> None:
        """Route claimed items to the tenant's own micro-batcher (which
        owns the retry / FE-only / circuit policy). Called for stale
        signatures, circuit-open tenants, per-tenant injected faults, and
        whole-co-batch failures — isolation means ONLY this tenant's
        items re-route."""
        with telemetry.metric_label_scope(tenant=t.name):
            if degraded:
                t.cobatch_degraded += 1
                faults.COUNTERS.increment("serving_degraded_batches")
            now = time.monotonic()
            for req, fut, t0, expiry in items:
                if expiry is not None and now >= expiry:
                    self._resolve(
                        t, fut, None, t0,
                        error=DeadlineExceeded(
                            "request expired before its co-batch fallback",
                            tenant=t.name,
                        ),
                    )
                    continue
                remaining = (
                    None if expiry is None else (expiry - now) * 1e3
                )
                try:
                    inner = t.batcher.submit(
                        req, block=False, deadline_ms=remaining
                    )
                except Overloaded as exc:
                    self._resolve(
                        t, fut, None, t0,
                        error=Overloaded(str(exc), tenant=t.name),
                    )
                except BaseException as exc:  # noqa: BLE001 - via future
                    self._resolve(t, fut, None, t0, error=exc)
                else:
                    self._chain(t, fut, inner, t0)

    def _dispatch_cobatch(
        self, sig: tuple, items: List[Tuple[Tenant, _Pending]]
    ) -> None:
        """One cross-tenant device dispatch. Group membership is EVERY
        registry tenant sharing the signature (stable program shapes —
        an idle member still contributes its parameter arrays), slots
        carry the claimed items. Per-tenant fault sites fire inside the
        tenant's label scope and degrade ONLY that tenant's slice to its
        solo path; a whole-dispatch failure (device error, watchdog
        DeviceHang) degrades every slice to its OWN tenant's batcher —
        one tenant's blast radius never fails another's future."""
        with self._cv:
            members = sorted(
                (
                    t
                    for t in self._tenants.values()
                    if t.signature() == sig
                ),
                key=lambda t: t.order,
            )
        member_index = {t.name: j for j, t in enumerate(members)}
        # Circuit routing + per-tenant permits: an open breaker routes
        # the tenant's items through its batcher (FE-only answers there).
        by_tenant: Dict[str, List[_Pending]] = {}
        for t, item in items:
            by_tenant.setdefault(t.name, []).append(item)
        live: List[Tuple[Tenant, List[_Pending]]] = []
        permits: Dict[str, object] = {}
        for name, t_items in by_tenant.items():
            t = self._tenants[name]
            if name not in member_index:
                self._fallback(t, t_items)
                continue
            permit = t.engine.breaker.acquire()
            if permit is None:
                self._fallback(t, t_items)
                continue
            permits[name] = permit
            live.append((t, t_items))
        if not live:
            return

        # Per-tenant engine-state snapshots (active++ so a concurrent
        # demotion's drain waits for this dispatch). The inner dispatch
        # pops permits as it resolves them, so the set of tenants whose
        # active count must be released is captured HERE.
        states = {}
        active_names = set(permits)
        for t in members:
            with t.engine._lock:
                st = t.engine._state
                if t.name in active_names:
                    st.active += 1
                states[t.name] = st
        try:
            self._dispatch_cobatch_inner(
                sig, members, member_index, live, permits, states
            )
        finally:
            for t in members:
                if t.name in active_names:
                    with t.engine._lock:
                        states[t.name].active -= 1
                        t.engine._lock.notify_all()

    def _dispatch_cobatch_inner(
        self, sig, members, member_index, live, permits, states
    ) -> None:
        task, kinds, dims = sig
        # Per-tenant pack: lookup faults fire per tenant inside its label
        # scope; an injected lookup degrades ONLY that tenant's slice.
        packed: List[Tuple[Tenant, _Pending, int, List]] = []
        survivors: List[Tuple[Tenant, List[_Pending]]] = []
        for t, t_items in live:
            st = states[t.name]
            try:
                with telemetry.metric_label_scope(tenant=t.name):
                    if t.engine.inject_faults:
                        faults.fault_point("lookup")
                        faults.fault_point("score")
                    rows_cold = self._lookup_tenant(st, t_items)
            except faults.InjectedFault:
                t.engine.breaker.on_abandon(permits.pop(t.name))
                self._fallback(t, t_items, degraded=True)
                continue
            survivors.append((t, t_items))
            for item, rc in zip(t_items, rows_cold):
                packed.append((t, item, member_index[t.name], rc))
        if not packed:
            return

        n = len(packed)
        # The claim phase bounds every round at max_batch slots total, so
        # a partition can never exceed the bucket ladder.
        assert n <= self.max_batch, (n, self.max_batch)
        bucket = next(b for b in self.buckets if b >= n)
        t_d = time.monotonic()
        try:
            total, means, cold_flags = self._pack_and_dispatch(
                sig, members, states, packed, bucket, survivors
            )
        except BaseException as exc:  # noqa: BLE001 - isolated below
            # A whole-dispatch failure is ambiguous across tenants, and a
            # malformed request poisons the shared PACK exactly like a
            # device error poisons the shared program — so the guard
            # covers packing AND dispatch: abandon every permit and let
            # each tenant's OWN solo path judge its own requests (the
            # micro-batcher's per-request isolation fails only the
            # offending future). The isolation contract is that no
            # tenant's future fails — and the dispatch thread never dies
            # — because of a co-batched neighbor.
            logger.warning(
                "co-batch of %d across %d tenant(s) degraded to solo "
                "dispatch: %s",
                n,
                len(survivors),
                exc,
            )
            for t, t_items in survivors:
                t.engine.breaker.on_abandon(permits.pop(t.name))
                self._fallback(t, t_items, degraded=True)
            return
        t_done = time.monotonic()
        with self._cv:
            self._cobatch_dispatches += 1
            try:
                self._cobatch_compiles = int(self._jit._cache_size())
            except AttributeError:
                pass
            # Decaying max of dispatch service time (claim -> answers),
            # the micro-batcher's deadline-horizon estimate.
            self._service_tail_s = max(
                t_done - t_d, 0.9 * self._service_tail_s
            )
        faults.COUNTERS.increment("tenant_cobatch_dispatches")
        for t, _ in survivors:
            t.engine.breaker.on_success(permits.pop(t.name))
        for i, (t, item, _, rc) in enumerate(packed):
            flags = cold_flags[i]
            res = ScoreResult(
                score=float(total[i]),
                mean=float(means[i]),
                uid=item[0].uid,
                cold_start=bool(flags.any()),
                n_cold=int(flags.sum()),
                fe_only=False,
            )
            self._resolve(t, item[1], res, item[2], cobatched=True)

    def _pack_and_dispatch(
        self, sig, members, states, packed, bucket, survivors
    ):
        """Assemble the shared bucket (per-coordinate feature buffers,
        per-tenant row arrays, tenant ids) and run ONE device dispatch.
        Raises on ANY failure — packing a malformed payload included —
        and the caller degrades every tenant's slice to its own solo
        path; nothing here may kill the dispatch thread."""
        task, kinds, dims = sig
        n = len(packed)
        offsets = np.zeros(bucket, np.float32)
        tids = np.zeros(bucket, np.int32)
        feats = [np.zeros((bucket, d), np.float32) for d in dims]
        re_positions = [k for k, kind in enumerate(kinds) if kind == "re"]
        rows = {
            k: [
                np.full(
                    bucket,
                    states[m.name].coords[k].unseen_row,
                    np.int32,
                )
                for m in members
            ]
            for k in re_positions
        }
        cold_flags = np.zeros((bucket, len(re_positions)), bool)
        for i, (t, item, tj, rc) in enumerate(packed):
            req = item[0]
            offsets[i] = req.offset
            tids[i] = tj
            st = states[t.name]
            for k, c in enumerate(st.coords):
                payload = req.features.get(c.shard)
                if payload is None:
                    continue
                if isinstance(payload, tuple):
                    idx, vals = payload
                    np.add.at(
                        feats[k][i], np.asarray(idx, np.int64), vals
                    )
                else:
                    feats[k][i, :] = payload
            for j, k in enumerate(re_positions):
                rows[k][tj][i] = rc[j]
                cold_flags[i, j] = rc[j] == st.coords[k].unseen_row

        params = tuple(
            tuple(states[m.name].coords[k].params for m in members)
            for k in range(len(kinds))
        )
        rows_arg = tuple(
            tuple(jnp.asarray(r) for r in rows[k]) if k in rows else None
            for k in range(len(kinds))
        )
        with telemetry.span(
            "tenant_cobatch",
            size=n,
            bucket=bucket,
            tenants=[t.name for t, _ in survivors],
        ):
            with self._watchdog.guard(
                self._watchdog_ms,
                f"tenant co-batch dispatch (bucket {bucket})",
            ):
                with self._device_mutex:
                    total, means = self._jit(
                        jnp.asarray(offsets),
                        jnp.asarray(tids),
                        tuple(jnp.asarray(f) for f in feats),
                        rows_arg,
                        params,
                        kinds=kinds,
                        task=task,
                    )
                total, means = jax.device_get((total, means))
        return np.asarray(total), np.asarray(means), cold_flags

    def _lookup_tenant(self, state, t_items) -> List[List[int]]:
        """Resolve one tenant's claimed items to per-RE-position rows
        (shard-load telemetry recorded exactly like the solo path)."""
        out = [[] for _ in t_items]
        for k, c in enumerate(state.coords):
            if not c.is_random_effect:
                continue
            ids = [
                item[0].entity_ids.get(c.random_effect_type)
                for item in t_items
            ]
            resolved, _ = c.lookup_rows(ids)
            sh = getattr(c, "shard_health", None)
            if sh is not None:
                sh.record_loads(resolved, c.unseen_row)
            for i, r in enumerate(resolved):
                out[i].append(int(r))
        return out

    # -------------------------------------------------------------- metrics

    def metrics(self) -> Dict[str, object]:
        """One snapshot: registry-level co-batch accounting plus a
        per-tenant block zipping TENANT_BLOCK_KEYS (the serving-summary
        `tenants` block and the bench multi_tenant section both consume
        it — every key always present so absence is loud)."""
        with self._cv:
            tenants = list(self._tenants.values())
            cobatch = self._cobatch_dispatches
        wd_labeled = telemetry.METRICS.labeled_counters("watchdog_trips")
        out: Dict[str, object] = {
            "n_tenants": len(tenants),
            "max_batch": self.max_batch,
            "cobatch_dispatches": cobatch,
            "cobatch_compiles": self._cobatch_compiles,
            "tenants": {},
        }
        for t in tenants:
            bm = t.batcher.metrics()
            health = t.engine.health.snapshot()
            block = {
                "completed": t.completed,
                "failed": t.failed,
                # Registry-side tallies only: every shed/deadline outcome
                # resolves through the registry future (submit raise,
                # claim expiry, or a chained batcher error), so adding
                # the batcher's own counters would double-count fallback
                # rejections.
                "shed": t.shed,
                "deadline_missed": t.deadline_missed,
                "fe_only_answers": int(bm["fe_only_answers"]),
                "degraded_batches": (
                    t.cobatch_degraded + int(bm["degraded_batches"])
                ),
                "cobatched_requests": t.cobatched,
                "p50_ms": (
                    round(float(t.latency.percentile(50.0)), 4)
                    if t.latency.count
                    else None
                ),
                "p95_ms": (
                    round(float(t.latency.percentile(95.0)), 4)
                    if t.latency.count
                    else None
                ),
                "p99_ms": (
                    round(float(t.latency.percentile(99.0)), 4)
                    if t.latency.count
                    else None
                ),
                "state": health["state"],
                "degraded_reasons": health["degraded_reasons"],
                "circuit_state": t.engine.breaker.snapshot()[
                    "circuit_state"
                ],
                "demoted": t.demoted,
                "device_bytes": t.device_bytes(),
                "watchdog_trips": int(
                    wd_labeled.get(f"tenant={t.name}", 0)
                ),
                # Precision-ladder sub-block (ISSUE 20): the tenant's
                # rung + ladder history, TIER_BLOCK_KEYS order.
                "tier": {
                    "tier": t.tier,
                    "quantized_coords": sum(
                        1
                        for k in t.engine._state.kinds
                        if k in ("re_bf16", "re_i8")
                    ),
                    "demotions": t.tier_demotions,
                    "restores": t.tier_restores,
                    "rollbacks": t.tier_rollbacks,
                    "quant_error_max": t.quant_error_max,
                },
            }
            assert set(block) == set(TENANT_BLOCK_KEYS), (
                "tenant metrics block drifted from utils/contracts."
                "TENANT_BLOCK_KEYS"
            )
            assert set(block["tier"]) == set(TIER_BLOCK_KEYS), (
                "tenant tier sub-block drifted from utils/contracts."
                "TIER_BLOCK_KEYS"
            )
            out["tenants"][t.name] = block
        return out

    # ------------------------------------------------------------ lifecycle

    @property
    def tenant_names(self) -> List[str]:
        with self._cv:
            return list(self._tenants)

    def tenant(self, name: str) -> Tenant:
        return self._tenant(name)

    def remove(
        self,
        name: str,
        *,
        release_bundle: bool = False,
        drain_timeout_s: float = 30.0,
    ) -> None:
        """Retire ONE tenant while the rest of the fleet keeps serving
        (ISSUE 18: a rejected shadow challenger is torn down with zero
        champion impact). New submits refuse immediately; queued and
        in-flight requests drain to completion (the dispatch thread may
        hold claimed items, so the tenant entry stays visible until
        in-flight hits zero — deleting early would strand them); then the
        tenant's engine closes (batcher + watchdog join there) and its
        bundle is optionally released. A tenant that cannot drain within
        `drain_timeout_s` raises loudly and stays admitted."""
        t = self._tenant(name)
        deadline = time.monotonic() + drain_timeout_s
        with self._cv:
            t.draining = True
            self._cv.notify_all()
            while t.queue or t.in_flight:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    t.draining = False
                    raise RuntimeError(
                        f"tenant {name!r} did not drain within "
                        f"{drain_timeout_s}s ({len(t.queue)} queued, "
                        f"{t.in_flight} in flight); still admitted"
                    )
                self._cv.wait(timeout=min(0.1, remaining))
            del self._tenants[name]
        t.engine.close()
        if release_bundle and not t.engine._state.bundle.released:
            t.engine._state.bundle.release()

    def close(self, release_bundles: bool = False) -> None:
        """Drain the co-batch queue (pending requests still answered),
        join the dispatch thread, close every tenant's engine (its
        batcher + watchdog join there) and the registry watchdog.
        Idempotent."""
        with self._cv:
            if self._stop:
                return
            self._stop = True
            self._cv.notify_all()
        self._thread.join()
        with self._cv:
            tenants = list(self._tenants.values())
        for t in tenants:
            t.engine.close()
            if release_bundles and not t.engine._state.bundle.released:
                t.engine._state.bundle.release()
        self._watchdog.close()

    def __enter__(self) -> "TenantRegistry":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

"""Serving lifecycle: health states, circuit breaking, atomic bundle swap.

PR 4's engine scores requests; this module is the management tier that
keeps it scoring under fire — the Snap ML hierarchy lesson (PAPERS.md,
arxiv 1803.06333) applied to serving: the accelerator runs fixed fused
programs, and everything that can go wrong around them (overload, a
persistently faulting device, a model push) is handled by explicit host
machinery with typed outcomes, never a hang or a silent wrong answer.

Pieces, all consumed by serving/engine.py and serving/batcher.py:

* Typed failures — `Overloaded` (admission control shed the request),
  `DeadlineExceeded` (the request expired in queue; standard library
  TimeoutError subclass so generic timeout handling catches it),
  `BatcherUnhealthy` (the flush thread died; every pending future got the
  error), `HbmBudgetExceeded` (a bundle swap would not fit device memory),
  `SwapIncompatible` (the next bundle's coordinate structure does not
  match the compiled programs).

* `ServingState` + `HealthStateMachine` — STARTING → READY ⇄ DEGRADED →
  DRAINING → CLOSED. DEGRADED is reason-tracked: the circuit opening and a
  flush-thread death each add a reason; READY returns only when every
  reason clears (a recovered circuit must not mask a dead batcher).
  Transitions are timestamped for the metrics snapshot.

* `CircuitBreaker` — counts CONSECUTIVE device-class failures that
  survived the bounded retry policy (utils/faults.is_device_error; a
  malformed request never counts). At `threshold` the circuit OPENs:
  traffic is routed to the engine's fixed-effect-only tier (bitwise-equal
  to FE-only GameTransformer output — the pinned zero-row cold-start
  path) instead of failing. After `probe_interval_s` one probe request is
  allowed through the full path (HALF_OPEN); success re-CLOSEs, failure
  re-arms the interval. The permit protocol is explicit: every
  `acquire() == True` must be resolved by exactly one of `on_success` /
  `on_failure` / `on_abandon` (abandon = the attempt failed for a
  non-device reason and proves nothing about the device).

* `BundleManager` — versioned atomic hot-swap. `swap()` double-buffers
  the next `ServingBundle` into device memory (HBM-budget check BEFORE
  staging), warms the engine's bucket programs against the new parameters
  (so the flip compiles nothing on live traffic), flips scoring atomically
  between batches, drains in-flight batches off the old bundle, and
  releases it. Staging or warmup faulting (fault sites `swap_stage`,
  `swap_commit`) rolls back: the old bundle keeps serving, the new one is
  released, `serving_swap_rollbacks` counts it, and the error propagates
  to the caller. Live traffic never observes a half-swapped engine.
"""

from __future__ import annotations

import collections
import enum
import logging
import threading
import time
from typing import Callable, Deque, Dict, List, Optional, Tuple

from photon_ml_tpu.utils import faults, telemetry
from photon_ml_tpu.utils.knobs import get_knob

logger = logging.getLogger(__name__)


# ------------------------------------------------------------ typed failures


class Overloaded(RuntimeError):
    """Admission control rejected the request: the pending queue is full
    (or an armed `admit` fault shed it). The client should back off —
    never retry in a tight loop.

    `tenant` names the overloaded tenant on the multi-tenant registry
    path (serving/tenancy.py) — one tenant blowing its quota is ITS
    typed rejection, never a shared-queue ambiguity; None on the
    single-tenant batcher path."""

    def __init__(self, *args, tenant: Optional[str] = None):
        super().__init__(*args)
        self.tenant = tenant


class DeadlineExceeded(TimeoutError):
    """The request's deadline budget expired while it waited in queue; it
    was failed BEFORE wasting a device slot. `tenant` names the owning
    tenant on the multi-tenant registry path; None otherwise."""

    def __init__(self, *args, tenant: Optional[str] = None):
        super().__init__(*args)
        self.tenant = tenant


class BatcherUnhealthy(RuntimeError):
    """The micro-batcher's flush thread died. Every pending future was
    failed with the original error; new submits are refused."""


class HbmBudgetExceeded(RuntimeError):
    """Double-buffering the next bundle would exceed the device-memory
    budget; nothing was staged."""


class SwapIncompatible(ValueError):
    """The next bundle's coordinate structure (ids, kinds, shards, dims)
    does not match the serving engine's compiled program family."""


# -------------------------------------------------------------- health state


class ServingState(enum.Enum):
    STARTING = "STARTING"
    READY = "READY"
    DEGRADED = "DEGRADED"
    DRAINING = "DRAINING"
    CLOSED = "CLOSED"


# The legal edges. DEGRADED<->READY flips with the degraded-reason set;
# DRAINING only completes to CLOSED; CLOSED is terminal.
_TRANSITIONS = {
    ServingState.STARTING: {
        ServingState.READY,
        ServingState.DEGRADED,
        ServingState.DRAINING,
        ServingState.CLOSED,
    },
    ServingState.READY: {
        ServingState.DEGRADED,
        ServingState.DRAINING,
        ServingState.CLOSED,
    },
    ServingState.DEGRADED: {
        ServingState.READY,
        ServingState.DRAINING,
        ServingState.CLOSED,
    },
    ServingState.DRAINING: {ServingState.CLOSED},
    ServingState.CLOSED: set(),
}


class HealthStateMachine:
    """Thread-safe serving health with reason-tracked degradation.

    `add_degraded(reason)` / `clear_degraded(reason)` manage a set of
    active degradation reasons; the READY <-> DEGRADED edge follows that
    set, so two independent degradations (open circuit + dead batcher)
    must BOTH clear before the engine reports READY again.
    """

    # Bounded transition history: a flapping degradation (intermittent
    # device, 1s probe interval) appends two entries per flap forever; a
    # metrics scrape must not pay O(uptime). The total count is kept
    # separately so truncation is visible.
    HISTORY_LIMIT = 64

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._state = ServingState.STARTING
        self._reasons: List[str] = []
        self._history: Deque[Tuple[float, str, str]] = collections.deque(
            [(clock(), "", ServingState.STARTING.value)],
            maxlen=self.HISTORY_LIMIT,
        )
        self._transitions_total = 0

    @property
    def state(self) -> ServingState:
        with self._lock:
            return self._state

    @property
    def degraded_reasons(self) -> List[str]:
        with self._lock:
            return list(self._reasons)

    def _to_locked(self, new: ServingState) -> None:
        if new is self._state:
            return
        if new not in _TRANSITIONS[self._state]:
            raise RuntimeError(
                f"illegal serving-state transition {self._state.value} -> "
                f"{new.value}"
            )
        self._history.append((self._clock(), self._state.value, new.value))
        self._transitions_total += 1
        logger.info("serving state %s -> %s", self._state.value, new.value)
        # Run journal (ISSUE 11): every health transition is a typed JSONL
        # line in the ambient journal (free no-op without one installed).
        telemetry.emit_event(
            "health_transition",
            from_state=self._state.value,
            to_state=new.value,
            reasons=list(self._reasons),
        )
        self._state = new

    def mark_ready(self) -> None:
        """STARTING -> READY (or DEGRADED, if reasons accrued during
        bring-up). No-op once past STARTING."""
        with self._lock:
            if self._state is ServingState.STARTING:
                self._to_locked(
                    ServingState.DEGRADED if self._reasons else ServingState.READY
                )

    def add_degraded(self, reason: str) -> None:
        with self._lock:
            if reason not in self._reasons:
                self._reasons.append(reason)
            if self._state is ServingState.READY:
                self._to_locked(ServingState.DEGRADED)

    def clear_degraded(self, reason: str) -> None:
        with self._lock:
            if reason in self._reasons:
                self._reasons.remove(reason)
            if self._state is ServingState.DEGRADED and not self._reasons:
                self._to_locked(ServingState.READY)

    def begin_drain(self) -> None:
        with self._lock:
            if self._state not in (ServingState.DRAINING, ServingState.CLOSED):
                self._to_locked(ServingState.DRAINING)

    def close(self) -> None:
        with self._lock:
            if self._state is not ServingState.CLOSED:
                if self._state is not ServingState.DRAINING:
                    self._to_locked(ServingState.DRAINING)
                self._to_locked(ServingState.CLOSED)

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            hist = list(self._history)
            return {
                "state": self._state.value,
                "degraded_reasons": list(self._reasons),
                "transitions_total": self._transitions_total,
                "transitions": [
                    {"t": round(t, 4), "from": a, "to": b}
                    for t, a, b in hist
                    if a  # drop the synthetic initial STARTING entry
                ],
            }


# ------------------------------------------------------------ circuit breaker


class CircuitState(enum.Enum):
    CLOSED = "CLOSED"
    OPEN = "OPEN"
    HALF_OPEN = "HALF_OPEN"


class CircuitPermit:
    """One full-path attempt's token. `probe=True` marks THE half-open
    probe permit; permits handed out while CLOSED are free. Resolution
    methods key off the token, so a stale CLOSED-era permit resolving
    late can never clobber another batcher's in-flight probe."""

    __slots__ = ("probe",)

    def __init__(self, probe: bool):
        self.probe = probe


class CircuitBreaker:
    """Consecutive-failure breaker with single-probe half-open recovery.

    Permit protocol (the batcher is the only caller): `acquire()` asks
    whether THIS attempt may use the full scoring path, returning a
    `CircuitPermit` or None. While CLOSED permits are free (no
    bookkeeping). While OPEN it returns None — route to the FE-only tier
    — until `probe_interval_s` has elapsed, when exactly one caller gets
    THE probe permit (HALF_OPEN). Every permit must be resolved with
    exactly one of `on_success(permit)` (re-closes), `on_failure(permit)`
    (re-opens and re-arms the interval), or `on_abandon(permit)` (returns
    the permit without judging the device — the attempt failed for a
    request-shaped reason). An unresolved probe would wedge the breaker
    in HALF_OPEN forever — the protocol makes that a local bug, not a
    distributed one; the permit token keeps concurrent batchers honest.
    """

    def __init__(
        self,
        *,
        threshold: int = 5,
        probe_interval_s: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
        on_open: Optional[Callable[[], None]] = None,
        on_close: Optional[Callable[[], None]] = None,
    ):
        if threshold < 1:
            raise ValueError("threshold must be >= 1")
        self.threshold = int(threshold)
        self.probe_interval_s = float(probe_interval_s)
        self._clock = clock
        self._on_open = on_open
        self._on_close = on_close
        self._lock = threading.Lock()
        self._state = CircuitState.CLOSED
        self._consecutive = 0
        self._probing = False
        self._next_probe_t = 0.0
        self._opens = 0
        self._probes = 0

    @property
    def state(self) -> CircuitState:
        with self._lock:
            return self._state

    @property
    def is_open(self) -> bool:
        return self.state is not CircuitState.CLOSED

    def acquire(self) -> Optional[CircuitPermit]:
        with self._lock:
            if self._state is CircuitState.CLOSED:
                return CircuitPermit(probe=False)
            if (
                self._state is CircuitState.OPEN
                and self._clock() >= self._next_probe_t
            ):
                self._state = CircuitState.HALF_OPEN
                self._probing = True
                self._probes += 1
                return CircuitPermit(probe=True)
            if self._state is CircuitState.HALF_OPEN and not self._probing:
                self._probing = True
                self._probes += 1
                return CircuitPermit(probe=True)
            return None

    def on_success(self, permit: CircuitPermit) -> None:
        notify = False
        with self._lock:
            if permit.probe:
                self._probing = False
            self._consecutive = 0
            # Only THE probe may re-close an open circuit: a stale
            # CLOSED-era permit succeeding late (acquired before the
            # failures that opened it) is evidence about the PAST, and
            # letting it close the breaker would route traffic back to a
            # dead device without any probe.
            if permit.probe and self._state is not CircuitState.CLOSED:
                self._state = CircuitState.CLOSED
                notify = True
                logger.info("serving circuit re-closed (probe succeeded)")
        if notify and self._on_close is not None:
            self._on_close()

    def on_failure(self, permit: CircuitPermit) -> None:
        notify = False
        with self._lock:
            if permit.probe:
                self._probing = False
            self._consecutive += 1
            # A failed PROBE re-opens unconditionally; a free (CLOSED-era)
            # permit failing while another batcher's probe is in flight
            # only counts toward the consecutive threshold — it must not
            # decide the probe's outcome.
            should_open = (
                permit.probe and self._state is CircuitState.HALF_OPEN
            ) or self._consecutive >= self.threshold
            if should_open and self._state is not CircuitState.OPEN:
                self._state = CircuitState.OPEN
                self._opens += 1
                notify = True
                logger.warning(
                    "serving circuit OPEN after %d consecutive device "
                    "failure(s); probing in %.2fs",
                    self._consecutive,
                    self.probe_interval_s,
                )
            if self._state is CircuitState.OPEN:
                self._next_probe_t = self._clock() + self.probe_interval_s
        if notify:
            faults.COUNTERS.increment("serving_circuit_opens")
            if self._on_open is not None:
                self._on_open()

    def on_abandon(self, permit: CircuitPermit) -> None:
        """Return an unused permit: the attempt failed, but not in a way
        that says anything about the device (e.g. a malformed request)."""
        if permit.probe:
            with self._lock:
                self._probing = False

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "circuit_state": self._state.value,
                "circuit_opens": self._opens,
                "circuit_probes": self._probes,
                "consecutive_device_failures": self._consecutive,
            }


# --------------------------------------------------------------- bundle swap


def device_memory_budget_bytes() -> Optional[int]:
    """The HBM budget a swap must fit in: PHOTON_SERVING_HBM_BUDGET_BYTES
    when set, else the device's reported bytes_limit (TPU/GPU runtimes
    expose memory_stats; CPU does not — None means 'unknown, skip the
    check' there, matching the virtual-mesh test platform)."""
    budget = int(get_knob("PHOTON_SERVING_HBM_BUDGET_BYTES"))
    if budget > 0:
        return budget
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
        if stats and "bytes_limit" in stats:
            return int(stats["bytes_limit"])
    except Exception:  # noqa: BLE001 - absent API means unknown budget
        pass
    return None


def _bundle_device_bytes(bundle) -> int:
    """Per-shard device bytes of a bundle for the HBM budget check: the
    hot-set tier of two-tier stores plus every pinned plane, with
    entity-sharded matrices charged at bytes/n_devices (the per-device
    peak is what a budget bounds). Falls back to `upload_bytes` for
    bundle-shaped test doubles."""
    fn = getattr(bundle, "device_bytes_per_shard", None)
    if fn is not None:
        try:
            return int(fn())
        except Exception:  # noqa: BLE001 - accounting must not kill a swap
            pass
    return int(getattr(bundle, "upload_bytes", 0))


class BundleManager:
    """Versioned, atomic, rollback-safe hot-swap of a ServingEngine's
    bundle. One manager per engine; `swap()` is serialized (a second
    concurrent swap waits its turn — model pushes are rare and ordering
    them is the correct semantics).

    The HBM budget check charges, per shard: both bundle generations'
    device-resident bytes (`_bundle_device_bytes` — the hot-set tier for
    two-tier bundles, bytes/n_devices for entity-sharded matrices) plus
    the engine's per-bucket warmup request buffers, so a sharded or
    two-tier swap can't over-commit a shard during the double-buffered
    window."""

    def __init__(self, engine):
        self.engine = engine
        self._swap_lock = threading.Lock()
        self._swaps = 0
        self._rollbacks = 0

    @property
    def mutex(self) -> threading.Lock:
        """The generation-change mutex. Shared with the live-reshard
        orchestrator (serving/reshard.py) so a model push and a mesh
        reshard serialize instead of racing the engine state — both are
        rare and ordering them is the correct semantics."""
        return self._swap_lock

    # Public counters (read by engine.metrics()).
    @property
    def swaps(self) -> int:
        return self._swaps

    @property
    def rollbacks(self) -> int:
        return self._rollbacks

    @property
    def version(self) -> int:
        return self.engine._state.version

    def swap(
        self,
        next_bundle,
        *,
        expected_bytes: Optional[int] = None,
        hbm_budget_bytes: Optional[int] = None,
        release_old: bool = True,
        drain_timeout_s: float = 30.0,
    ) -> Dict[str, object]:
        """Replace the engine's bundle with `next_bundle` under live
        traffic. `next_bundle` is a ServingBundle or a zero-arg builder
        returning one (the builder form is the production path: the HBM
        check runs BEFORE any device allocation, using `expected_bytes`).

        Sequence: budget check -> `swap_stage` fault point + build (staged
        double-buffered; transient staging faults get the bounded retry
        policy) -> compatibility check -> warm every bucket program against
        the new parameters -> `swap_commit` fault point -> atomic flip ->
        drain in-flight batches off the old state -> release the old
        bundle. Any failure before the flip rolls back: the old bundle
        never stopped serving, the new one is released, and the error
        propagates (counted in `serving_swap_rollbacks`).
        """
        with self._swap_lock:
            engine = self.engine
            old_state = engine._state
            builder = next_bundle if callable(next_bundle) else None

            # HBM budget: both generations are resident during the swap,
            # PLUS the pre-warm's per-bucket request buffers (warmup
            # compiles every bucket against the new parameters before the
            # flip). Accounting is PER SHARD — entity-sharded matrices
            # divide over their mesh and two-tier bundles charge only
            # their hot set — so a sharded swap can't over-commit a shard.
            budget = (
                hbm_budget_bytes
                if hbm_budget_bytes is not None
                else device_memory_budget_bytes()
            )
            need = expected_bytes
            if need is None and builder is None:
                need = _bundle_device_bytes(next_bundle) or None
            have = _bundle_device_bytes(old_state.bundle)
            warm = int(
                getattr(engine, "warmup_buffer_bytes", lambda *a: 0)()
            )
            if (
                budget is not None
                and need is not None
                and have + need + warm > budget
            ):
                raise HbmBudgetExceeded(
                    f"staging {need} bytes beside the active bundle's {have} "
                    f"bytes + {warm} bytes of warmup request buffers exceeds "
                    f"the {budget}-byte HBM budget; swap refused before "
                    "staging"
                )

            staged = None
            try:
                t0 = time.perf_counter()

                def _stage():
                    faults.fault_point("swap_stage")
                    return builder() if builder is not None else next_bundle

                staged = faults.retry(_stage, label="bundle swap staging")
                if getattr(staged, "released", False):
                    raise SwapIncompatible("next bundle is already released")
                # Post-build budget re-check for prebuilt/unknown sizes.
                got = _bundle_device_bytes(staged)
                if (
                    budget is not None
                    and need is None
                    and have + got + warm > budget
                ):
                    raise HbmBudgetExceeded(
                        f"staged bundle is {got} bytes/shard; with the active "
                        f"bundle's {have} bytes + {warm} bytes of warmup "
                        f"request buffers that exceeds the {budget}-byte HBM "
                        "budget"
                    )
                new_state = engine._build_state(
                    staged, version=old_state.version + 1
                )
                self._check_compatible(old_state, new_state)
                # Re-check against the NEW state's warmup buffers: the
                # incoming bundle may need bigger per-bucket scratch (a
                # two-tier coordinate's override buffers, wider shards)
                # than the pre-staging estimate taken from the old state.
                warm_new = int(
                    getattr(engine, "warmup_buffer_bytes", lambda *a: 0)(
                        new_state
                    )
                )
                if (
                    budget is not None
                    and have + got + max(warm, warm_new) > budget
                ):
                    raise HbmBudgetExceeded(
                        f"staged bundle is {got} bytes/shard; with the "
                        f"active bundle's {have} bytes + {max(warm, warm_new)} "
                        "bytes of warmup request buffers that exceeds the "
                        f"{budget}-byte HBM budget"
                    )
                # Pre-compile the new parameter shapes for every bucket so
                # the flip pays zero compile latency on live traffic. The
                # compile delta bumps the engine's warmup baseline at
                # commit — staging compiles are warmup, not hot-path.
                compiles_before_warm = engine.compiles
                engine._warm_state(new_state)
                staging_compiles = engine.compiles - compiles_before_warm
                faults.fault_point("swap_commit")
                stage_s = time.perf_counter() - t0
            except BaseException:
                self._rollbacks += 1
                faults.COUNTERS.increment("serving_swap_rollbacks")
                telemetry.emit_event(
                    "bundle_swap",
                    version=old_state.version + 1,
                    outcome="rolled_back",
                )
                logger.warning(
                    "bundle swap to version %d rolled back; version %d "
                    "keeps serving",
                    old_state.version + 1,
                    old_state.version,
                )
                if staged is not None and staged is not old_state.bundle:
                    try:
                        staged.release()
                    except Exception:  # noqa: BLE001 - rollback best-effort
                        pass
                raise

            # The flip itself: one attribute assignment under the engine
            # lock — in-flight batches finish on the old state, every batch
            # claimed after this scores on the new one.
            engine._commit_state(new_state, baseline_bump=staging_compiles)
            self._swaps += 1
            faults.COUNTERS.increment("serving_swaps")
            telemetry.emit_event(
                "bundle_swap", version=new_state.version, outcome="committed"
            )
            telemetry.METRICS.set_gauge(
                "serving_bundle_generation", new_state.version
            )
            drained = engine._drain_state(old_state, timeout_s=drain_timeout_s)
            if not drained:
                logger.warning(
                    "old bundle version %d still has in-flight batches after "
                    "%.1fs; leaving it allocated",
                    old_state.version,
                    drain_timeout_s,
                )
            if release_old and drained:
                old_state.bundle.release()
            logger.info(
                "bundle hot-swap committed: version %d -> %d (staged in %.3fs)",
                old_state.version,
                new_state.version,
                stage_s,
            )
            return {
                "version": new_state.version,
                "previous_version": old_state.version,
                "stage_s": round(stage_s, 4),
                "old_released": bool(release_old and drained),
                "staged_bytes": int(getattr(staged, "upload_bytes", 0)),
            }

    @staticmethod
    def _check_compatible(old_state, new_state) -> None:
        """The compiled program family keys on (coordinate order, kinds,
        shards, feature dims); entity counts may differ (those are traced
        argument shapes, re-warmed during staging)."""
        if old_state.kinds != new_state.kinds or [
            c.cid for c in old_state.coords
        ] != [c.cid for c in new_state.coords]:
            raise SwapIncompatible(
                "next bundle's coordinate ids/kinds differ from the serving "
                "engine's"
            )
        if old_state.coord_shards != new_state.coord_shards:
            raise SwapIncompatible(
                "next bundle maps coordinates to different feature shards"
            )
        if old_state.shard_dims != new_state.shard_dims:
            raise SwapIncompatible(
                f"next bundle's shard dims {new_state.shard_dims} differ "
                f"from the engine's {old_state.shard_dims}"
            )

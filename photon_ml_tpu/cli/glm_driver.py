"""Legacy single-GLM training driver: the staged pipeline.

Counterpart of photon-client Driver.scala:60-524 (stages
INIT → PREPROCESSED → TRAINED → VALIDATED, DriverStage.scala:38-49),
PhotonMLCmdLineParser.scala / Params.scala (argument surface),
ModelSelection.scala:26-92 (best reg weight), io/deprecated/GLMSuite.scala
(Avro/LibSVM input formats, constraint maps, text + Avro model output) and
IOUtils.writeModelsInText:242-280.

The deprecated driver predates GAME: one fixed-effect GLM, a regularization
sweep trained with warm start (ModelTraining.scala:175-213), per-weight
validation metrics (evaluation/Evaluation.scala) and model selection. The
modern GAME driver (`cli/train.py`) covers the same math; this CLI preserves
the legacy surface — staged execution with stage assertions, LibSVM or
TrainingExample-Avro input, inline JSON constraint strings, text model
output (one `name\tterm\tvalue\tregWeight` line per coefficient, sorted by
value descending) — so reference jobs port directly.
"""

from __future__ import annotations

import argparse
import dataclasses
import enum
import json
import logging
import os
import shutil
import sys
from typing import Dict, List, Optional

import numpy as np

from photon_ml_tpu.types import (
    NormalizationType,
    OptimizerType,
    RegularizationType,
    TaskType,
)

logger = logging.getLogger("photon_ml_tpu.cli.glm_driver")


class DriverStage(enum.IntEnum):
    """DriverStage.scala:45-49."""

    INIT = 0
    PREPROCESSED = 1
    TRAINED = 2
    VALIDATED = 3


class InputFormat(enum.Enum):
    """io/deprecated/InputFormatFactory: TRAINING_EXAMPLE (Avro) | LIBSVM."""

    TRAINING_EXAMPLE = "TRAINING_EXAMPLE"
    LIBSVM = "LIBSVM"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon-ml-tpu-glm-driver",
        description="Legacy single-GLM staged training driver (Driver.scala)",
    )
    p.add_argument("--training-data-directory", required=True)
    p.add_argument("--validate-data-directory", default=None)
    p.add_argument("--output-directory", required=True)
    p.add_argument("--delete-output-dirs-if-exist", action="store_true")
    p.add_argument("--format", type=lambda s: InputFormat[s.strip().upper()],
                   default=InputFormat.TRAINING_EXAMPLE,
                   help="TRAINING_EXAMPLE (Avro) or LIBSVM")
    p.add_argument("--task", type=TaskType.parse, default=TaskType.LOGISTIC_REGRESSION)
    p.add_argument("--regularization-weights", default="0.1,1,10,100",
                   help="comma-separated sweep (trained descending, warm start)")
    p.add_argument("--regularization-type", type=RegularizationType.parse,
                   default=RegularizationType.L2)
    p.add_argument("--elastic-net-alpha", type=float, default=None)
    p.add_argument("--optimizer", type=OptimizerType.parse, default=OptimizerType.LBFGS)
    p.add_argument("--max-iterations", type=int, default=100)
    p.add_argument("--tolerance", type=float, default=1e-7)
    p.add_argument("--normalization-type", type=NormalizationType.parse,
                   default=NormalizationType.NONE)
    p.add_argument("--intercept", default="true",
                   help="append the intercept pseudo-feature (true/false)")
    p.add_argument("--coefficient-constraints", default=None,
                   help="inline JSON constraint string (GLMSuite.scala:46 "
                        "format, wildcards supported)")
    p.add_argument("--selected-features-file", default=None,
                   help="Avro of FeatureNameTermAvro records (or JSON lines "
                        "of {name, term}); training restricts to these "
                        "features + intercept (Driver.prepareTrainingData, "
                        "GLMSuite selectedFeaturesFile)")
    p.add_argument("--summarization-output-dir", default=None,
                   help="write per-feature statistics as "
                        "FeatureSummarizationResultAvro")
    p.add_argument("--logging-level", default="INFO")
    return p


@dataclasses.dataclass
class _State:
    stage: DriverStage = DriverStage.INIT
    stage_history: List[DriverStage] = dataclasses.field(default_factory=list)

    def assert_stage(self, expected: DriverStage) -> None:
        """Driver.assertDriverStage: refuse to run stages out of order."""
        if self.stage != expected:
            raise RuntimeError(
                f"Expected driver stage {expected.name} but found {self.stage.name}"
            )

    def update(self, new: DriverStage) -> None:
        self.stage_history.append(self.stage)
        self.stage = new


def _read(args, path: str, index_map=None):
    """preprocess(): LibSVM or TrainingExample Avro -> LabeledData (+ map)."""
    from photon_ml_tpu.data.containers import LabeledData, pack_csr_to_ell
    import jax.numpy as jnp

    flag = args.intercept.strip().lower()
    if flag not in ("true", "false"):
        raise ValueError(f"--intercept must be true or false, got {args.intercept!r}")
    with_intercept = flag == "true"
    if args.format == InputFormat.LIBSVM:
        if args.selected_features_file:
            # LibSVM features are positional — a (name, term) whitelist has
            # no meaning there (the reference's selectedFeaturesFile rides
            # the Avro input format); refuse rather than silently ignore.
            raise ValueError(
                "--selected-features-file requires --format TRAINING_EXAMPLE"
            )
        from photon_ml_tpu.data.libsvm import read_libsvm

        num_features = None
        if index_map is not None:
            num_features = index_map.size - (1 if with_intercept else 0)
        csr = read_libsvm(path, add_intercept=with_intercept, num_features=num_features)
        feats = pack_csr_to_ell(csr.indptr, csr.indices, csr.values, csr.dim)
        n = csr.num_rows
        data = LabeledData(
            feats,
            jnp.asarray(csr.labels, jnp.float32),
            jnp.zeros(n, jnp.float32),
            jnp.ones(n, jnp.float32),
        )
        # LibSVM features are positional; synthesize the name map (feature i
        # is named str(i+1), as in the reference's LibSVM input format).
        from photon_ml_tpu.data.index_map import IndexMap

        if index_map is None:
            names = [str(i + 1) for i in range(csr.dim - (1 if with_intercept else 0))]
            index_map = IndexMap(
                {**{n_: i for i, n_ in enumerate(names)},
                 **({"(INTERCEPT)": csr.dim - 1} if with_intercept else {})}
            )
        return data, index_map
    from photon_ml_tpu.io.avro_data import FeatureShardConfig, read_game_dataset

    shards = {"global": FeatureShardConfig(("features",), with_intercept)}
    if index_map is None and args.selected_features_file:
        index_map = _selected_features_map(
            args.selected_features_file, with_intercept
        )
    maps = None if index_map is None else {"global": index_map}
    ds, built = read_game_dataset(path, shards, index_maps=maps)
    data = LabeledData(ds.shards["global"], ds.labels, ds.offsets, ds.weights)
    return data, built["global"]


def _selected_features_map(path: str, with_intercept: bool):
    """selectedFeaturesFile (Driver.prepareTrainingData:199-205; GLMSuite
    whitelist): build the index map from the listed (name, term) tuples so
    every other feature is dropped at read time. Accepts the reference's
    FeatureNameTermAvro container or JSON-lines of {name, term}."""
    from photon_ml_tpu.data.index_map import IndexMap, feature_key

    if not os.path.exists(path):
        raise IOError(f"Could not find [{path}]. Check that the file exists")
    from photon_ml_tpu.io import avro as avro_io

    # Sniff the container magic to pick the parser — a corrupt Avro file
    # must surface its own error, not a misleading JSON one.
    probe = path
    if os.path.isdir(path):
        parts = [
            n for n in sorted(os.listdir(path))
            if n.endswith(".avro") and not n.startswith((".", "_"))
        ]
        probe = os.path.join(path, parts[0]) if parts else path
    is_avro = False
    if os.path.isfile(probe):
        with open(probe, "rb") as f:
            is_avro = f.read(4) == b"Obj\x01"
    if is_avro:
        _, records = avro_io.read_directory(path)
    else:
        with open(path) as f:
            records = [json.loads(line) for line in f if line.strip()]
    keys = [feature_key(r["name"], r.get("term", "")) for r in records]
    if not keys:
        raise ValueError(f"selected-features file {path} lists no features")
    return IndexMap.from_feature_names(keys, add_intercept=with_intercept)


def run(args) -> Dict[str, object]:
    logging.basicConfig(
        level=getattr(logging, args.logging_level.upper(), logging.INFO),
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    from photon_ml_tpu.utils import telemetry
    from photon_ml_tpu.utils.observability import EventEmitter, journal_listener

    out_dir = args.output_directory
    if os.path.exists(out_dir):
        if not args.delete_output_dirs_if_exist:
            raise FileExistsError(
                f"{out_dir} exists; pass --delete-output-dirs-if-exist"
            )
        shutil.rmtree(out_dir)
    os.makedirs(out_dir)

    state = _State()
    emitter = EventEmitter()
    # Run journal (ISSUE 11): the legacy GLM driver gets the same typed
    # JSONL lifecycle record as the GAME training driver.
    journal = telemetry.RunJournal(os.path.join(out_dir, "journal.jsonl"))
    emitter.register(journal_listener(journal))
    try:
        return _run_stages(args, state, emitter, out_dir)
    finally:
        # Close on EVERY exit path — a failed stage otherwise leaks the
        # open journal handle (cli/train and cli/serve close in a finally).
        journal.close()


def _run_stages(args, state, emitter, out_dir) -> Dict[str, object]:
    import jax.numpy as jnp

    from photon_ml_tpu.data.stats import summarize
    from photon_ml_tpu.evaluation import legacy
    from photon_ml_tpu.io.model_store import write_basic_statistics
    from photon_ml_tpu.models.training import train_glm_sweep
    from photon_ml_tpu.ops.normalization import from_feature_stats
    from photon_ml_tpu.optimize.config import (
        CoordinateOptimizationConfig,
        OptimizerConfig,
        RegularizationContext,
    )
    from photon_ml_tpu.utils.observability import (
        TrainingFinishEvent,
        TrainingStartEvent,
    )

    emitter.send(TrainingStartEvent(num_samples=-1))

    # INIT -> PREPROCESSED (Driver.preprocess: read, summarize, normalize).
    state.assert_stage(DriverStage.INIT)
    train_data, index_map = _read(args, args.training_data_directory)
    logger.info(
        "training data: %d samples, %d features",
        train_data.num_rows,
        train_data.feature_dim,
    )
    stats = summarize(train_data.features, intercept_index=index_map.intercept_index)
    if args.summarization_output_dir:
        n_rec = write_basic_statistics(args.summarization_output_dir, stats, index_map)
        logger.info("feature summary: %d records", n_rec)
    norm = None
    if args.normalization_type != NormalizationType.NONE:
        norm = from_feature_stats(
            args.normalization_type,
            mean=stats.mean,
            variance=stats.variance,
            max_abs=stats.max_abs,
            intercept_index=index_map.intercept_index,
        )
    state.update(DriverStage.PREPROCESSED)

    # PREPROCESSED -> TRAINED (Driver.train -> ModelTraining sweep).
    state.assert_stage(DriverStage.PREPROCESSED)
    reg = RegularizationContext(
        args.regularization_type,
        elastic_net_alpha=(
            args.elastic_net_alpha
            if args.regularization_type == RegularizationType.ELASTIC_NET
            else None
        ),
    )
    box = None
    if args.coefficient_constraints:
        from photon_ml_tpu.optimize.constraints import (
            bounds_arrays,
            create_constraint_feature_map,
        )

        if args.normalization_type != NormalizationType.NONE:
            raise ValueError(
                "constraints cannot combine with normalization (bounds are "
                "original-space; the optimizer clips normalized coefficients)"
            )
        cmap = create_constraint_feature_map(args.coefficient_constraints, index_map)
        box = bounds_arrays(cmap, index_map.size)
    cfg = CoordinateOptimizationConfig(
        optimizer=OptimizerConfig(
            args.optimizer, args.max_iterations, args.tolerance, box_constraints=box
        ),
        regularization=reg,
    )
    weights = [float(w) for w in args.regularization_weights.split(",") if w.strip()]
    if not weights:
        raise ValueError("--regularization-weights parsed to an empty list")
    sweep = train_glm_sweep(train_data, args.task, cfg, weights, norm=norm)
    state.update(DriverStage.TRAINED)

    # TRAINED -> VALIDATED (Driver.validate: metrics per weight + selection).
    summary: Dict[str, object] = {
        "num_features": int(train_data.feature_dim),
        "num_training_samples": int(train_data.num_rows),
        "regularization_weights": weights,
    }
    if args.validate_data_directory:
        state.assert_stage(DriverStage.TRAINED)
        val_data, _ = _read(args, args.validate_data_directory, index_map=index_map)
        metrics_per_weight = {}
        for rw, model in sweep.models.items():
            metrics_per_weight[str(rw)] = legacy.evaluate_glm(model, val_data)
        # Model selection from the metrics just computed (ModelSelection.scala
        # :26-92: AUC maximized for classifiers, RMSE minimized otherwise) —
        # no second scoring pass.
        if args.task in (TaskType.LOGISTIC_REGRESSION, TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM):
            key, better = legacy.AREA_UNDER_ROC, max
        else:
            key, better = legacy.ROOT_MEAN_SQUARE_ERROR, min
        best_weight = better(sweep.models, key=lambda rw: metrics_per_weight[str(rw)][key])
        best_value = metrics_per_weight[str(best_weight)][key]
        summary["validation_metrics"] = metrics_per_weight
        summary["best_regularization_weight"] = best_weight
        summary["best_metric_value"] = best_value
        state.update(DriverStage.VALIDATED)
        logger.info("best reg weight %s (%s %.5f)", best_weight, key, best_value)

    # Output: learned-models-text (IOUtils.writeModelsInText:242-280 format:
    # name\tterm\tvalue\tregWeight, sorted by value descending) + Avro.
    from photon_ml_tpu.io.model_store import (
        FixedEffectArtifact,
        GameModelArtifact,
        save_game_model,
    )

    text_dir = os.path.join(out_dir, "learned-models-text")
    os.makedirs(text_dir)
    from photon_ml_tpu.data.index_map import DELIMITER

    for rw, model in sweep.models.items():
        means = np.asarray(model.coefficients.means)
        order = np.argsort(-means)
        lines = []
        for idx in order:
            key = index_map.get_feature_name(int(idx))
            if key is None:
                continue
            name, _, term = key.partition(DELIMITER)
            lines.append(f"{name}\t{term}\t{means[idx]}\t{rw}")
        with open(os.path.join(text_dir, f"model-{rw}.txt"), "w") as f:
            f.write("\n".join(lines) + "\n")
        save_game_model(
            os.path.join(out_dir, "models", str(rw)),
            GameModelArtifact(
                task=args.task,
                coordinates={"global": FixedEffectArtifact("global", means)},
            ),
            {"global": index_map},
        )
    index_map.save(os.path.join(out_dir, "feature-index.json"))
    summary["stages"] = [s.name for s in state.stage_history + [state.stage]]
    summary_path = os.path.join(out_dir, "driver-summary.json")
    with open(summary_path, "w") as f:
        json.dump(summary, f, indent=2, default=str)
    emitter.send(TrainingFinishEvent(num_configs=len(sweep.models)))
    logger.info("final models written to %s", text_dir)
    return summary


def main(argv: Optional[List[str]] = None) -> None:
    run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main(sys.argv[1:])

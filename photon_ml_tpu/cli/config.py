"""Compound-argument configuration mini-DSL + coordinate configurations.

Counterpart of photon-client io/scopt/ScoptParserHelpers.scala:61-151 (the
`name=global,feature.shard=globalShard,...` expand/collapse DSL),
io/CoordinateConfiguration.scala (data config + opt config + reg-weight
sweep -> Seq[GameOptimizationConfiguration]) and
io/FeatureShardConfiguration.scala. The DSL strings are accepted verbatim
from the reference's README examples (README.md:283-292) so existing Photon
ML job configs port unchanged; parsers round-trip (`to_string`) for
reproducibility, as the scopt parsers print the effective config back out.

Delimiters (ScoptParserHelpers.scala:40-44): `=` key/value, `,` list,
`|` secondary list, `-` range.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from photon_ml_tpu.data.game_dataset import (
    FixedEffectDataConfig,
    RandomEffectDataConfig,
)
from photon_ml_tpu.io.avro_data import FeatureShardConfig
from photon_ml_tpu.optimize.config import (
    CoordinateOptimizationConfig,
    OptimizerConfig,
    RegularizationContext,
)
from photon_ml_tpu.types import OptimizerType, ProjectorType, RegularizationType

KV_DELIMITER = "="
LIST_DELIMITER = ","
SECONDARY_LIST_DELIMITER = "|"

# Feature-shard DSL keys (ScoptParserHelpers.scala:48-55).
FEATURE_SHARD_CONFIG_NAME = "name"
FEATURE_SHARD_CONFIG_FEATURE_BAGS = "feature.bags"
FEATURE_SHARD_CONFIG_INTERCEPT = "intercept"

# Coordinate DSL keys (ScoptParserHelpers.scala:57-76).
COORDINATE_CONFIG_NAME = "name"
COORDINATE_DATA_CONFIG_RANDOM_EFFECT_TYPE = "random.effect.type"
COORDINATE_DATA_CONFIG_FEATURE_SHARD = "feature.shard"
COORDINATE_DATA_CONFIG_MIN_PARTITIONS = "min.partitions"
COORDINATE_DATA_CONFIG_ACTIVE_DATA_LOWER_BOUND = "active.data.lower.bound"
COORDINATE_DATA_CONFIG_ACTIVE_DATA_UPPER_BOUND = "active.data.upper.bound"
COORDINATE_DATA_CONFIG_FEATURES_TO_SAMPLES_RATIO = "features.to.samples.ratio"
COORDINATE_OPT_CONFIG_OPTIMIZER = "optimizer"
COORDINATE_OPT_CONFIG_MAX_ITER = "max.iter"
COORDINATE_OPT_CONFIG_TOLERANCE = "tolerance"
COORDINATE_OPT_CONFIG_REGULARIZATION = "regularization"
COORDINATE_OPT_CONFIG_REG_ALPHA = "reg.alpha"
COORDINATE_OPT_CONFIG_REG_WEIGHTS = "reg.weights"
COORDINATE_OPT_CONFIG_DOWN_SAMPLING_RATE = "down.sampling.rate"
# Box-constraint map: path to a JSON file in the legacy constraint-string
# format (GLMSuite.scala:190-265; resolved against the shard's index map by
# the training driver).
COORDINATE_OPT_CONFIG_CONSTRAINTS_FILE = "constraints.file"
# TPU-build extensions (no reference equivalent; entity blocking replaces
# Spark partitioning, and projection is configured per coordinate).
COORDINATE_DATA_CONFIG_MIN_BUCKET = "min.bucket"
COORDINATE_DATA_CONFIG_PROJECTOR = "projector"
COORDINATE_DATA_CONFIG_PROJECTED_DIM = "projected.dim"


def parse_compound(arg: str) -> Dict[str, str]:
    """`k1=v1,k2=v2,...` -> dict (ScoptParserHelpers expand direction)."""
    out: Dict[str, str] = {}
    for piece in arg.split(LIST_DELIMITER):
        piece = piece.strip()
        if not piece:
            continue
        if KV_DELIMITER not in piece:
            raise ValueError(f"malformed `key=value` pair {piece!r} in {arg!r}")
        k, v = piece.split(KV_DELIMITER, 1)
        k, v = k.strip(), v.strip()
        if k in out:
            raise ValueError(f"duplicate key {k!r} in {arg!r}")
        out[k] = v
    return out


def _parse_bool(v: str) -> bool:
    if v.lower() in ("true", "1", "yes"):
        return True
    if v.lower() in ("false", "0", "no"):
        return False
    raise ValueError(f"not a boolean: {v!r}")


def parse_feature_shard_config(arg: str) -> Tuple[str, FeatureShardConfig]:
    """`name=shard,feature.bags=f1|f2,intercept=true` ->
    (shard id, FeatureShardConfiguration) (ScoptParserHelpers
    parseFeatureShardConfiguration:151+)."""
    kv = parse_compound(arg)
    try:
        name = kv.pop(FEATURE_SHARD_CONFIG_NAME)
    except KeyError:
        raise ValueError(f"feature shard config missing 'name': {arg!r}") from None
    bags = tuple(
        b for b in kv.pop(FEATURE_SHARD_CONFIG_FEATURE_BAGS, "features").split(
            SECONDARY_LIST_DELIMITER
        )
        if b
    )
    intercept = _parse_bool(kv.pop(FEATURE_SHARD_CONFIG_INTERCEPT, "true"))
    if kv:
        raise ValueError(f"unknown feature shard config keys {sorted(kv)} in {arg!r}")
    return name, FeatureShardConfig(feature_bags=bags, has_intercept=intercept)


def feature_shard_config_to_string(name: str, cfg: FeatureShardConfig) -> str:
    """Collapse direction (featureShardConfigsToStrings:358-390)."""
    parts = [f"{FEATURE_SHARD_CONFIG_NAME}{KV_DELIMITER}{name}"]
    parts.append(
        f"{FEATURE_SHARD_CONFIG_FEATURE_BAGS}{KV_DELIMITER}"
        + SECONDARY_LIST_DELIMITER.join(cfg.feature_bags)
    )
    parts.append(
        f"{FEATURE_SHARD_CONFIG_INTERCEPT}{KV_DELIMITER}{str(cfg.has_intercept).lower()}"
    )
    return LIST_DELIMITER.join(parts)


@dataclasses.dataclass
class CoordinateConfiguration:
    """Data config + opt config + regularization-weight sweep for one
    coordinate (io/CoordinateConfiguration.scala).

    `expand()` returns one CoordinateOptimizationConfig per reg weight,
    sorted DESCENDING (most regularization first — the warm-start-friendly
    order, CoordinateConfiguration.scala:71-77)."""

    name: str
    data_config: object  # FixedEffectDataConfig | RandomEffectDataConfig
    opt_config: CoordinateOptimizationConfig
    reg_weights: Tuple[float, ...] = (0.0,)
    constraint_file: Optional[str] = None  # JSON constraint map (GLMSuite.scala:46)

    def expand(self) -> List[CoordinateOptimizationConfig]:
        return [
            dataclasses.replace(self.opt_config, reg_weight=w)
            for w in sorted(set(self.reg_weights), reverse=True)
        ]


def parse_coordinate_config(arg: str) -> CoordinateConfiguration:
    """Parse one `--coordinate-configurations` DSL string
    (ScoptParserHelpers.parseCoordinateConfiguration:180-270)."""
    kv = parse_compound(arg)

    def pop(key: str, default: Optional[str] = None) -> Optional[str]:
        return kv.pop(key, default)

    try:
        name = kv.pop(COORDINATE_CONFIG_NAME)
        shard = kv.pop(COORDINATE_DATA_CONFIG_FEATURE_SHARD)
    except KeyError as e:
        raise ValueError(f"coordinate config missing {e.args[0]!r}: {arg!r}") from None

    # Spark partitioning is meaningless here; accepted and ignored for
    # compatibility with reference job configs.
    pop(COORDINATE_DATA_CONFIG_MIN_PARTITIONS)

    re_type = pop(COORDINATE_DATA_CONFIG_RANDOM_EFFECT_TYPE)
    lower = pop(COORDINATE_DATA_CONFIG_ACTIVE_DATA_LOWER_BOUND)
    upper = pop(COORDINATE_DATA_CONFIG_ACTIVE_DATA_UPPER_BOUND)
    ratio = pop(COORDINATE_DATA_CONFIG_FEATURES_TO_SAMPLES_RATIO)
    min_bucket = pop(COORDINATE_DATA_CONFIG_MIN_BUCKET)
    projector = pop(COORDINATE_DATA_CONFIG_PROJECTOR)
    projected_dim = pop(COORDINATE_DATA_CONFIG_PROJECTED_DIM)

    if re_type is not None:
        data_config = RandomEffectDataConfig(
            random_effect_type=re_type,
            feature_shard=shard,
            active_upper_bound=None if upper is None else int(upper),
            active_lower_bound=None if lower is None else int(lower),
            num_features_to_samples_ratio_upper_bound=(
                None if ratio is None else float(ratio)
            ),
            min_bucket=8 if min_bucket is None else int(min_bucket),
            projector_type=(
                ProjectorType.INDEX_MAP
                if projector is None
                else ProjectorType[projector.strip().upper()]
            ),
            projected_dim=None if projected_dim is None else int(projected_dim),
        )
    else:
        # Reference logs-and-ignores RE settings on FE coordinates
        # (ScoptParserHelpers.scala:248-267); mirror that leniency.
        import logging

        for key, val in ((COORDINATE_DATA_CONFIG_ACTIVE_DATA_LOWER_BOUND, lower),
                         (COORDINATE_DATA_CONFIG_ACTIVE_DATA_UPPER_BOUND, upper)):
            if val is not None:
                logging.getLogger(__name__).warning(
                    "ignoring random-effect setting %s=%s on fixed-effect "
                    "coordinate %r", key, val, name,
                )
        data_config = FixedEffectDataConfig(feature_shard=shard)

    optimizer = OptimizerType.parse(pop(COORDINATE_OPT_CONFIG_OPTIMIZER, "LBFGS"))
    max_iter = int(pop(COORDINATE_OPT_CONFIG_MAX_ITER, "100"))
    tolerance = float(pop(COORDINATE_OPT_CONFIG_TOLERANCE, "1e-7"))
    reg_type = RegularizationType.parse(pop(COORDINATE_OPT_CONFIG_REGULARIZATION, "NONE"))
    alpha = pop(COORDINATE_OPT_CONFIG_REG_ALPHA)
    weights_str = pop(COORDINATE_OPT_CONFIG_REG_WEIGHTS)
    down_sampling = float(pop(COORDINATE_OPT_CONFIG_DOWN_SAMPLING_RATE, "1.0"))
    constraint_file = pop(COORDINATE_OPT_CONFIG_CONSTRAINTS_FILE)
    if kv:
        raise ValueError(f"unknown coordinate config keys {sorted(kv)} in {arg!r}")

    reg = RegularizationContext(
        reg_type,
        elastic_net_alpha=(
            float(alpha)
            if alpha is not None and reg_type == RegularizationType.ELASTIC_NET
            else None
        ),
    )
    if reg_type == RegularizationType.NONE:
        reg_weights: Tuple[float, ...] = (0.0,)
    else:
        if weights_str is None:
            raise ValueError(
                f"regularization enabled but no '{COORDINATE_OPT_CONFIG_REG_WEIGHTS}' "
                f"given: {arg!r}"
            )
        reg_weights = tuple(
            float(w) for w in weights_str.split(SECONDARY_LIST_DELIMITER) if w
        )

    opt = CoordinateOptimizationConfig(
        optimizer=OptimizerConfig(
            optimizer_type=optimizer, max_iterations=max_iter, tolerance=tolerance
        ),
        regularization=reg,
        reg_weight=max(reg_weights),
        down_sampling_rate=down_sampling,
    )
    return CoordinateConfiguration(
        name, data_config, opt, reg_weights, constraint_file=constraint_file
    )


def coordinate_config_to_string(cfg: CoordinateConfiguration) -> str:
    """Collapse direction (coordinateConfigsToStrings:429+) — round-trips
    through parse_coordinate_config."""
    parts = [f"{COORDINATE_CONFIG_NAME}{KV_DELIMITER}{cfg.name}"]
    dc = cfg.data_config
    parts.append(f"{COORDINATE_DATA_CONFIG_FEATURE_SHARD}{KV_DELIMITER}{dc.feature_shard}")
    if isinstance(dc, RandomEffectDataConfig):
        parts.append(
            f"{COORDINATE_DATA_CONFIG_RANDOM_EFFECT_TYPE}{KV_DELIMITER}{dc.random_effect_type}"
        )
        if dc.active_lower_bound is not None:
            parts.append(
                f"{COORDINATE_DATA_CONFIG_ACTIVE_DATA_LOWER_BOUND}{KV_DELIMITER}{dc.active_lower_bound}"
            )
        if dc.active_upper_bound is not None:
            parts.append(
                f"{COORDINATE_DATA_CONFIG_ACTIVE_DATA_UPPER_BOUND}{KV_DELIMITER}{dc.active_upper_bound}"
            )
        if dc.num_features_to_samples_ratio_upper_bound is not None:
            parts.append(
                f"{COORDINATE_DATA_CONFIG_FEATURES_TO_SAMPLES_RATIO}{KV_DELIMITER}"
                f"{dc.num_features_to_samples_ratio_upper_bound}"
            )
        parts.append(f"{COORDINATE_DATA_CONFIG_MIN_BUCKET}{KV_DELIMITER}{dc.min_bucket}")
        parts.append(
            f"{COORDINATE_DATA_CONFIG_PROJECTOR}{KV_DELIMITER}{dc.projector_type.value}"
        )
        if dc.projected_dim is not None:
            parts.append(
                f"{COORDINATE_DATA_CONFIG_PROJECTED_DIM}{KV_DELIMITER}{dc.projected_dim}"
            )
    oc = cfg.opt_config
    parts.append(
        f"{COORDINATE_OPT_CONFIG_OPTIMIZER}{KV_DELIMITER}{oc.optimizer.optimizer_type.value}"
    )
    parts.append(f"{COORDINATE_OPT_CONFIG_TOLERANCE}{KV_DELIMITER}{oc.optimizer.tolerance}")
    parts.append(f"{COORDINATE_OPT_CONFIG_MAX_ITER}{KV_DELIMITER}{oc.optimizer.max_iterations}")
    parts.append(
        f"{COORDINATE_OPT_CONFIG_REGULARIZATION}{KV_DELIMITER}{oc.regularization.reg_type.value}"
    )
    if oc.regularization.elastic_net_alpha is not None:
        parts.append(
            f"{COORDINATE_OPT_CONFIG_REG_ALPHA}{KV_DELIMITER}{oc.regularization.elastic_net_alpha}"
        )
    if oc.regularization.reg_type != RegularizationType.NONE:
        parts.append(
            f"{COORDINATE_OPT_CONFIG_REG_WEIGHTS}{KV_DELIMITER}"
            + SECONDARY_LIST_DELIMITER.join(str(w) for w in cfg.reg_weights)
        )
    if oc.down_sampling_rate < 1.0:
        parts.append(
            f"{COORDINATE_OPT_CONFIG_DOWN_SAMPLING_RATE}{KV_DELIMITER}{oc.down_sampling_rate}"
        )
    if cfg.constraint_file:
        parts.append(
            f"{COORDINATE_OPT_CONFIG_CONSTRAINTS_FILE}{KV_DELIMITER}{cfg.constraint_file}"
        )
    return LIST_DELIMITER.join(parts)


def expand_game_opt_configs(
    coordinate_configs: Mapping[str, CoordinateConfiguration],
) -> List[Dict[str, CoordinateOptimizationConfig]]:
    """Cross product of every coordinate's reg-weight expansion
    (GameTrainingDriver.prepareGameOptConfigs — foldLeft cartesian product)."""
    ids = list(coordinate_configs.keys())
    expanded = [coordinate_configs[c].expand() for c in ids]
    return [
        dict(zip(ids, combo)) for combo in itertools.product(*expanded)
    ]

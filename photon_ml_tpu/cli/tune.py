"""Hyperparameter sweep driver: the pod-parallel tuning CLI (ISSUE 12).

Where `cli/train.py` reproduces GameTrainingDriver's tuning loop — one full
`estimator.fit` per observation, the reference's inherently serial search
(GameTrainingDriver.scala:643-680) — this driver runs the sweep through the
batched trial executor (`hyperparameter/sweep.py`): the GP/Sobol searcher
proposes k-candidate rounds and each round evaluates as ONE stacked XLA
dispatch (or one trial per device shard group), with per-trial
`trial_start`/`trial_finish` journal events and warm-started rounds. The
winner is cold-refit and saved, bitwise-equal to a standalone fit of the
winning configuration.

Pipeline:

    parse args -> read training/validation Avro data
    -> GameEstimator.sweep_executor (stacked | shard_group | serial | auto)
    -> HyperparameterTuner.sweep (RANDOM | BAYESIAN, batched rounds)
    -> save winner model + tuning-summary.json (+ journal.jsonl, trace)

Usage: python -m photon_ml_tpu.cli.tune --help
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
from typing import Dict, List, Optional

from photon_ml_tpu.cli.config import parse_coordinate_config
from photon_ml_tpu.cli.train import (
    TUNING_REG_WEIGHT_RANGE,
    _read_data,
    _tuning_dimensions,
    _validate_rows,
)
from photon_ml_tpu.estimators.game_estimator import GameEstimator
from photon_ml_tpu.evaluation.suite import EvaluatorType
from photon_ml_tpu.hyperparameter.tuner import (
    HyperparameterTuningMode,
    get_tuner,
)
from photon_ml_tpu.io import model_bridge, model_store
from photon_ml_tpu.types import (
    DataValidationType,
    NormalizationType,
    TaskType,
)

logger = logging.getLogger("photon_ml_tpu.cli.tune")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon_ml_tpu.cli.tune",
        description="Pod-parallel hyperparameter sweeps over GAME/GLMix "
        "regularization weights (batched trial executor)",
    )
    p.add_argument("--training-task", required=True, type=TaskType.parse)
    p.add_argument("--input-data-directories", required=True, nargs="+")
    p.add_argument("--validation-data-directories", required=True, nargs="+",
                   help="validation data (the trial metric) — a sweep "
                        "without validation has no objective")
    p.add_argument("--input-column-names", default=None)
    p.add_argument("--root-output-directory", required=True)
    p.add_argument("--override-output-directory", action="store_true")
    p.add_argument("--feature-shard-configurations", required=True, nargs="+",
                   metavar="DSL")
    p.add_argument("--coordinate-configurations", required=True, nargs="+",
                   metavar="DSL",
                   help="same mini-DSL as cli/train; each coordinate's "
                        "reg weight is the BASE the sweep tunes around")
    p.add_argument("--coordinate-update-sequence", default=None)
    p.add_argument("--coordinate-descent-iterations", type=int, default=1)
    p.add_argument("--normalization", type=NormalizationType.parse,
                   default=NormalizationType.NONE)
    p.add_argument("--validation-evaluators", nargs="*", default=[])
    p.add_argument("--offheap-indexmap-dir", default=None)
    p.add_argument("--data-validation",
                   type=lambda s: DataValidationType[s.strip().upper()],
                   default=DataValidationType.VALIDATE_FULL)
    p.add_argument("--tuning-mode", type=HyperparameterTuningMode.parse,
                   default=HyperparameterTuningMode.BAYESIAN,
                   help="RANDOM | BAYESIAN (constant-liar qEI rounds)")
    p.add_argument("--tuning-iter", type=int, default=16,
                   help="total trials across all rounds")
    p.add_argument("--tuning-batch-size", type=int, default=4,
                   help="candidates proposed AND evaluated per round (one "
                        "stacked dispatch / one pass over the shard groups)")
    p.add_argument("--sweep-mode", default=None,
                   choices=["stacked", "shard_group", "serial"],
                   help="trial evaluation mode (default: auto — stacked "
                        "when every coordinate store is replicated, else "
                        "shard groups on a multi-device fleet)")
    p.add_argument("--no-warm-start", action="store_true",
                   help="disable warm-starting rounds from the incumbent "
                        "(the bitwise-parity comparison mode)")
    p.add_argument("--max-stack", type=int, default=None,
                   help="override PHOTON_SWEEP_MAX_STACK for this run")
    p.add_argument("--shard-groups", type=int, default=None,
                   help="override PHOTON_SWEEP_SHARD_GROUPS for this run")
    p.add_argument("--profile", default=None,
                   help="a persisted run profile the adaptive planner "
                        "consumes for the sweep's fits (layout/routing/"
                        "prefetch decisions); topology-checked loudly. "
                        "Overrides PHOTON_PLAN_PROFILE")
    p.add_argument("--random-seed", type=int, default=0)
    p.add_argument("--logging-level", default="INFO")
    return p


def run(args) -> Dict[str, object]:
    logging.basicConfig(
        level=getattr(logging, args.logging_level.upper(), logging.INFO),
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    import time

    out_root = args.root_output_directory
    models_root = os.path.join(out_root, "models")
    if os.path.exists(models_root):
        if not args.override_output_directory:
            raise FileExistsError(
                f"{models_root} exists; pass --override-output-directory"
            )
        import shutil

        shutil.rmtree(models_root)
    os.makedirs(out_root, exist_ok=True)

    # Same job-scoped observability surface as cli/train: run journal
    # (trial_start/trial_finish land here), optional span tracing.
    from photon_ml_tpu.utils import telemetry

    journal = telemetry.RunJournal(os.path.join(out_root, "journal.jsonl"))
    journal_owned = telemetry.current_journal() is None
    if journal_owned:
        telemetry.install_journal(journal)
    tracer_owned = telemetry.current_tracer() is None
    tracer = telemetry.start_tracing_if_enabled()
    # Adaptive runtime planner (ISSUE 14): same ownership discipline as
    # the journal/tracer; installed after the journal so plan_decision
    # events land in it, before ingest so chunk rows are planned.
    from photon_ml_tpu import planner

    plan_owned = planner.current_plan() is None
    if not plan_owned and getattr(args, "profile", None):
        logger.warning(
            "--profile %s ignored: a runtime plan is already installed "
            "by the caller (uninstall it to let this run plan itself)",
            args.profile,
        )
    try:
        if plan_owned:
            planner.ensure_ambient_plan(getattr(args, "profile", None))
        return _run_job(args, out_root, models_root, time)
    finally:
        if plan_owned:
            planner.uninstall_plan()
        if tracer is not None and tracer_owned:
            tracer.export(os.path.join(out_root, "trace.json"))
            telemetry.uninstall_tracer()
        if journal_owned:
            telemetry.uninstall_journal()
        journal.close()


def _run_job(args, out_root, models_root, time) -> Dict[str, object]:
    coordinate_configs = {}
    for s in args.coordinate_configurations:
        cfg = parse_coordinate_config(s)
        coordinate_configs[cfg.name] = cfg
    update_sequence = (
        [c.strip() for c in args.coordinate_update_sequence.split(",")]
        if args.coordinate_update_sequence
        else list(coordinate_configs.keys())
    )

    train, validation, index_maps, _shard_configs = _read_data(
        args, coordinate_configs
    )
    if validation is None:
        raise ValueError("--validation-data-directories produced no data")
    _validate_rows(train, args.training_task, args.data_validation)
    _validate_rows(validation, args.training_task, args.data_validation)
    logger.info(
        "sweep data: %d training / %d validation samples",
        train.num_samples,
        validation.num_samples,
    )

    dims = _tuning_dimensions(coordinate_configs, set(update_sequence))
    if not dims:
        raise ValueError(
            "no tunable coordinates: every coordinate's regularization is "
            "NONE (the sweep tunes reg weights)"
        )

    estimator = GameEstimator(
        args.training_task,
        {cid: c.data_config for cid, c in coordinate_configs.items()},
        update_sequence=update_sequence,
        coordinate_descent_iterations=args.coordinate_descent_iterations,
        normalization=args.normalization,
        validation_evaluators=[
            EvaluatorType.parse(e) for e in args.validation_evaluators
        ],
        intercept_indices={
            shard: index_maps[shard].intercept_index
            for shard in index_maps
            if index_maps[shard].intercept_index is not None
        },
        seed=args.random_seed,
    )
    base_config = {
        cid: coordinate_configs[cid].opt_config for cid in update_sequence
    }
    executor = estimator.sweep_executor(
        train,
        validation,
        base_config,
        tuned_ids=[d.name for d in dims],
        mode=args.sweep_mode,
        warm_start=not args.no_warm_start,
        max_stack=args.max_stack,
        shard_groups=args.shard_groups,
    )

    t0 = time.perf_counter()
    tuner = get_tuner(args.tuning_mode)
    out = tuner.sweep(
        args.tuning_iter,
        dims,
        args.tuning_mode,
        executor,
        seed=args.random_seed + 1,
        batch_size=args.tuning_batch_size,
    )
    if out is None:
        raise ValueError("tuning mode NONE / zero iterations: nothing to do")
    search_result, sweep_result = out
    sweep_wall = time.perf_counter() - t0
    logger.info(
        "sweep: %d trials in %.1fs, best %s=%.6f at %s",
        len(sweep_result.trials),
        sweep_wall,
        str(executor.validation_suite.primary),
        sweep_result.best_value,
        dict(zip([d.name for d in dims], sweep_result.best_point.tolist())),
    )

    # Save the winner (the COLD refit — bitwise-equal to a standalone fit
    # of the winning config) in the same layout cli/train uses.
    specs = estimator.scoring_specs()
    artifact = model_bridge.artifact_from_game_model(
        sweep_result.winner_model,
        specs,
        args.training_task,
        opt_configs={
            cid: {
                "optimizer": c.optimizer.optimizer_type.value,
                "max_iterations": c.optimizer.max_iterations,
                "tolerance": c.optimizer.tolerance,
                "regularization": c.regularization.reg_type.value,
                "reg_weight": (
                    float(
                        sweep_result.best_point[
                            [d.name for d in dims].index(cid)
                        ]
                    )
                    if cid in [d.name for d in dims]
                    else c.reg_weight
                ),
            }
            for cid, c in base_config.items()
        },
    )
    mdir = os.path.join(models_root, "tuned-best")
    model_store.save_game_model(mdir, artifact, index_maps)
    idx_dir = os.path.join(mdir, "feature-indexes")
    os.makedirs(idx_dir, exist_ok=True)
    for shard, imap in index_maps.items():
        imap.save(os.path.join(idx_dir, f"{shard}.json"))

    summary: Dict[str, object] = {
        "num_training_samples": int(train.num_samples),
        "num_validation_samples": int(validation.num_samples),
        "tuning_mode": args.tuning_mode.value,
        "trials": [t.timing_entry() for t in sweep_result.trials],
        "rounds": executor.rounds,
        "batch_size": int(args.tuning_batch_size),
        "modes": sorted({t.mode for t in sweep_result.trials}),
        "stack_decisions": sweep_result.stack_decisions,
        "sweep_wall_s": round(sweep_wall, 3),
        "winner_refit_s": round(sweep_result.winner_refit_s, 3),
        "tuned_coordinates": [d.name for d in dims],
        "tuning_range": list(TUNING_REG_WEIGHT_RANGE),
        "best_trial": sweep_result.best_trial,
        "best_point": sweep_result.best_point.tolist(),
        "best_value": sweep_result.best_value,
        "winner_value": sweep_result.winner_value,
        "best_observation": float(search_result.best_value),
    }
    with open(os.path.join(out_root, "tuning-summary.json"), "w") as f:
        json.dump(summary, f, indent=2, default=str)
    logger.info("winner model saved to %s", mdir)
    return summary


def main(argv: Optional[List[str]] = None) -> None:
    run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main(sys.argv[1:])

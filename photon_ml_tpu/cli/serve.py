"""Online serving driver: load a model bundle once, replay a request stream.

A deliberate extension beyond the reference (GameScoringDriver only scores
full datasets offline): this driver stages the model into device memory
exactly once (serving/bundle.py), warms the engine's bounded bucket set,
and streams scoring requests through the deadline micro-batcher —
reporting latency percentiles, qps, cold-start fraction, and recompile
counts at exit.

Request formats:
  * JSON lines (`.json`/`.jsonl`, the native format): one object per line,
        {"uid": "r1", "offset": 0.0, "ids": {"userId": "u3"},
         "features": {"shardA": {"f1": 0.5, "f2t": 1.0}}}
    Feature payloads per shard may be a {feature_key: value} mapping
    (resolved through the model's index maps), an {"indices": [...],
    "values": [...]} pair, or a dense list.
  * Avro (a file or part-file directory of reference-shaped records with
    name/term/value feature bags): pass the same feature-shard DSL the
    training/scoring drivers use, so a replayed record builds exactly the
    feature row offline ingest would.

Usage: python -m photon_ml_tpu.cli.serve --help
"""

from __future__ import annotations

import argparse
import itertools
import json
import logging
import os
import sys
import time
from typing import Iterator, List, Optional

import numpy as np

from photon_ml_tpu.io import score_store
from photon_ml_tpu.serving.bundle import (
    ScoreRequest,
    ServingBundle,
    load_bundle,
    request_from_record,
)
from photon_ml_tpu.serving.engine import ServingEngine

logger = logging.getLogger("photon_ml_tpu.cli.serve")

# Stream requests through the batcher in bounded windows: submit a window,
# drain its futures, write its scores — memory stays O(window), not O(stream).
REPLAY_WINDOW = 8192


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon_ml_tpu.cli.serve",
        description="Replay scoring requests through the online serving "
        "engine (TPU-native Photon ML)",
    )
    p.add_argument("--model-input-directory", required=False, default=None,
                   help="a model directory written by the training driver "
                        "(single-tenant mode; or use --tenant)")
    p.add_argument("--tenant", action="append", default=None,
                   metavar="NAME=MODEL_DIR",
                   help="multi-tenant mode (repeatable): serve N named "
                        "model bundles on one device fleet through the "
                        "TenantRegistry — per-tenant admission quotas, "
                        "deadlines and failure domains, weighted-fair "
                        "cross-tenant co-batching. Replay traffic is "
                        "assigned round-robin across tenants; the summary "
                        "gains a per-tenant block")
    p.add_argument("--requests", required=True,
                   help="request stream: a .json/.jsonl file (one request "
                        "object per line) or an Avro file/part-directory")
    p.add_argument("--root-output-directory", required=True)
    p.add_argument("--feature-shard-configurations", nargs="+", default=None,
                   metavar="DSL",
                   help="required for Avro request replay: the same shard "
                        "DSL the scoring driver takes")
    p.add_argument("--offheap-indexmap-dir", default=None,
                   help="prebuilt feature-index partitions; default: the "
                        "JSON maps saved beside the model")
    p.add_argument("--max-batch", type=int, default=None,
                   help="largest micro-batch / compiled bucket size "
                        "(default: the installed plan's choice, else 256; "
                        "an explicit value overrides the planner)")
    p.add_argument("--max-wait-ms", type=float, default=None,
                   help="flush a partial batch once its oldest request has "
                        "waited this long (default: the installed plan's "
                        "choice, else 2.0 ms; explicit overrides the "
                        "planner)")
    p.add_argument("--profile", default=None,
                   help="a persisted run profile (profile.json from a prior "
                        "run) the adaptive planner consumes for bucket/wait "
                        "decisions; topology-checked loudly. Overrides "
                        "PHOTON_PLAN_PROFILE")
    p.add_argument("--max-pending", type=int, default=None,
                   help="admission-control bound on the pending queue "
                        "(default: 4x max-batch); replay submits are "
                        "backpressured, live submits past the bound shed "
                        "with a typed Overloaded rejection")
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="per-request deadline budget; a request queued past "
                        "it fails with DeadlineExceeded instead of wasting "
                        "a device slot (default: no deadline)")
    p.add_argument("--reshard-to", type=int, default=None,
                   help="live mesh elasticity drill: once replay traffic is "
                        "flowing, reshard the engine's coefficient layout "
                        "to this many entity shards (1 = replicated) on a "
                        "background worker — zero failed requests, rollback "
                        "on any staging/commit failure; the summary gains a "
                        "'reshard' block")
    p.add_argument("--model-id", default=None,
                   help="model id tag written into every score record")
    p.add_argument("--shadow", default=None, metavar="NAME=MODEL_DIR",
                   help="shadow deployment (single-tenant mode only): admit "
                        "a challenger bundle as a shadow tenant receiving "
                        "mirrored traffic co-batched with the champion — its "
                        "answers are never returned; online evaluation "
                        "windows (see --labels) drive a journaled "
                        "promote/reject verdict through the atomic "
                        "generation flip, and the summary gains a 'shadow' "
                        "block")
    p.add_argument("--labels", default=None, metavar="PATH",
                   help="label stream for the shadow's online evaluation: a "
                        ".json/.jsonl file of {\"uid\": ..., \"label\": ..., "
                        "\"weight\"?: ...} joined by uid into the scoring "
                        "windows; without it the shadow mirrors but no "
                        "verdict can fire")
    p.add_argument("--shadow-window", type=int, default=64,
                   help="joined rows per shadow evaluation window (default "
                        "64); the verdict needs PHOTON_SHADOW_MIN_WINDOWS "
                        "consecutive windows agreeing")
    p.add_argument("--autopilot", action="store_true",
                   help="closed-loop autoscaling (multi-tenant mode only): "
                        "run the photon-autopilot control loop over the "
                        "tenant fleet — shard grow from load skew, hot-row "
                        "rebalance, the HBM demote/restore ladder, batch-"
                        "wait retune — with hysteresis/cooldown/budget "
                        "hygiene (PHOTON_AUTOPILOT_* knobs), every decision "
                        "journaled; the summary gains an 'autopilot' block")
    p.add_argument("--multihost", type=int, default=0, metavar="N",
                   help="multi-host production serving: N share-nothing "
                        "OS-process hosts, each staging only its own "
                        "partition of every random-effect coordinate's "
                        "rows (host-local two-tier stores); a host killed "
                        "mid-replay costs fidelity (its rows answer "
                        "FE-only through the survivors), never a failed "
                        "request, and rejoins by restaging its partition")
    p.add_argument("--multihost-devices-per-host", type=int, default=4,
                   metavar="M",
                   help="virtual devices per serving host (the per-host "
                        "shard count of each coordinate's store); only "
                        "meaningful with --multihost")
    # Hidden plumbing between the multi-host serve supervisor and the
    # worker processes it spawns — never passed by operators.
    p.add_argument("--mh-serve-worker", action="store_true",
                   help=argparse.SUPPRESS)
    p.add_argument("--mh-host-id", type=int, default=0,
                   help=argparse.SUPPRESS)
    p.add_argument("--mh-num-hosts", type=int, default=0,
                   help=argparse.SUPPRESS)
    p.add_argument("--mh-attempt", type=int, default=0,
                   help=argparse.SUPPRESS)
    p.add_argument("--mh-resume-window", type=int, default=0,
                   help=argparse.SUPPRESS)
    p.add_argument("--logging-level", default="INFO")
    return p


def _encode_json_request(bundle: ServingBundle, doc: dict) -> ScoreRequest:
    """One parsed JSON request document -> ScoreRequest against `bundle`
    (shared by the single-tenant stream and the multi-tenant round-robin,
    which encodes each document against its ASSIGNED tenant's bundle)."""
    features = {}
    for shard, payload in (doc.get("features") or {}).items():
        if isinstance(payload, dict) and "indices" in payload:
            features[shard] = (
                np.asarray(payload["indices"], np.int32),
                np.asarray(payload.get("values", []), np.float32),
            )
        elif isinstance(payload, dict):
            features[shard] = payload  # named features -> index maps
        else:
            features[shard] = np.asarray(payload, np.float32)
    return bundle.encode_request(
        features,
        entity_ids=doc.get("ids") or {},
        offset=float(doc.get("offset") or 0.0),
        uid=None if doc.get("uid") is None else str(doc["uid"]),
    )


def _iter_json_docs(path: str, malformed: List[int]) -> Iterator[dict]:
    """Parsed JSON request documents; a malformed line costs ONE record
    (counted), never the rest of the stream."""
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except Exception as exc:  # noqa: BLE001 - per-record isolation
                malformed[0] += 1
                logger.warning(
                    "skipping malformed request at %s:%d: %s", path, lineno, exc
                )


def _iter_json_requests(
    path: str, bundle: ServingBundle, malformed: List[int]
) -> Iterator[ScoreRequest]:
    for doc in _iter_json_docs(path, malformed):
        # One malformed line costs ONE record (counted), never the
        # rest of the stream — same isolation the per-future harvest
        # gives requests that fail at scoring time.
        try:
            req = _encode_json_request(bundle, doc)
        except Exception as exc:  # noqa: BLE001 - per-record isolation
            malformed[0] += 1
            logger.warning("skipping malformed request in %s: %s", path, exc)
            continue
        yield req


def _iter_avro_requests(
    path: str, bundle: ServingBundle, shard_configs, malformed: List[int]
) -> Iterator[ScoreRequest]:
    from photon_ml_tpu.io import avro as avro_io

    paths = (
        avro_io.list_container_files(path) if os.path.isdir(path) else [path]
    )
    for p in paths:
        # Block-streaming read: only one Avro block's decoded records are
        # live at a time, keeping replay memory O(window), not O(file).
        # quarantine=True: one corrupt block costs its requests (counted),
        # never the rest of the replay file. A decodable record that fails
        # request conversion (missing/garbage field) likewise costs one
        # record, not the stream.
        for _, rec in avro_io.iter_container(p, quarantine=True):
            try:
                req = request_from_record(bundle, rec, shard_configs)
            except Exception as exc:  # noqa: BLE001 - per-record isolation
                malformed[0] += 1
                logger.warning(
                    "skipping malformed replay record in %s: %s", p, exc
                )
                continue
            yield req


def run(args) -> dict:
    logging.basicConfig(
        level=getattr(logging, args.logging_level.upper(), logging.INFO),
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    # Validate BEFORE staging anything: a missing shard DSL must not cost a
    # full bundle load + warmup before erroring (and the request-iterator
    # generator body would only run on first consumption).
    is_json = args.requests.endswith((".json", ".jsonl"))
    if not is_json and not args.feature_shard_configurations:
        raise ValueError(
            "Avro request replay needs --feature-shard-configurations "
            "(the bag -> shard mapping offline ingest uses)"
        )
    if getattr(args, "multihost", 0) or getattr(args, "mh_serve_worker", False):
        # Loud, not a silent single-process fallback: the multi-host
        # paths are dispatched by main(); run() is one serving host.
        raise ValueError(
            "--multihost serving dispatches in serve.main(); run() is "
            "the single-process path"
        )
    tenants = getattr(args, "tenant", None)
    if bool(tenants) == bool(args.model_input_directory):
        raise ValueError(
            "pass exactly one of --model-input-directory (single-tenant) "
            "or --tenant NAME=MODEL_DIR (repeatable, multi-tenant)"
        )
    if tenants and getattr(args, "reshard_to", None) is not None:
        # Loud refusal, not a silent no-op: the reshard drill drives ONE
        # engine's orchestrator and has no multi-tenant form yet.
        raise ValueError(
            "--reshard-to is a single-tenant drill; it cannot be combined "
            "with --tenant"
        )
    shadow_spec = getattr(args, "shadow", None)
    if shadow_spec:
        # Loud refusals (ISSUE 18): the shadow rides the SINGLE-tenant
        # replay (one champion, one challenger); the round-robin
        # multi-tenant path has no champion to mirror, and the reshard
        # drill would race the promotion's generation flip.
        if tenants:
            raise ValueError(
                "--shadow mirrors one champion's traffic; it cannot be "
                "combined with --tenant"
            )
        if getattr(args, "reshard_to", None) is not None:
            raise ValueError(
                "--shadow and --reshard-to both drive generation flips; "
                "run them separately"
            )
    if getattr(args, "autopilot", False):
        # Loud refusals (ISSUE 19): the autopilot supervises a tenant
        # FLEET — its sensors and actuators are the TenantRegistry's;
        # and it owns the reshard actuator, so the manual drill and the
        # controller must not both drive generation flips.
        if not tenants:
            raise ValueError(
                "--autopilot supervises a multi-tenant fleet; combine it "
                "with --tenant"
            )
        if getattr(args, "reshard_to", None) is not None:
            raise ValueError(
                "--autopilot owns the reshard actuator; it cannot be "
                "combined with the --reshard-to drill"
            )
    tenant_specs: List[tuple] = []
    for spec in tenants or []:
        name, sep, model_dir = spec.partition("=")
        if not sep or not name or not model_dir:
            raise ValueError(
                f"--tenant {spec!r}: expected NAME=MODEL_DIR"
            )
        if name in dict(tenant_specs):
            raise ValueError(f"duplicate tenant name {name!r}")
        tenant_specs.append((name, model_dir))
    index_maps = None
    if getattr(args, "offheap_indexmap_dir", None):
        from photon_ml_tpu.cli.config import parse_feature_shard_config
        from photon_ml_tpu.io.paldb import resolve_offheap_index_maps

        cfgs = dict(
            parse_feature_shard_config(s)
            for s in (args.feature_shard_configurations or [])
        )
        index_maps = resolve_offheap_index_maps(args.offheap_indexmap_dir, cfgs)

    # Run telemetry (ISSUE 11): the journal records health transitions,
    # swaps, watchdog trips and shard loss during the replay; PHOTON_TRACE
    # exports a Perfetto-loadable trace; the serve profile persists below.
    from photon_ml_tpu.utils import telemetry

    out_root = args.root_output_directory
    os.makedirs(out_root, exist_ok=True)
    journal = telemetry.RunJournal(os.path.join(out_root, "journal.jsonl"))
    # Adaptive runtime planner (ISSUE 14): installed AFTER the journal
    # (inside the try below) so plan_decision events land in it, owned so
    # a caller's ambient plan survives this run. Explicit
    # --max-batch/--max-wait-ms still win.
    from photon_ml_tpu import planner

    plan_owned = planner.current_plan() is None
    if not plan_owned and getattr(args, "profile", None):
        logger.warning(
            "--profile %s ignored: a runtime plan is already installed "
            "by the caller (uninstall it to let this run plan itself)",
            args.profile,
        )
    # Only adopt the process-ambient slots we own (same discipline for
    # journal and tracer): a caller's pre-installed journal/tracer must
    # survive this run, not be clobbered and uninstalled to None.
    journal_owned = telemetry.current_journal() is None
    if journal_owned:
        telemetry.install_journal(journal)
    tracer_owned = telemetry.current_tracer() is None
    tracer = telemetry.start_tracing_if_enabled()

    # The ambient journal/tracer/plan uninstall on EVERY exit path —
    # including a failed bundle load — or the process-global sinks leak
    # into the next run in this process (and its trace would never
    # export).
    try:
        if plan_owned:
            # After install_journal so every plan_decision event lands in
            # THIS run's journal. Loud on topology mismatch by design.
            planner.ensure_ambient_plan(getattr(args, "profile", None))
        if tenant_specs:
            return _run_multi_tenant(args, tenant_specs, index_maps)
        if shadow_spec:
            return _run_with_shadow(args, index_maps)
        bundle = load_bundle(args.model_input_directory, index_maps=index_maps)
        logger.info(
            "bundle pinned: %d coordinate(s), %.1f MB uploaded in %.3fs",
            len(bundle.coordinates),
            bundle.upload_bytes / 1e6,
            bundle.upload_s,
        )
        # Release on EVERY exit path (finally below): a two-tier store's
        # async promotion worker must be joined while the XLA runtime is
        # still alive — a daemon thread dispatching device updates during
        # interpreter teardown aborts the process ("terminate called
        # without an active exception"), which on an error path would mask
        # the real traceback.
        try:
            return _run_with_bundle(args, bundle)
        finally:
            bundle.release()
    finally:
        if plan_owned:
            planner.uninstall_plan()
        if tracer is not None and tracer_owned:
            tracer.export(os.path.join(out_root, "trace.json"))
            telemetry.uninstall_tracer()
        if journal_owned:
            telemetry.uninstall_journal()
        journal.close()


def _write_score_part(scores_dir: str, k: int, results, model_id: str) -> str:
    """Write one replay window's scores as a crash-safe Avro part file:
    a dot-prefixed temp name (invisible to list_container_files) then
    os.replace into place — a SIGKILL mid-write tears the temp file,
    never a part a reader would pick up. `results` is a list of (stream
    position, ScoreResult); uids default to the position. Shared by the
    single-tenant and multi-tenant replay paths."""
    from photon_ml_tpu.io import avro as avro_io
    from photon_ml_tpu.io import schemas

    os.makedirs(scores_dir, exist_ok=True)
    part = os.path.join(scores_dir, f"part-{k:05d}.avro")
    tmp = os.path.join(scores_dir, f".part-{k:05d}.avro.tmp")
    avro_io.write_container(
        tmp,
        schemas.SCORING_RESULT,
        score_store.score_records(
            np.asarray([r.score for _, r in results], np.float64),
            model_id,
            uids=[
                r.uid if r.uid is not None else str(pos)
                for pos, r in results
            ],
        ),
    )
    os.replace(tmp, part)
    return part


def _run_with_bundle(args, bundle: ServingBundle) -> dict:
    from photon_ml_tpu import planner as _planner_mod

    # Explicit CLI flags that override planned serving decisions — fed
    # into the recorded plan block so it reports source "knob" for them.
    _cli_plan_overrides = {}
    if args.max_batch is not None:
        _cli_plan_overrides["serving_max_batch"] = int(args.max_batch)
    if args.max_wait_ms is not None:
        _cli_plan_overrides["serving_max_wait_ms"] = float(args.max_wait_ms)

    is_json = args.requests.endswith((".json", ".jsonl"))
    shard_configs = None
    if args.feature_shard_configurations:
        from photon_ml_tpu.cli.config import parse_feature_shard_config

        shard_configs = dict(
            parse_feature_shard_config(s)
            for s in args.feature_shard_configurations
        )

    malformed = [0]  # records dropped at parse time, before submission
    if is_json:
        stream = _iter_json_requests(args.requests, bundle, malformed)
    else:
        stream = _iter_avro_requests(
            args.requests, bundle, shard_configs, malformed
        )

    from photon_ml_tpu.utils import telemetry

    out_root = args.root_output_directory
    os.makedirs(out_root, exist_ok=True)
    engine = ServingEngine(bundle, max_batch=args.max_batch)
    t_warm = time.perf_counter()
    with telemetry.span("serve_warmup"):
        compiles = engine.warmup()
    warmup_s = time.perf_counter() - t_warm
    logger.info("engine warm: %d bucket program(s) compiled", compiles)

    # Scores are written one part file per replay window, so memory stays
    # O(window) end to end — accumulating the whole stream's scores/uids
    # host-side would re-create exactly the pattern the chunked
    # score_records path removed from cli/score.py.

    scores_dir = os.path.join(out_root, "scores")
    os.makedirs(scores_dir, exist_ok=True)
    model_id = args.model_id or "game-model"
    n_requests = 0
    n_failed = 0
    # Live reshard drill (--reshard-to): kicked on a background worker
    # once the first replay window has answered, so the generation flip
    # happens UNDER traffic — the elastic_mesh bench contract, driveable
    # from the CLI. Joined before the summary so the outcome is recorded.
    reshard_to = getattr(args, "reshard_to", None)
    reshard_info: dict = {}
    reshard_thread = None

    def _live_reshard():
        try:
            from photon_ml_tpu.parallel.mesh import surviving_mesh

            reshard_info.update(
                engine.reshard_orchestrator.reshard(
                    surviving_mesh(reshard_to)
                )
            )
            logger.info("live reshard committed: %s", reshard_info)
        except Exception as exc:  # noqa: BLE001 - recorded, replay goes on
            reshard_info["error"] = repr(exc)
            logger.warning("live reshard rolled back: %r", exc)

    t_replay = time.perf_counter()
    with telemetry.span("serve_replay"), engine, engine.batcher(
        max_wait_ms=args.max_wait_ms,
        max_pending=args.max_pending,
        default_deadline_ms=args.deadline_ms,
    ) as batcher:
      # The reshard worker must be joined on EVERY exit path, inside the
      # engine context: a replay error escaping this loop would otherwise
      # close the engine while the worker is mid-stage/mid-commit.
      try:
        for k in itertools.count():
            window = list(itertools.islice(stream, REPLAY_WINDOW))
            if not window:
                break
            if k == 1 and reshard_to is not None and reshard_thread is None:
                import threading

                reshard_thread = threading.Thread(
                    target=_live_reshard, name="photon-reshard-cli"
                )
                reshard_thread.start()
            # Per-future harvesting, not score_all: one malformed request
            # must cost ONE failed record (logged, counted), never the
            # window's healthy co-batched answers or the summary. Replay is
            # a closed-loop client: block=True backpressures against the
            # bounded queue instead of shedding its own offline traffic.
            futures = [batcher.submit(r, block=True) for r in window]
            results = []  # (stream position, ScoreResult) of the successes
            for i, fut in enumerate(futures):
                try:
                    results.append((n_requests + i, fut.result()))
                except Exception as exc:  # noqa: BLE001 - per-request isolation
                    n_failed += 1
                    logger.warning(
                        "request %r failed: %s",
                        window[i].uid if window[i].uid is not None
                        else str(n_requests + i),
                        exc,
                    )
            if results:
                _write_score_part(scores_dir, k, results, model_id)
            n_requests += len(window)
        if reshard_to is not None and reshard_thread is None:
            # Single-window replay: the drill still runs (and is still
            # recorded), just without concurrent traffic to flow past it.
            _live_reshard()
      finally:
        if reshard_thread is not None:
            reshard_thread.join()
        metrics = batcher.metrics()
        # The PLANNED-or-overridden values actually served with (the
        # argparse values may be None = "let the planner decide").
        resolved_wait_ms = batcher.max_wait_s * 1e3
    replay_s = time.perf_counter() - t_replay
    logger.info(
        "replayed %d request(s), %d failed, %d malformed record(s) skipped; "
        "scores written to %s",
        n_requests,
        n_failed,
        malformed[0],
        scores_dir,
    )

    # Drain-on-shutdown already ran (the context exits answered every
    # pending future); the health machine must have landed CLOSED.
    from photon_ml_tpu.utils import faults
    from photon_ml_tpu.utils.contracts import ROBUSTNESS_CLEAN_ZERO_KEYS

    summary = {
        "num_requests": n_requests,
        "failed_requests": n_failed,
        "malformed_records": malformed[0],
        "serving": metrics,
        "health": engine.health.snapshot(),
        # The pod-scale mesh counters (ROBUSTNESS_CLEAN_ZERO_KEYS) are
        # always present — an all-zero block is the clean-run proof, and
        # a missing key would read as one.
        "robustness_counters": {
            **{k: 0 for k in ROBUSTNESS_CLEAN_ZERO_KEYS},
            **faults.counters(),
        },
        # The adaptive-runtime plan block (ISSUE 14): always present —
        # inactive on an unplanned replay — mirroring fit_timing["plan"].
        # Explicit --max-batch/--max-wait-ms flags re-source their
        # decisions as "knob" so the audit shows what actually served.
        "plan": _planner_mod.plan_block(overrides=_cli_plan_overrides),
        # The per-tenant block (ISSUE 15): always present so absence is
        # loud — empty on a single-tenant replay, one TENANT_BLOCK_KEYS
        # dict per tenant under --tenant.
        "tenants": {},
        # Bundle lineage (ISSUE 16, BUNDLE_PROVENANCE_KEYS): where the
        # served model came from and how many delta applies it absorbed.
        "provenance": dict(engine.bundle.provenance),
        # The shadow-deployment block (ISSUE 18): always present so
        # absence is loud — empty here, SHADOW_BLOCK_KEYS under --shadow.
        "shadow": {},
        # ISSUE 19: the autopilot block — empty on this open-loop path.
        "autopilot": {},
    }
    if reshard_to is not None:
        summary["reshard"] = reshard_info
    with open(os.path.join(out_root, "serving-summary.json"), "w") as f:
        json.dump(summary, f, indent=2, default=str)
    # The persisted serve profile (ISSUE 11): latency/dispatch record the
    # planner consumes beside the fit profile (same loud-read contract).
    profile = telemetry.build_profile(
        "serve",
        wall_s=warmup_s + replay_s,
        stages={
            "warmup_s": round(warmup_s, 4),
            "replay_s": round(replay_s, 4),
        },
        dispatch={
            "max_batch": int(engine.max_batch),
            "max_wait_ms": float(resolved_wait_ms),
            "sharding": metrics.get("sharding"),
        },
        bucket_shapes={"engine_buckets": list(engine.buckets)},
        serving=metrics,
    )
    # Plan decisions round-trip through the profile (ISSUE 14), with the
    # same explicit-flag re-sourcing as the summary block.
    profile["plan"] = _planner_mod.plan_block(overrides=_cli_plan_overrides)
    telemetry.write_profile(os.path.join(out_root, "profile.json"), profile)
    logger.info("serving metrics: %s", metrics)
    return summary


def _run_multi_tenant(args, tenant_specs, index_maps) -> dict:
    """Multi-tenant replay (`--tenant NAME=MODEL_DIR` repeatable): every
    tenant's bundle pins onto ONE device fleet behind a TenantRegistry —
    per-tenant admission quotas, deadline budgets and failure domains,
    weighted-fair cross-tenant co-batching — and the replay stream is
    assigned round-robin across tenants (each record encoded against its
    assigned tenant's bundle). Scores land under scores/<tenant>/, and
    the summary carries one TENANT_BLOCK_KEYS dict per tenant."""
    from photon_ml_tpu import planner as _planner_mod
    from photon_ml_tpu.serving.tenancy import TenantRegistry
    from photon_ml_tpu.utils import faults, telemetry
    from photon_ml_tpu.utils.contracts import ROBUSTNESS_CLEAN_ZERO_KEYS

    _cli_plan_overrides = {}
    if args.max_batch is not None:
        _cli_plan_overrides["serving_max_batch"] = int(args.max_batch)
    if args.max_wait_ms is not None:
        _cli_plan_overrides["serving_max_wait_ms"] = float(args.max_wait_ms)

    is_json = args.requests.endswith((".json", ".jsonl"))
    shard_configs = None
    if args.feature_shard_configurations:
        from photon_ml_tpu.cli.config import parse_feature_shard_config

        shard_configs = dict(
            parse_feature_shard_config(s)
            for s in args.feature_shard_configurations
        )

    out_root = args.root_output_directory
    os.makedirs(out_root, exist_ok=True)
    t_warm = time.perf_counter()
    registry = TenantRegistry(
        max_batch=args.max_batch, max_wait_ms=args.max_wait_ms
    )
    names: List[str] = []
    pilot = None
    autopilot_block: dict = {}
    try:
        for name, model_dir in tenant_specs:
            bundle = load_bundle(model_dir, index_maps=index_maps)
            registry.admit(
                name,
                bundle,
                max_pending=args.max_pending,
                deadline_ms=args.deadline_ms,
            )
            names.append(name)
            logger.info(
                "tenant %r pinned: %d coordinate(s), %.1f MB",
                name,
                len(bundle.coordinates),
                bundle.upload_bytes / 1e6,
            )
        warmup_s = time.perf_counter() - t_warm

        if getattr(args, "autopilot", False):
            # Closed-loop autoscaling (ISSUE 19): the photon-autopilot
            # worker ticks beside the replay, every decision journaled;
            # knob-deferred hygiene (PHOTON_AUTOPILOT_*).
            from photon_ml_tpu.autopilot import Autopilot

            pilot = Autopilot(registry)
            logger.info(
                "autopilot armed: %d rule(s), tick %dms",
                len(pilot.rules),
                pilot.tick_ms,
            )

        malformed = [0]
        if is_json:
            raw_stream = _iter_json_docs(args.requests, malformed)
        else:
            raw_stream = _iter_avro_records(args.requests)


        scores_root = os.path.join(out_root, "scores")
        model_id = args.model_id or "game-model"
        n_requests = 0
        n_failed = 0
        assigned = 0  # round-robin cursor over raw records
        t_replay = time.perf_counter()
        with telemetry.span("serve_replay", tenants=names):
            for k in itertools.count():
                window = []  # (tenant name, request)
                for raw in itertools.islice(raw_stream, REPLAY_WINDOW):
                    name = names[assigned % len(names)]
                    assigned += 1
                    bundle = registry.tenant(name).bundle
                    try:
                        if is_json:
                            req = _encode_json_request(bundle, raw)
                        else:
                            req = request_from_record(
                                bundle, raw, shard_configs
                            )
                    except Exception as exc:  # noqa: BLE001 - per-record
                        malformed[0] += 1
                        logger.warning(
                            "skipping malformed request for tenant %r: %s",
                            name,
                            exc,
                        )
                        continue
                    window.append((name, req))
                if not window:
                    break
                futures = [
                    (name, registry.submit(name, r, block=True))
                    for name, r in window
                ]
                by_tenant: dict = {}
                for i, (name, fut) in enumerate(futures):
                    try:
                        res = fut.result()
                    except Exception as exc:  # noqa: BLE001 - per-request
                        n_failed += 1
                        logger.warning(
                            "tenant %r request %d failed: %s",
                            name,
                            n_requests + i,
                            exc,
                        )
                        continue
                    by_tenant.setdefault(name, []).append(
                        (n_requests + i, res)
                    )
                for name, results in by_tenant.items():
                    _write_score_part(
                        os.path.join(scores_root, name),
                        k,
                        results,
                        model_id,
                    )
                n_requests += len(window)
        replay_s = time.perf_counter() - t_replay
        metrics = registry.metrics()
        health = {
            name: registry.tenant(name).engine.health.snapshot()
            for name in names
        }
        provenance = {
            name: dict(registry.tenant(name).bundle.provenance)
            for name in names
        }
    finally:
        if pilot is not None:
            pilot.close()
            autopilot_block = pilot.summary()
        registry.close(release_bundles=True)
    logger.info(
        "replayed %d request(s) across %d tenant(s), %d failed, %d "
        "malformed skipped",
        n_requests,
        len(names),
        n_failed,
        malformed[0],
    )

    summary = {
        "num_requests": n_requests,
        "failed_requests": n_failed,
        "malformed_records": malformed[0],
        "serving": metrics,
        "health": health,
        "robustness_counters": {
            **{k: 0 for k in ROBUSTNESS_CLEAN_ZERO_KEYS},
            **faults.counters(),
        },
        "plan": _planner_mod.plan_block(overrides=_cli_plan_overrides),
        "tenants": metrics["tenants"],
        # Per-tenant bundle lineage (ISSUE 16, BUNDLE_PROVENANCE_KEYS).
        "provenance": provenance,
        # ISSUE 18: always present, empty off the --shadow path.
        "shadow": {},
        # ISSUE 19: always present — AUTOPILOT_BLOCK_KEYS under
        # --autopilot, empty on an open-loop replay.
        "autopilot": autopilot_block,
    }
    with open(os.path.join(out_root, "serving-summary.json"), "w") as f:
        json.dump(summary, f, indent=2, default=str)
    profile = telemetry.build_profile(
        "serve",
        wall_s=warmup_s + replay_s,
        stages={
            "warmup_s": round(warmup_s, 4),
            "replay_s": round(replay_s, 4),
        },
        dispatch={
            "max_batch": int(registry.max_batch),
            "max_wait_ms": float(registry.max_wait_s * 1e3),
            "tenants": names,
        },
        bucket_shapes={"registry_buckets": list(registry.buckets)},
        serving=metrics,
    )
    profile["plan"] = _planner_mod.plan_block(overrides=_cli_plan_overrides)
    telemetry.write_profile(os.path.join(out_root, "profile.json"), profile)
    logger.info("multi-tenant serving metrics: %s", metrics)
    return summary


def _load_labels(path: str) -> dict:
    """uid -> (label, weight) from a .json/.jsonl label stream; a
    malformed line costs ONE label (logged), never the join."""
    labels: dict = {}
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
                labels[str(doc["uid"])] = (
                    float(doc["label"]),
                    float(doc.get("weight", 1.0)),
                )
            except Exception as exc:  # noqa: BLE001 - per-record isolation
                logger.warning(
                    "skipping malformed label at %s:%d: %s", path, lineno, exc
                )
    return labels


def _run_with_shadow(args, index_maps) -> dict:
    """Single-tenant replay with a shadow challenger (ISSUE 18,
    `--shadow NAME=MODEL_DIR`): the champion bundle serves as a tenant on
    a TenantRegistry, the challenger rides as a shadow tenant receiving
    mirrored traffic co-batched with the champion — its answers are never
    returned (scores are written for the champion ONLY) — and `--labels`
    joins labels into the online evaluation windows that drive the
    journaled promote/reject verdict. Champion and challenger must share
    the feature space (one request encoding serves both); that is the
    refresh-challenger shape by construction."""
    from photon_ml_tpu import planner as _planner_mod
    from photon_ml_tpu.serving.shadow import ShadowController
    from photon_ml_tpu.serving.tenancy import TenantRegistry
    from photon_ml_tpu.utils import faults, telemetry
    from photon_ml_tpu.utils.contracts import ROBUSTNESS_CLEAN_ZERO_KEYS

    shadow_name, sep, shadow_dir = args.shadow.partition("=")
    if not sep or not shadow_name or not shadow_dir:
        raise ValueError(f"--shadow {args.shadow!r}: expected NAME=MODEL_DIR")
    champion_name = "champion"
    if shadow_name == champion_name:
        raise ValueError(
            f"--shadow name {shadow_name!r} collides with the champion "
            "tenant name"
        )

    _cli_plan_overrides = {}
    if args.max_batch is not None:
        _cli_plan_overrides["serving_max_batch"] = int(args.max_batch)
    if args.max_wait_ms is not None:
        _cli_plan_overrides["serving_max_wait_ms"] = float(args.max_wait_ms)

    is_json = args.requests.endswith((".json", ".jsonl"))
    shard_configs = None
    if args.feature_shard_configurations:
        from photon_ml_tpu.cli.config import parse_feature_shard_config

        shard_configs = dict(
            parse_feature_shard_config(s)
            for s in args.feature_shard_configurations
        )
    labels = _load_labels(args.labels) if args.labels else {}

    out_root = args.root_output_directory
    os.makedirs(out_root, exist_ok=True)
    t_warm = time.perf_counter()
    registry = TenantRegistry(
        max_batch=args.max_batch, max_wait_ms=args.max_wait_ms
    )
    controller = None
    try:
        champ_bundle = load_bundle(
            args.model_input_directory, index_maps=index_maps
        )
        registry.admit(
            champion_name,
            champ_bundle,
            max_pending=args.max_pending,
            deadline_ms=args.deadline_ms,
        )
        chall_bundle = load_bundle(shadow_dir, index_maps=index_maps)
        controller = ShadowController(
            registry,
            champion_name,
            shadow_name,
            chall_bundle,
            window_size=args.shadow_window,
            max_pending=args.max_pending,
            deadline_ms=args.deadline_ms,
        )
        warmup_s = time.perf_counter() - t_warm
        logger.info(
            "champion pinned; challenger %r riding shadow (window=%d, "
            "%d label(s) preloaded)",
            shadow_name,
            args.shadow_window,
            len(labels),
        )

        malformed = [0]
        if is_json:
            raw_stream = _iter_json_docs(args.requests, malformed)
        else:
            raw_stream = _iter_avro_records(args.requests)

        scores_dir = os.path.join(out_root, "scores")
        model_id = args.model_id or "game-model"
        n_requests = 0
        n_failed = 0
        t_replay = time.perf_counter()
        with telemetry.span("serve_replay", shadow=shadow_name):
            for k in itertools.count():
                window = []
                # Encode against the champion's CURRENT bundle: after a
                # promotion flips the generation, later windows encode
                # against the promoted challenger.
                bundle = registry.tenant(champion_name).bundle
                for raw in itertools.islice(raw_stream, REPLAY_WINDOW):
                    try:
                        if is_json:
                            req = _encode_json_request(bundle, raw)
                        else:
                            req = request_from_record(
                                bundle, raw, shard_configs
                            )
                    except Exception as exc:  # noqa: BLE001 - per-record
                        malformed[0] += 1
                        logger.warning(
                            "skipping malformed request: %s", exc
                        )
                        continue
                    window.append(req)
                if not window:
                    break
                futures = []
                for req in window:
                    fut = registry.submit(champion_name, req, block=True)
                    futures.append(fut)
                    # Mirror AFTER the champion submit so the pair lands
                    # in the same dispatch round; a False return (fraction
                    # gate, fault, post-verdict) is champion-only, never
                    # an error.
                    if controller.mirror(req, fut) and req.uid in labels:
                        lab, w = labels[req.uid]
                        controller.record_label(req.uid, lab, weight=w)
                results = []
                for i, fut in enumerate(futures):
                    try:
                        results.append((n_requests + i, fut.result()))
                    except Exception as exc:  # noqa: BLE001 - per-request
                        n_failed += 1
                        logger.warning(
                            "request %d failed: %s", n_requests + i, exc
                        )
                if results:
                    _write_score_part(scores_dir, k, results, model_id)
                n_requests += len(window)
        replay_s = time.perf_counter() - t_replay
        if labels:
            # A short replay outruns the async evaluation worker (the
            # first metric compile alone can cost more than the whole
            # replay): drain the joined-window backlog so the verdict
            # loop gets its chance to actuate before the snapshot. With
            # too few joined rows for a verdict this returns as soon as
            # the backlog is digested, not after the full timeout.
            controller.drain(timeout_s=120.0)
        # The shadow block snapshots BEFORE the controller closes (its
        # champion-generation field reads the live engine); close()
        # retires a still-observing shadow without a verdict.
        shadow_block = controller.summary()
        controller.close()
        metrics = registry.metrics()
        health = registry.tenant(champion_name).engine.health.snapshot()
        provenance = dict(registry.tenant(champion_name).bundle.provenance)
    finally:
        if controller is not None:
            controller.close()
        registry.close(release_bundles=True)
    logger.info(
        "replayed %d request(s), %d failed, %d malformed skipped; shadow "
        "%r finished %s",
        n_requests,
        n_failed,
        malformed[0],
        shadow_name,
        shadow_block["status"],
    )

    summary = {
        "num_requests": n_requests,
        "failed_requests": n_failed,
        "malformed_records": malformed[0],
        "serving": metrics,
        "health": health,
        "robustness_counters": {
            **{k: 0 for k in ROBUSTNESS_CLEAN_ZERO_KEYS},
            **faults.counters(),
        },
        "plan": _planner_mod.plan_block(overrides=_cli_plan_overrides),
        "tenants": metrics["tenants"],
        "provenance": provenance,
        # The online-quality-gate evidence (SHADOW_BLOCK_KEYS).
        "shadow": shadow_block,
        # ISSUE 19: the autopilot block — empty on the shadow path (the
        # shadow controller owns this run's actuations).
        "autopilot": {},
    }
    with open(os.path.join(out_root, "serving-summary.json"), "w") as f:
        json.dump(summary, f, indent=2, default=str)
    profile = telemetry.build_profile(
        "serve",
        wall_s=warmup_s + replay_s,
        stages={
            "warmup_s": round(warmup_s, 4),
            "replay_s": round(replay_s, 4),
        },
        dispatch={
            "max_batch": int(registry.max_batch),
            "max_wait_ms": float(registry.max_wait_s * 1e3),
            "tenants": [champion_name, shadow_name],
        },
        bucket_shapes={"registry_buckets": list(registry.buckets)},
        serving=metrics,
    )
    profile["plan"] = _planner_mod.plan_block(overrides=_cli_plan_overrides)
    telemetry.write_profile(os.path.join(out_root, "profile.json"), profile)
    logger.info("shadow serving metrics: %s", metrics)
    return summary


def _iter_avro_records(path: str) -> Iterator[dict]:
    """Raw reference-shaped Avro replay records (block-streaming,
    corrupt blocks quarantined) — the multi-tenant round-robin encodes
    each against its assigned tenant's bundle."""
    from photon_ml_tpu.io import avro as avro_io

    paths = (
        avro_io.list_container_files(path) if os.path.isdir(path) else [path]
    )
    for p in paths:
        for _, rec in avro_io.iter_container(p, quarantine=True):
            yield rec


def main(argv: Optional[List[str]] = None) -> None:
    raw_argv = list(sys.argv[1:] if argv is None else argv)
    args = build_parser().parse_args(raw_argv)
    if args.mh_serve_worker:
        # Spawned by the multi-host serve supervisor: one share-nothing
        # serving host (host-local store + mirrored replay).
        from photon_ml_tpu.cli import serve_multihost

        raise SystemExit(serve_multihost.run_worker(args))
    if args.multihost:
        from photon_ml_tpu.cli import serve_multihost

        serve_multihost.run_supervisor(args, raw_argv)
        return
    run(args)


if __name__ == "__main__":
    main(sys.argv[1:])

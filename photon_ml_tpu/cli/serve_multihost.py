"""Multi-host serving (`cli/serve --multihost N`): host-local stores,
whole-host loss as a survivable failure domain.

The Spark-era deployment spread the coefficient table across executors
and survived executor loss through YARN relaunch; this is the serving
half of that contract (PARITY.md "Mesh failure semantics", ISSUE 17).
N OS-process serving hosts each stage the FULL fixed-effect model but
only their OWN partition of every random-effect coordinate's rows: host
k owns shard s iff `s % N == k`, and marks every other shard LOST in
its bundle's ShardHealth at startup — which *is* the host-local
two-tier store: lookups for a non-owned row resolve to the pinned zero
row, exactly the PR 10 shard-loss degradation.

Every host replays the full mirrored request stream (the serving twin
of the fit's mirrored sample arrays) and writes per-window result
parts; the supervisor routes at merge time — for each request it keeps
the answer with the FEWEST shard-loss fallbacks (`ScoreResult.n_lost`,
ties to fewer cold lookups then the lowest host id), so:

  * owner alive  -> its answer is bitwise-identical to a single-process
    serve of the same artifact (marking OTHER shards lost never touches
    an owned row's lookup or dispatch);
  * owner dead   -> every survivor already answered those rows through
    the pinned-zero FE-only tier, bitwise-identically to each other —
    the request degrades, it never fails.

A worker that dies (SIGKILL drill) is journaled as `host_loss`; while
PHOTON_HOST_LOSS_RETRIES allows, the supervisor relaunches it from its
last durable window (`--mh-resume-window`), and the rejoining worker
restages its row partition through `hostmesh.restage_host_rows` (the
`host_join` fault site + journal event). Workers share NOTHING — no
jax.distributed group, no heartbeat — so a host loss cannot take the
process group down with it; the supervisor's poll is the detector.
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger("photon_ml_tpu.cli.serve_multihost")

# Worker result parts are JSONL (one answered request per line) rather
# than Avro score parts: the merge needs per-request fidelity fields
# (n_lost/n_cold) that the score schema deliberately does not carry.
_RESULT_FIELDS = ("i", "uid", "score", "mean", "cold", "n_cold", "n_lost", "fe")


def _host_dir(out_root: str, attempt: int, host_id: int) -> str:
    return os.path.join(out_root, "hosts", f"attempt{attempt}-host{host_id}")


def _validate_scope(args) -> None:
    """Refuse, loudly and before any staging, every flag combination the
    multi-host serve path does not implement — a silent fallback to
    single-process behavior would invalidate the contract the operator
    asked for."""
    refusals = []
    if getattr(args, "tenant", None):
        refusals.append("--tenant (multi-tenant) has no multi-host form")
    if getattr(args, "reshard_to", None) is not None:
        refusals.append(
            "--reshard-to is a single-process drill (each multi-host "
            "worker's layout IS the shard ownership map)"
        )
    if not args.model_input_directory:
        refusals.append("--model-input-directory is required")
    if refusals:
        raise ValueError(
            "--multihost serve scope: " + "; ".join(refusals)
        )


# ------------------------------------------------------------------ worker


def _mark_host_local(bundle, host_id: int, num_hosts: int):
    """Degrade `bundle` to this host's partition: every random-effect
    shard NOT owned by this host (owner = shard index mod num_hosts) is
    marked LOST, so its rows answer through the pinned-zero FE-only tier.
    Returns ({cid: [owned shard indices]}, total owned rows)."""
    owned: Dict[str, List[int]] = {}
    owned_rows = 0
    for cid, c in bundle.coordinates.items():
        sh = getattr(c, "shard_health", None)
        if not getattr(c, "is_random_effect", False) or sh is None:
            continue
        owned[cid] = []
        for s in range(sh.n_shards):
            if s % num_hosts == host_id:
                owned[cid].append(s)
                lo, hi = sh.row_range(s)
                owned_rows += hi - lo
            else:
                bundle.mark_shard_lost(cid, s)
    return owned, owned_rows


def run_worker(args) -> int:
    """One serving host: full artifact load, host-local store (non-owned
    shards LOST), full-stream mirrored replay from `--mh-resume-window`,
    crash-safe per-window JSONL result parts + a progress marker the
    supervisor reads to relaunch a killed worker where it left off."""
    from photon_ml_tpu.cli import serve as serve_cli
    from photon_ml_tpu.parallel import hostmesh
    from photon_ml_tpu.serving.engine import ServingEngine
    from photon_ml_tpu.utils import telemetry

    host_id, num_hosts = args.mh_host_id, args.mh_num_hosts
    logging.basicConfig(
        level=getattr(logging, args.logging_level.upper(), logging.INFO),
        format=(
            f"%(asctime)s h{host_id} %(name)s %(levelname)s %(message)s"
        ),
    )
    _validate_scope(args)
    out_root = args.root_output_directory
    host_dir = _host_dir(out_root, args.mh_attempt, host_id)
    results_dir = os.path.join(host_dir, "results")
    os.makedirs(results_dir, exist_ok=True)
    # The pid file lands before ANY heavy work: it is the chaos drill's
    # SIGKILL target, and a kill window that opens only after staging
    # would never exercise a load-phase loss.
    with open(os.path.join(host_dir, "pid"), "w") as f:
        f.write(str(os.getpid()))

    journal = telemetry.RunJournal(os.path.join(host_dir, "journal.jsonl"))
    journal_owned = telemetry.current_journal() is None
    if journal_owned:
        telemetry.install_journal(journal)

    shard_configs = None
    if args.feature_shard_configurations:
        from photon_ml_tpu.cli.config import parse_feature_shard_config

        shard_configs = dict(
            parse_feature_shard_config(s)
            for s in args.feature_shard_configurations
        )
    index_maps = None
    if args.offheap_indexmap_dir:
        from photon_ml_tpu.io.paldb import resolve_offheap_index_maps

        index_maps = resolve_offheap_index_maps(
            args.offheap_indexmap_dir, shard_configs or {}
        )

    bundle = None
    try:
        bundle = serve_cli.load_bundle(
            args.model_input_directory, index_maps=index_maps
        )
        owned, owned_rows = _mark_host_local(bundle, host_id, num_hosts)
        logger.info(
            "host-local store: %s (%d owned rows of every RE coordinate)",
            {cid: len(s) for cid, s in owned.items()},
            owned_rows,
        )
        if args.mh_attempt > 0:
            # Rejoin after a loss: the partition was just restaged from
            # the artifact (the load above); the host_join fault site can
            # still veto it — an injected failure exits nonzero and the
            # fleet keeps answering this host's rows FE-only.
            hostmesh.restage_host_rows(host_id, num_hosts, owned_rows)

        is_json = args.requests.endswith((".json", ".jsonl"))
        malformed = [0]
        if is_json:
            stream = serve_cli._iter_json_requests(
                args.requests, bundle, malformed
            )
        else:
            stream = serve_cli._iter_avro_requests(
                args.requests, bundle, shard_configs, malformed
            )

        engine = ServingEngine(bundle, max_batch=args.max_batch)
        engine.warmup()
        n_requests = 0
        n_failed = 0
        import itertools

        with engine, engine.batcher(
            max_wait_ms=args.max_wait_ms,
            max_pending=args.max_pending,
            default_deadline_ms=args.deadline_ms,
        ) as batcher:
            for k in itertools.count():
                window = list(
                    itertools.islice(stream, serve_cli.REPLAY_WINDOW)
                )
                if not window:
                    break
                if k < args.mh_resume_window:
                    # Already answered durably before this relaunch; the
                    # stream is still consumed so positions stay global.
                    n_requests += len(window)
                    continue
                futures = [batcher.submit(r, block=True) for r in window]
                lines = []
                for i, fut in enumerate(futures):
                    try:
                        r = fut.result()
                    except Exception as exc:  # noqa: BLE001 - per-request isolation
                        n_failed += 1
                        logger.warning(
                            "request %d failed: %s", n_requests + i, exc
                        )
                        continue
                    lines.append({
                        "i": n_requests + i,
                        "uid": r.uid,
                        "score": r.score,
                        "mean": r.mean,
                        "cold": bool(r.cold_start),
                        "n_cold": int(r.n_cold),
                        "n_lost": int(r.n_lost),
                        "fe": bool(r.fe_only),
                    })
                # Crash-safe part + progress marker: a SIGKILL tears only
                # the dot-prefixed temp, never a part or marker a merge
                # or relaunch would trust.
                part = os.path.join(results_dir, f"part-{k:05d}.jsonl")
                tmp = part + ".tmp"
                with open(tmp, "w") as f:
                    for ln in lines:
                        f.write(json.dumps(ln) + "\n")
                os.replace(tmp, part)
                prog_tmp = os.path.join(host_dir, ".progress.tmp")
                with open(prog_tmp, "w") as f:
                    json.dump({"next_window": k + 1}, f)
                os.replace(prog_tmp, os.path.join(host_dir, "progress"))
                n_requests += len(window)
            metrics = batcher.metrics()
        summary = {
            "host": host_id,
            "attempt": args.mh_attempt,
            "num_requests": n_requests,
            "failed_requests": n_failed,
            "malformed_records": malformed[0],
            "owned_shards": owned,
            "owned_rows": owned_rows,
            "serving": metrics,
            "counters": telemetry.METRICS.counters(),
        }
        tmp = os.path.join(host_dir, ".worker-summary.json.tmp")
        with open(tmp, "w") as f:
            json.dump(summary, f, indent=2, default=str)
        os.replace(tmp, os.path.join(host_dir, "worker-summary.json"))
        return 0
    except Exception:
        logger.exception("serve worker h%d failed", host_id)
        return 1
    finally:
        if bundle is not None:
            bundle.release()
        if journal_owned:
            telemetry.uninstall_journal()
        journal.close()


# -------------------------------------------------------------- supervisor


def _read_progress(out_root: str, host_id: int, upto_attempt: int) -> int:
    """Latest durable window marker across a host's attempts (0 if it
    never completed a window) — where a relaunch resumes."""
    best = 0
    for a in range(upto_attempt + 1):
        p = os.path.join(_host_dir(out_root, a, host_id), "progress")
        try:
            with open(p) as f:
                best = max(best, int(json.load(f)["next_window"]))
        except (OSError, ValueError, KeyError):
            continue
    return best


def _collect_parts(
    out_root: str, host_id: int, upto_attempt: int
) -> Dict[int, List[dict]]:
    """One host's answered windows, later attempts overriding earlier
    (a resumed worker re-answers the window its predecessor died in)."""
    windows: Dict[int, List[dict]] = {}
    for a in range(upto_attempt + 1):
        rdir = os.path.join(_host_dir(out_root, a, host_id), "results")
        if not os.path.isdir(rdir):
            continue
        for fn in sorted(os.listdir(rdir)):
            if not (fn.startswith("part-") and fn.endswith(".jsonl")):
                continue
            k = int(fn[len("part-"):-len(".jsonl")])
            with open(os.path.join(rdir, fn)) as f:
                windows[k] = [json.loads(ln) for ln in f if ln.strip()]
    return windows


def _merge_scores(
    out_root: str,
    per_host: Dict[int, Dict[int, List[dict]]],
    model_id: str,
) -> Tuple[int, int, int]:
    """Route at merge time: for every request keep the answer with the
    fewest shard-loss fallbacks (then fewest cold lookups, then lowest
    host id — survivors' FE-only answers for a lost host's rows are
    bitwise-identical, so the tie-break is cosmetic). Writes the same
    crash-safe Avro score parts a single-process replay writes. Returns
    (merged requests, fe_only_answers, degraded-and-cold answers)."""
    from photon_ml_tpu.cli.serve import _write_score_part
    from photon_ml_tpu.serving.engine import ScoreResult

    scores_dir = os.path.join(out_root, "scores")
    all_windows = sorted({k for w in per_host.values() for k in w})
    merged = 0
    fe_only_answers = 0
    degraded_cold = 0
    for k in all_windows:
        best: Dict[int, Tuple[tuple, dict]] = {}
        for host in sorted(per_host):
            for ln in per_host[host].get(k, []):
                rank = (ln["n_lost"], ln["n_cold"], host)
                cur = best.get(ln["i"])
                if cur is None or rank < cur[0]:
                    best[ln["i"]] = (rank, ln)
        results = []
        for i in sorted(best):
            _, ln = best[i]
            if ln["n_lost"] > 0:
                fe_only_answers += 1
                if ln["cold"]:
                    degraded_cold += 1
            results.append((
                i,
                ScoreResult(
                    score=ln["score"],
                    mean=ln["mean"],
                    uid=ln["uid"],
                    cold_start=ln["cold"],
                    n_cold=ln["n_cold"],
                    fe_only=ln["fe"],
                    n_lost=ln["n_lost"],
                ),
            ))
        if results:
            _write_score_part(scores_dir, k, results, model_id)
            merged += len(results)
    return merged, fe_only_answers, degraded_cold


def run_supervisor(args, raw_argv: List[str]) -> dict:
    """Spawn N share-nothing serve workers over the same artifact and
    request stream, absorb whole-host losses (journal + bounded
    relaunch), merge the durable result parts into the final score
    parts, and write the serving summary with its `multihost` block."""
    from photon_ml_tpu.utils import telemetry
    from photon_ml_tpu.utils.knobs import get_knob

    logging.basicConfig(
        level=getattr(logging, args.logging_level.upper(), logging.INFO),
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    _validate_scope(args)
    num_hosts = int(args.multihost)
    devices_per_host = int(args.multihost_devices_per_host)
    retries = int(get_knob("PHOTON_HOST_LOSS_RETRIES"))
    out_root = args.root_output_directory
    os.makedirs(out_root, exist_ok=True)

    journal = telemetry.RunJournal(os.path.join(out_root, "journal.jsonl"))
    journal_owned = telemetry.current_journal() is None
    if journal_owned:
        telemetry.install_journal(journal)
    try:
        return _supervise(args, raw_argv, num_hosts, devices_per_host,
                          retries, out_root)
    finally:
        if journal_owned:
            telemetry.uninstall_journal()
        journal.close()


def _supervise(
    args,
    raw_argv: List[str],
    num_hosts: int,
    devices_per_host: int,
    retries: int,
    out_root: str,
) -> dict:
    from photon_ml_tpu.parallel import hostmesh
    from photon_ml_tpu.utils import faults, telemetry
    from photon_ml_tpu.utils.contracts import ROBUSTNESS_CLEAN_ZERO_KEYS

    # attempt/resume/done per host; each host's relaunch counter is its
    # own, but the RETRY budget is fleet-wide (matches the fit side).
    attempt = {k: 0 for k in range(num_hosts)}
    procs: Dict[int, subprocess.Popen] = {}
    logs: List = []
    done: Dict[int, bool] = {}
    dead: Dict[int, bool] = {}
    losses = 0
    rejoins = 0

    def _spawn(host_id: int, att: int, resume: int) -> None:
        host_dir = _host_dir(out_root, att, host_id)
        os.makedirs(host_dir, exist_ok=True)
        argv = [
            sys.executable, "-m", "photon_ml_tpu.cli.serve", *raw_argv,
            "--mh-serve-worker",
            "--mh-host-id", str(host_id),
            "--mh-num-hosts", str(num_hosts),
            "--mh-attempt", str(att),
            "--mh-resume-window", str(resume),
        ]
        # Entity sharding ON is what makes the store host-LOCAL: each
        # coordinate stages row-sharded over the worker's devices, so its
        # ShardHealth has one shard per device for ownership to partition.
        env = hostmesh.worker_env(
            num_hosts,
            devices_per_host,
            extra={"PHOTON_SERVING_ENTITY_SHARD": "1"},
        )
        fo = open(os.path.join(host_dir, "worker.out"), "w")
        fe = open(os.path.join(host_dir, "worker.err"), "w")
        logs.extend([fo, fe])
        procs[host_id] = subprocess.Popen(
            argv, env=env, stdout=fo, stderr=fe
        )
        logger.info(
            "serve worker h%d up (attempt %d, resume window %d, pid %d)",
            host_id, att, resume, procs[host_id].pid,
        )

    try:
        for k in range(num_hosts):
            _spawn(k, 0, 0)
        deadline = time.monotonic() + 900.0
        while not all(done.get(k) or dead.get(k) for k in range(num_hosts)):
            if time.monotonic() > deadline:
                raise RuntimeError(
                    "multi-host serve timed out; killing workers"
                )
            time.sleep(0.1)
            for k in range(num_hosts):
                if done.get(k) or dead.get(k):
                    continue
                rc = procs[k].poll()
                if rc is None:
                    continue
                if rc == 0:
                    done[k] = True
                    continue
                # Whole-host loss mid-replay: journal it, and while the
                # retry budget allows, relaunch from the last durable
                # window (the rejoin restages the host's partition). Out
                # of budget, the fleet degrades — survivors keep
                # answering the lost rows FE-only; nothing fails.
                losses += 1
                telemetry.METRICS.increment("host_losses")
                telemetry.emit_event(
                    "host_loss",
                    host=k,
                    missed_beats=0,
                    num_hosts=num_hosts,
                    source="serve-supervisor",
                )
                logger.warning(
                    "serve worker h%d lost (rc %s), loss %d/%d budget",
                    k, rc, losses, retries,
                )
                if losses <= retries:
                    attempt[k] += 1
                    rejoins += 1
                    _spawn(
                        k,
                        attempt[k],
                        _read_progress(out_root, k, attempt[k]),
                    )
                else:
                    dead[k] = True
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        for p in procs.values():
            try:
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001 - best-effort reap
                pass
        for f in logs:
            f.close()

    survivors = sorted(k for k in range(num_hosts) if done.get(k))
    if not survivors:
        raise RuntimeError(
            "every serve worker died; no durable results to merge "
            f"(hosts under {os.path.join(out_root, 'hosts')})"
        )

    per_host = {
        k: _collect_parts(out_root, k, attempt[k]) for k in range(num_hosts)
    }
    model_id = args.model_id or "game-model"
    merged, fe_only_answers, degraded_cold = _merge_scores(
        out_root, per_host, model_id
    )

    # Stream totals come from a worker that finished the whole replay —
    # by construction at least one survivor did.
    wsum = {}
    for k in survivors:
        p = os.path.join(
            _host_dir(out_root, attempt[k], k), "worker-summary.json"
        )
        with open(p) as f:
            wsum[k] = json.load(f)
    ref = wsum[survivors[0]]
    num_requests = max(w["num_requests"] for w in wsum.values())
    failed = num_requests - merged

    summary = {
        "num_requests": num_requests,
        "failed_requests": failed,
        "malformed_records": ref["malformed_records"],
        "serving": ref["serving"],
        "robustness_counters": {
            **{k: 0 for k in ROBUSTNESS_CLEAN_ZERO_KEYS},
            **faults.counters(),
        },
        "multihost": {
            "num_hosts": num_hosts,
            "devices_per_host": devices_per_host,
            "attempts": {str(k): attempt[k] + 1 for k in range(num_hosts)},
            "host_losses": losses,
            "rejoins": rejoins,
            "survivor_hosts": len(survivors),
            "fe_only_answers": fe_only_answers,
            "degraded_cold_answers": degraded_cold,
            "owned_rows": {
                str(k): wsum[k]["owned_rows"] for k in survivors
            },
        },
    }
    with open(os.path.join(out_root, "serving-summary.json"), "w") as f:
        json.dump(summary, f, indent=2, default=str)
    logger.info(
        "multi-host replay merged: %d request(s), %d failed, %d FE-only "
        "degraded, %d host loss(es), %d survivor(s)",
        num_requests, failed, fe_only_answers, losses, len(survivors),
    )
    return summary

"""GAME scoring driver: load model -> score dataset -> write scores.

Counterpart of photon-client cli/game/scoring/GameScoringDriver.scala:39-284
(see SURVEY.md §3.2): read data with the model's feature index maps, load the
GAME model artifact, transform through GameTransformer, optionally evaluate,
and write ScoringResultAvro records (saveScoresToHDFS:229-260).

Usage: python -m photon_ml_tpu.cli.score --help
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
from typing import List, Optional

import numpy as np

from photon_ml_tpu.cli.config import parse_feature_shard_config
from photon_ml_tpu.evaluation.suite import EvaluationSuite, EvaluatorType
from photon_ml_tpu.io import avro_data, model_bridge, model_store, score_store
from photon_ml_tpu.io.avro_data import UID
from photon_ml_tpu.transformers.game_transformer import GameTransformer

logger = logging.getLogger("photon_ml_tpu.cli.score")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon_ml_tpu.cli.score",
        description="Score data with a trained GAME model (TPU-native Photon ML)",
    )
    p.add_argument("--input-data-directories", required=True, nargs="+")
    p.add_argument("--model-input-directory", required=True,
                   help="a model directory written by the training driver "
                        "(e.g. <root>/models/best)")
    p.add_argument("--root-output-directory", required=True)
    p.add_argument("--feature-shard-configurations", required=True, nargs="+",
                   metavar="DSL")
    p.add_argument("--offheap-indexmap-dir", default=None,
                   help="prebuilt feature-index partitions (PalDB or PHIDX); "
                        "default: the JSON maps saved beside the model")
    p.add_argument("--input-column-names", default=None,
                   help="Rename record fields (see the training driver)")
    p.add_argument("--input-data-date-range", default=None,
                   help="Inclusive 'yyyyMMdd-yyyyMMdd' range of daily input "
                        "subdirectories (inputDataDateRange, GameDriver.scala:64)")
    p.add_argument("--input-data-days-range", default=None,
                   help="Relative '<start>-<end>' days-ago range "
                        "(inputDataDaysRange, GameDriver.scala:69)")
    p.add_argument("--evaluators", nargs="*", default=[],
                   help="optional validation metrics computed on the scored data")
    p.add_argument("--model-id", default=None,
                   help="model id tag written into every score record")
    p.add_argument("--logging-level", default="INFO")
    return p


def run(args) -> dict:
    logging.basicConfig(
        level=getattr(logging, args.logging_level.upper(), logging.INFO),
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    shard_configs = dict(
        parse_feature_shard_config(s) for s in args.feature_shard_configurations
    )

    # Feature index maps: an explicit off-heap store (the reference's PalDB
    # partitions or this framework's PHIDX, prepareFeatureMaps
    # GameDriver.scala:231-236) or, by default, the JSON maps the training
    # driver saved beside the model artifact.
    from photon_ml_tpu.data.index_map import IndexMap

    if getattr(args, "offheap_indexmap_dir", None):
        from photon_ml_tpu.io.paldb import resolve_offheap_index_maps

        index_maps = resolve_offheap_index_maps(
            args.offheap_indexmap_dir, shard_configs
        )
    else:
        index_dir = os.path.join(args.model_input_directory, "feature-indexes")
        index_maps = {
            shard: IndexMap.load(os.path.join(index_dir, f"{shard}.json"))
            for shard in shard_configs
        }
    artifact = model_store.load_game_model(args.model_input_directory, index_maps)
    model, specs = model_bridge.game_model_from_artifact(artifact)

    id_tags = [
        spec.random_effect_type for spec in specs.values() if spec.is_random_effect
    ]
    for ev in args.evaluators:
        et = EvaluatorType.parse(ev)
        if et.is_grouped and et.id_tag not in id_tags:
            id_tags.append(et.id_tag)

    from photon_ml_tpu.utils.date_range import paths_for_date_range, resolve_range

    in_range = resolve_range(
        getattr(args, "input_data_date_range", None),
        getattr(args, "input_data_days_range", None),
    )
    dataset, _ = avro_data.read_game_dataset(
        paths_for_date_range(args.input_data_directories, in_range),
        shard_configs,
        index_maps=index_maps,
        id_tag_fields=id_tags,
        columns=(
            avro_data.InputColumnNames.parse(args.input_column_names)
            if getattr(args, "input_column_names", None)
            else None
        ),
    )
    # Scoring never packs a bucketed layout; cancel ingest's background
    # pack and drop the CSR stash rather than compute a layout nothing
    # will consume / pin ~12 bytes/nnz of host RAM for the run.
    dataset.release_stash()
    logger.info("scoring %d samples", dataset.num_samples)

    transformer = GameTransformer(model, specs, artifact.task)
    result = transformer.transform(dataset)

    out_root = args.root_output_directory
    os.makedirs(out_root, exist_ok=True)
    # Columns go to the writer as-is (device score array, host uid column):
    # save_scores streams them in fixed-size chunks, so a large scoring job
    # never holds a full host copy of any column (the former uids.tolist()
    # materialized an n-element Python string list, and scores/labels/
    # weights were each np.asarray'd whole).
    uids = (
        dataset.id_tags[UID]
        if UID in dataset.id_tags
        else np.arange(dataset.num_samples)
    )
    scores_dir = os.path.join(out_root, "scores")
    score_store.save_scores(
        scores_dir,
        result.scores,
        args.model_id or "game-model",
        uids=uids,
        labels=dataset.labels,
        weights=dataset.weights,
    )
    logger.info("scores written to %s", scores_dir)

    summary = {"num_scored": dataset.num_samples}
    if args.evaluators:
        suite = EvaluationSuite(
            [EvaluatorType.parse(e) for e in args.evaluators],
            dataset.labels,
            dataset.weights,
            id_tag_values=dataset.id_tags,
        )
        evaluation = suite.evaluate(result.scores)
        summary["evaluation"] = evaluation.results
        logger.info("evaluation: %s", evaluation.results)
    with open(os.path.join(out_root, "scoring-summary.json"), "w") as f:
        json.dump(summary, f, indent=2, default=str)
    return summary


def main(argv: Optional[List[str]] = None) -> None:
    run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main(sys.argv[1:])

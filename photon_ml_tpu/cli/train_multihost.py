"""Multi-host production training: the supervisor/worker pair behind
`cli/train --multihost N`.

The supervisor (`run_supervisor`) owns no JAX state at all: it spawns one
worker process per host through `parallel/hostmesh.supervise`, classifies
exits, and on a whole-host loss (a worker signal-killed, or a survivor
self-exiting `EXIT_HOST_LOSS` after its heartbeat fired) relaunches the
SURVIVOR set against the durable checkpoint directory — each loss costs
exactly one repeated sweep, never the job. This is the driver-side
analogue of the reference's Spark behavior: an executor loss triggers a
YARN relaunch and lineage recomputes the lost partitions
(RDD.scala:262-290); here the supervisor relaunch + sweep-boundary
checkpoint resume replay exactly one sweep of work.

Each worker (`run_worker`) is one host of the process group: it forms the
global mesh over ICI+DCN (`hostmesh.bringup`), Avro-decodes only ITS
byte-balanced slice of the input files, exchanges decoded row planes so
every host assembles the IDENTICAL global dataset (`exchange_ingest` —
the bitwise-parity keystone), builds the production compute layout
(fixed effects replicated, random effects entity-sharded over the global
mesh), and runs the same `run_coordinate_descent` loop the single-host
estimator uses — with `MultihostCheckpoint` substituting per-host shard
writes behind a cross-host commit barrier.

Scope: the multi-host mode deliberately supports the production fit path
only. Anything that would need a second scoring pipeline inside the
worker (validation, tuning, warm start, variance, normalization,
non-identity projection, constraints, locked coordinates, reg sweeps) is
refused LOUDLY at worker start — run those single-host, or extend the
worker; never let them silently diverge across hosts.
"""

from __future__ import annotations

import json
import logging
import os
import sys
from typing import Dict, List, Optional

from photon_ml_tpu.cli.config import (
    parse_coordinate_config,
    parse_feature_shard_config,
)
from photon_ml_tpu.data.game_dataset import (
    RandomEffectDataConfig,
    build_random_effect_dataset,
)
from photon_ml_tpu.types import NormalizationType, ProjectorType

logger = logging.getLogger(__name__)

# The one artifact subdir the multi-host fit writes (output-mode BEST; the
# restricted scope has no tuning, so best == the single explicit fit).
_BEST_SUBDIR = "best"
_FIT_SUMMARY = "multihost-fit-summary.json"


# ---------------------------------------------------------------- supervisor


def run_supervisor(args, argv: List[str]) -> Dict[str, object]:
    """`cli/train --multihost N`: spawn N workers, absorb whole-host
    losses, and assemble the final training summary from the surviving
    host 0's fit summary plus the relaunch accounting."""
    from photon_ml_tpu.parallel import hostmesh
    from photon_ml_tpu.utils import telemetry

    _validate_scope(args)
    out_root = args.root_output_directory
    models_root = os.path.join(out_root, "models")
    if os.path.exists(models_root):
        if not args.override_output_directory:
            raise FileExistsError(
                f"{models_root} exists; pass --override-output-directory "
                "to replace"
            )
        import shutil

        shutil.rmtree(models_root)
    os.makedirs(out_root, exist_ok=True)
    rendezvous = os.path.join(out_root, "rendezvous")
    if os.path.exists(rendezvous):
        # Rendezvous state (barriers, heartbeats, exchanged row planes) is
        # strictly per-run; stale markers from a prior run must never
        # satisfy this run's barriers. Only the checkpoint dir is durable.
        import shutil

        shutil.rmtree(rendezvous)

    # The supervisor's journal records the loss/relaunch lifecycle; each
    # worker keeps its own journal under hosts/ (one RunJournal per
    # process — the journal file is truncate-on-open and process-locked).
    journal = telemetry.RunJournal(os.path.join(out_root, "journal.jsonl"))
    journal_owned = telemetry.current_journal() is None
    if journal_owned:
        telemetry.install_journal(journal)

    def build_argv(
        attempt: int, coordinator: str, hosts: int, host_id: int
    ) -> List[str]:
        return [
            sys.executable,
            "-m",
            "photon_ml_tpu.cli.train",
            *argv,
            "--mh-worker",
            "--mh-attempt", str(attempt),
            "--mh-coordinator", coordinator,
            "--mh-num-hosts", str(hosts),
            "--mh-host-id", str(host_id),
            "--mh-rendezvous",
            os.path.join(rendezvous, f"attempt{attempt}"),
        ]

    try:
        res = hostmesh.supervise(
            build_argv,
            num_hosts=args.multihost,
            devices_per_host=args.multihost_devices_per_host,
            rendezvous=rendezvous,
            # The scan-group cache device_puts host arrays, which cannot
            # cross processes; the per-bucket loop is bitwise-identical
            # (certified by tests/test_sweep_scan.py), so workers pin it
            # off. Part of worker_env's contract, not a user choice.
            env_extra={"PHOTON_SWEEP_SCAN": "0"},
        )
    finally:
        if journal_owned:
            telemetry.uninstall_journal()
        journal.close()

    fit_summary: Dict[str, object] = {}
    fit_path = os.path.join(out_root, _FIT_SUMMARY)
    try:
        with open(fit_path) as f:
            fit_summary = json.load(f)
    except OSError:
        raise RuntimeError(
            f"multi-host fit reported success but {fit_path} is missing — "
            "host 0 died after the fit-complete barrier?"
        ) from None
    summary: Dict[str, object] = dict(fit_summary)
    summary["multihost"] = {
        "num_hosts": int(args.multihost),
        "devices_per_host": int(args.multihost_devices_per_host),
        "attempts": res.attempts,
        "host_losses": res.host_losses,
        # Sweep-boundary resume: each relaunch replays exactly the one
        # uncommitted sweep, so losses == repeated sweeps.
        "repeated_sweeps": res.host_losses,
        "final_hosts": res.final_hosts,
    }
    with open(os.path.join(out_root, "training-summary.json"), "w") as f:
        json.dump(summary, f, indent=2, default=str)
    logger.info(
        "multi-host training complete: %d host(s), %d attempt(s), "
        "%d host loss(es)",
        res.final_hosts,
        res.attempts,
        res.host_losses,
    )
    return summary


def _validate_scope(args) -> None:
    """Refuse everything outside the supported multi-host fit scope —
    loudly, before any process spawns. Every branch here is a feature
    that would need its own cross-host design (scoring pipeline inside
    the worker, per-host validation exchange, ...); silently running it
    host-local would fit N divergent models."""
    refusals = []
    if not args.checkpoint_directory:
        refusals.append(
            "--checkpoint-directory is required (host-loss recovery "
            "resumes from the last committed sweep)"
        )
    if not getattr(args, "offheap_indexmap_dir", None):
        refusals.append(
            "--offheap-indexmap-dir is required (feature ids must agree "
            "across hosts; build one with cli/build_index.py)"
        )
    if args.validation_data_directories:
        refusals.append("validation data is single-host only")
    if args.validation_evaluators:
        refusals.append("validation evaluators are single-host only")
    if str(getattr(args, "hyper_parameter_tuning", "NONE")).upper().find(
        "NONE"
    ) < 0:
        refusals.append("hyperparameter tuning is single-host only")
    if str(getattr(args, "variance_computation_type", "NONE")).upper().find(
        "NONE"
    ) < 0:
        refusals.append("coefficient variances are single-host only")
    if args.normalization != NormalizationType.NONE:
        refusals.append("normalization is single-host only")
    if getattr(args, "model_input_directory", None):
        refusals.append("warm start is single-host only")
    if getattr(args, "partial_retrain_locked_coordinates", None):
        refusals.append("partial retrain is single-host only")
    for s in args.coordinate_configurations:
        cfg = parse_coordinate_config(s)
        if cfg.constraint_file:
            refusals.append(
                f"coordinate {cfg.name!r}: constraints are single-host only"
            )
        if len(set(cfg.reg_weights)) > 1:
            refusals.append(
                f"coordinate {cfg.name!r}: reg-weight sweeps are "
                "single-host only (sweeps need validation)"
            )
        dc = cfg.data_config
        if (
            isinstance(dc, RandomEffectDataConfig)
            and dc.projector_type != ProjectorType.IDENTITY
        ):
            refusals.append(
                f"coordinate {cfg.name!r}: only projector=IDENTITY is "
                "supported multi-host (projected shards are built after "
                "the global replication step)"
            )
    if refusals:
        raise ValueError(
            "--multihost: unsupported options:\n  - " + "\n  - ".join(refusals)
        )


# -------------------------------------------------------------------- worker


def run_worker(args) -> int:
    """One host of the process group. Returns the process exit code:
    0 on success, `EXIT_HOST_LOSS` when a peer loss was detected (the
    supervisor relaunches the survivors), 1 on a real error."""
    from photon_ml_tpu.parallel import hostmesh
    from photon_ml_tpu.utils import telemetry
    from photon_ml_tpu.utils.faults import HostLoss

    logging.basicConfig(
        level=getattr(logging, args.logging_level.upper(), logging.INFO),
        format=f"%(asctime)s h{args.mh_host_id} %(name)s %(levelname)s "
        "%(message)s",
    )
    _validate_scope(args)

    out_root = args.root_output_directory
    host_dir = os.path.join(
        out_root, "hosts", f"attempt{args.mh_attempt}-host{args.mh_host_id}"
    )
    os.makedirs(host_dir, exist_ok=True)
    # PID file first: chaos drills need a target to SIGKILL before the
    # (slow) process-group bring-up completes.
    with open(os.path.join(host_dir, "pid"), "w") as f:
        f.write(str(os.getpid()))

    journal = telemetry.RunJournal(os.path.join(host_dir, "journal.jsonl"))
    telemetry.install_journal(journal)

    def escalate(loss: HostLoss) -> None:
        # The heartbeat thread declared a peer (or an injected self) lost.
        # The journal line is already written by _declare_loss; flush it
        # and die with the typed exit code — collectives over a mesh with
        # a dead member would otherwise hang until the runtime timeout.
        try:
            telemetry.uninstall_journal()
            journal.close()
        finally:
            os._exit(hostmesh.EXIT_HOST_LOSS)

    heartbeat = None
    try:
        hm = hostmesh.bringup(
            args.mh_coordinator,
            args.mh_num_hosts,
            args.mh_host_id,
            args.multihost_devices_per_host,
            args.mh_rendezvous,
        )
        heartbeat = hostmesh.HostHeartbeat(hm, escalate).start()
        _fit(args, hm)
        return 0
    except HostLoss as loss:
        # Losses surfacing OUTSIDE the heartbeat thread (barrier timeout,
        # MultihostCheckpoint commit backstop): journal and escalate the
        # same way.
        telemetry.METRICS.increment("host_losses")
        telemetry.emit_event(
            "host_loss",
            host=-1,
            missed_beats=0,
            num_hosts=args.mh_num_hosts,
            source="barrier",
        )
        logger.error("host loss: %s", loss)
        telemetry.uninstall_journal()
        journal.close()
        return hostmesh.EXIT_HOST_LOSS
    except Exception:
        logger.exception("multi-host worker failed")
        telemetry.uninstall_journal()
        journal.close()
        return 1
    finally:
        if heartbeat is not None:
            heartbeat.stop()
        if telemetry.current_journal() is journal:
            telemetry.uninstall_journal()
            journal.close()


def _resolve_files(args, shard_configs) -> List[str]:
    """The global input FILE list (sorted): every host computes the same
    list, `hostmesh.partition_files` hands each its byte-balanced slice."""
    from photon_ml_tpu.io.avro import list_container_files
    from photon_ml_tpu.utils.date_range import paths_for_date_range, resolve_range

    train_range = resolve_range(
        getattr(args, "input_data_date_range", None),
        getattr(args, "input_data_days_range", None),
    )
    paths = paths_for_date_range(args.input_data_directories, train_range)
    files: List[str] = []
    for p in paths:
        files.extend(list_container_files(p))
    return sorted(files)


def _fit(args, hm) -> None:
    """The worker fit path: disjoint ingest + exchange, global compute
    layout, checkpointed coordinate descent, host-0 artifact save."""
    from photon_ml_tpu.game.coordinate import (
        FixedEffectCoordinate,
        RandomEffectCoordinate,
    )
    from photon_ml_tpu.game.coordinate_descent import run_coordinate_descent
    from photon_ml_tpu.game.model import GameModel
    from photon_ml_tpu.game.projector import project_shard
    from photon_ml_tpu.io import avro_data, model_bridge, model_store
    from photon_ml_tpu.io.paldb import resolve_offheap_index_maps
    from photon_ml_tpu.parallel import hostmesh
    from photon_ml_tpu.transformers.game_transformer import CoordinateScoringSpec
    from photon_ml_tpu.utils import telemetry

    coordinate_configs = {}
    for s in args.coordinate_configurations:
        cfg = parse_coordinate_config(s)
        coordinate_configs[cfg.name] = cfg
    update_sequence = (
        [c.strip() for c in args.coordinate_update_sequence.split(",")]
        if args.coordinate_update_sequence
        else list(coordinate_configs.keys())
    )
    shard_configs = dict(
        parse_feature_shard_config(s) for s in args.feature_shard_configurations
    )
    id_tags = [
        c.data_config.random_effect_type
        for c in coordinate_configs.values()
        if isinstance(c.data_config, RandomEffectDataConfig)
    ]
    index_maps = resolve_offheap_index_maps(
        args.offheap_indexmap_dir, shard_configs
    )
    columns = (
        avro_data.InputColumnNames.parse(args.input_column_names)
        if getattr(args, "input_column_names", None)
        else None
    )

    files = _resolve_files(args, shard_configs)
    dataset, mine = hostmesh.exchange_ingest(
        hm,
        files,
        shard_configs,
        index_maps=index_maps,
        id_tag_fields=id_tags,
        columns=columns,
    )
    logger.info(
        "host %d ingested %d/%d files; global dataset: %d samples",
        hm.host_id,
        len(mine),
        len(files),
        dataset.num_samples,
    )

    # Global compute layout: FE columns replicated (every device runs the
    # identical full solve — bitwise by construction), RE entity stores
    # sharded over the global mesh (where capacity scaling lives).
    ds_rep = hostmesh.replicate_dataset_global(dataset, hm)
    coords: Dict[str, object] = {}
    specs: Dict[str, CoordinateScoringSpec] = {}
    opt_configs: Dict[str, dict] = {}
    for cid in update_sequence:
        cfg = coordinate_configs[cid]
        oc = cfg.expand()[0]  # single reg weight (sweeps refused)
        dc = cfg.data_config
        if isinstance(dc, RandomEffectDataConfig):
            red = build_random_effect_dataset(dataset, dc)
            ps = project_shard(
                dataset,
                red,
                dc.projector_type,
                projected_dim=dc.projected_dim,
                seed=args.random_seed,
            )
            red_g = hostmesh.shard_random_effect_global(red, hm)
            coords[cid] = RandomEffectCoordinate(
                ds_rep, red_g, oc, args.training_task, None
            )
            specs[cid] = CoordinateScoringSpec(
                shard=dc.feature_shard,
                norm=None,
                random_effect_type=dc.random_effect_type,
                entity_index=red.entity_index,
                projector=ps.projector,
            )
        else:
            coords[cid] = FixedEffectCoordinate(
                ds_rep, dc.feature_shard, oc, args.training_task, None
            )
            specs[cid] = CoordinateScoringSpec(shard=dc.feature_shard, norm=None)
        opt_configs[cid] = {
            "optimizer": oc.optimizer.optimizer_type.value,
            "max_iterations": oc.optimizer.max_iterations,
            "tolerance": oc.optimizer.tolerance,
            "regularization": oc.regularization.reg_type.value,
            "reg_weight": oc.reg_weight,
        }

    result = run_coordinate_descent(
        coords,
        args.coordinate_descent_iterations,
        seed=args.random_seed,
        checkpoint_dir=args.checkpoint_directory,
        checkpoint_factory=lambda d: hostmesh.MultihostCheckpoint(
            d, hm, attempt=args.mh_attempt
        ),
        # In-process mesh shrink cannot help when the lost devices belong
        # to a dead PROCESS — escalate immediately; the supervisor
        # relaunches the survivor set against the durable checkpoint.
        max_mesh_losses=0,
    )
    hm.barrier("fit-complete")

    if hm.host_id == 0:
        # Reassemble the final host-side models from the checkpoint (its
        # shard files are the durable any-shape layout) rather than
        # pulling device arrays: entity-sharded matrices are only
        # partially addressable from any one process.
        st = hostmesh.MultihostCheckpoint(
            args.checkpoint_directory, hm, attempt=args.mh_attempt
        ).load(args.training_task)
        model = GameModel(dict(st.models))
        artifact = model_bridge.artifact_from_game_model(
            model, specs, args.training_task, opt_configs=opt_configs
        )
        mdir = os.path.join(
            args.root_output_directory, "models", _BEST_SUBDIR
        )
        model_store.save_game_model(
            mdir,
            artifact,
            index_maps,
            sparsity_threshold=args.model_sparsity_threshold,
        )
        idx_dir = os.path.join(mdir, "feature-indexes")
        os.makedirs(idx_dir, exist_ok=True)
        for shard, imap in index_maps.items():
            imap.save(os.path.join(idx_dir, f"{shard}.json"))
        summary = {
            "num_samples": int(dataset.num_samples),
            "num_files": len(files),
            "files_this_host": len(mine),
            "completed_steps": int(
                args.coordinate_descent_iterations * len(coords)
            ),
            "coordinates": list(coords),
            "timings_s": {
                name: round(total, 3)
                for name, total in result.timing.items()
            },
            "counters": telemetry.METRICS.counters(),
        }
        tmp = os.path.join(args.root_output_directory, _FIT_SUMMARY + ".tmp")
        with open(tmp, "w") as f:
            json.dump(summary, f, indent=2, default=str)
        os.replace(
            tmp, os.path.join(args.root_output_directory, _FIT_SUMMARY)
        )
    # Peers hold the process group open until host 0's artifact save is
    # durable: a peer exiting early can tear down the distributed runtime
    # under host 0 (the coordinator service dies with process 0's peers'
    # connections erroring out).
    hm.barrier("artifact-saved")

"""photon-obs: inspect the telemetry artifacts a run persists (ISSUE 11).

Three subcommands over the three file artifacts of utils/telemetry.py:

  * `trace <trace.json>` — summarize a Chrome trace-event export (span
    count, per-thread tracks, wall coverage). `--min-coverage P` exits
    nonzero when the span union covers less than P% of the traced wall —
    the acceptance gate for "spans cover the run".
  * `journal <journal.jsonl>` — event counts by type; `--validate`
    re-checks every line against its contracts.JOURNAL_EVENT_SCHEMAS
    schema and exits nonzero on any invalid line.
  * `profile <profile.json>` — pretty-print a run profile read through
    the loud `read_profile` contract (stage table, dispatch decisions,
    topology, roofline).

Load the trace itself in Perfetto (https://ui.perfetto.dev) or
chrome://tracing; this CLI is the headless companion.

Usage: python -m photon_ml_tpu.cli.obs --help
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional, Tuple

from photon_ml_tpu.utils import telemetry


def _interval_union_us(spans: List[Tuple[float, float]]) -> float:
    """Total microseconds covered by the union of [start, end) intervals."""
    total = 0.0
    end = None
    for s, e in sorted(spans):
        if end is None or s > end:
            total += e - s
            end = e
        elif e > end:
            total += e - end
            end = e
    return total


def cmd_trace(args) -> int:
    with open(args.path) as f:
        doc = json.load(f)
    events = [e for e in doc.get("traceEvents", []) if e.get("ph") == "X"]
    threads = {
        e["tid"]: e["args"]["name"]
        for e in doc.get("traceEvents", [])
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    }
    if not events:
        print("no spans recorded (was PHOTON_TRACE=1 set?)")
        return 1
    intervals = [(e["ts"], e["ts"] + e.get("dur", 0.0)) for e in events]
    t0 = min(s for s, _ in intervals)
    t1 = max(e for _, e in intervals)
    wall_us = max(t1 - t0, 1e-9)
    covered = _interval_union_us(intervals)
    coverage = 100.0 * covered / wall_us
    by_thread: dict = {}
    for e in events:
        by_thread.setdefault(e["tid"], []).append(e)
    print(f"trace: {len(events)} span(s), {len(by_thread)} thread track(s), "
          f"{wall_us / 1e6:.3f}s traced wall")
    print(f"span coverage of traced wall: {coverage:.1f}%")
    for tid, evs in sorted(by_thread.items(), key=lambda kv: -len(kv[1])):
        name = threads.get(tid, str(tid))
        top = max(evs, key=lambda e: e.get("dur", 0.0))
        print(
            f"  {name:32s} {len(evs):6d} span(s)  "
            f"longest: {top['name']} ({top.get('dur', 0.0) / 1e3:.1f} ms)"
        )
    span_ids = {e["args"].get("span_id") for e in events}
    orphans = [
        e
        for e in events
        if e["args"].get("parent_id") is not None
        and e["args"]["parent_id"] not in span_ids
    ]
    if orphans:
        print(f"WARNING: {len(orphans)} span(s) reference a missing parent")
    if args.min_coverage is not None and coverage < args.min_coverage:
        print(
            f"FAIL: coverage {coverage:.1f}% < required {args.min_coverage}%"
        )
        return 1
    return 0


def cmd_journal(args) -> int:
    n_ok, errors = telemetry.validate_journal(args.path)
    counts: dict = {}
    with open(args.path) as f:
        for raw in f:
            raw = raw.strip()
            if not raw:
                continue
            try:
                etype = json.loads(raw).get("type")
            except ValueError:
                etype = "<unparseable>"
            counts[etype] = counts.get(etype, 0) + 1
    total = sum(counts.values())
    print(f"journal: {total} line(s), {n_ok} valid, {len(errors)} invalid")
    for etype in sorted(counts, key=counts.get, reverse=True):
        print(f"  {etype:24s} {counts[etype]}")
    for err in errors[:20]:
        print(f"  INVALID: {err}")
    if args.validate and errors:
        return 1
    return 0


def cmd_profile(args) -> int:
    profile = telemetry.read_profile(args.path)  # loud missing-key contract
    topo = profile["device_topology"]
    print(
        f"{profile['kind']} profile: {profile['wall_s']}s wall on "
        f"{topo['device_count']}x {topo['platform']} "
        f"({topo.get('device_kind', '?')})"
    )
    roof = profile["roofline"].get("hbm_gb_per_s")
    if roof:
        print(f"  HBM roofline: {roof} GB/s")
    print("  stages:")
    stages = profile["stages"]
    width = max((len(k) for k in stages), default=0)
    for k in sorted(stages, key=lambda k: -float(stages[k] or 0)):
        print(f"    {k.ljust(width)}  {float(stages[k]):10.3f}s")
    print("  dispatch decisions:")
    for k, v in sorted(profile["dispatch"].items()):
        print(f"    {k}: {json.dumps(v, default=str)}")
    shapes = profile["bucket_shapes"]
    if shapes:
        print("  bucket shapes:")
        for k, v in sorted(shapes.items()):
            print(f"    {k}: {json.dumps(v)[:120]}")
    counters = (profile.get("metrics") or {}).get("counters") or {}
    nonzero = {k: v for k, v in counters.items() if v}
    print(f"  nonzero counters: {json.dumps(nonzero) if nonzero else '(none)'}")
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m photon_ml_tpu.cli.obs",
        description="Inspect photon-trace telemetry artifacts "
        "(trace.json / journal.jsonl / profile.json)",
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    t = sub.add_parser("trace", help="summarize a Chrome trace export")
    t.add_argument("path")
    t.add_argument(
        "--min-coverage",
        type=float,
        default=None,
        help="exit 1 when span union covers less than this %% of the "
        "traced wall",
    )
    j = sub.add_parser("journal", help="summarize/validate a run journal")
    j.add_argument("path")
    j.add_argument(
        "--validate",
        action="store_true",
        help="exit 1 when any line fails its schema",
    )
    pr = sub.add_parser("profile", help="pretty-print a run profile")
    pr.add_argument("path")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.cmd == "trace":
        return cmd_trace(args)
    if args.cmd == "journal":
        return cmd_journal(args)
    return cmd_profile(args)


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))

"""photon-obs: inspect the telemetry artifacts a run persists (ISSUE 11).

Three subcommands over the three file artifacts of utils/telemetry.py:

  * `trace <trace.json>` — summarize a Chrome trace-event export (span
    count, per-thread tracks, wall coverage). `--min-coverage P` exits
    nonzero when the span union covers less than P% of the traced wall —
    the acceptance gate for "spans cover the run".
  * `journal <journal.jsonl>` — event counts by type; `--validate`
    re-checks every line against its contracts.JOURNAL_EVENT_SCHEMAS
    schema and exits nonzero on any invalid line.
  * `profile <profile.json>` — pretty-print a run profile read through
    the loud `read_profile` contract (stage table, dispatch decisions,
    topology, roofline).
  * `decisions <journal.jsonl>` — the control-plane timeline (ISSUE 19):
    every `plan_decision`, `autopilot_decision`, and `shadow_verdict`
    event in emit order, with the evidence each decision carried and
    its outcome, plus the autopilot's rollback/quarantine annotations.
    Exits nonzero when ANY journal line is schema-invalid — an operator
    auditing the controller must not read a corrupt journal as clean.
  * `profile diff <a> <b>` — typed key-wise comparison of two run
    profiles: per-stage wall deltas, dispatch-decision changes,
    plan-block decision changes (added/removed/value- or source-
    changed), and topology changes. The operator tool for "what did the
    planner change between rounds". Exits nonzero when either profile
    violates its contract (read_profile refusal) or the kinds differ.

Load the trace itself in Perfetto (https://ui.perfetto.dev) or
chrome://tracing; this CLI is the headless companion.

Usage: python -m photon_ml_tpu.cli.obs --help
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional, Tuple

from photon_ml_tpu.utils import telemetry


def _interval_union_us(spans: List[Tuple[float, float]]) -> float:
    """Total microseconds covered by the union of [start, end) intervals."""
    total = 0.0
    end = None
    for s, e in sorted(spans):
        if end is None or s > end:
            total += e - s
            end = e
        elif e > end:
            total += e - end
            end = e
    return total


def cmd_trace(args) -> int:
    with open(args.path) as f:
        doc = json.load(f)
    events = [e for e in doc.get("traceEvents", []) if e.get("ph") == "X"]
    threads = {
        e["tid"]: e["args"]["name"]
        for e in doc.get("traceEvents", [])
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    }
    if not events:
        print("no spans recorded (was PHOTON_TRACE=1 set?)")
        return 1
    intervals = [(e["ts"], e["ts"] + e.get("dur", 0.0)) for e in events]
    t0 = min(s for s, _ in intervals)
    t1 = max(e for _, e in intervals)
    wall_us = max(t1 - t0, 1e-9)
    covered = _interval_union_us(intervals)
    coverage = 100.0 * covered / wall_us
    by_thread: dict = {}
    for e in events:
        by_thread.setdefault(e["tid"], []).append(e)
    print(f"trace: {len(events)} span(s), {len(by_thread)} thread track(s), "
          f"{wall_us / 1e6:.3f}s traced wall")
    print(f"span coverage of traced wall: {coverage:.1f}%")
    for tid, evs in sorted(by_thread.items(), key=lambda kv: -len(kv[1])):
        name = threads.get(tid, str(tid))
        top = max(evs, key=lambda e: e.get("dur", 0.0))
        print(
            f"  {name:32s} {len(evs):6d} span(s)  "
            f"longest: {top['name']} ({top.get('dur', 0.0) / 1e3:.1f} ms)"
        )
    span_ids = {e["args"].get("span_id") for e in events}
    orphans = [
        e
        for e in events
        if e["args"].get("parent_id") is not None
        and e["args"]["parent_id"] not in span_ids
    ]
    if orphans:
        print(f"WARNING: {len(orphans)} span(s) reference a missing parent")
    if args.min_coverage is not None and coverage < args.min_coverage:
        print(
            f"FAIL: coverage {coverage:.1f}% < required {args.min_coverage}%"
        )
        return 1
    return 0


def cmd_journal(args) -> int:
    n_ok, errors = telemetry.validate_journal(args.path)
    counts: dict = {}
    with open(args.path) as f:
        for raw in f:
            raw = raw.strip()
            if not raw:
                continue
            try:
                etype = json.loads(raw).get("type")
            except ValueError:
                etype = "<unparseable>"
            counts[etype] = counts.get(etype, 0) + 1
    total = sum(counts.values())
    print(f"journal: {total} line(s), {n_ok} valid, {len(errors)} invalid")
    for etype in sorted(counts, key=counts.get, reverse=True):
        print(f"  {etype:24s} {counts[etype]}")
    for err in errors[:20]:
        print(f"  INVALID: {err}")
    if args.validate and errors:
        return 1
    return 0


# Event types rendered as first-class timeline rows; the autopilot's
# rollback/quarantine events ride along as indented annotations so the
# operator sees WHY a rule went quiet right under the decision stream.
_DECISION_TYPES = (
    "plan_decision",
    "autopilot_decision",
    "shadow_verdict",
    # Precision-ladder transitions (ISSUE 20): every quantize/restore
    # step is a first-class, auditable control-plane decision.
    "tier_demote",
    "tier_restore",
)
_ANNOTATION_TYPES = ("autopilot_rollback", "rule_quarantined")


def _fmt_evidence(ev) -> str:
    if not ev:
        return ""
    if isinstance(ev, dict):
        parts = []
        for k in sorted(ev):
            v = ev[k]
            if isinstance(v, float):
                parts.append(f"{k}={v:.4g}")
            else:
                parts.append(f"{k}={json.dumps(v, default=str)}")
        return " ".join(parts)
    return json.dumps(ev, default=str)


def cmd_decisions(args) -> int:
    n_ok, errors = telemetry.validate_journal(args.path)
    rows: List[dict] = []
    with open(args.path) as f:
        for raw in f:
            raw = raw.strip()
            if not raw:
                continue
            try:
                doc = json.loads(raw)
            except ValueError:
                continue  # already reported by validate_journal
            if doc.get("type") in _DECISION_TYPES + _ANNOTATION_TYPES:
                rows.append(doc)
    counts: dict = {}
    for doc in rows:
        counts[doc["type"]] = counts.get(doc["type"], 0) + 1
    print(
        f"decisions: {len(rows)} control-plane event(s) "
        f"({', '.join(f'{counts[t]} {t}' for t in sorted(counts)) or 'none'})"
    )
    t0 = rows[0].get("ts", 0.0) if rows else 0.0
    for doc in rows:
        try:
            dt = float(doc.get("ts", t0)) - float(t0)
        except (TypeError, ValueError):
            dt = 0.0
        etype = doc["type"]
        if etype == "plan_decision":
            line = (
                f"plan      {doc.get('decision')} = "
                f"{json.dumps(doc.get('value'), default=str)} "
                f"[{doc.get('source')}] "
                f"(fallback {json.dumps(doc.get('fallback'), default=str)})"
            )
        elif etype == "autopilot_decision":
            action = doc.get("action") or {}
            what = (
                f"{action.get('kind')}"
                + (f" tenant={action.get('tenant')}" if action.get("tenant") else "")
                if isinstance(action, dict)
                else "(no action)"
            )
            line = (
                f"autopilot {doc.get('rule')}: {what} -> {doc.get('outcome')}"
            )
            ev = _fmt_evidence(doc.get("evidence"))
            if ev:
                line += f"  | {ev}"
        elif etype == "shadow_verdict":
            line = (
                f"shadow    {doc.get('challenger')} vs "
                f"{doc.get('champion')}: {doc.get('decision')} "
                f"after {doc.get('windows')} window(s) "
                f"({doc.get('evaluator')}: "
                f"{doc.get('challenger_metric')} vs "
                f"{doc.get('champion_metric')}) — {doc.get('reason')}"
            )
        elif etype in ("tier_demote", "tier_restore"):
            arrow = "v" if etype == "tier_demote" else "^"
            bytes_key = (
                "freed_bytes" if etype == "tier_demote" else "repinned_bytes"
            )
            line = (
                f"tier {arrow}    tenant={doc.get('tenant')} "
                f"{doc.get('from_tier')} -> {doc.get('to_tier')} "
                f"[{doc.get('reason')}] "
                f"({bytes_key}={doc.get(bytes_key)})"
            )
            ev = _fmt_evidence(doc.get("evidence"))
            if ev:
                line += f"  | {ev}"
        elif etype == "autopilot_rollback":
            action = doc.get("action") or {}
            kind = action.get("kind") if isinstance(action, dict) else action
            line = (
                f"  ROLLBACK  {doc.get('rule')} ({kind}): "
                f"{doc.get('reason')}"
            )
        else:  # rule_quarantined
            line = (
                f"  QUARANTINE {doc.get('rule')} after "
                f"{doc.get('rollbacks')} rollback(s): {doc.get('reason')}"
            )
        print(f"  +{dt:9.3f}s  {line}")
    if errors:
        print(f"{len(errors)} schema-invalid journal line(s):")
        for err in errors[:20]:
            print(f"  INVALID: {err}")
        return 1
    return 0


def cmd_profile(args) -> int:
    profile = telemetry.read_profile(args.path)  # loud missing-key contract
    topo = profile["device_topology"]
    print(
        f"{profile['kind']} profile: {profile['wall_s']}s wall on "
        f"{topo['device_count']}x {topo['platform']} "
        f"({topo.get('device_kind', '?')})"
    )
    roof = profile["roofline"].get("hbm_gb_per_s")
    if roof:
        print(f"  HBM roofline: {roof} GB/s")
    print("  stages:")
    stages = profile["stages"]
    width = max((len(k) for k in stages), default=0)
    for k in sorted(stages, key=lambda k: -float(stages[k] or 0)):
        print(f"    {k.ljust(width)}  {float(stages[k]):10.3f}s")
    print("  dispatch decisions:")
    for k, v in sorted(profile["dispatch"].items()):
        print(f"    {k}: {json.dumps(v, default=str)}")
    shapes = profile["bucket_shapes"]
    if shapes:
        print("  bucket shapes:")
        for k, v in sorted(shapes.items()):
            print(f"    {k}: {json.dumps(v)[:120]}")
    counters = (profile.get("metrics") or {}).get("counters") or {}
    nonzero = {k: v for k, v in counters.items() if v}
    print(f"  nonzero counters: {json.dumps(nonzero) if nonzero else '(none)'}")
    return 0


def _plan_decisions(profile: dict) -> dict:
    """decision name -> (value, source) from a profile's plan block;
    empty for unplanned / pre-planner (r06-era) profiles."""
    block = profile.get("plan") or {}
    return {
        d["decision"]: (d.get("value"), d.get("source"))
        for d in block.get("decisions", [])
        if isinstance(d, dict) and "decision" in d
    }


def cmd_profile_diff(path_a: str, path_b: str) -> int:
    """Typed key-wise diff of two run profiles (see module doc). Returns
    nonzero on contract violations — a profile that cannot be read
    loudly must fail the operator's comparison, not silently skip."""
    try:
        a = telemetry.read_profile(path_a)
        b = telemetry.read_profile(path_b)
    except (ValueError, OSError) as exc:
        print(f"CONTRACT VIOLATION: {exc}")
        return 1
    if a.get("kind") != b.get("kind"):
        print(
            f"CONTRACT VIOLATION: profile kinds differ "
            f"({a.get('kind')!r} vs {b.get('kind')!r}) — comparing a fit "
            "profile to a serve profile is not a round-over-round diff"
        )
        return 1
    print(
        f"{a['kind']} profiles: {path_a} ({a['wall_s']}s) vs "
        f"{path_b} ({b['wall_s']}s)"
    )

    # -- topology (a mismatch here means the diff crosses hardware)
    topo_a, topo_b = a["device_topology"], b["device_topology"]
    topo_changed = {
        k: (topo_a.get(k), topo_b.get(k))
        for k in sorted({*topo_a, *topo_b})
        if topo_a.get(k) != topo_b.get(k)
    }
    if topo_changed:
        print("  topology changes:")
        for k, (va, vb) in topo_changed.items():
            print(f"    {k}: {va!r} -> {vb!r}")

    # -- stage walls (typed: every key of either side, delta annotated)
    st_a, st_b = a["stages"], b["stages"]
    keys = sorted({*st_a, *st_b})
    width = max((len(k) for k in keys), default=0)
    print("  stage deltas (a -> b):")
    for k in keys:
        va = float(st_a.get(k) or 0.0)
        vb = float(st_b.get(k) or 0.0)
        mark = "" if abs(vb - va) < 1e-4 else f"  ({vb - va:+.3f}s)"
        print(f"    {k.ljust(width)}  {va:10.3f}s -> {vb:10.3f}s{mark}")

    # -- dispatch decisions (the runtime choices each run took)
    d_a, d_b = a["dispatch"], b["dispatch"]
    changed = [
        k for k in sorted({*d_a, *d_b}) if d_a.get(k) != d_b.get(k)
    ]
    if changed:
        print("  dispatch-decision changes:")
        for k in changed:
            print(
                f"    {k}: {json.dumps(d_a.get(k), default=str)} -> "
                f"{json.dumps(d_b.get(k), default=str)}"
            )
    else:
        print("  dispatch decisions: identical")

    # -- plan blocks (what the planner chose, round over round)
    plan_a, plan_b = _plan_decisions(a), _plan_decisions(b)
    added = sorted(set(plan_b) - set(plan_a))
    removed = sorted(set(plan_a) - set(plan_b))
    altered = sorted(
        k for k in set(plan_a) & set(plan_b) if plan_a[k] != plan_b[k]
    )
    if not (plan_a or plan_b):
        print("  plan blocks: none on either side (unplanned runs)")
    elif not (added or removed or altered):
        print(f"  plan decisions: identical ({len(plan_b)})")
    else:
        print("  plan-block changes:")
        for k in added:
            v, s = plan_b[k]
            print(f"    + {k} = {json.dumps(v, default=str)} [{s}]")
        for k in removed:
            v, s = plan_a[k]
            print(f"    - {k} (was {json.dumps(v, default=str)} [{s}])")
        for k in altered:
            va, sa = plan_a[k]
            vb, sb = plan_b[k]
            print(
                f"    ~ {k}: {json.dumps(va, default=str)} [{sa}] -> "
                f"{json.dumps(vb, default=str)} [{sb}]"
            )
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m photon_ml_tpu.cli.obs",
        description="Inspect photon-trace telemetry artifacts "
        "(trace.json / journal.jsonl / profile.json)",
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    t = sub.add_parser("trace", help="summarize a Chrome trace export")
    t.add_argument("path")
    t.add_argument(
        "--min-coverage",
        type=float,
        default=None,
        help="exit 1 when span union covers less than this %% of the "
        "traced wall",
    )
    j = sub.add_parser("journal", help="summarize/validate a run journal")
    j.add_argument("path")
    j.add_argument(
        "--validate",
        action="store_true",
        help="exit 1 when any line fails its schema",
    )
    d = sub.add_parser(
        "decisions",
        help="control-plane timeline: plan / autopilot / shadow decisions "
        "with evidence and outcome (exits 1 on schema-invalid lines)",
    )
    d.add_argument("path")
    pr = sub.add_parser(
        "profile",
        help="pretty-print a run profile, or `profile diff <a> <b>`",
    )
    pr.add_argument(
        "paths",
        nargs="+",
        metavar="ARG",
        help="<profile.json>  |  diff <a.json> <b.json>",
    )
    return p


def main(argv: Optional[List[str]] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.cmd == "trace":
        return cmd_trace(args)
    if args.cmd == "journal":
        return cmd_journal(args)
    if args.cmd == "decisions":
        return cmd_decisions(args)
    if args.paths[0] == "diff":
        if len(args.paths) != 3:
            parser.error("profile diff takes exactly two profile paths")
        return cmd_profile_diff(args.paths[1], args.paths[2])
    if len(args.paths) != 1:
        parser.error("profile takes one path (or: profile diff <a> <b>)")
    args.path = args.paths[0]
    return cmd_profile(args)


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))

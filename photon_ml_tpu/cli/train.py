"""GAME training driver: the end-to-end train CLI.

Counterpart of photon-client cli/game/training/GameTrainingDriver.scala:55-855
(see SURVEY.md §3.1 for the reference call stack). Pipeline:

    parse args -> read training/validation Avro data -> (warm-start model)
    -> GameEstimator.fit over the expanded reg-weight sweep
    -> optional hyperparameter tuning (RANDOM | BAYESIAN)
    -> model selection -> save models + metadata under the output root.

Output layout mirrors ModelProcessingUtils.saveGameModelToHDFS:
    <root>/models/best/...               (unless output mode NONE)
    <root>/models/explicit-<i>/...       (EXPLICIT | ALL)
    <root>/models/tuned-<i>/...          (TUNED | ALL)
Option names match the reference's scopt surface (kebab-cased Param names,
e.g. --coordinate-configurations with the compound mini-DSL of
ScoptParserHelpers — README.md:283-292 examples parse verbatim).

Usage: python -m photon_ml_tpu.cli.train --help
"""

from __future__ import annotations

import argparse
import enum
import json
import logging
import os
import sys
from typing import Dict, List, Optional

import numpy as np

from photon_ml_tpu.cli.config import (
    CoordinateConfiguration,
    coordinate_config_to_string,
    expand_game_opt_configs,
    feature_shard_config_to_string,
    parse_coordinate_config,
    parse_feature_shard_config,
)
from photon_ml_tpu.data.game_dataset import RandomEffectDataConfig
from photon_ml_tpu.estimators.game_estimator import (
    GameEstimator,
    GameResult,
    select_best_result,
)
from photon_ml_tpu.evaluation.suite import EvaluatorType, better_than
from photon_ml_tpu.hyperparameter.search import HyperparameterConfig
from photon_ml_tpu.hyperparameter.tuner import HyperparameterTuningMode, get_tuner
from photon_ml_tpu.io import avro_data, model_bridge, model_store
from photon_ml_tpu.types import (
    DataValidationType,
    NormalizationType,
    ProjectorType,
    RegularizationType,
    TaskType,
    VarianceComputationType,
)

logger = logging.getLogger("photon_ml_tpu.cli.train")

# Default tuning range for regularization weights (the reference's tuning
# JSON defaults, GameHyperparameterDefaults.scala:20: log-scale weights).
TUNING_REG_WEIGHT_RANGE = (1e-4, 1e4)


class ModelOutputMode(enum.Enum):
    """Reference: io/ModelOutputMode.scala."""

    NONE = "NONE"
    BEST = "BEST"
    EXPLICIT = "EXPLICIT"
    TUNED = "TUNED"
    ALL = "ALL"

    @classmethod
    def parse(cls, name: str) -> "ModelOutputMode":
        return cls[name.strip().upper()]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon_ml_tpu.cli.train",
        description="Train GAME/GLMix models (TPU-native Photon ML)",
    )
    p.add_argument("--training-task", required=True, type=TaskType.parse,
                   help="LINEAR_REGRESSION | LOGISTIC_REGRESSION | POISSON_REGRESSION | "
                        "SMOOTHED_HINGE_LOSS_LINEAR_SVM")
    p.add_argument("--input-data-directories", required=True, nargs="+",
                   help="training data dirs/files (Avro TrainingExample records)")
    p.add_argument("--input-column-names", default=None,
                   help="Rename record fields: 'response=the_label,weight=w,"
                        "offset=o,uid=id,metadataMap=meta' (inputColumnsNames,"
                        " InputColumnsNames.scala:65-73)")
    p.add_argument("--input-data-date-range", default=None,
                   help="Inclusive 'yyyyMMdd-yyyyMMdd' range of daily input "
                        "subdirectories <dir>/yyyy/MM/dd (inputDataDateRange, "
                        "GameDriver.scala:64)")
    p.add_argument("--input-data-days-range", default=None,
                   help="Relative '<start days ago>-<end days ago>' range "
                        "(inputDataDaysRange, GameDriver.scala:69)")
    p.add_argument("--validation-data-date-range", default=None,
                   help="Date range for validation dirs "
                        "(validationDataDateRange, GameTrainingDriver.scala:91)")
    p.add_argument("--validation-data-days-range", default=None,
                   help="Days range for validation dirs "
                        "(validationDataDaysRange, GameTrainingDriver.scala:96)")
    p.add_argument("--validation-data-directories", nargs="*", default=[],
                   help="validation data dirs/files")
    p.add_argument("--root-output-directory", required=True)
    p.add_argument("--override-output-directory", action="store_true",
                   help="overwrite an existing output directory")
    p.add_argument("--feature-shard-configurations", required=True, nargs="+",
                   metavar="DSL",
                   help='e.g. "name=globalShard,feature.bags=features|context,intercept=true"')
    p.add_argument("--coordinate-configurations", required=True, nargs="+",
                   metavar="DSL",
                   help='e.g. "name=global,feature.shard=globalShard,optimizer=LBFGS,'
                        'tolerance=1.0E-6,max.iter=50,regularization=L2,reg.weights=0.1|1|10"')
    p.add_argument("--coordinate-update-sequence", default=None,
                   help="comma-separated coordinate ids (default: config order)")
    p.add_argument("--coordinate-descent-iterations", type=int, default=1)
    p.add_argument("--normalization", type=NormalizationType.parse,
                   default=NormalizationType.NONE)
    p.add_argument("--validation-evaluators", nargs="*", default=[],
                   help="e.g. AUC RMSE PRECISION@5:queryId AUC:documentId")
    p.add_argument("--offheap-indexmap-dir", default=None,
                   help="directory of prebuilt persistent feature-index "
                        "partitions (cli.build_index output; the reference's "
                        "off-heap PalDB index dir, GameDriver.scala:231-236)")
    p.add_argument("--model-input-directory", default=None,
                   help="warm-start / partial-retrain model directory")
    p.add_argument("--partial-retrain-locked-coordinates", default=None,
                   help="comma-separated coordinate ids to lock (reuse from "
                        "--model-input-directory)")
    p.add_argument("--variance-computation-type", type=VarianceComputationType.parse,
                   default=VarianceComputationType.NONE)
    p.add_argument("--data-validation", type=lambda s: DataValidationType[s.strip().upper()],
                   default=DataValidationType.VALIDATE_FULL)
    p.add_argument("--checkpoint-directory", default=None,
                   help="Checkpoint-restart root for the coordinate-descent "
                        "outer loop (SURVEY §5.3): a rerun with identical "
                        "arguments resumes from the last completed "
                        "coordinate update")
    p.add_argument("--data-summary-directory", default=None,
                   help="Write per-feature-shard summary statistics as "
                        "FeatureSummarizationResultAvro under this directory "
                        "(dataSummaryDirectory, GameTrainingDriver.scala:582)")
    p.add_argument("--output-mode", type=ModelOutputMode.parse, default=ModelOutputMode.BEST)
    p.add_argument("--model-sparsity-threshold", type=float, default=0.0)
    p.add_argument("--hyper-parameter-tuning", type=HyperparameterTuningMode.parse,
                   default=HyperparameterTuningMode.NONE)
    p.add_argument("--hyper-parameter-tuning-iter", type=int, default=20)
    p.add_argument("--hyper-parameter-tuning-batch-size", type=int, default=1,
                   help="trials proposed per round (>1: constant-liar qEI for "
                        "BAYESIAN, Sobol batches for RANDOM); evaluations run "
                        "sequentially in this driver but proposals are batched")
    p.add_argument("--random-seed", type=int, default=0)
    p.add_argument("--profile", default=None,
                   help="a persisted run profile (profile.json from a prior "
                        "run) the adaptive planner consumes for layout/"
                        "routing/batching decisions; refuses loudly on a "
                        "mismatched device topology. Overrides "
                        "PHOTON_PLAN_PROFILE; explicit PHOTON_* knobs "
                        "override individual plan decisions")
    p.add_argument("--logging-level", default="INFO")
    p.add_argument("--application-name", default="photon-ml-tpu-training")
    p.add_argument("--multihost", type=int, default=0, metavar="N",
                   help="production multi-host mode: supervise N worker "
                        "processes forming one global mesh over ICI+DCN; "
                        "each host ingests a disjoint file slice, a "
                        "whole-host loss is absorbed by relaunching the "
                        "survivors from the last committed sweep "
                        "(requires --checkpoint-directory and "
                        "--offheap-indexmap-dir; N=1 is the parity "
                        "baseline running the same worker pipeline)")
    p.add_argument("--multihost-devices-per-host", type=int, default=4,
                   metavar="M",
                   help="devices each multi-host worker drives (virtual "
                        "CPU devices under JAX_PLATFORMS=cpu; the global "
                        "mesh has N*M devices)")
    # Internal worker flags, set only by the supervisor's build_argv —
    # never by hand (hidden from --help).
    p.add_argument("--mh-worker", action="store_true", help=argparse.SUPPRESS)
    p.add_argument("--mh-attempt", type=int, default=0, help=argparse.SUPPRESS)
    p.add_argument("--mh-coordinator", default=None, help=argparse.SUPPRESS)
    p.add_argument("--mh-num-hosts", type=int, default=0, help=argparse.SUPPRESS)
    p.add_argument("--mh-host-id", type=int, default=0, help=argparse.SUPPRESS)
    p.add_argument("--mh-rendezvous", default=None, help=argparse.SUPPRESS)
    return p


def _read_data(args, coordinate_configs: Dict[str, CoordinateConfiguration]):
    """readTrainingData/readValidationData (GameTrainingDriver.scala:503-547)."""
    shard_configs = dict(
        parse_feature_shard_config(s) for s in args.feature_shard_configurations
    )
    id_tags = [
        c.data_config.random_effect_type
        for c in coordinate_configs.values()
        if isinstance(c.data_config, RandomEffectDataConfig)
    ]
    for ev in args.validation_evaluators:
        et = EvaluatorType.parse(ev)
        if et.is_grouped and et.id_tag not in id_tags:
            id_tags.append(et.id_tag)

    # prepareFeatureMaps (GameDriver.scala:231-236): prebuilt off-heap index
    # partitions when given, else index maps derived from the data itself.
    prebuilt = None
    if getattr(args, "offheap_indexmap_dir", None):
        # prepareFeatureMaps (GameDriver.scala:231-236): PalDB or PHIDX
        # partitions, auto-detected per shard.
        from photon_ml_tpu.io.paldb import resolve_offheap_index_maps

        prebuilt = resolve_offheap_index_maps(
            args.offheap_indexmap_dir, shard_configs
        )

    # Date-range resolution (IOUtils.resolveRange + pathsForDateRange,
    # GameTrainingDriver.scala:508-509): expand base dirs to daily subdirs.
    from photon_ml_tpu.utils.date_range import paths_for_date_range, resolve_range

    train_range = resolve_range(
        getattr(args, "input_data_date_range", None),
        getattr(args, "input_data_days_range", None),
    )
    train_paths = paths_for_date_range(args.input_data_directories, train_range)
    columns = (
        avro_data.InputColumnNames.parse(args.input_column_names)
        if getattr(args, "input_column_names", None)
        else None
    )
    # NOTE: read_game_dataset supports per-process file slicing
    # (process_index/process_count) for multi-host ingest, but this driver
    # deliberately does NOT auto-engage it: the estimator trains on
    # process-local arrays, so handing each host a disjoint slice without
    # assembling global sharded arrays first (the
    # jax.make_array_from_process_local_data step parallel/multihost.py
    # demonstrates) would silently fit N divergent models. Multi-host
    # pipelines call the reader directly and own that assembly.
    train, index_maps = avro_data.read_game_dataset(
        train_paths,
        shard_configs,
        index_maps=prebuilt,
        id_tag_fields=id_tags,
        columns=columns,
    )

    validation = None
    if args.validation_data_directories:
        val_range = resolve_range(
            getattr(args, "validation_data_date_range", None),
            getattr(args, "validation_data_days_range", None),
        )
        val_paths = paths_for_date_range(
            args.validation_data_directories, val_range
        )
        validation, _ = avro_data.read_game_dataset(
            val_paths,
            shard_configs,
            index_maps=index_maps,
            id_tag_fields=id_tags,
            columns=columns,
        )
    return train, validation, index_maps, shard_configs


def _validate_rows(dataset, task: TaskType, mode: DataValidationType) -> None:
    """DataValidators.sanityCheckDataFrameForTraining (DataValidators.scala:32)."""
    from photon_ml_tpu.data.validators import validate_game_dataset

    validate_game_dataset(dataset, task, mode)


def _tuning_dimensions(
    coordinate_configs: Dict[str, CoordinateConfiguration],
    tunable_ids,
) -> List[HyperparameterConfig]:
    """One LOG-scale dimension per regularized TRAINABLE coordinate
    (GameEstimatorEvaluationFunction.configurationToVector:152); locked
    coordinates have no config entry in the sweep and are not tuned."""
    dims = []
    for cid, cfg in coordinate_configs.items():
        if cid not in tunable_ids:
            continue
        if cfg.opt_config.regularization.reg_type != RegularizationType.NONE:
            dims.append(
                HyperparameterConfig(
                    name=cid,
                    min_value=TUNING_REG_WEIGHT_RANGE[0],
                    max_value=TUNING_REG_WEIGHT_RANGE[1],
                    transform="LOG",
                )
            )
    return dims


def run(args, event_emitter=None) -> Dict[str, object]:
    logging.basicConfig(
        level=getattr(logging, args.logging_level.upper(), logging.INFO),
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    out_root = args.root_output_directory
    models_root = os.path.join(out_root, "models")
    if os.path.exists(models_root):
        if not args.override_output_directory:
            raise FileExistsError(
                f"{models_root} exists; pass --override-output-directory to replace"
            )
        # Clean replace — never mix stale model subdirs into the new run
        # (cleanOutputDirs, GameTrainingDriver.scala:487).
        import shutil

        shutil.rmtree(models_root)
    os.makedirs(out_root, exist_ok=True)

    # Job-scoped observability: file log under the output root (the
    # reference's PhotonLogger HDFS file), timed sections, lifecycle
    # events — and since ISSUE 11 the run journal (every lifecycle event
    # as a typed JSONL line), optional span tracing (PHOTON_TRACE=1 ->
    # Perfetto-loadable trace.json), and the persisted run profile.
    from photon_ml_tpu.utils import telemetry
    from photon_ml_tpu.utils.observability import (
        EventEmitter,
        PhotonLogger,
        PhotonSetupEvent,
        Timed,
        TimingRegistry,
        journal_listener,
    )

    timings = TimingRegistry()
    job_logger = PhotonLogger(
        os.path.join(out_root, "photon-ml-tpu.log"), level=args.logging_level
    )
    if event_emitter is None:
        event_emitter = EventEmitter()
    journal = telemetry.RunJournal(os.path.join(out_root, "journal.jsonl"))
    event_emitter.register(journal_listener(journal))
    # Only adopt the process-ambient slots we own (same discipline for
    # journal and tracer): a caller's pre-installed journal/tracer must
    # survive this run, not be clobbered and uninstalled to None.
    journal_owned = telemetry.current_journal() is None
    if journal_owned:
        telemetry.install_journal(journal)
    tracer_owned = telemetry.current_tracer() is None
    tracer = telemetry.start_tracing_if_enabled()
    event_emitter.send(PhotonSetupEvent(args=str(vars(args))))
    # Adaptive runtime planner (ISSUE 14): installed HERE — after the
    # journal (plan_decision events land in it) and before ingest (chunk
    # rows are a planned quantity). --profile beats PHOTON_PLAN_PROFILE;
    # explicit PHOTON_* knobs beat the plan; owned so a caller's ambient
    # plan survives this run.
    from photon_ml_tpu import planner

    plan_owned = planner.current_plan() is None
    if not plan_owned and getattr(args, "profile", None):
        logger.warning(
            "--profile %s ignored: a runtime plan is already installed "
            "by the caller (uninstall it to let this run plan itself)",
            args.profile,
        )
    try:
        if plan_owned:
            planner.ensure_ambient_plan(getattr(args, "profile", None))
        return _run_job(
            args, event_emitter, out_root, models_root, timings, Timed,
        )
    except Exception as e:
        from photon_ml_tpu.utils.observability import PhotonFailureEvent

        logger.exception("training job failed")
        event_emitter.send(PhotonFailureEvent(error=repr(e)))
        raise
    finally:
        if plan_owned:
            planner.uninstall_plan()
        if tracer is not None and tracer_owned:
            tracer.export(os.path.join(out_root, "trace.json"))
            telemetry.uninstall_tracer()
            logger.info("trace written to %s", os.path.join(out_root, "trace.json"))
        if journal_owned:
            telemetry.uninstall_journal()
        journal.close()
        job_logger.close()


def _run_job(
    args, event_emitter, out_root, models_root, timings, Timed,
) -> Dict[str, object]:
    coordinate_configs = {}
    for s in args.coordinate_configurations:
        cfg = parse_coordinate_config(s)
        coordinate_configs[cfg.name] = cfg
    update_sequence = (
        [c.strip() for c in args.coordinate_update_sequence.split(",")]
        if args.coordinate_update_sequence
        else list(coordinate_configs.keys())
    )
    locked = (
        {c.strip() for c in args.partial_retrain_locked_coordinates.split(",")}
        if args.partial_retrain_locked_coordinates
        else set()
    )

    # Log the effective config back out (the scopt parsers' round-trip print).
    logger.info("effective feature shard configurations:")
    shard_configs_parsed = dict(
        parse_feature_shard_config(s) for s in args.feature_shard_configurations
    )
    for name, fc in shard_configs_parsed.items():
        logger.info("  %s", feature_shard_config_to_string(name, fc))
    logger.info("effective coordinate configurations:")
    for cfg in coordinate_configs.values():
        logger.info("  %s", coordinate_config_to_string(cfg))

    with Timed("read data", registry=timings):
        train, validation, index_maps, shard_configs = _read_data(args, coordinate_configs)
    logger.info(
        "training data: %d samples, shards %s",
        train.num_samples,
        {k: v.size for k, v in index_maps.items()},
    )
    with Timed("validate data", registry=timings):
        _validate_rows(train, args.training_task, args.data_validation)
        if validation is not None:
            _validate_rows(validation, args.training_task, args.data_validation)

    # Feature-shard summarization output (calculateAndSaveFeatureShardStats,
    # GameTrainingDriver.scala:575-593 -> writeBasicStatistics).
    if args.data_summary_directory:
        from photon_ml_tpu.data.stats import summarize
        from photon_ml_tpu.io.model_store import write_basic_statistics

        with Timed("feature summarization", registry=timings):
            for shard, imap in index_maps.items():
                stats = summarize(
                    train.shards[shard], intercept_index=imap.intercept_index
                )
                n_written = write_basic_statistics(
                    os.path.join(args.data_summary_directory, shard), stats, imap
                )
                logger.info(
                    "feature summary: shard %s -> %d records", shard, n_written
                )

    # Per-coordinate variance type (driver-level param applied to every
    # coordinate, GameTrainingDriver varianceComputationType).
    if args.variance_computation_type != VarianceComputationType.NONE:
        import dataclasses as _dc

        for cfg in coordinate_configs.values():
            cfg.opt_config = _dc.replace(
                cfg.opt_config, variance_computation=args.variance_computation_type
            )

    # Box-constraint maps (constraints.file in the coordinate DSL): resolve
    # the legacy JSON constraint string against the shard's index map
    # (GLMSuite.createConstraintFeatureMap:190-265) into (lower, upper)
    # vectors for the projected-L-BFGS optimizer.
    for cfg in coordinate_configs.values():
        if not cfg.constraint_file:
            continue
        import dataclasses as _dc

        from photon_ml_tpu.optimize.constraints import (
            bounds_arrays,
            create_constraint_feature_map,
        )

        if args.normalization != NormalizationType.NONE:
            # The bounds are original-space per-feature boxes; the optimizer
            # clips TRANSFORMED-space coefficients, so with normalization a
            # clipped model could still violate the user's bounds after the
            # original-space fold-out. Refuse rather than silently violate.
            raise ValueError(
                f"coordinate {cfg.name!r}: box constraints cannot combine "
                "with --normalization (bounds apply in original feature "
                "space; the optimizer works in normalized space)"
            )
        dc_cfg = cfg.data_config
        if isinstance(dc_cfg, RandomEffectDataConfig) and dc_cfg.projector_type not in (
            ProjectorType.IDENTITY,
        ):
            raise ValueError(
                f"coordinate {cfg.name!r}: box constraints require the "
                "IDENTITY projector (bounds are per global feature index)"
            )
        imap = index_maps[dc_cfg.feature_shard]
        with open(cfg.constraint_file) as f:
            cmap = create_constraint_feature_map(f.read(), imap)
        box = bounds_arrays(cmap, imap.size)
        if box is not None:
            cfg.opt_config = _dc.replace(
                cfg.opt_config,
                optimizer=_dc.replace(cfg.opt_config.optimizer, box_constraints=box),
            )
            logger.info(
                "coordinate %s: box constraints on %d feature(s)",
                cfg.name,
                len(cmap),
            )

    estimator = GameEstimator(
        args.training_task,
        {cid: c.data_config for cid, c in coordinate_configs.items()},
        update_sequence=update_sequence,
        coordinate_descent_iterations=args.coordinate_descent_iterations,
        normalization=args.normalization,
        validation_evaluators=[EvaluatorType.parse(e) for e in args.validation_evaluators],
        locked_coordinates=locked or None,
        intercept_indices={
            shard: index_maps[shard].intercept_index
            for shard in index_maps
            if index_maps[shard].intercept_index is not None
        },
        seed=args.random_seed,
        checkpoint_dir=getattr(args, "checkpoint_directory", None),
        # The estimator emits start/sweep/coordinate/checkpoint/finish
        # events itself (ISSUE 11 satellite), so library fits and CLI
        # fits produce the same journal record.
        event_emitter=event_emitter,
    )

    # Warm start / partial retrain (GameTrainingDriver.scala:370-409).
    initial_model = None
    if args.model_input_directory:
        artifact = model_store.load_game_model(
            os.path.join(args.model_input_directory), index_maps
        )
        estimator.prepare(train)
        initial_model = model_bridge.warm_start_model_for_estimator(
            artifact, estimator.scoring_specs()
        )
        logger.info("warm start from %s", args.model_input_directory)
    elif locked:
        raise ValueError("--partial-retrain-locked-coordinates requires "
                         "--model-input-directory")

    sweep = expand_game_opt_configs(
        {cid: coordinate_configs[cid] for cid in update_sequence if cid not in locked}
    )
    logger.info("training %d explicit configuration(s)", len(sweep))
    with Timed("train explicit configurations", registry=timings):
        explicit_results = estimator.fit(
            train, validation, sweep, initial_model=initial_model
        )

    # Hyperparameter tuning (GameTrainingDriver.runHyperparameterTuning:643).
    tuned_results: List[GameResult] = []
    if (
        args.hyper_parameter_tuning != HyperparameterTuningMode.NONE
        and validation is not None
    ):
        dims = _tuning_dimensions(coordinate_configs, set(explicit_results[0].config))
        if dims:
            _, base = select_best_result(explicit_results)
            evaluator = base.evaluation.primary
            maximize = better_than(evaluator, 1.0, 0.0)

            def evaluate(point: np.ndarray) -> float:
                cfgs = dict(base.config)
                for d, cid in zip(point, [c.name for c in dims]):
                    import dataclasses as _dc

                    cfgs[cid] = _dc.replace(cfgs[cid], reg_weight=float(d))
                res = estimator.fit(
                    train, validation, [cfgs], initial_model=base.model
                )[0]
                tuned_results.append(res)
                return res.evaluation.primary_value

            tuner = get_tuner(args.hyper_parameter_tuning)
            tuner.search(
                args.hyper_parameter_tuning_iter,
                dims,
                args.hyper_parameter_tuning,
                evaluate,
                maximize=maximize,
                seed=args.random_seed + 1,
                batch_size=args.hyper_parameter_tuning_batch_size,
            )
            logger.info("hyperparameter tuning: %d trials", len(tuned_results))

    # Model selection + save (GameTrainingDriver.scala:683-779).
    all_results = explicit_results + tuned_results
    best_i, best = select_best_result(all_results)
    specs = estimator.scoring_specs()
    summary: Dict[str, object] = {
        "num_samples": int(train.num_samples),
        "num_explicit": len(explicit_results),
        "num_tuned": len(tuned_results),
        "best_index": best_i,
        "best_evaluation": None if best.evaluation is None else best.evaluation.results,
    }

    def _save(result: GameResult, subdir: str) -> None:
        artifact = model_bridge.artifact_from_game_model(
            result.model,
            specs,
            args.training_task,
            opt_configs={
                cid: {
                    "optimizer": c.optimizer.optimizer_type.value,
                    "max_iterations": c.optimizer.max_iterations,
                    "tolerance": c.optimizer.tolerance,
                    "regularization": c.regularization.reg_type.value,
                    "reg_weight": c.reg_weight,
                }
                for cid, c in result.config.items()
            },
        )
        mdir = os.path.join(models_root, subdir)
        model_store.save_game_model(
            mdir,
            artifact,
            index_maps,
            sparsity_threshold=args.model_sparsity_threshold,
        )
        # Ship the feature index maps with the model so the scoring driver
        # resolves names identically (stands in for the off-heap index dir).
        idx_dir = os.path.join(mdir, "feature-indexes")
        os.makedirs(idx_dir, exist_ok=True)
        for shard, imap in index_maps.items():
            imap.save(os.path.join(idx_dir, f"{shard}.json"))

    mode = args.output_mode
    if mode != ModelOutputMode.NONE:
        with Timed("save models", registry=timings):
            _save(best, "best")
            if mode in (ModelOutputMode.EXPLICIT, ModelOutputMode.ALL):
                for i, r in enumerate(explicit_results):
                    _save(r, f"explicit-{i}")
            if mode in (ModelOutputMode.TUNED, ModelOutputMode.ALL):
                for i, r in enumerate(tuned_results):
                    _save(r, f"tuned-{i}")

    for i, r in enumerate(all_results):
        logger.info(
            "config %d%s: %s",
            i,
            " (best)" if i == best_i else "",
            None if r.evaluation is None else r.evaluation.results,
        )
    # Fold per-coordinate descent timings into the job summary so profiling
    # data from inside the estimator reaches the final report.
    for r in all_results:
        for section, seconds in r.timing.items():
            timings.record(f"coordinate {section}", seconds)
    # Persist stage walls with the summary: benchmarks and users read the
    # ingest/train/save split from the artifact instead of scraping logs
    # (the reference logs its Timed sections the same way,
    # GameTrainingDriver.scala:360-480).
    summary["timings_s"] = {
        name: round(total, 3) for name, total in timings.sections.items()
    }
    with open(os.path.join(out_root, "training-summary.json"), "w") as f:
        json.dump(summary, f, indent=2, default=str)
    # The persisted run profile (ISSUE 11): the machine-readable artifact
    # the adaptive-runtime planner consumes — stage breakdown, dispatch
    # decisions, bucket shapes, topology, metrics snapshot. Validated on
    # write; consumers re-read through telemetry.read_profile (loud).
    from photon_ml_tpu.utils import telemetry

    profile_path = telemetry.write_profile(
        os.path.join(out_root, "profile.json"), estimator.run_profile()
    )
    logger.info("run profile written to %s", profile_path)
    logger.info("timing summary:\n%s", timings.summary())
    return summary


def main(argv: Optional[List[str]] = None) -> None:
    raw_argv = list(sys.argv[1:] if argv is None else argv)
    args = build_parser().parse_args(raw_argv)
    if args.mh_worker:
        # One host of a supervised process group (spawned by
        # run_supervisor's build_argv; never invoked by hand).
        from photon_ml_tpu.cli import train_multihost

        raise SystemExit(train_multihost.run_worker(args))
    if args.multihost:
        from photon_ml_tpu.cli import train_multihost

        train_multihost.run_supervisor(args, raw_argv)
        return
    run(args)


if __name__ == "__main__":
    main(sys.argv[1:])

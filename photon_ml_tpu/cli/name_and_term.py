"""Name-and-term feature-bag extraction driver.

Counterpart of photon-client data/avro/NameAndTermFeatureBagsDriver.scala:32
with NameAndTerm.scala:25 / NameAndTermFeatureMapUtils.scala:26: scan the
input Avro records and write the distinct (name, term) pairs of each feature
bag as one merged text file `<output>/<bagName>` with tab-delimited lines
(NameAndTerm.STRING_DELIMITER = "\\t", NameAndTerm.scala:39,63). These files
feed the feature-indexing driver (cli/build_index.py) so index builds don't
re-scan the raw data.

Usage:
    python -m photon_ml_tpu.cli.name_and_term \
        --input-data-directories data/train \
        --feature-bags-keys features songFeatures \
        --output-dir out/name-and-term
"""

from __future__ import annotations

import argparse
import logging
import os
import sys
from typing import Dict, Iterable, List, Set, Tuple

from photon_ml_tpu.io import avro as avro_io

logger = logging.getLogger("photon_ml_tpu.cli.name_and_term")

STRING_DELIMITER = "\t"


def extract_name_and_terms(
    records: Iterable[dict], feature_bags: List[str]
) -> Dict[str, Set[Tuple[str, str]]]:
    """Distinct (name, term) per bag (NameAndTermFeatureMapUtils
    readNameAndTermFeatureMapFromRawRecords role)."""
    out: Dict[str, Set[Tuple[str, str]]] = {bag: set() for bag in feature_bags}
    for record in records:
        for bag in feature_bags:
            for f in record.get(bag) or ():
                out[bag].add((f["name"], f.get("term", "") or ""))
    return out


def write_name_and_term_file(path: str, pairs: Set[Tuple[str, str]]) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        for name, term in sorted(pairs):
            if "\t" in name or "\n" in name or "\t" in term or "\n" in term:
                # The text format cannot represent delimiter characters; a
                # silent write would corrupt the roundtrip and the index.
                raise ValueError(
                    f"feature (name, term) ({name!r}, {term!r}) contains "
                    "tab/newline, unrepresentable in name-and-term text format"
                )
            f.write(f"{name}{STRING_DELIMITER}{term}\n")


def read_name_and_term_file(path: str) -> List[Tuple[str, str]]:
    """Parse the text format back (readNameAndTermRDDFromTextFiles:136-146:
    1 field = name with empty term, 2 fields = name and term)."""
    pairs: List[Tuple[str, str]] = []
    with open(path) as f:
        for line in f:
            line = line.rstrip("\n")
            if not line:
                continue
            parts = line.split(STRING_DELIMITER)
            if len(parts) == 1:
                pairs.append((parts[0], ""))
            else:
                pairs.append((parts[0], parts[1]))
    return pairs


def main(argv: List[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="photon-ml-tpu-name-and-term",
        description="Extract distinct (name, term) feature sets per bag "
        "(NameAndTermFeatureBagsDriver equivalent).",
    )
    parser.add_argument("--input-data-directories", nargs="+", required=True)
    parser.add_argument(
        "--feature-bags-keys",
        nargs="+",
        required=True,
        help="Feature bag field names to extract.",
    )
    parser.add_argument("--output-dir", required=True)
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO, format="%(levelname)s %(name)s: %(message)s")
    records: List[dict] = []
    for path in args.input_data_directories:
        _, recs = avro_io.read_directory(path)
        records.extend(recs)

    bags = extract_name_and_terms(records, list(args.feature_bags_keys))
    for bag, pairs in bags.items():
        out_path = os.path.join(args.output_dir, bag)
        write_name_and_term_file(out_path, pairs)
        logger.info("wrote %d distinct (name, term) pairs for bag %s", len(pairs), bag)
    return 0


if __name__ == "__main__":
    sys.exit(main())

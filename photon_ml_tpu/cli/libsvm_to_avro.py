"""LibSVM text -> TrainingExample Avro converter.

Counterpart of the reference's only Python tool,
dev-scripts/libsvm_text_to_trainingexample_avro.py (README.md:330-334): each
LibSVM column index becomes a feature `name` with an empty `term`; binary
{-1,+1} labels map to {0,1} responses unless --regression is given.

Usage:
    python -m photon_ml_tpu.cli.libsvm_to_avro INPUT OUTPUT [--regression]
"""

from __future__ import annotations

import argparse
from typing import List, Optional

from photon_ml_tpu.data.libsvm import parse_libsvm_line
from photon_ml_tpu.io.avro_data import write_training_examples


def convert(
    input_path: str,
    output_path: str,
    *,
    regression: bool = False,
    zero_based: bool = False,
    tag_comments: bool = False,
) -> int:
    """Convert one LibSVM file (buffered in memory); returns the record count.

    Feature keys are the bare LibSVM indices as names (term empty), matching
    the reference converter's `{"name": id, "term": ""}` records. The
    intercept is NOT added here — the training driver's feature-shard config
    controls that (`intercept=true`), as with Avro data in the reference.

    Classification label mapping follows `read_libsvm`: {-1,+1} -> {0,1} only
    when EVERY label is in {-1,+1} (a whole-file property), so regression
    files that merely contain some ±1 targets are never silently corrupted.

    With `tag_comments`, trailing `# key=value[,key=value...]` comments are
    captured as id-tag fields (entity keys for GAME random effects) instead
    of being discarded — an extension over the reference converter so LibSVM
    sources can feed GLMix training.
    """
    features: List[List[tuple]] = []
    labels: List[float] = []
    tags: dict = {}
    with open(input_path) as f:
        for line in f:
            parsed = parse_libsvm_line(line, zero_based=zero_based)
            if parsed is None:
                continue
            label, pairs, comment = parsed
            row = [(str(idx), value) for idx, value in pairs]
            if tag_comments and comment:
                for pair in comment.split(","):
                    key, _, value = pair.partition("=")
                    if value:
                        tags.setdefault(key.strip(), {})[len(labels)] = value.strip()
            features.append(row)
            labels.append(label)
    if not regression and set(labels) <= {-1.0, 1.0}:
        labels = [1.0 if l > 0 else 0.0 for l in labels]
    n = len(labels)
    id_tags = {
        key: [by_row.get(i, "") for i in range(n)] for key, by_row in tags.items()
    }
    return write_training_examples(
        output_path,
        features,
        labels,
        uids=[str(i) for i in range(n)],
        id_tags=id_tags or None,
    )


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon-ml-tpu-libsvm-to-avro",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("input", help="LibSVM text file")
    p.add_argument("output", help="output Avro file")
    p.add_argument(
        "--regression",
        action="store_true",
        help="keep labels as-is instead of mapping {-1,+1} to {0,1}",
    )
    p.add_argument(
        "--zero-based",
        action="store_true",
        help="LibSVM indices start at 0 (default: 1-based)",
    )
    p.add_argument(
        "--tag-comments",
        action="store_true",
        help="capture trailing '# key=value' comments as id-tag fields",
    )
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    n = convert(
        args.input,
        args.output,
        regression=args.regression,
        zero_based=args.zero_based,
        tag_comments=args.tag_comments,
    )
    print(f"wrote {n} records to {args.output}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

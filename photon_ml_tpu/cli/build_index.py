"""Feature-indexing driver: build the persistent partitioned index store.

Counterpart of photon-client index/FeatureIndexingDriver.scala:41-320 (see
SURVEY.md §3.5): read training records, take the distinct feature keys per
feature shard (union of the shard's feature bags, plus the intercept key when
the shard has one), route each key to a hash partition, and build one
memory-mapped store partition per hash bucket — `index-partition-<shard>-<k>
.bin`, the PHIDX equivalent of the reference's `paldb-partition-<shard>-<n>
.dat`. Where the reference shuffles the keys with a Spark HashPartitioner
and writes PalDB stores per Spark partition, this is a host-side ETL pass:
ingest is sequential Avro/LibSVM decode, the store build is the native C++
writer (photon_ml_tpu/native/index_store.cc).

Also accepts pre-extracted name-and-term text files (the
NameAndTermFeatureBagsDriver output, cli/name_and_term.py) as input, the
same coupling the reference has between its two indexing drivers.

Usage:
    python -m photon_ml_tpu.cli.build_index \
        --input-data-directories data/train \
        --feature-shard-configurations "name=globalShard,feature.bags=features" \
        --num-partitions 4 --output-dir out/index
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
from typing import Dict, Iterable, List, Set

from photon_ml_tpu.cli.config import parse_feature_shard_config
from photon_ml_tpu.cli.name_and_term import read_name_and_term_file
from photon_ml_tpu.data.index_map import INTERCEPT_KEY, feature_key
from photon_ml_tpu.io import avro as avro_io
from photon_ml_tpu.io.avro_data import FeatureShardConfig
from photon_ml_tpu.native.index_store import build_partitioned_store

logger = logging.getLogger("photon_ml_tpu.cli.build_index")

METADATA_FILE = "_index_metadata.json"


def collect_shard_keys(
    records: Iterable[dict], shard_configs: Dict[str, FeatureShardConfig]
) -> Dict[str, Set[str]]:
    """Distinct feature keys per shard (FeatureIndexingDriver
    partitionedUniqueFeatures:217-251, intercept injected like :243)."""
    keys: Dict[str, Set[str]] = {name: set() for name in shard_configs}
    for record in records:
        for shard_name, cfg in shard_configs.items():
            bucket = keys[shard_name]
            for bag in cfg.feature_bags:
                for f in record.get(bag) or ():
                    bucket.add(feature_key(f["name"], f.get("term", "") or ""))
    for shard_name, cfg in shard_configs.items():
        if cfg.has_intercept:
            keys[shard_name].add(INTERCEPT_KEY)
    return keys


def build_index_stores(
    shard_keys: Dict[str, Set[str]],
    output_dir: str,
    num_partitions: int,
    store_format: str = "phidx",
) -> Dict[str, int]:
    """Build one partitioned store per shard namespace + metadata JSON.

    `store_format='paldb'` writes the reference's PalDB v1 partitions
    (loadable by PalDBIndexMap.scala:43-118 — two-way format interop; the
    byte-level format fidelity is proven in tests/test_paldb.py against the
    reference's own fixture stores) instead of this framework's PHIDX.
    """
    os.makedirs(output_dir, exist_ok=True)
    counts: Dict[str, int] = {}
    for shard_name, keys in shard_keys.items():
        if store_format == "paldb":
            from photon_ml_tpu.io.paldb import write_index_map

            counts[shard_name] = len(
                write_index_map(
                    output_dir, shard_name, sorted(keys), num_partitions
                )
            )
        else:
            counts[shard_name] = build_partitioned_store(
                output_dir, sorted(keys), num_partitions, namespace=shard_name
            )
        logger.info(
            "indexed %d features for shard %s (%d partitions)",
            counts[shard_name],
            shard_name,
            num_partitions,
        )
    with open(os.path.join(output_dir, METADATA_FILE), "w") as f:
        json.dump(
            {
                "num_partitions": num_partitions,
                "shards": {name: {"num_features": n} for name, n in counts.items()},
            },
            f,
            indent=2,
        )
    return counts


def main(argv: List[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="photon-ml-tpu-build-index",
        description="Build partitioned persistent feature-index stores "
        "(FeatureIndexingDriver equivalent).",
    )
    parser.add_argument(
        "--input-data-directories",
        nargs="+",
        default=[],
        help="Avro training-data files or directories.",
    )
    parser.add_argument(
        "--name-and-term-directory",
        default=None,
        help="Directory of per-bag name-and-term text files "
        "(NameAndTermFeatureBagsDriver output) to index instead of raw data.",
    )
    parser.add_argument(
        "--feature-shard-configurations",
        nargs="+",
        required=True,
        help="Shard mini-DSL, e.g. 'name=globalShard,feature.bags=f1|f2'.",
    )
    parser.add_argument("--num-partitions", type=int, default=1)
    parser.add_argument("--output-dir", required=True)
    parser.add_argument(
        "--output-format",
        choices=("phidx", "paldb"),
        default="phidx",
        help="Store format: this framework's PHIDX (default) or the "
        "reference's PalDB v1 partitions (readable by its PalDBIndexMap).",
    )
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO, format="%(levelname)s %(name)s: %(message)s")
    shard_configs = dict(
        parse_feature_shard_config(s) for s in args.feature_shard_configurations
    )

    if args.name_and_term_directory:
        shard_keys: Dict[str, Set[str]] = {}
        for shard_name, cfg in shard_configs.items():
            bucket: Set[str] = set()
            for bag in cfg.feature_bags:
                path = os.path.join(args.name_and_term_directory, bag)
                for name, term in read_name_and_term_file(path):
                    bucket.add(feature_key(name, term))
            if cfg.has_intercept:
                bucket.add(INTERCEPT_KEY)
            shard_keys[shard_name] = bucket
    else:
        if not args.input_data_directories:
            parser.error(
                "either --input-data-directories or --name-and-term-directory "
                "is required"
            )
        records: List[dict] = []
        for path in args.input_data_directories:
            _, recs = avro_io.read_directory(path)
            records.extend(recs)
        shard_keys = collect_shard_keys(records, shard_configs)

    build_index_stores(
        shard_keys, args.output_dir, args.num_partitions, args.output_format
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Continuous-refresh loop driver: data -> incremental fit -> delta swap.

The ISSUE 16 runbook entry point. The reference's production cadence is
"retrain from scratch, redeploy the whole artifact" (GameTrainingDriver
-> new model dir -> serving restart); this driver runs the incremental
alternative end to end against a LIVE engine:

    round 0: full fit -> stage serving bundle
    each round: ingest delta batch -> fingerprint diff -> warm-start
        incremental fit (changed coordinates/entities only) -> delta
        bundle -> in-place generation flip (serving/delta.apply_delta)

and records per-round freshness (`data_to_served_s` — delta batch in
hand to new generation live) in `refresh-summary.json`, with every
`delta_fit_start`/`delta_fit_finish`/`delta_apply`/`delta_rollback`
event in `journal.jsonl` and the characterized parity trail in
`checkpoints/delta_records.jsonl`.

Data source: `--synthetic` draws a base dataset plus streamed delta
batches (entity churn + brand-new entities) — the self-contained demo /
smoke mode the bench's `continuous_loop` section mirrors. Batch size
targets PHOTON_REFRESH_BATCH_ROWS (planner-routed: `refresh_batch_rows`)
unless --batch-rows overrides; churn past
PHOTON_REFRESH_MAX_DELTA_FRACTION of the merged rows escapes to one
warm-started full refit (see game/incremental.plan_delta_fit).

`--shadow-gate` (ISSUE 18) puts every round's delta behind the online
shadow gate instead of committing it blind: the challenger state is
staged as a shadow tenant next to the live engine, probe traffic with
known labels is mirrored into it (serving/shadow.ShadowController with
`auto_actuate=False`), and the delta only commits — the usual
apply_delta generation flip — on a clean `promote` verdict. A
regression (or no verdict at all) journals `delta_rollback`, leaves the
live engine on its current generation untouched, and the loop carries
on from the previous state. Gated runs draw signal-bearing labels so
champion/challenger quality is measurable; the per-round summary gains
a `shadow` block (the controller's SHADOW_BLOCK_KEYS evidence).

Usage: python -m photon_ml_tpu.cli.refresh --help
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import time
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import planner
from photon_ml_tpu.data.game_dataset import (
    FixedEffectDataConfig,
    GameDataset,
    RandomEffectDataConfig,
    concat_datasets,
)
from photon_ml_tpu.game import incremental
from photon_ml_tpu.optimize.config import (
    L2,
    CoordinateOptimizationConfig,
    OptimizerConfig,
)
from photon_ml_tpu.serving.bundle import ScoreRequest, ServingBundle
from photon_ml_tpu.serving.delta import (
    apply_delta,
    apply_delta_for_tenant,
    build_delta_bundle,
)
from photon_ml_tpu.serving.engine import ServingEngine
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.utils import faults, telemetry

logger = logging.getLogger("photon_ml_tpu.cli.refresh")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon_ml_tpu.cli.refresh",
        description="Continuous refresh: incremental fits + delta-bundle "
        "swaps against a live serving engine",
    )
    p.add_argument("--root-output-directory", required=True)
    p.add_argument("--synthetic", action="store_true",
                   help="draw a synthetic base dataset + streamed delta "
                        "batches (the self-contained demo mode)")
    p.add_argument("--rounds", type=int, default=3,
                   help="number of delta batches to stream (default 3)")
    p.add_argument("--base-rows", type=int, default=512,
                   help="synthetic base dataset rows (default 512)")
    p.add_argument("--batch-rows", type=int, default=None,
                   help="rows per streamed delta batch (default: the "
                        "PHOTON_REFRESH_BATCH_ROWS knob via the planner)")
    p.add_argument("--entities", type=int, default=24,
                   help="synthetic entity count in the base data")
    p.add_argument("--new-entities-per-round", type=int, default=2,
                   help="brand-new entities appearing in each delta batch")
    p.add_argument("--churn-entities", type=int, default=3,
                   help="existing entities each delta batch touches")
    p.add_argument("--training-task", type=TaskType.parse,
                   default=TaskType.LOGISTIC_REGRESSION)
    p.add_argument("--shadow-gate", action="store_true",
                   help="land each round's delta as a SHADOW tenant first "
                        "and only commit on a clean online verdict "
                        "(regressions journal delta_rollback and leave the "
                        "live generation untouched)")
    p.add_argument("--probe-rows", type=int, default=48,
                   help="labelled probe requests mirrored through the "
                        "shadow per round (two evaluation windows; only "
                        "used with --shadow-gate)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--logging-level", default="INFO")
    return p


def _synthetic_batch(rng, n: int, entities: np.ndarray, d_fe: int, d_re: int,
                     w_true: Optional[np.ndarray] = None):
    """One data batch over the given entity pool (rows cycle the pool so
    every listed entity actually appears — deterministic churn). With
    `w_true` the labels carry signal (a noisy linear rule on the fixed
    features) instead of coin flips — the shadow gate compares champion
    and challenger QUALITY, which only means something when there is a
    signal to learn; the default coin labels keep the ungated loop's
    draws bitwise-identical to previous releases."""
    ent = np.resize(entities, n)
    Xg = rng.normal(size=(n, d_fe)).astype(np.float32)
    Xre = rng.normal(size=(n, d_re)).astype(np.float32)
    if w_true is None:
        y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    else:
        y = (Xg @ w_true + 0.25 * rng.normal(size=n) > 0.0).astype(np.float32)
    return GameDataset.build(
        {"g": jnp.asarray(Xg), "re": jnp.asarray(Xre)},
        y,
        id_tags={"eid": ent},
    )


def _probe_requests(rng, n: int, entities: int, d_fe: int, d_re: int,
                    w_true: np.ndarray, round_idx: int):
    """Fresh labelled probe traffic for one shadow-gated round: rows the
    models have never seen, drawn from the same distribution as the
    training stream, with ground-truth labels from the same noisy linear
    rule. Entity ids cycle the BASE pool, so both champion and
    challenger answer warm."""
    ent = np.resize(np.arange(entities, dtype=np.int64), n)
    Xg = rng.normal(size=(n, d_fe)).astype(np.float32)
    Xre = rng.normal(size=(n, d_re)).astype(np.float32)
    y = (Xg @ w_true + 0.25 * rng.normal(size=n) > 0.0).astype(np.float32)
    reqs = [
        ScoreRequest(
            features={"g": Xg[i], "re": Xre[i]},
            entity_ids={"eid": int(ent[i])},
            uid=f"probe-r{round_idx}-{i}",
        )
        for i in range(n)
    ]
    return reqs, y


def _shadow_gate_round(
    registry, r: int, result, delta, data_configs, task: TaskType, *,
    entities: int, d_fe: int, d_re: int, w_true: np.ndarray,
    probe_rows: int, seed: int,
):
    """ISSUE 18: land one round's delta as a SHADOW before committing it.

    The freshly-fit challenger state is staged as a shadow tenant on the
    live registry, labelled probe traffic is mirrored into it, and the
    round's delta commits to the live engine (the normal apply_delta
    generation flip) ONLY on a clean `promote` verdict. A `reject` — or
    no verdict at all before the timeout — journals `delta_rollback`
    and leaves the live engine untouched. Returns
    `(apply_info_or_None, shadow_block, verdict)`."""
    from photon_ml_tpu.serving.shadow import ShadowController

    chall_bundle = ServingBundle.from_model(
        result.state.model,
        incremental.scoring_specs(data_configs, result.state.entity_indices),
        task,
    )
    window = max(4, probe_rows // 2)
    controller = ShadowController(
        registry, "live", f"delta-r{r}", chall_bundle,
        auto_actuate=False,
        window_size=window,
        min_windows=2,
        cooldown_s=0.0,
        mirror_fraction=1.0,
    )
    probe_rng = np.random.default_rng(seed + 7919 * (r + 1))
    reqs, labels = _probe_requests(
        probe_rng, 2 * window, entities, d_fe, d_re, w_true, r
    )
    try:
        futures = []
        for req, label in zip(reqs, labels):
            fut = registry.submit("live", req, block=True)
            futures.append(fut)
            if controller.mirror(req, fut):
                controller.record_label(req.uid, float(label))
        for fut in futures:
            fut.result(timeout=60.0)
        verdict = controller.wait_for_verdict(timeout_s=120.0)
        shadow_block = controller.summary()
    finally:
        # Idempotent: a rejected shadow is already torn down; a
        # promote-ready one exits WITHOUT a verdict counter (the commit
        # below is the real actuation, via the delta path).
        controller.close()
    if verdict == "promote":
        info = apply_delta_for_tenant(registry, "live", delta)
        return info, shadow_block, verdict
    reason = (
        "shadow gate: challenger regressed on probe traffic"
        if verdict == "reject"
        else "shadow gate: no clean verdict before timeout"
    )
    live_version = int(registry.tenant("live").engine._state.version)
    telemetry.emit_event("delta_rollback", version=live_version, reason=reason)
    faults.COUNTERS.increment("delta_rollbacks")
    logger.warning("round %d delta rejected by shadow gate: %s", r, reason)
    return None, shadow_block, verdict or "no-verdict"


def run_refresh_loop(
    out_root: str,
    *,
    rounds: int,
    base_rows: int,
    batch_rows: Optional[int],
    entities: int,
    new_entities_per_round: int,
    churn_entities: int,
    task: TaskType,
    seed: int,
    d_fe: int = 6,
    d_re: int = 4,
    shadow_gate: bool = False,
    probe_rows: int = 48,
) -> Dict[str, object]:
    """The full synthetic loop; returns (and writes) the refresh summary."""
    rng = np.random.default_rng(seed)
    if batch_rows is None:
        batch_rows = int(planner.planned_value("refresh_batch_rows"))
    data_configs = {
        "fixed": FixedEffectDataConfig("g"),
        "per-entity": RandomEffectDataConfig("eid", "re", min_bucket=4),
    }
    oc = CoordinateOptimizationConfig(
        optimizer=OptimizerConfig(max_iterations=25),
        regularization=L2,
        reg_weight=1.0,
    )
    opt_configs = {"fixed": oc, "per-entity": oc}
    ckpt_dir = os.path.join(out_root, "checkpoints")
    os.makedirs(ckpt_dir, exist_ok=True)

    # Shadow-gated runs need measurable model quality (see
    # _synthetic_batch); the ungated stream keeps its coin labels.
    w_true = (
        np.linspace(1.5, -1.5, d_fe).astype(np.float32)
        if shadow_gate
        else None
    )
    t_full = time.perf_counter()
    dataset = _synthetic_batch(
        rng, base_rows, np.arange(entities, dtype=np.int64), d_fe, d_re,
        w_true=w_true,
    )
    state = incremental.full_fit(
        dataset, data_configs, opt_configs, task, seed=seed
    )
    full_fit_s = time.perf_counter() - t_full
    specs = incremental.scoring_specs(data_configs, state.entity_indices)
    bundle0 = ServingBundle.from_model(state.model, specs, task)
    registry = None
    if shadow_gate:
        from photon_ml_tpu.serving.tenancy import TenantRegistry

        registry = TenantRegistry(max_batch=16)
        registry.admit("live", bundle0)
        engine = registry.tenant("live").engine
    else:
        engine = ServingEngine(bundle0, max_batch=16)
    next_entity = entities
    round_records: List[Dict[str, object]] = []
    try:
        for r in range(rounds):
            churn = rng.choice(entities, size=min(churn_entities, entities),
                               replace=False)
            fresh = np.arange(next_entity,
                              next_entity + new_entities_per_round)
            next_entity += new_entities_per_round
            pool = np.concatenate([churn, fresh]).astype(np.int64)
            t_data = time.perf_counter()
            batch = _synthetic_batch(rng, batch_rows, pool, d_fe, d_re,
                                     w_true=w_true)
            dataset = concat_datasets(dataset, batch)
            result = incremental.incremental_fit(
                dataset, data_configs, opt_configs, task,
                prev=state, seed=seed, checkpoint_dir=ckpt_dir,
            )
            delta = build_delta_bundle(
                state, result.state,
                source=f"round-{r}", mode=result.plan.mode,
                delta_rows=result.plan.delta_rows,
                total_rows=result.plan.total_rows,
            )
            shadow_block = verdict = None
            if shadow_gate:
                info, shadow_block, verdict = _shadow_gate_round(
                    registry, r, result, delta, data_configs, task,
                    entities=entities, d_fe=d_fe, d_re=d_re, w_true=w_true,
                    probe_rows=probe_rows, seed=seed,
                )
            else:
                info = apply_delta(engine, delta)
            data_to_served_s = time.perf_counter() - t_data
            committed = info is not None and bool(info["committed"])
            if committed:
                # A rejected round does NOT advance the model: the next
                # delta is fit from the last state the gate let through
                # (the data is kept — only the weights roll back).
                state = result.state
            generation = (
                int(info["version"]) if info is not None
                else int(engine._state.version)
            )
            record = {
                "round": r,
                "mode": result.plan.mode,
                "delta": delta.manifest(),
                "incremental_fit_s": round(result.seconds, 4),
                "max_rel_diff": result.max_rel_diff,
                "generation": generation,
                "committed": committed,
                "data_to_served_s": round(data_to_served_s, 4),
            }
            if shadow_block is not None:
                record["shadow"] = shadow_block
                record["shadow_verdict"] = verdict
            round_records.append(record)
            logger.info(
                "round %d: mode=%s delta_rows=%d/%d generation=%d "
                "committed=%s data->served %.3fs",
                r, result.plan.mode, result.plan.delta_rows,
                result.plan.total_rows, generation, committed,
                data_to_served_s,
            )
        provenance = dict(engine.bundle.provenance)
        metrics = engine.metrics()
    finally:
        if registry is not None:
            registry.close(release_bundles=True)
        else:
            engine.close()
            engine.bundle.release()
    summary = {
        "rounds": round_records,
        "full_fit_s": round(full_fit_s, 4),
        "batch_rows": int(batch_rows),
        "provenance": provenance,
        "bundle_deltas": metrics["bundle_deltas"],
        "plan": planner.plan_block(),
    }
    with open(os.path.join(out_root, "refresh-summary.json"), "w") as f:
        json.dump(summary, f, indent=2, default=str)
    return summary


def main(argv: Optional[List[str]] = None) -> None:
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=getattr(logging, args.logging_level.upper(), logging.INFO)
    )
    if not args.synthetic:
        raise SystemExit(
            "only --synthetic data is supported; production refresh loops "
            "drive game.incremental + serving.delta directly against their "
            "ingest (see the README 'Continuous refresh' runbook)"
        )
    out_root = args.root_output_directory
    os.makedirs(out_root, exist_ok=True)
    journal = telemetry.RunJournal(os.path.join(out_root, "journal.jsonl"))
    telemetry.install_journal(journal)
    try:
        summary = run_refresh_loop(
            out_root,
            rounds=args.rounds,
            base_rows=args.base_rows,
            batch_rows=args.batch_rows,
            entities=args.entities,
            new_entities_per_round=args.new_entities_per_round,
            churn_entities=args.churn_entities,
            task=args.training_task,
            seed=args.seed,
            shadow_gate=args.shadow_gate,
            probe_rows=args.probe_rows,
        )
    finally:
        telemetry.uninstall_journal()
        journal.close()
    served = [r["data_to_served_s"] for r in summary["rounds"]]
    logger.info(
        "refresh loop done: %d round(s), data->served %s s, summary at %s",
        len(served),
        [round(s, 3) for s in served],
        os.path.join(out_root, "refresh-summary.json"),
    )


if __name__ == "__main__":
    main()

"""Continuous-refresh loop driver: data -> incremental fit -> delta swap.

The ISSUE 16 runbook entry point. The reference's production cadence is
"retrain from scratch, redeploy the whole artifact" (GameTrainingDriver
-> new model dir -> serving restart); this driver runs the incremental
alternative end to end against a LIVE engine:

    round 0: full fit -> stage serving bundle
    each round: ingest delta batch -> fingerprint diff -> warm-start
        incremental fit (changed coordinates/entities only) -> delta
        bundle -> in-place generation flip (serving/delta.apply_delta)

and records per-round freshness (`data_to_served_s` — delta batch in
hand to new generation live) in `refresh-summary.json`, with every
`delta_fit_start`/`delta_fit_finish`/`delta_apply`/`delta_rollback`
event in `journal.jsonl` and the characterized parity trail in
`checkpoints/delta_records.jsonl`.

Data source: `--synthetic` draws a base dataset plus streamed delta
batches (entity churn + brand-new entities) — the self-contained demo /
smoke mode the bench's `continuous_loop` section mirrors. Batch size
targets PHOTON_REFRESH_BATCH_ROWS (planner-routed: `refresh_batch_rows`)
unless --batch-rows overrides; churn past
PHOTON_REFRESH_MAX_DELTA_FRACTION of the merged rows escapes to one
warm-started full refit (see game/incremental.plan_delta_fit).

Usage: python -m photon_ml_tpu.cli.refresh --help
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import time
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import planner
from photon_ml_tpu.data.game_dataset import (
    FixedEffectDataConfig,
    GameDataset,
    RandomEffectDataConfig,
    concat_datasets,
)
from photon_ml_tpu.game import incremental
from photon_ml_tpu.optimize.config import (
    L2,
    CoordinateOptimizationConfig,
    OptimizerConfig,
)
from photon_ml_tpu.serving.bundle import ServingBundle
from photon_ml_tpu.serving.delta import apply_delta, build_delta_bundle
from photon_ml_tpu.serving.engine import ServingEngine
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.utils import telemetry

logger = logging.getLogger("photon_ml_tpu.cli.refresh")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon_ml_tpu.cli.refresh",
        description="Continuous refresh: incremental fits + delta-bundle "
        "swaps against a live serving engine",
    )
    p.add_argument("--root-output-directory", required=True)
    p.add_argument("--synthetic", action="store_true",
                   help="draw a synthetic base dataset + streamed delta "
                        "batches (the self-contained demo mode)")
    p.add_argument("--rounds", type=int, default=3,
                   help="number of delta batches to stream (default 3)")
    p.add_argument("--base-rows", type=int, default=512,
                   help="synthetic base dataset rows (default 512)")
    p.add_argument("--batch-rows", type=int, default=None,
                   help="rows per streamed delta batch (default: the "
                        "PHOTON_REFRESH_BATCH_ROWS knob via the planner)")
    p.add_argument("--entities", type=int, default=24,
                   help="synthetic entity count in the base data")
    p.add_argument("--new-entities-per-round", type=int, default=2,
                   help="brand-new entities appearing in each delta batch")
    p.add_argument("--churn-entities", type=int, default=3,
                   help="existing entities each delta batch touches")
    p.add_argument("--training-task", type=TaskType.parse,
                   default=TaskType.LOGISTIC_REGRESSION)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--logging-level", default="INFO")
    return p


def _synthetic_batch(rng, n: int, entities: np.ndarray, d_fe: int, d_re: int):
    """One data batch over the given entity pool (rows cycle the pool so
    every listed entity actually appears — deterministic churn)."""
    ent = np.resize(entities, n)
    return GameDataset.build(
        {
            "g": jnp.asarray(rng.normal(size=(n, d_fe)).astype(np.float32)),
            "re": jnp.asarray(rng.normal(size=(n, d_re)).astype(np.float32)),
        },
        (rng.uniform(size=n) < 0.5).astype(np.float32),
        id_tags={"eid": ent},
    )


def run_refresh_loop(
    out_root: str,
    *,
    rounds: int,
    base_rows: int,
    batch_rows: Optional[int],
    entities: int,
    new_entities_per_round: int,
    churn_entities: int,
    task: TaskType,
    seed: int,
    d_fe: int = 6,
    d_re: int = 4,
) -> Dict[str, object]:
    """The full synthetic loop; returns (and writes) the refresh summary."""
    rng = np.random.default_rng(seed)
    if batch_rows is None:
        batch_rows = int(planner.planned_value("refresh_batch_rows"))
    data_configs = {
        "fixed": FixedEffectDataConfig("g"),
        "per-entity": RandomEffectDataConfig("eid", "re", min_bucket=4),
    }
    oc = CoordinateOptimizationConfig(
        optimizer=OptimizerConfig(max_iterations=25),
        regularization=L2,
        reg_weight=1.0,
    )
    opt_configs = {"fixed": oc, "per-entity": oc}
    ckpt_dir = os.path.join(out_root, "checkpoints")
    os.makedirs(ckpt_dir, exist_ok=True)

    t_full = time.perf_counter()
    dataset = _synthetic_batch(
        rng, base_rows, np.arange(entities, dtype=np.int64), d_fe, d_re
    )
    state = incremental.full_fit(
        dataset, data_configs, opt_configs, task, seed=seed
    )
    full_fit_s = time.perf_counter() - t_full
    specs = incremental.scoring_specs(data_configs, state.entity_indices)
    engine = ServingEngine(
        ServingBundle.from_model(state.model, specs, task), max_batch=16
    )
    next_entity = entities
    round_records: List[Dict[str, object]] = []
    try:
        for r in range(rounds):
            churn = rng.choice(entities, size=min(churn_entities, entities),
                               replace=False)
            fresh = np.arange(next_entity,
                              next_entity + new_entities_per_round)
            next_entity += new_entities_per_round
            pool = np.concatenate([churn, fresh]).astype(np.int64)
            t_data = time.perf_counter()
            batch = _synthetic_batch(rng, batch_rows, pool, d_fe, d_re)
            dataset = concat_datasets(dataset, batch)
            result = incremental.incremental_fit(
                dataset, data_configs, opt_configs, task,
                prev=state, seed=seed, checkpoint_dir=ckpt_dir,
            )
            delta = build_delta_bundle(
                state, result.state,
                source=f"round-{r}", mode=result.plan.mode,
                delta_rows=result.plan.delta_rows,
                total_rows=result.plan.total_rows,
            )
            info = apply_delta(engine, delta)
            data_to_served_s = time.perf_counter() - t_data
            state = result.state
            round_records.append({
                "round": r,
                "mode": result.plan.mode,
                "delta": delta.manifest(),
                "incremental_fit_s": round(result.seconds, 4),
                "max_rel_diff": result.max_rel_diff,
                "generation": info["version"],
                "committed": bool(info["committed"]),
                "data_to_served_s": round(data_to_served_s, 4),
            })
            logger.info(
                "round %d: mode=%s delta_rows=%d/%d generation=%d "
                "data->served %.3fs",
                r, result.plan.mode, result.plan.delta_rows,
                result.plan.total_rows, info["version"], data_to_served_s,
            )
        provenance = dict(engine.bundle.provenance)
        metrics = engine.metrics()
    finally:
        engine.close()
        engine.bundle.release()
    summary = {
        "rounds": round_records,
        "full_fit_s": round(full_fit_s, 4),
        "batch_rows": int(batch_rows),
        "provenance": provenance,
        "bundle_deltas": metrics["bundle_deltas"],
        "plan": planner.plan_block(),
    }
    with open(os.path.join(out_root, "refresh-summary.json"), "w") as f:
        json.dump(summary, f, indent=2, default=str)
    return summary


def main(argv: Optional[List[str]] = None) -> None:
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=getattr(logging, args.logging_level.upper(), logging.INFO)
    )
    if not args.synthetic:
        raise SystemExit(
            "only --synthetic data is supported; production refresh loops "
            "drive game.incremental + serving.delta directly against their "
            "ingest (see the README 'Continuous refresh' runbook)"
        )
    out_root = args.root_output_directory
    os.makedirs(out_root, exist_ok=True)
    journal = telemetry.RunJournal(os.path.join(out_root, "journal.jsonl"))
    telemetry.install_journal(journal)
    try:
        summary = run_refresh_loop(
            out_root,
            rounds=args.rounds,
            base_rows=args.base_rows,
            batch_rows=args.batch_rows,
            entities=args.entities,
            new_entities_per_round=args.new_entities_per_round,
            churn_entities=args.churn_entities,
            task=args.training_task,
            seed=args.seed,
        )
    finally:
        telemetry.uninstall_journal()
        journal.close()
    served = [r["data_to_served_s"] for r in summary["rounds"]]
    logger.info(
        "refresh loop done: %d round(s), data->served %s s, summary at %s",
        len(served),
        [round(s, 3) for s in served],
        os.path.join(out_root, "refresh-summary.json"),
    )


if __name__ == "__main__":
    main()

"""Native-decoder assembly for `read_game_dataset` (block-level Avro ingest).

Mirrors photon-client's executor-parallel AvroDataReader
(AvroDataReader.scala:85-220) in spirit: the record decode runs in native
code over whole container blocks (photon_ml_tpu/native/avro_reader.cc) and
Python only assembles columns — index maps, CSR merges, ELL packing. Any
schema/feature the op-program compiler cannot express makes this module
return None and `read_game_dataset` stays on the pure-Python codec, so this
is strictly a fast path with identical results (tests assert parity on the
reference fixtures).
"""

from __future__ import annotations

from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.data.containers import pack_csr_to_ell
from photon_ml_tpu.data.game_dataset import GameDataset
from photon_ml_tpu.data.index_map import DELIMITER, IndexMap
from photon_ml_tpu.io import avro as avro_io
from photon_ml_tpu.native import avro_reader


def try_read_native(
    paths: Sequence[str],
    shard_configs,
    index_maps,
    id_tag_fields: Sequence[str],
    cols,
    label_fallback: str,
):
    """Native read of the given paths, or None (caller falls back)."""
    files: List[str] = []
    for p in paths:
        files.extend(avro_io.list_container_files(p))
    if not files:
        return None

    bag_names: List[str] = []
    for cfg in shard_configs.values():
        for b in cfg.feature_bags:
            if b not in bag_names:
                bag_names.append(b)

    decoded: List[avro_reader.DecodedFile] = []
    tag_slots: Optional[Tuple[str, ...]] = None
    for path in files:
        with open(path, "rb") as f:
            data = f.read()
        try:
            schema, codec, sync, body = avro_io.read_header(data, path)
        except (ValueError, KeyError):
            return None
        program = avro_reader.compile_program(
            schema,
            response=cols.response,
            fallback_label=label_fallback,
            offset=cols.offset,
            weight=cols.weight,
            uid=cols.uid,
            metadata_map=cols.metadata_map,
            bag_names=bag_names,
            tag_fields=tuple(id_tag_fields),
        )
        if program is None:
            return None
        if tag_slots is None:
            tag_slots = program.tag_slots
        elif tag_slots != program.tag_slots:
            return None
        out = avro_reader.decode_file_native(
            data, body, codec, sync, program, DELIMITER
        )
        if out is None:
            return None
        decoded.append(out)

    # ---- concatenate files; remap per-file interned keys to global ids ----
    n = sum(len(d.labels) for d in decoded)
    if n == 0:
        return None
    labels = np.concatenate([d.labels for d in decoded]).astype(np.float32)
    offsets = np.concatenate([d.offsets for d in decoded]).astype(np.float32)
    weights = np.concatenate([d.weights for d in decoded]).astype(np.float32)

    global_ids: Dict[str, int] = {}
    key_list: List[str] = []

    def _global(keys: List[str]) -> np.ndarray:
        out = np.empty(len(keys), np.int64)
        for i, k in enumerate(keys):
            g = global_ids.get(k)
            if g is None:
                g = len(key_list)
                global_ids[k] = g
                key_list.append(k)
            out[i] = g
        return out

    # Intern each file's key dictionary once (not once per bag).
    file_l2g = [_global(d.keys) for d in decoded]

    bag_rows: List[np.ndarray] = []
    bag_gkeys: List[np.ndarray] = []
    bag_vals: List[np.ndarray] = []
    for b in range(len(bag_names)):
        rows_parts, keys_parts, vals_parts = [], [], []
        row0 = 0
        for fi, d in enumerate(decoded):
            local_to_global = file_l2g[fi]
            counts = np.diff(d.bag_indptr[b])
            rows_parts.append(
                np.repeat(np.arange(len(counts), dtype=np.int64) + row0, counts)
            )
            keys_parts.append(
                local_to_global[d.bag_keys[b]] if len(d.bag_keys[b]) else
                np.empty(0, np.int64)
            )
            vals_parts.append(d.bag_vals[b])
            row0 += len(counts)
        bag_rows.append(np.concatenate(rows_parts) if rows_parts else np.empty(0, np.int64))
        bag_gkeys.append(np.concatenate(keys_parts) if keys_parts else np.empty(0, np.int64))
        bag_vals.append(np.concatenate(vals_parts) if vals_parts else np.empty(0, np.float32))

    # ---- id tags --------------------------------------------------------
    id_tags: Dict[str, np.ndarray] = {}
    all_tag_ids = np.concatenate([d.tag_ids for d in decoded], axis=0)
    val_tables = [np.asarray(d.tag_values + [""], dtype=object) for d in decoded]
    # Rebuild per-file segments to index each file's own value table.
    seg_starts = np.cumsum([0] + [len(d.labels) for d in decoded])
    for slot, tag in enumerate(tag_slots):
        parts = []
        for fi, d in enumerate(decoded):
            ids = d.tag_ids[:, slot]
            tbl = val_tables[fi]
            parts.append(tbl[np.where(ids >= 0, ids, len(tbl) - 1)])
        col = np.concatenate(parts)
        if tag == cols.uid:
            if bool((all_tag_ids[:, slot] >= 0).any()):
                from photon_ml_tpu.io.avro_data import UID

                id_tags[UID] = col.astype(str)
        else:
            id_tags[tag] = col.astype(str)

    # ---- per-shard merge, index maps, ELL pack --------------------------
    built: Dict[str, IndexMap] = {}
    shards = {}
    bag_index = {b: i for i, b in enumerate(bag_names)}
    key_arr = np.asarray(key_list, dtype=object)
    for shard, cfg in shard_configs.items():
        idxs = [bag_index[b] for b in cfg.feature_bags]
        rows = np.concatenate([bag_rows[i] for i in idxs])
        gkeys = np.concatenate([bag_gkeys[i] for i in idxs])
        vals = np.concatenate([bag_vals[i] for i in idxs])
        # Stable sort by record reproduces the Python path's order: bags in
        # config order, entries in record order within each bag.
        order = np.argsort(rows, kind="stable")
        rows, gkeys, vals = rows[order], gkeys[order], vals[order]

        if index_maps is not None and shard in index_maps:
            imap = index_maps[shard]
        else:
            uniq = np.unique(gkeys) if len(gkeys) else np.empty(0, np.int64)
            imap = IndexMap.from_feature_names(
                set(key_arr[uniq]), add_intercept=cfg.has_intercept
            )
        built[shard] = imap
        intercept_idx = imap.intercept_index
        if cfg.has_intercept and intercept_idx is None:
            raise ValueError(
                f"feature shard '{shard}' is configured with an intercept but "
                "the index map has no intercept entry — rebuild the index "
                "store with the intercept key or set has_intercept=False"
            )
        # gid -> index-map id (vectorized over unique gids only).
        uniq, inv = (
            np.unique(gkeys, return_inverse=True)
            if len(gkeys)
            else (np.empty(0, np.int64), np.empty(0, np.int64))
        )
        uniq_idx = np.asarray(
            [imap.get_index(k) for k in key_arr[uniq]], np.int64
        ) if len(uniq) else np.empty(0, np.int64)
        fidx = uniq_idx[inv] if len(gkeys) else np.empty(0, np.int64)
        keep = fidx >= 0
        rows_k, fidx_k, vals_k = rows[keep], fidx[keep], vals[keep]
        if cfg.has_intercept:
            rows_k = np.concatenate([rows_k, np.arange(n, dtype=np.int64)])
            fidx_k = np.concatenate([fidx_k, np.full(n, intercept_idx, np.int64)])
            vals_k = np.concatenate([vals_k, np.ones(n, np.float32)])
            order = np.argsort(rows_k, kind="stable")
            rows_k, fidx_k, vals_k = rows_k[order], fidx_k[order], vals_k[order]
        indptr = np.zeros(n + 1, np.int64)
        np.cumsum(np.bincount(rows_k, minlength=n), out=indptr[1:])
        shards[shard] = pack_csr_to_ell(
            indptr, fidx_k, vals_k.astype(np.float32), imap.size
        )

    ds = GameDataset.build(
        shards, labels, offsets=offsets, weights=weights, id_tags=id_tags
    )
    return ds, built

"""Native-decoder assembly for `read_game_dataset` (block-level Avro ingest).

Mirrors photon-client's executor-parallel AvroDataReader
(AvroDataReader.scala:85-220) in spirit: the record decode runs in native
code over whole container blocks (photon_ml_tpu/native/avro_reader.cc) and
Python only assembles columns — index maps, CSR merges, ELL packing. Any
schema/feature the op-program compiler cannot express makes this module
return None and `read_game_dataset` stays on the pure-Python codec, so this
is strictly a fast path with identical results (tests assert parity on the
reference fixtures).
"""

from __future__ import annotations

import logging
import os
import time
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.utils import faults, telemetry
from photon_ml_tpu.utils.knobs import get_knob

from photon_ml_tpu.data.containers import pack_csr_to_ell
from photon_ml_tpu.data.game_dataset import GameDataset, HostCSR
from photon_ml_tpu.data.index_map import DELIMITER, IndexMap
from photon_ml_tpu.io import avro as avro_io
from photon_ml_tpu.native import avro_reader
from photon_ml_tpu.utils.observability import (
    current_stage_registry,
    set_stage_note,
    stage_timer,
)

logger = logging.getLogger(__name__)


def _concat_parts(parts: Sequence[np.ndarray], empty_dtype) -> np.ndarray:
    """Concatenate per-file/per-chunk column parts. np.concatenate copies
    even for a single part; most reads are one container file, so skip
    the copy there."""
    if not len(parts):
        return np.empty(0, empty_dtype)
    return parts[0] if len(parts) == 1 else np.concatenate(parts)


def stream_ingest_enabled() -> bool:
    """Should ingest overlap decode of chunk k+1 with assembly of chunk k?

    PHOTON_STREAM_INGEST forces (1/0); empty = auto — on only when the
    host has more than one effective core, the same policy as every other
    host-parallel knob (a producer thread on a 1-core host only steals
    the consumer's core; the ORDER of assembly is file order either way,
    so streaming never changes results, only when work happens)."""
    env = str(get_knob("PHOTON_STREAM_INGEST")).strip().lower()
    if env in ("0", "false", "off", "no"):
        return False
    if env in ("1", "true", "on", "yes"):
        return True
    from photon_ml_tpu.data.pipeline import effective_host_parallelism

    return effective_host_parallelism() > 1


class _IngestAssembler:
    """Incremental per-file assembly of decoded columns.

    `add(d)` consumes one file's DecodedFile strictly in file order —
    interning its key dictionary into the global id space, remapping its
    bag keys, and stringifying its tag value table — i.e. the per-chunk
    host work that the streaming pipeline overlaps with the NEXT file's
    native decode. `finalize()` is the order-insensitive tail (big
    concatenations + the per-shard merge), identical whether the adds
    were interleaved with decode or ran after it, so streaming and
    monolithic ingest are bitwise-identical by construction.
    """

    def __init__(self, n_bags: int):
        self.n_bags = n_bags
        self.global_ids: Dict[str, int] = {}
        self.key_list: List[str] = []
        self.labels: List[np.ndarray] = []
        self.offsets: List[np.ndarray] = []
        self.weights: List[np.ndarray] = []
        self.bag_ip: List[List[np.ndarray]] = [[] for _ in range(n_bags)]
        self.bag_keys: List[List[np.ndarray]] = [[] for _ in range(n_bags)]
        self.bag_vals: List[List[np.ndarray]] = [[] for _ in range(n_bags)]
        self.tag_ids: List[np.ndarray] = []
        self.val_tables: List[np.ndarray] = []
        self.n = 0
        self.n_files = 0

    def _global(self, keys: List[str]) -> np.ndarray:
        out = np.empty(len(keys), np.int64)
        global_ids, key_list = self.global_ids, self.key_list
        for i, k in enumerate(keys):
            g = global_ids.get(k)
            if g is None:
                g = len(key_list)
                global_ids[k] = g
                key_list.append(k)
            out[i] = g
        return out

    def add(self, d: "avro_reader.DecodedFile") -> None:
        fi = self.n_files
        self.n_files += 1
        self.n += len(d.labels)
        self.labels.append(d.labels)
        self.offsets.append(d.offsets)
        self.weights.append(d.weights)
        # Intern the file's key dictionary once (not once per bag). The
        # first file's local ids ARE the global ids by construction — no
        # remap gather there.
        l2g = self._global(d.keys)
        for b in range(self.n_bags):
            self.bag_ip[b].append(d.bag_indptr[b])
            if not len(d.bag_keys[b]):
                self.bag_keys[b].append(np.empty(0, np.int64))
            elif fi == 0:
                self.bag_keys[b].append(d.bag_keys[b])  # identity (int32 ok)
            else:
                self.bag_keys[b].append(l2g[d.bag_keys[b]])
            self.bag_vals[b].append(d.bag_vals[b])
        self.tag_ids.append(d.tag_ids)
        self.val_tables.append(
            np.asarray([str(v) for v in d.tag_values] + [""], dtype=object)
        )

    def finalize(self):
        """Concatenate the per-file parts: (labels, offsets, weights,
        per-bag (indptr, global keys, values)). Single-file reads skip
        every copy, exactly like the monolithic path did."""
        _concat = _concat_parts
        labels = _concat(self.labels, np.float64).astype(np.float32, copy=False)
        offsets = _concat(self.offsets, np.float64).astype(
            np.float32, copy=False
        )
        weights = _concat(self.weights, np.float64).astype(
            np.float32, copy=False
        )
        bag_indptr: List[np.ndarray] = []
        bag_gkeys: List[np.ndarray] = []
        bag_vals: List[np.ndarray] = []
        for b in range(self.n_bags):
            if self.n_files == 1:
                bag_indptr.append(self.bag_ip[b][0])
                bag_gkeys.append(self.bag_keys[b][0])
                bag_vals.append(self.bag_vals[b][0])
                continue
            ip_parts = [np.zeros(1, np.int64)]
            off = 0
            for ip in self.bag_ip[b]:
                ip_parts.append(ip[1:] + off)
                off += int(ip[-1])
            bag_indptr.append(np.concatenate(ip_parts))
            bag_gkeys.append(_concat(self.bag_keys[b], np.int64))
            bag_vals.append(_concat(self.bag_vals[b], np.float32))
        return labels, offsets, weights, bag_indptr, bag_gkeys, bag_vals


def _stash_worthwhile(n_samples: int) -> bool:
    """Would the data-plane bucketed pack even consider this dataset? The
    gates live in pallas_sparse so ingest and pack cannot drift apart."""
    try:
        from photon_ml_tpu.ops import pallas_sparse

        return pallas_sparse.pack_worth_considering(n_samples)
    except Exception:
        return False


def try_read_native(
    paths: Sequence[str],
    shard_configs,
    index_maps,
    id_tag_fields: Sequence[str],
    cols,
    label_fallback: str,
):
    """Native read of the given paths, or None (caller falls back)."""
    files: List[str] = []
    for p in paths:
        files.extend(avro_io.list_container_files(p))
    if not files:
        return None

    bag_names: List[str] = []
    for cfg in shard_configs.values():
        for b in cfg.feature_bags:
            if b not in bag_names:
                bag_names.append(b)

    # Compile one program per file from its header alone; the heavy decode
    # then fans out across files on a thread pool — ctypes releases the GIL,
    # and each in-file decode additionally threads over container blocks, so
    # the TOTAL thread budget (pool width x per-file decode threads) stays
    # within the machine/env cap (the reference reads its mapred splits
    # executor-parallel the same way, AvroUtils.scala:47). Each task reads
    # its own file's bytes so peak memory holds pool-width files, not all.
    compiled = []
    tag_slots: Optional[Tuple[str, ...]] = None
    for path in files:
        # Header only: schema + codec + sync live in the first few KB; the
        # reader re-reads the whole file inside the decode task. A header
        # that straddles the probe boundary can parse with a silently
        # truncated sync marker — detect that and re-parse from the full
        # file rather than handing a short sync buffer to the native side.
        with open(path, "rb") as f:
            head = f.read(1 << 20)
        try:
            probe_miss = False
            try:
                schema, codec, sync, body = avro_io.read_header(head, path)
                probe_miss = len(sync) != 16 or body > len(head)
            except (ValueError, KeyError, IndexError):
                if len(head) < (1 << 20):  # whole file read: genuinely bad
                    return None
                probe_miss = True
            if probe_miss:
                # Header straddles the probe boundary (huge schema, or a
                # silently truncated sync marker): re-parse from the full
                # file before giving up on the native path.
                with open(path, "rb") as f:
                    head = f.read()
                schema, codec, sync, body = avro_io.read_header(head, path)
                if len(sync) != 16:
                    return None
        except (ValueError, KeyError, IndexError):
            return None
        program = avro_reader.compile_program(
            schema,
            response=cols.response,
            fallback_label=label_fallback,
            offset=cols.offset,
            weight=cols.weight,
            uid=cols.uid,
            metadata_map=cols.metadata_map,
            bag_names=bag_names,
            tag_fields=tuple(id_tag_fields),
        )
        if program is None:
            return None
        if tag_slots is None:
            tag_slots = program.tag_slots
        elif tag_slots != program.tag_slots:
            return None
        compiled.append((path, body, codec, sync, program))

    from photon_ml_tpu.data.pipeline import effective_host_parallelism

    # Affinity/cgroup-aware budget: on a 1-core host the file fan-out and
    # the per-file block threading both collapse to synchronous decode (a
    # thread pool on one core only adds contention — the same reasoning
    # that defers the background bucketed pack below).
    budget = avro_reader._default_threads() or effective_host_parallelism()
    # Worker threads record their decode walls into the SPAWNER's ingest
    # stage registry (stage scopes are thread-local, AsyncUploader-style)
    # — and their trace spans under the spawner's span via the same
    # handoff discipline, so photon-ingest-decode tracks parent correctly.
    stage_reg = current_stage_registry()
    span_h = telemetry.span_handoff()

    def _decode_one(c, n_threads):
        path, body, codec, sync, program = c

        def _attempt():
            # `decode` fault site + transient-I/O retries: the whole file is
            # re-read per attempt, so a torn read never leaks into a retry.
            faults.fault_point("decode")
            with open(path, "rb") as f:
                data = f.read()
            return avro_reader.decode_file_native(
                data, body, codec, sync, program, DELIMITER, n_threads=n_threads
            )

        t0 = time.perf_counter()
        try:
            with telemetry.adopt_span(span_h), telemetry.span(
                "decode_file", file=os.path.basename(path)
            ):
                return faults.retry(_attempt, label=f"avro decode {path}")
        except Exception:
            # Retries exhausted (or non-transient): degrade to the
            # synchronous pure-Python codec instead of killing the read —
            # the caller treats None as "native path unavailable".
            logger.warning(
                "native decode of %s failed; falling back to the Python "
                "codec",
                path,
                exc_info=True,
            )
            return None
        finally:
            if stage_reg is not None:
                stage_reg.record("decode", time.perf_counter() - t0)

    # One failed file means a full fallback to the Python codec, so stop
    # decoding as soon as a failure surfaces instead of paying for the
    # remaining files' native decode only to discard it.
    #
    # Streaming pipeline (tentpole, r09): files decode on a bounded-width
    # pool and the assembler consumes them IN FILE ORDER as they land —
    # interning/remap/tag work for file k overlaps the decode of file
    # k+1, and at most `width + 1` decoded files are ever resident (the
    # double-buffering discipline of data/pipeline.py applied to ingest).
    # The monolithic path (1 core, forced off, or a single file) decodes
    # then assembles; the assembler order is identical, so the results
    # are bitwise-equal — tests/test_streaming_ingest.py pins it.
    failed = False
    asm = _IngestAssembler(len(bag_names))
    streaming = stream_ingest_enabled() and len(compiled) > 1 and budget > 1
    if len(compiled) > 1 and budget > 1:
        from concurrent.futures import ThreadPoolExecutor

        width = min(budget, len(compiled))
        per_file = max(1, budget // width)

        def _guarded(c):
            nonlocal failed
            if failed:
                return None
            out = _decode_one(c, per_file)
            if out is None:
                failed = True
            return out

        with ThreadPoolExecutor(
            max_workers=width, thread_name_prefix="photon-ingest-decode"
        ) as pool:
            if streaming:
                from collections import deque

                queue = deque()
                pending = list(compiled)

                def _submit():
                    while pending and len(queue) <= width:
                        queue.append(pool.submit(_guarded, pending.pop(0)))

                _submit()
                while queue:
                    out = queue.popleft().result()
                    if out is None:
                        failed = True
                        break
                    _submit()
                    with stage_timer("assemble"):
                        asm.add(out)
            else:
                for out in pool.map(_guarded, compiled):
                    if out is None:
                        failed = True
                        break
                    with stage_timer("assemble"):
                        asm.add(out)
    else:
        for c in compiled:
            out = _decode_one(c, budget)
            if out is None:
                return None
            with stage_timer("assemble"):
                asm.add(out)
    if failed:
        return None
    set_stage_note("ingest_path", "native-stream" if streaming else "native")
    set_stage_note("chunks", str(asm.n_files))
    set_stage_note("streaming", "1" if streaming else "0")

    # ---- concatenate files; remap per-file interned keys to global ids ----
    n = asm.n
    if n == 0:
        return None
    _concat = _concat_parts

    with stage_timer("assemble"):
        (
            labels,
            offsets,
            weights,
            bag_indptr,
            bag_gkeys,
            bag_vals,
        ) = asm.finalize()
    key_list = asm.key_list

    # ---- id tags --------------------------------------------------------
    # Factorized form: per-file interned value tables merge into ONE sorted
    # global table; each tag column is then integer codes into it. The
    # string columns (id_tags) are a cheap table gather, and the codes +
    # table are kept on the dataset (tag_codes) so entity grouping
    # (build_random_effect_dataset) and scoring-time entity resolution sort
    # the SMALL value table instead of n_samples strings.
    t_tags = time.perf_counter()
    id_tags: Dict[str, np.ndarray] = {}
    tag_codes: Dict[str, tuple] = {}
    all_tag_ids = _concat(asm.tag_ids, np.int32)
    val_tables = asm.val_tables
    cat_tbl = np.concatenate(val_tables)
    guniq, ginv = np.unique(cat_tbl.astype(str), return_inverse=True)
    tbl_starts = np.cumsum([0] + [len(t) for t in val_tables])
    file_maps = [
        ginv[tbl_starts[fi] : tbl_starts[fi + 1]]
        for fi in range(asm.n_files)
    ]
    for slot, tag in enumerate(tag_slots):
        code_parts = []
        for fi, ids_f in enumerate(asm.tag_ids):
            ids = ids_f[:, slot]
            fmap = file_maps[fi]
            code_parts.append(fmap[np.where(ids >= 0, ids, len(fmap) - 1)])
        codes = _concat(code_parts, np.int64).astype(np.int64, copy=False)
        col = guniq[codes]
        if tag == cols.uid:
            if bool((all_tag_ids[:, slot] >= 0).any()):
                from photon_ml_tpu.io.avro_data import UID

                id_tags[UID] = col
                tag_codes[UID] = (codes, guniq)
        else:
            id_tags[tag] = col
            tag_codes[tag] = (codes, guniq)
    if stage_reg is not None:
        stage_reg.record("tags", time.perf_counter() - t_tags)

    # ---- per-shard merge, index maps, ELL pack --------------------------
    built: Dict[str, IndexMap] = {}
    shards = {}
    host_csr: Dict[str, HostCSR] = {}
    host_ell: Dict[str, tuple] = {}
    bag_index = {b: i for i, b in enumerate(bag_names)}
    key_arr = np.asarray(key_list, dtype=object)
    stash_ok = _stash_worthwhile(n)
    for shard, cfg in shard_configs.items():
        idxs = [bag_index[b] for b in cfg.feature_bags]
        single_bag = len(idxs) == 1
        if single_bag:
            indptr = bag_indptr[idxs[0]]
            gkeys = bag_gkeys[idxs[0]]
            vals = bag_vals[idxs[0]]
        else:
            # Multi-bag union: expand row ids, stable sort by record to
            # reproduce the Python path's order (bags in config order,
            # entries in record order within each bag).
            rows = np.concatenate(
                [
                    np.repeat(
                        np.arange(n, dtype=np.int64), np.diff(bag_indptr[i])
                    )
                    for i in idxs
                ]
            )
            gkeys = np.concatenate([bag_gkeys[i] for i in idxs])
            vals = np.concatenate([bag_vals[i] for i in idxs])
            order = np.argsort(rows, kind="stable")
            rows, gkeys, vals = rows[order], gkeys[order], vals[order]
            indptr = np.zeros(n + 1, np.int64)
            np.cumsum(np.bincount(rows, minlength=n), out=indptr[1:])
        # The decoder ACCUMULATES in-record duplicate keys at decode time
        # (avro_reader.cc dedup_row), so single-bag shards are always clean;
        # a record can still repeat a key ACROSS bags, so the multi-bag
        # merge keeps the duplicate pass in pack_csr_to_ell.
        clean = single_bag

        # gids are dense interned ints, so "which keys appear in this shard"
        # is a bincount mask and gid -> index-map id is one LUT gather — no
        # np.unique / argsort over the nnz entries anywhere on this path.
        present = (
            np.bincount(gkeys, minlength=len(key_list)).astype(bool)
            if len(gkeys)
            else np.zeros(len(key_list), bool)
        )
        present_gids = np.nonzero(present)[0]
        from_data = index_maps is None or shard not in index_maps
        if from_data:
            imap = IndexMap.from_feature_names(
                set(key_arr[present_gids]), add_intercept=cfg.has_intercept
            )
        else:
            imap = index_maps[shard]
        built[shard] = imap
        intercept_idx = imap.intercept_index
        if cfg.has_intercept and intercept_idx is None:
            raise ValueError(
                f"feature shard '{shard}' is configured with an intercept but "
                "the index map has no intercept entry — rebuild the index "
                "store with the intercept key or set has_intercept=False"
            )
        # int32 LUT: the native ELL fill consumes int32 ids without a
        # conversion copy (feature spaces are < 2^31 by construction).
        lut = np.full(len(key_list) + 1, -1, np.int32)
        for gid in present_gids:
            lut[gid] = imap.get_index(key_arr[gid])
        fidx_k = lut[gkeys] if len(gkeys) else np.empty(0, np.int32)
        vals_k = vals.astype(np.float32, copy=False)
        if not from_data:
            # Supplied maps (scoring / multi-host) may not cover every key:
            # drop unmapped entries, shifting the CSR boundaries in one
            # cumsum — no row-id expansion.
            keep = fidx_k >= 0
            if not keep.all():
                cs = np.zeros(len(keep) + 1, np.int64)
                np.cumsum(keep, out=cs[1:])
                indptr = cs[indptr]
                fidx_k = fidx_k[keep]
                vals_k = vals_k[keep]
        # Intercept: appended as one constant ELL column unless the data
        # itself carries the intercept key (then the CSR rebuild + re-sort
        # keeps the dedupe semantics of the Python path).
        extra_col = None
        if cfg.has_intercept:
            if clean and not np.any(fidx_k == intercept_idx):
                extra_col = (intercept_idx, 1.0)
            else:
                rows_k = np.repeat(np.arange(n, dtype=np.int64), np.diff(indptr))
                rows_k = np.concatenate([rows_k, np.arange(n, dtype=np.int64)])
                fidx_k = np.concatenate(
                    [fidx_k.astype(np.int64), np.full(n, intercept_idx, np.int64)]
                )
                vals_k = np.concatenate([vals_k, np.ones(n, np.float32)])
                order = np.argsort(rows_k, kind="stable")
                rows_k, fidx_k, vals_k = rows_k[order], fidx_k[order], vals_k[order]
                clean = False
                indptr = np.zeros(n + 1, np.int64)
                np.cumsum(np.bincount(rows_k, minlength=n), out=indptr[1:])
        with stage_timer("ell"):
            shards[shard], host_planes = pack_csr_to_ell(
                indptr,
                fidx_k,
                vals_k,
                imap.size,
                assume_clean=clean,
                extra_col=extra_col,
                return_host=True,
                device=False,  # ShardDict uploads on first device use
            )
        host_ell[shard] = host_planes
        # Stash the host CSR (entry order is irrelevant to the bucketed
        # pack — it re-sorts by segment) so the data-plane sparse pack runs
        # from host arrays with no device round trip. Stash only when a pack
        # could actually engage (backend + size gates) — otherwise it would
        # pin ~12 bytes/nnz of host RAM with no consumer. Row-id expansion
        # and the intercept column are deferred to HostCSR.to_coo(), so the
        # ingest path never pays the COO concatenation.
        if stash_ok:
            t_stash = time.perf_counter()
            host_csr[shard] = HostCSR(
                indptr, fidx_k, vals_k, imap.size, extra_col
            )
            # Kick the host-side bucketed pack off NOW on a background
            # thread (the native counting sort releases the GIL): it
            # overlaps the remaining shards, tag assembly, device uploads
            # and the estimator's prepare, so the first consuming
            # coordinate pays only the join remainder + one upload
            # (VERDICT r04 item 6 — the layout is built in the data plane,
            # as the reference builds its partition layout at dataset
            # construction, RandomEffectDataset.scala:229-264). On a
            # 1-core host begin_pack_async itself DEFERS (no thread): the
            # "background" pack would steal ingest's only core — the
            # measured cause of the r05 4.5x e2e-vs-micro ingest gap —
            # and the pack runs synchronously at first consumption
            # instead, attributed to the `pack` stage.
            try:
                from photon_ml_tpu.ops import pallas_sparse

                pallas_sparse.begin_pack_async(host_csr[shard], n)
            except Exception:
                pass
            if stage_reg is not None:
                stage_reg.record("stash", time.perf_counter() - t_stash)

    ds = GameDataset.build(
        shards, labels, offsets=offsets, weights=weights, id_tags=id_tags
    )
    ds.host_csr = host_csr
    ds.host_ell = host_ell
    ds.tag_codes = tag_codes
    return ds, built

"""Pure-Python reader for PalDB v1 stores — the reference's off-heap
feature-index format.

The reference distributes feature index maps as PalDB partitions
(`paldb-partition-<shard>-<n>.dat`, written by PalDBIndexMapBuilder.scala:27
via `com.linkedin.paldb:paldb:1.1.0` and read back memory-mapped by
PalDBIndexMap.scala:43-118). Each store holds BOTH directions — feature name
→ integer id AND id → name (PalDBIndexMapBuilder.put:59-62) — and a
multi-partition map offsets each partition's internal ids by the cumulative
`size/2` of its predecessors (PalDBIndexMap.load:88-96).

This module decodes the on-disk format (reverse-engineered from the
reference's own fixture stores and validated against their known contents;
see tests/test_paldb.py):

    writeUTF("PALDB_V1") | long timestamp | int keyCount
    int keyLengthCount | int maxKeyLength
    per serialized-key-length: int keyLength, int keyCount, int slotCount,
        int slotSize, int indexOffset, long dataOffset
    long indexGlobalOffset | long dataGlobalOffset
    ... index section: per length, slotCount slots of
        [serialized key (keyLength bytes)][LSB base-128 varint data offset,
         zero-padded to slotSize]  (all-zero key bytes = empty slot)
    ... data section: per length group, a reserved 0x00 at offset 0, then
        entries [varint length][serialized value]

Value/key serialization (the subset PalDB's index maps use):
    int:    codes 0x05..0x0D encode 0..8 directly; 0x0E + raw byte encodes
            9..254; 0x10 + LSB base-128 varint encodes larger values
    string: 'g' + varint(byteCount) + utf-8 bytes (feature keys carry the
            reference's embedded name/term delimiter \x01, trailing for
            empty terms)

Only whole-store loading is implemented (the framework keeps index maps
in-memory / in its own mmap store); random access hashing is unnecessary.
"""

from __future__ import annotations

import os
import re
import struct
from typing import Dict, List, Optional, Tuple, Union

MAGIC = "PALDB_V1"

Key = Union[int, str]


def _read_varint(b: bytes, pos: int) -> Tuple[int, int]:
    """LSB base-128 varint (high bit = continuation)."""
    shift = 0
    out = 0
    while True:
        c = b[pos]
        pos += 1
        out |= (c & 0x7F) << shift
        if not c & 0x80:
            return out, pos
        shift += 7


def _decode(b: bytes, pos: int) -> Tuple[Key, int]:
    """Decode one serialized key/value at `pos`."""
    c = b[pos]
    if 0x05 <= c <= 0x0D:
        return c - 5, pos + 1
    if c == 0x0E:
        return b[pos + 1], pos + 2
    if c == 0x10:
        return _read_varint(b, pos + 1)
    if c == ord("g"):
        n, pos = _read_varint(b, pos + 1)
        s = b[pos : pos + n].decode("utf-8")
        return s, pos + n
    raise ValueError(f"unsupported PalDB serialization code 0x{c:02x} at {pos}")


def read_store(path: str) -> Dict[Key, Key]:
    """Load every (key, value) pair of one PalDB partition file."""
    with open(path, "rb") as f:
        b = f.read()
    ulen = struct.unpack(">H", b[:2])[0]
    if b[2 : 2 + ulen].decode() != MAGIC:
        raise ValueError(f"{path}: not a {MAGIC} store")
    off = 2 + ulen + 8  # skip timestamp
    key_count, klc, _max_kl = struct.unpack(">iii", b[off : off + 12])
    off += 12
    entries = []
    for _ in range(klc):
        kl, kc, slots, slot_size, idx_off = struct.unpack(">iiiii", b[off : off + 20])
        off += 20
        data_off = struct.unpack(">q", b[off : off + 8])[0]
        off += 8
        entries.append((kl, kc, slots, slot_size, idx_off, data_off))
    idx_abs, data_abs = struct.unpack(">qq", b[off : off + 16])

    out: Dict[Key, Key] = {}
    for kl, kc, slots, slot_size, idx_off, data_off in entries:
        base = idx_abs + idx_off
        group = data_abs + data_off
        found = 0
        for s in range(slots):
            slot = b[base + s * slot_size : base + (s + 1) * slot_size]
            if not any(slot[:kl]):
                continue  # empty slot
            key, _ = _decode(slot, 0)
            rel, _ = _read_varint(slot, kl)
            vlen, vpos = _read_varint(b, group + rel)
            value, _ = _decode(b, vpos)
            out[key] = value
            found += 1
        if found != kc:
            raise ValueError(
                f"{path}: key-length {kl} group decoded {found} of {kc} keys"
            )
    if len(out) != key_count:
        raise ValueError(f"{path}: decoded {len(out)} of {key_count} keys")
    return out


# ---------------------------------------------------------------- writer
#
# The write side of the same format, so index stores built by this framework
# are loadable by the reference's PalDBIndexMap (PalDBIndexMap.scala:43-118
# via com.linkedin.paldb:paldb:1.1.0) — closing the one remaining one-way
# format door (the reader above has consumed the reference's stores since
# r2). Layout facts were reverse-engineered from the reference's own fixture
# stores and are byte-validated in tests/test_paldb.py:
#
#   * slot placement: murmur3_32(keyBytes, seed=42) & 0x7FFFFFFF, modulo the
#     group's slot count, linear probing in insertion order (verified against
#     all 30k keys of the GameIntegTest shard1 store);
#   * slots per group: Math.round(keyCount / 0.75);
#   * slotSize: keyLength + byte length of the largest data-offset varint in
#     the group;
#   * per-group data streams start with one reserved 0x00 so offset 0 never
#     addresses a real entry; entries are [varint valueLen][value bytes] in
#     insertion order;
#   * int serialization: 0x05+v for 0..8, 0x0E + raw byte for 9..254,
#     0x10 + LSB varint for >=255 (all three observed in the fixtures).


def _murmur3_32(data: bytes, seed: int = 42) -> int:
    """MurmurHash3 x86 32-bit — PalDB's HashUtils slot hash (seed 42)."""
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = seed
    n = len(data)
    rounded = n - (n % 4)
    for i in range(0, rounded, 4):
        k = int.from_bytes(data[i : i + 4], "little")
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
        h = ((h << 13) | (h >> 19)) & 0xFFFFFFFF
        h = (h * 5 + 0xE6546B64) & 0xFFFFFFFF
    k = 0
    tail = data[rounded:]
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h


def _write_varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _encode(key: Key) -> bytes:
    if isinstance(key, bool):
        raise TypeError("bool is not a PalDB index-map key type")
    if isinstance(key, int):
        if key < 0:
            raise ValueError("negative ids are not used by index maps")
        if key <= 8:
            return bytes([0x05 + key])
        if key <= 254:
            return bytes([0x0E, key])
        return bytes([0x10]) + _write_varint(key)
    b = str(key).encode("utf-8")
    return bytes([ord("g")]) + _write_varint(len(b)) + b


def java_string_hash(s: str) -> int:
    """java.lang.String.hashCode over UTF-16 code units, wrapped to int32.

    PalDBIndexMap routes lookups with `new HashPartitioner(n)` on the raw
    key string (PalDBIndexMap.scala:79,145-151), so multi-partition writes
    must split features exactly this way.
    """
    h = 0
    units = s.encode("utf-16-be")
    for i in range(0, len(units), 2):
        h = (31 * h + int.from_bytes(units[i : i + 2], "big")) & 0xFFFFFFFF
    return h - 0x100000000 if h >= 0x80000000 else h


def _nonneg_mod(x: int, n: int) -> int:
    r = x % n
    return r + n if r < 0 else r


def java_partition(s: str, n: int) -> int:
    """Spark HashPartitioner.getPartition: non-negative hashCode mod n."""
    return _nonneg_mod(java_string_hash(s), n)


def write_store(
    path: str,
    entries,
    timestamp_ms: Optional[int] = None,
) -> None:
    """Write one PalDB v1 partition file from (key, value) pairs in
    insertion order (the order defines both data layout and linear-probe
    displacement, matching paldb's StorageWriter)."""
    groups: Dict[int, dict] = {}
    total = 0
    for k, v in entries:
        kb = _encode(k)
        vb = _encode(v)
        g = groups.setdefault(
            len(kb), {"keys": [], "data": bytearray(b"\x00")}
        )
        rel = len(g["data"])
        g["data"] += _write_varint(len(vb)) + vb
        g["keys"].append((kb, rel))
        total += 1

    if timestamp_ms is None:
        import time

        timestamp_ms = int(time.time() * 1000)

    kls = sorted(groups)
    # Per group: slots = Math.round(count / 0.75); slotSize = keyLength +
    # widest offset varint; place keys by murmur hash with linear probing.
    index_blobs = []
    data_blobs = []
    table = []
    idx_off = 0
    data_off = 0
    for kl in kls:
        g = groups[kl]
        count = len(g["keys"])
        slots = int(count / 0.75 + 0.5)  # Java Math.round
        slot_size = kl + max(len(_write_varint(rel)) for _, rel in g["keys"])
        blob = bytearray(slots * slot_size)
        for kb, rel in g["keys"]:
            s = (_murmur3_32(kb) & 0x7FFFFFFF) % slots
            for _ in range(slots):
                start = s * slot_size
                if not any(blob[start : start + kl]):
                    blob[start : start + kl] = kb
                    off_bytes = _write_varint(rel)
                    blob[start + kl : start + kl + len(off_bytes)] = off_bytes
                    break
                s = (s + 1) % slots
            else:
                raise RuntimeError("hash table overflow (corrupt slot count)")
        table.append((kl, count, slots, slot_size, idx_off, data_off))
        index_blobs.append(bytes(blob))
        data_blobs.append(bytes(g["data"]))
        idx_off += len(blob)
        data_off += len(g["data"])

    out = bytearray()
    magic = MAGIC.encode()
    out += struct.pack(">H", len(magic)) + magic
    out += struct.pack(">q", timestamp_ms)
    out += struct.pack(">iii", total, len(kls), max(kls) if kls else 0)
    for kl, count, slots, slot_size, io_, do in table:
        out += struct.pack(">iiiii", kl, count, slots, slot_size, io_)
        out += struct.pack(">q", do)
    header_len = len(out) + 16
    out += struct.pack(">qq", header_len, header_len + idx_off)
    for blob in index_blobs:
        out += blob
    for blob in data_blobs:
        out += blob
    with open(path, "wb") as f:
        f.write(bytes(out))


def write_index_map(
    store_dir: str,
    shard: str,
    feature_names,
    num_partitions: int = 1,
) -> Dict[str, int]:
    """Build PalDB partition stores for a feature set, returning the
    name -> global id mapping the layout defines.

    Mirrors FeatureIndexingDriver's structure (partition by the key
    string's Java hashCode mod n — FeatureIndexingDriver.scala:251 via
    HashPartitioner — local ids 0.. within each partition in insertion
    order, global id = local + cumulative predecessor sizes as
    PalDBIndexMap.load:88-96 reconstructs) but with a DETERMINISTIC
    insertion order (sorted feature keys) instead of Spark shuffle order.
    Keys are stored in the reference's name+DELIMITER+term form (trailing
    delimiter for empty terms).
    """
    from photon_ml_tpu.data.index_map import DELIMITER

    os.makedirs(store_dir, exist_ok=True)
    parts: List[List[str]] = [[] for _ in range(num_partitions)]
    for key in feature_names:
        stored = key if DELIMITER in key else key + DELIMITER
        parts[java_partition(stored, num_partitions)].append(stored)

    mapping: Dict[str, int] = {}
    offset = 0
    for pid, keys in enumerate(parts):
        keys.sort()
        entries = []
        for local, stored in enumerate(keys):
            entries.append((stored, local))
            entries.append((local, stored))
        write_store(
            os.path.join(store_dir, f"paldb-partition-{shard}-{pid}.dat"),
            entries,
        )
        from photon_ml_tpu.data.index_map import feature_key

        for local, stored in enumerate(keys):
            n_, _, t_ = stored.partition(DELIMITER)
            mapping[feature_key(n_, t_)] = local + offset
        offset += len(keys)
    return mapping


def lookup(path_bytes: bytes, key: Key) -> Optional[Key]:
    """Emulate paldb StorageReader.get(): hash -> slot -> linear probe ->
    data offset -> value. Used by tests to certify that stores written by
    `write_store` resolve every key the way the reference's reader would."""
    b = path_bytes
    ulen = struct.unpack(">H", b[:2])[0]
    off = 2 + ulen + 8
    key_count, klc, _ = struct.unpack(">iii", b[off : off + 12])
    off += 12
    table = []
    for _ in range(klc):
        kl, kc, slots, ss, io_ = struct.unpack(">iiiii", b[off : off + 20])
        off += 20
        do = struct.unpack(">q", b[off : off + 8])[0]
        off += 8
        table.append((kl, kc, slots, ss, io_, do))
    ia, da = struct.unpack(">qq", b[off : off + 16])
    kb = _encode(key)
    for kl, kc, slots, ss, io_, do in table:
        if kl != len(kb):
            continue
        base = ia + io_
        s = (_murmur3_32(kb) & 0x7FFFFFFF) % slots
        for _ in range(slots):
            slot = b[base + s * ss : base + (s + 1) * ss]
            if not any(slot[:kl]):
                return None  # empty slot terminates the probe
            if bytes(slot[:kl]) == kb:
                rel, _ = _read_varint(slot, kl)
                vlen, vpos = _read_varint(b, da + do + rel)
                value, _ = _decode(b, vpos)
                return value
            s = (s + 1) % slots
        return None
    return None


def partition_files(store_dir: str, shard: str) -> List[str]:
    """The shard's partition files in partition order
    (PalDBIndexMapLoader's `paldb-partition-<shard>-<n>.dat`).

    Matching is exact on the shard name with a strictly numeric partition
    suffix — a glob would let shard 'global' swallow 'global-v2' partitions
    (corrupting the id space via wrong offsets) or trip over stray
    non-numeric .dat files."""
    pat = re.compile(rf"paldb-partition-{re.escape(shard)}-(\d+)\.dat$")
    if not os.path.isdir(store_dir):
        return []
    matches = []
    for name in os.listdir(store_dir):
        m = pat.fullmatch(name)
        if m:
            matches.append((int(m.group(1)), os.path.join(store_dir, name)))
    return [p for _, p in sorted(matches)]


def resolve_offheap_index_maps(store_dir: str, shards):
    """Per-shard index maps from an off-heap store directory, auto-detecting
    the reference's PalDB partitions vs this framework's PHIDX partitions
    (prepareFeatureMaps, GameDriver.scala:231-236). Shared by the training
    and scoring drivers so format detection cannot drift between them."""
    from photon_ml_tpu.native.index_store import PartitionedIndexStore

    out = {}
    for shard in shards:
        if partition_files(store_dir, shard):
            out[shard] = load_index_map(store_dir, shard)
        else:
            out[shard] = PartitionedIndexStore(store_dir, shard)
    return out


def load_index_map(store_dir: str, shard: str):
    """Load a shard's PalDB partitions into an in-memory IndexMap.

    Mirrors PalDBIndexMap.load:88-96: partition i's internal ids are
    offset by the cumulative size/2 of partitions 0..i-1, making global ids
    unique. Both stored directions are cross-checked.
    """
    from photon_ml_tpu.data.index_map import IndexMap

    paths = partition_files(store_dir, shard)
    if not paths:
        raise FileNotFoundError(
            f"no paldb-partition-{shard}-*.dat files under {store_dir}"
        )
    name_to_id: Dict[str, int] = {}
    offset = 0
    for p in paths:
        store = read_store(p)
        id_to_name = {k: v for k, v in store.items() if isinstance(k, int)}
        names = {k: v for k, v in store.items() if isinstance(k, str)}
        if len(id_to_name) != len(names):
            raise ValueError(f"{p}: asymmetric id/name entries")
        from photon_ml_tpu.data.index_map import DELIMITER, feature_key

        for name, internal in names.items():
            # Cross-check the reverse direction the builder wrote.
            if id_to_name.get(internal) != name:
                raise ValueError(f"{p}: id->name mismatch for {name!r}")
            # Reference keys are always name+DELIMITER+term (trailing
            # delimiter for empty terms); normalize to this framework's
            # feature_key convention (bare name when the term is empty).
            n_, _, t_ = name.partition(DELIMITER)
            name_to_id[feature_key(n_, t_)] = internal + offset
        offset += len(names)
    return IndexMap(name_to_id)

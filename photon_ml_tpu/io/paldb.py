"""Pure-Python reader for PalDB v1 stores — the reference's off-heap
feature-index format.

The reference distributes feature index maps as PalDB partitions
(`paldb-partition-<shard>-<n>.dat`, written by PalDBIndexMapBuilder.scala:27
via `com.linkedin.paldb:paldb:1.1.0` and read back memory-mapped by
PalDBIndexMap.scala:43-118). Each store holds BOTH directions — feature name
→ integer id AND id → name (PalDBIndexMapBuilder.put:59-62) — and a
multi-partition map offsets each partition's internal ids by the cumulative
`size/2` of its predecessors (PalDBIndexMap.load:88-96).

This module decodes the on-disk format (reverse-engineered from the
reference's own fixture stores and validated against their known contents;
see tests/test_paldb.py):

    writeUTF("PALDB_V1") | long timestamp | int keyCount
    int keyLengthCount | int maxKeyLength
    per serialized-key-length: int keyLength, int keyCount, int slotCount,
        int slotSize, int indexOffset, long dataOffset
    long indexGlobalOffset | long dataGlobalOffset
    ... index section: per length, slotCount slots of
        [serialized key (keyLength bytes)][LSB base-128 varint data offset,
         zero-padded to slotSize]  (all-zero key bytes = empty slot)
    ... data section: per length group, a reserved 0x00 at offset 0, then
        entries [varint length][serialized value]

Value/key serialization (the subset PalDB's index maps use):
    int:    codes 0x05..0x0D encode 0..8 directly; 0x0E + raw byte encodes
            9..254; 0x10 + LSB base-128 varint encodes larger values
    string: 'g' + varint(byteCount) + utf-8 bytes (feature keys carry the
            reference's embedded name/term delimiter \x01, trailing for
            empty terms)

Only whole-store loading is implemented (the framework keeps index maps
in-memory / in its own mmap store); random access hashing is unnecessary.
"""

from __future__ import annotations

import os
import re
import struct
from typing import Dict, List, Tuple, Union

MAGIC = "PALDB_V1"

Key = Union[int, str]


def _read_varint(b: bytes, pos: int) -> Tuple[int, int]:
    """LSB base-128 varint (high bit = continuation)."""
    shift = 0
    out = 0
    while True:
        c = b[pos]
        pos += 1
        out |= (c & 0x7F) << shift
        if not c & 0x80:
            return out, pos
        shift += 7


def _decode(b: bytes, pos: int) -> Tuple[Key, int]:
    """Decode one serialized key/value at `pos`."""
    c = b[pos]
    if 0x05 <= c <= 0x0D:
        return c - 5, pos + 1
    if c == 0x0E:
        return b[pos + 1], pos + 2
    if c == 0x10:
        return _read_varint(b, pos + 1)
    if c == ord("g"):
        n, pos = _read_varint(b, pos + 1)
        s = b[pos : pos + n].decode("utf-8")
        return s, pos + n
    raise ValueError(f"unsupported PalDB serialization code 0x{c:02x} at {pos}")


def read_store(path: str) -> Dict[Key, Key]:
    """Load every (key, value) pair of one PalDB partition file."""
    with open(path, "rb") as f:
        b = f.read()
    ulen = struct.unpack(">H", b[:2])[0]
    if b[2 : 2 + ulen].decode() != MAGIC:
        raise ValueError(f"{path}: not a {MAGIC} store")
    off = 2 + ulen + 8  # skip timestamp
    key_count, klc, _max_kl = struct.unpack(">iii", b[off : off + 12])
    off += 12
    entries = []
    for _ in range(klc):
        kl, kc, slots, slot_size, idx_off = struct.unpack(">iiiii", b[off : off + 20])
        off += 20
        data_off = struct.unpack(">q", b[off : off + 8])[0]
        off += 8
        entries.append((kl, kc, slots, slot_size, idx_off, data_off))
    idx_abs, data_abs = struct.unpack(">qq", b[off : off + 16])

    out: Dict[Key, Key] = {}
    for kl, kc, slots, slot_size, idx_off, data_off in entries:
        base = idx_abs + idx_off
        group = data_abs + data_off
        found = 0
        for s in range(slots):
            slot = b[base + s * slot_size : base + (s + 1) * slot_size]
            if not any(slot[:kl]):
                continue  # empty slot
            key, _ = _decode(slot, 0)
            rel, _ = _read_varint(slot, kl)
            vlen, vpos = _read_varint(b, group + rel)
            value, _ = _decode(b, vpos)
            out[key] = value
            found += 1
        if found != kc:
            raise ValueError(
                f"{path}: key-length {kl} group decoded {found} of {kc} keys"
            )
    if len(out) != key_count:
        raise ValueError(f"{path}: decoded {len(out)} of {key_count} keys")
    return out


def partition_files(store_dir: str, shard: str) -> List[str]:
    """The shard's partition files in partition order
    (PalDBIndexMapLoader's `paldb-partition-<shard>-<n>.dat`).

    Matching is exact on the shard name with a strictly numeric partition
    suffix — a glob would let shard 'global' swallow 'global-v2' partitions
    (corrupting the id space via wrong offsets) or trip over stray
    non-numeric .dat files."""
    pat = re.compile(rf"paldb-partition-{re.escape(shard)}-(\d+)\.dat$")
    if not os.path.isdir(store_dir):
        return []
    matches = []
    for name in os.listdir(store_dir):
        m = pat.fullmatch(name)
        if m:
            matches.append((int(m.group(1)), os.path.join(store_dir, name)))
    return [p for _, p in sorted(matches)]


def resolve_offheap_index_maps(store_dir: str, shards):
    """Per-shard index maps from an off-heap store directory, auto-detecting
    the reference's PalDB partitions vs this framework's PHIDX partitions
    (prepareFeatureMaps, GameDriver.scala:231-236). Shared by the training
    and scoring drivers so format detection cannot drift between them."""
    from photon_ml_tpu.native.index_store import PartitionedIndexStore

    out = {}
    for shard in shards:
        if partition_files(store_dir, shard):
            out[shard] = load_index_map(store_dir, shard)
        else:
            out[shard] = PartitionedIndexStore(store_dir, shard)
    return out


def load_index_map(store_dir: str, shard: str):
    """Load a shard's PalDB partitions into an in-memory IndexMap.

    Mirrors PalDBIndexMap.load:88-96: partition i's internal ids are
    offset by the cumulative size/2 of partitions 0..i-1, making global ids
    unique. Both stored directions are cross-checked.
    """
    from photon_ml_tpu.data.index_map import IndexMap

    paths = partition_files(store_dir, shard)
    if not paths:
        raise FileNotFoundError(
            f"no paldb-partition-{shard}-*.dat files under {store_dir}"
        )
    name_to_id: Dict[str, int] = {}
    offset = 0
    for p in paths:
        store = read_store(p)
        id_to_name = {k: v for k, v in store.items() if isinstance(k, int)}
        names = {k: v for k, v in store.items() if isinstance(k, str)}
        if len(id_to_name) != len(names):
            raise ValueError(f"{p}: asymmetric id/name entries")
        from photon_ml_tpu.data.index_map import DELIMITER, feature_key

        for name, internal in names.items():
            # Cross-check the reverse direction the builder wrote.
            if id_to_name.get(internal) != name:
                raise ValueError(f"{p}: id->name mismatch for {name!r}")
            # Reference keys are always name+DELIMITER+term (trailing
            # delimiter for empty terms); normalize to this framework's
            # feature_key convention (bare name when the term is empty).
            n_, _, t_ = name.partition(DELIMITER)
            name_to_id[feature_key(n_, t_)] = internal + offset
        offset += len(names)
    return IndexMap(name_to_id)

"""Score persistence: ScoredItem <-> ScoringResultAvro.

Counterpart of photon-client data/avro/ScoreProcessingUtils.scala:29-88 and
cli/game/scoring/ScoredItem.scala:37. Scores are written as one Avro
container directory of ScoringResultAvro records (the GameScoringDriver's
saveScoresToHDFS output format, GameScoringDriver.scala:229-260).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

from photon_ml_tpu.io import avro as avro_io
from photon_ml_tpu.io import schemas


@dataclasses.dataclass
class ScoredItem:
    """One scored datum (ScoredItem.scala:37)."""

    prediction_score: float
    uid: Optional[str] = None
    label: Optional[float] = None
    weight: Optional[float] = None
    ids: Dict[str, str] = dataclasses.field(default_factory=dict)


def score_records(
    scores,
    model_id: str,
    *,
    uids: Optional[Sequence] = None,
    labels=None,
    weights=None,
    id_tags: Optional[Dict[str, Sequence]] = None,
    chunk_size: int = 65536,
) -> Iterator[dict]:
    """ScoringResultAvro record stream in fixed-size chunks.

    Column inputs may be numpy arrays, plain sequences, OR device (jax)
    arrays: each chunk is sliced and converted independently, so a
    large scoring job never materializes a full host copy of any column
    (the former `uids.tolist()` built an n-element Python string list up
    front) and device columns transfer chunk by chunk. Shared by the
    offline scoring driver (cli/score.py) and the online replay driver
    (cli/serve.py)."""
    n = len(scores)
    step = max(1, chunk_size)
    for lo in range(0, n, step):
        hi = min(lo + step, n)
        sc = np.asarray(scores[lo:hi], np.float64)
        uc = None if uids is None else uids[lo:hi]
        lc = None if labels is None else np.asarray(labels[lo:hi], np.float64)
        wc = None if weights is None else np.asarray(weights[lo:hi], np.float64)
        tc = (
            {k: v[lo:hi] for k, v in id_tags.items()} if id_tags else None
        )
        for i in range(hi - lo):
            yield {
                "uid": None if uc is None else str(uc[i]),
                "label": None if lc is None else float(lc[i]),
                "modelId": model_id,
                "predictionScore": float(sc[i]),
                "weight": None if wc is None else float(wc[i]),
                "metadataMap": (
                    {k: str(v[i]) for k, v in tc.items()} if tc else None
                ),
            }


def save_scores(
    output_dir: str,
    scores,
    model_id: str,
    *,
    uids: Optional[Sequence[str]] = None,
    labels=None,
    weights=None,
    id_tags: Optional[Dict[str, Sequence]] = None,
    records_per_file: int = 500_000,
    chunk_size: int = 65536,
) -> int:
    """Write scores as ScoringResultAvro part files; returns record count.
    Streams through `score_records` — columns are converted chunk-wise,
    never materialized whole."""
    os.makedirs(output_dir, exist_ok=True)
    return avro_io.write_part_files(
        output_dir,
        schemas.SCORING_RESULT,
        score_records(
            scores,
            model_id,
            uids=uids,
            labels=labels,
            weights=weights,
            id_tags=id_tags,
            chunk_size=chunk_size,
        ),
        len(scores),
        records_per_file=records_per_file,
    )


def load_scores(path: str) -> List[ScoredItem]:
    """Read ScoringResultAvro records back into ScoredItems
    (ScoreProcessingUtils.loadScoredItemsFromHDFS)."""
    _, recs = avro_io.read_directory(path)
    return [
        ScoredItem(
            prediction_score=r["predictionScore"],
            uid=r.get("uid"),
            label=r.get("label"),
            weight=r.get("weight"),
            ids=r.get("metadataMap") or {},
        )
        for r in recs
    ]

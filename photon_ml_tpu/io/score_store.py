"""Score persistence: ScoredItem <-> ScoringResultAvro.

Counterpart of photon-client data/avro/ScoreProcessingUtils.scala:29-88 and
cli/game/scoring/ScoredItem.scala:37. Scores are written as one Avro
container directory of ScoringResultAvro records (the GameScoringDriver's
saveScoresToHDFS output format, GameScoringDriver.scala:229-260).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

from photon_ml_tpu.io import avro as avro_io
from photon_ml_tpu.io import schemas


@dataclasses.dataclass
class ScoredItem:
    """One scored datum (ScoredItem.scala:37)."""

    prediction_score: float
    uid: Optional[str] = None
    label: Optional[float] = None
    weight: Optional[float] = None
    ids: Dict[str, str] = dataclasses.field(default_factory=dict)


def save_scores(
    output_dir: str,
    scores: np.ndarray,
    model_id: str,
    *,
    uids: Optional[Sequence[str]] = None,
    labels: Optional[np.ndarray] = None,
    weights: Optional[np.ndarray] = None,
    id_tags: Optional[Dict[str, Sequence]] = None,
    records_per_file: int = 500_000,
) -> int:
    """Write scores as ScoringResultAvro part files; returns record count."""
    os.makedirs(output_dir, exist_ok=True)
    n = len(scores)

    def records() -> Iterator[dict]:
        for i in range(n):
            meta = None
            if id_tags:
                meta = {k: str(v[i]) for k, v in id_tags.items()}
            yield {
                "uid": None if uids is None else str(uids[i]),
                "label": None if labels is None else float(labels[i]),
                "modelId": model_id,
                "predictionScore": float(scores[i]),
                "weight": None if weights is None else float(weights[i]),
                "metadataMap": meta,
            }

    return avro_io.write_part_files(
        output_dir,
        schemas.SCORING_RESULT,
        records(),
        n,
        records_per_file=records_per_file,
    )


def load_scores(path: str) -> List[ScoredItem]:
    """Read ScoringResultAvro records back into ScoredItems
    (ScoreProcessingUtils.loadScoredItemsFromHDFS)."""
    _, recs = avro_io.read_directory(path)
    return [
        ScoredItem(
            prediction_score=r["predictionScore"],
            uid=r.get("uid"),
            label=r.get("label"),
            weight=r.get("weight"),
            ids=r.get("metadataMap") or {},
        )
        for r in recs
    ]

"""Bridging trained GAME models <-> persisted model artifacts.

The reference saves models in ORIGINAL feature space with feature names
resolved through the index maps (ModelProcessingUtils.scala:77-141); training
happens in normalized and (for random effects) projected space. This module
owns the space conversions on the way in and out of the model store:

  save:  transformed/projected device matrices -> original-space numpy rows
         (normalization folded out via modelToOriginalSpace —
         NormalizationContext.scala:73-90 — and projections reversed through
         the projector).
  load:  original-space artifact -> GameModel scoring in original space
         (no norm/projector needed), OR -> warm-start matrices re-projected
         into an estimator's training representation
         (GameTrainingDriver.scala:370-378 warm-start path).
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.model import (
    Coefficients,
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.io.model_store import (
    FixedEffectArtifact,
    GameModelArtifact,
    RandomEffectArtifact,
)
from photon_ml_tpu.transformers.game_transformer import CoordinateScoringSpec
from photon_ml_tpu.types import TaskType


def _ordered_entity_ids(entity_index: Mapping[object, int]) -> list:
    out = [None] * len(entity_index)
    for k, i in entity_index.items():
        out[i] = k
    return out


def artifact_from_game_model(
    model: GameModel,
    specs: Mapping[str, CoordinateScoringSpec],
    task: TaskType,
    *,
    opt_configs: Optional[Dict[str, dict]] = None,
) -> GameModelArtifact:
    """Convert a trained GameModel (+ its scoring specs) to the persistable
    original-space artifact."""
    coords: Dict[str, object] = {}
    for cid, m in model.items():
        spec = specs[cid]
        norm = spec.norm
        if isinstance(m, FixedEffectModel):
            means = m.coefficients.means
            variances = m.coefficients.variances
            if norm is not None:
                means, variances = norm.coefficients_to_original_space(
                    means, variances
                )
            coords[cid] = FixedEffectArtifact(
                spec.shard,
                np.asarray(means),
                None if variances is None else np.asarray(variances),
            )
        elif isinstance(m, RandomEffectModel):
            from photon_ml_tpu.ops.normalization import PerEntityNormalization

            matrix = m.coefficients_matrix
            variances = m.variances_matrix
            # Mesh-trained matrices are row-padded past E+1 (entity-sharded
            # store); slice BEFORE per-entity transforms/back-projection,
            # whose tables are (E+1)-row shaped.
            logical_rows = m.num_entities + 1
            if matrix.shape[0] > logical_rows:
                matrix = matrix[:logical_rows]
                if variances is not None:
                    variances = variances[:logical_rows]
            if isinstance(norm, PerEntityNormalization):
                # Projected-space contexts: per-entity factor/shift rows
                # (IndexMapProjectorRDD.scala:133), still in projected space;
                # the projector scatter below maps to global indices.
                matrix, variances = norm.matrix_to_original_space(
                    jnp.asarray(matrix), variances
                )
            elif norm is not None and not norm.is_identity:
                # Row-wise modelToOriginalSpace: factors plus, for identity-
                # projected shards with shifts, the intercept fold-in.
                import jax

                matrix = jax.vmap(norm.model_to_original_space)(jnp.asarray(matrix))
                if variances is not None and norm.factors is not None:
                    variances = variances * jnp.square(norm.factors)
            if spec.projector is not None:
                matrix = spec.projector.back_project_matrix(matrix)
                if variances is not None:
                    variances = spec.projector.back_project_matrix(variances)
            # Drop the pinned zero row for unseen entities.
            e = len(spec.entity_index)
            coords[cid] = RandomEffectArtifact(
                spec.random_effect_type,
                spec.shard,
                [str(k) for k in _ordered_entity_ids(spec.entity_index)],
                np.asarray(matrix)[:e],
                None if variances is None else np.asarray(variances)[:e],
            )
        else:
            raise TypeError(f"unknown model type {type(m)} for coordinate {cid!r}")
    return GameModelArtifact(task=task, coordinates=coords, opt_configs=opt_configs or {})


def game_model_from_artifact(
    artifact: GameModelArtifact,
) -> Tuple[GameModel, Dict[str, CoordinateScoringSpec]]:
    """Artifact -> (GameModel, scoring specs) in ORIGINAL feature space —
    the scoring-driver path (GameScoringDriver loadModel -> GameTransformer).
    """
    models: Dict[str, object] = {}
    specs: Dict[str, CoordinateScoringSpec] = {}
    for cid, coord in artifact.coordinates.items():
        if isinstance(coord, FixedEffectArtifact):
            models[cid] = FixedEffectModel(
                Coefficients(
                    jnp.asarray(coord.means, jnp.float32),
                    None
                    if coord.variances is None
                    else jnp.asarray(coord.variances, jnp.float32),
                ),
                artifact.task,
            )
            specs[cid] = CoordinateScoringSpec(shard=coord.feature_shard)
        elif isinstance(coord, RandomEffectArtifact):
            e, d = coord.means.shape
            matrix = np.zeros((e + 1, d), np.float32)
            matrix[:e] = coord.means
            var_matrix = None
            if coord.variances is not None:
                var_matrix = np.zeros((e + 1, d), np.float32)
                var_matrix[:e] = coord.variances
            models[cid] = RandomEffectModel(
                jnp.asarray(matrix),
                None if var_matrix is None else jnp.asarray(var_matrix),
                artifact.task,
            )
            specs[cid] = CoordinateScoringSpec(
                shard=coord.feature_shard,
                random_effect_type=coord.random_effect_type,
                entity_index={k: i for i, k in enumerate(coord.entity_ids)},
            )
        else:
            raise TypeError(f"unknown artifact type {type(coord)} for {cid!r}")
    return GameModel(models), specs


def warm_start_model_for_estimator(
    artifact: GameModelArtifact,
    specs: Mapping[str, CoordinateScoringSpec],
) -> GameModel:
    """Artifact -> GameModel in the ESTIMATOR's training representation
    (transformed + projected spaces), aligned to the training dataset's
    entity indexing. The reference's per-entity leftOuterJoin warm start
    (RandomEffectCoordinate.scala:110-121): entities present in both keep
    their coefficients; training-set-only entities start at zero; artifact-
    only entities are dropped."""
    models: Dict[str, object] = {}
    for cid, coord in artifact.coordinates.items():
        if cid not in specs:
            continue
        spec = specs[cid]
        norm = spec.norm
        if isinstance(coord, FixedEffectArtifact):
            means = jnp.asarray(coord.means, jnp.float32)
            if norm is not None and not norm.is_identity:
                means = norm.model_to_transformed_space(means)
            models[cid] = FixedEffectModel(Coefficients(means), artifact.task)
        elif isinstance(coord, RandomEffectArtifact):
            e_train = len(spec.entity_index)
            d = coord.means.shape[1]
            aligned = np.zeros((e_train + 1, d), np.float32)
            art_rows = {k: i for i, k in enumerate(coord.entity_ids)}
            for key, row in spec.entity_index.items():
                i = art_rows.get(str(key))
                if i is not None:
                    aligned[row] = coord.means[i]
            matrix = jnp.asarray(aligned)
            if spec.projector is not None:
                matrix = spec.projector.project_matrix(matrix)
            from photon_ml_tpu.ops.normalization import PerEntityNormalization

            if isinstance(norm, PerEntityNormalization):
                matrix = norm.matrix_to_transformed_space(matrix)
            elif norm is not None and not norm.is_identity:
                import jax

                matrix = jax.vmap(norm.model_to_transformed_space)(matrix)
            models[cid] = RandomEffectModel(jnp.asarray(matrix), None, artifact.task)
    return GameModel(models)

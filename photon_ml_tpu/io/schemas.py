"""Avro record schemas matching the reference's interchange formats.

Counterpart of photon-avro-schemas/src/main/avro/*.avsc (8 records). Field
names, types, and defaults must match the reference byte-for-byte so that
models/data written by either framework load in the other
(BayesianLinearModelAvro is the model checkpoint format, README.md:205).
Expressed as Avro-JSON Python dicts consumed by photon_ml_tpu.io.avro.
"""

from __future__ import annotations

NAMESPACE = "com.linkedin.photon.avro.generated"

NAME_TERM_VALUE = {
    "name": "NameTermValueAvro",
    "namespace": NAMESPACE,
    "type": "record",
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string"},
        {"name": "value", "type": "double"},
    ],
}

FEATURE = {
    "name": "FeatureAvro",
    "namespace": NAMESPACE,
    "type": "record",
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string"},
        {"name": "value", "type": "double"},
    ],
}

BAYESIAN_LINEAR_MODEL = {
    "name": "BayesianLinearModelAvro",
    "namespace": NAMESPACE,
    "type": "record",
    "fields": [
        {"name": "modelId", "type": "string"},
        {"name": "modelClass", "type": ["null", "string"], "default": None},
        {"name": "means", "type": {"type": "array", "items": NAME_TERM_VALUE}},
        {
            "name": "variances",
            "type": ["null", {"type": "array", "items": "NameTermValueAvro"}],
            "default": None,
        },
        {"name": "lossFunction", "type": ["null", "string"], "default": None},
    ],
}

TRAINING_EXAMPLE = {
    "name": "TrainingExampleAvro",
    "namespace": NAMESPACE,
    "type": "record",
    "fields": [
        {"name": "uid", "type": ["null", "string"], "default": None},
        {"name": "label", "type": "double"},
        {"name": "features", "type": {"type": "array", "items": FEATURE}},
        {"name": "weight", "type": "double", "default": 1.0},
        {"name": "offset", "type": "double", "default": 0.0},
        {
            "name": "metadataMap",
            "type": ["null", {"type": "map", "values": "string"}],
            "default": None,
        },
    ],
}

RESPONSE_PREDICTION = {
    "name": "SimplifiedResponsePrediction",
    "namespace": NAMESPACE,
    "type": "record",
    "fields": [
        {"name": "response", "type": "double"},
        {"name": "features", "type": {"type": "array", "items": FEATURE}},
        {"name": "weight", "type": "double", "default": 1.0},
        {"name": "offset", "type": "double", "default": 0.0},
    ],
}

SCORING_RESULT = {
    "name": "ScoringResultAvro",
    "namespace": NAMESPACE,
    "type": "record",
    "fields": [
        {"name": "uid", "type": ["null", "string"], "default": None},
        {"name": "label", "type": ["null", "double"], "default": None},
        {"name": "modelId", "type": "string"},
        {"name": "predictionScore", "type": "double"},
        {"name": "weight", "type": ["null", "double"], "default": None},
        {
            "name": "metadataMap",
            "type": ["null", {"type": "map", "values": "string"}],
            "default": None,
        },
    ],
}

FEATURE_SUMMARIZATION = {
    "name": "FeatureSummarizationResultAvro",
    "namespace": NAMESPACE,
    "type": "record",
    "fields": [
        {"name": "featureName", "type": "string"},
        {"name": "featureTerm", "type": "string"},
        {"name": "metrics", "type": {"type": "map", "values": "double"}},
    ],
}

LATENT_FACTOR = {
    "name": "LatentFactorAvro",
    "namespace": NAMESPACE,
    "type": "record",
    "fields": [
        {"name": "effectId", "type": "string"},
        {"name": "latentFactor", "type": {"type": "array", "items": "double"}},
    ],
}

"""Host-side I/O: Avro codec, training-data ingestion, model + score stores."""

from photon_ml_tpu.io.avro import read_container, read_directory, write_container
from photon_ml_tpu.io.avro_data import (
    FeatureShardConfig,
    read_game_dataset,
    write_training_examples,
)
from photon_ml_tpu.io.model_store import (
    FixedEffectArtifact,
    GameModelArtifact,
    RandomEffectArtifact,
    load_game_model,
    save_game_model,
)
from photon_ml_tpu.io.score_store import ScoredItem, load_scores, save_scores

"""GAME model persistence in the reference's HDFS directory layout.

Counterpart of photon-client data/avro/ModelProcessingUtils.scala:59-625 and
AvroConstants.scala. Layout written/read here (identical to the reference so
model artifacts interoperate):

    <dir>/model-metadata.json                      (saveGameModelMetadataToHDFS:489)
    <dir>/fixed-effect/<coordinateId>/id-info      (one line: featureShardId)
    <dir>/fixed-effect/<coordinateId>/coefficients/part-00000.avro
         (single BayesianLinearModelAvro record, saveModelToHDFS:300-320)
    <dir>/random-effect/<coordinateId>/id-info     (lines: randomEffectType, featureShardId)
    <dir>/random-effect/<coordinateId>/coefficients/part-<k>.avro
         (one BayesianLinearModelAvro per entity, modelId = entity id,
          saveModelsRDDToHDFS:354-378)

Coefficients are written as (name, term, value) records resolved through the
feature IndexMap in both directions, filtered by `sparsity_threshold`
(|value| <= threshold dropped, like the reference's VectorUtils filter);
variances ride along when present. The metadata JSON carries the task type
under "modelType" plus the per-coordinate optimization configs
(gameOptConfigToJson:408-487).
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.data.index_map import DELIMITER, IndexMap, feature_key
from photon_ml_tpu.io import avro as avro_io
from photon_ml_tpu.io import schemas
from photon_ml_tpu.types import TaskType

FIXED_EFFECT = "fixed-effect"
RANDOM_EFFECT = "random-effect"
COEFFICIENTS = "coefficients"
ID_INFO = "id-info"
METADATA_FILE = "model-metadata.json"
MODEL_TYPE = "modelType"
DEFAULT_AVRO_FILE = "part-00000.avro"

# modelClass strings the reference writes (AvroUtils.convertGLMModelTo...);
# kept verbatim for artifact-level compatibility.
_MODEL_CLASS = {
    TaskType.LOGISTIC_REGRESSION: "com.linkedin.photon.ml.supervised.classification.LogisticRegressionModel",
    TaskType.LINEAR_REGRESSION: "com.linkedin.photon.ml.supervised.regression.LinearRegressionModel",
    TaskType.POISSON_REGRESSION: "com.linkedin.photon.ml.supervised.regression.PoissonRegressionModel",
    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: "com.linkedin.photon.ml.supervised.classification.SmoothedHingeLossLinearSVMModel",
}
_CLASS_TO_TASK = {v: k for k, v in _MODEL_CLASS.items()}


def _split_key(key: str) -> Tuple[str, str]:
    if DELIMITER in key:
        name, term = key.split(DELIMITER, 1)
        return name, term
    return key, ""


def _coeffs_to_ntv(
    vector: np.ndarray, index_map: IndexMap, threshold: float
) -> List[dict]:
    out = []
    for idx in np.flatnonzero(np.abs(vector) > threshold):
        key = index_map.get_feature_name(int(idx))
        if key is None:
            continue
        name, term = _split_key(key)
        out.append({"name": name, "term": term, "value": float(vector[idx])})
    return out


def _ntv_to_coeffs(
    records: Sequence[dict],
    index_map: IndexMap,
    out: Optional[np.ndarray] = None,
) -> np.ndarray:
    """(name, term, value) records -> coefficient vector (writes into `out`
    when given — the random-effect loader fills matrix rows in place)."""
    vec = np.zeros(index_map.size, np.float64) if out is None else out
    for r in records:
        idx = index_map.get_index(feature_key(r["name"], r["term"]))
        if idx >= 0:
            vec[idx] = r["value"]
    return vec


def _glm_record(
    model_id: str,
    task: Optional[TaskType],
    means: np.ndarray,
    variances: Optional[np.ndarray],
    index_map: IndexMap,
    threshold: float,
) -> dict:
    rec = {
        "modelId": model_id,
        "modelClass": _MODEL_CLASS.get(task) if task else None,
        "means": _coeffs_to_ntv(means, index_map, threshold),
        "variances": None,
        "lossFunction": None,
    }
    if variances is not None:
        finite = np.where(np.isfinite(variances), variances, 0.0)
        rec["variances"] = _coeffs_to_ntv(finite, index_map, 0.0)
    return rec


@dataclasses.dataclass
class FixedEffectArtifact:
    """Host-side fixed-effect coordinate payload for save/load."""

    feature_shard: str
    means: np.ndarray
    variances: Optional[np.ndarray] = None


@dataclasses.dataclass
class RandomEffectArtifact:
    """Host-side random-effect coordinate payload: one row per entity id."""

    random_effect_type: str
    feature_shard: str
    entity_ids: List[str]
    means: np.ndarray  # (E, D)
    variances: Optional[np.ndarray] = None  # (E, D)


@dataclasses.dataclass
class GameModelArtifact:
    """A GAME model as saved/loaded: coordinate id -> artifact + metadata."""

    task: TaskType
    coordinates: Dict[str, object]  # FixedEffectArtifact | RandomEffectArtifact
    opt_configs: Dict[str, dict] = dataclasses.field(default_factory=dict)


def save_game_model(
    output_dir: str,
    artifact: GameModelArtifact,
    index_maps: Mapping[str, IndexMap],
    *,
    sparsity_threshold: float = 0.0,
    random_effect_file_limit: Optional[int] = None,
    records_per_file: int = 100_000,
) -> None:
    """saveGameModelToHDFS (ModelProcessingUtils.scala:77-141)."""
    os.makedirs(output_dir, exist_ok=True)
    _save_metadata(output_dir, artifact)

    for cid, coord in artifact.coordinates.items():
        if isinstance(coord, FixedEffectArtifact):
            cdir = os.path.join(output_dir, FIXED_EFFECT, cid)
            os.makedirs(os.path.join(cdir, COEFFICIENTS), exist_ok=True)
            with open(os.path.join(cdir, ID_INFO), "w") as f:
                f.write(coord.feature_shard + "\n")
            rec = _glm_record(
                FIXED_EFFECT,
                artifact.task,
                coord.means,
                coord.variances,
                index_maps[coord.feature_shard],
                sparsity_threshold,
            )
            avro_io.write_container(
                os.path.join(cdir, COEFFICIENTS, DEFAULT_AVRO_FILE),
                schemas.BAYESIAN_LINEAR_MODEL,
                [rec],
            )
        elif isinstance(coord, RandomEffectArtifact):
            cdir = os.path.join(output_dir, RANDOM_EFFECT, cid)
            os.makedirs(os.path.join(cdir, COEFFICIENTS), exist_ok=True)
            with open(os.path.join(cdir, ID_INFO), "w") as f:
                f.write(coord.random_effect_type + "\n" + coord.feature_shard + "\n")
            imap = index_maps[coord.feature_shard]
            recs = (
                _glm_record(
                    str(eid),
                    artifact.task,
                    coord.means[i],
                    None if coord.variances is None else coord.variances[i],
                    imap,
                    sparsity_threshold,
                )
                for i, eid in enumerate(coord.entity_ids)
            )
            avro_io.write_part_files(
                os.path.join(cdir, COEFFICIENTS),
                schemas.BAYESIAN_LINEAR_MODEL,
                recs,
                len(coord.entity_ids),
                records_per_file=records_per_file,
                file_limit=random_effect_file_limit,
            )
        else:
            raise TypeError(f"unknown coordinate artifact {type(coord)} for {cid!r}")


def load_game_model(
    models_dir: str,
    index_maps: Mapping[str, IndexMap],
    *,
    coordinates_to_load: Optional[Sequence[str]] = None,
    dtype=np.float32,
) -> GameModelArtifact:
    """loadGameModelFromHDFS (ModelProcessingUtils.scala:143-265).

    Random-effect coefficient matrices are materialized dense (E, D) in
    `dtype` (float32 by default — the device-side precision) with rows filled
    in place, so loading the reference's thousands-of-entities artifacts
    stays at one matrix allocation rather than E temporary float64 rows.
    """
    task = _load_metadata_task(models_dir)
    wanted = set(coordinates_to_load) if coordinates_to_load else None
    coords: Dict[str, object] = {}

    fe_dir = os.path.join(models_dir, FIXED_EFFECT)
    if os.path.isdir(fe_dir):
        for cid in sorted(os.listdir(fe_dir)):
            if wanted is not None and cid not in wanted:
                continue
            cdir = os.path.join(fe_dir, cid)
            with open(os.path.join(cdir, ID_INFO)) as f:
                shard = f.read().split()[0]
            imap = index_maps[shard]
            _, recs = avro_io.read_container(
                os.path.join(cdir, COEFFICIENTS, DEFAULT_AVRO_FILE)
            )
            rec = recs[0]
            means = _ntv_to_coeffs(rec["means"], imap)
            variances = (
                _ntv_to_coeffs(rec["variances"], imap)
                if rec.get("variances")
                else None
            )
            coords[cid] = FixedEffectArtifact(shard, means, variances)

    re_dir = os.path.join(models_dir, RANDOM_EFFECT)
    if os.path.isdir(re_dir):
        for cid in sorted(os.listdir(re_dir)):
            if wanted is not None and cid not in wanted:
                continue
            cdir = os.path.join(re_dir, cid)
            with open(os.path.join(cdir, ID_INFO)) as f:
                lines = f.read().split()
            re_type, shard = lines[0], lines[1]
            imap = index_maps[shard]
            # Stream part files: decode one part's records, fill its dense
            # block, release — only one part's dicts are live at a time.
            entity_ids = []
            mean_blocks: List[np.ndarray] = []
            var_blocks: List[Optional[np.ndarray]] = []
            for part in sorted(glob.glob(os.path.join(cdir, COEFFICIENTS, "*.avro"))):
                _, recs = avro_io.read_container(part)
                if not recs:
                    continue  # empty part files (partitions > entities) are inert
                block = np.zeros((len(recs), imap.size), dtype)
                vblock = (
                    np.zeros_like(block)
                    if recs and all(r.get("variances") for r in recs)
                    else None
                )
                for i, rec in enumerate(recs):
                    entity_ids.append(rec["modelId"])
                    _ntv_to_coeffs(rec["means"], imap, out=block[i])
                    if vblock is not None:
                        _ntv_to_coeffs(rec["variances"], imap, out=vblock[i])
                mean_blocks.append(block)
                var_blocks.append(vblock)
            means = (
                np.concatenate(mean_blocks)
                if mean_blocks
                else np.zeros((0, imap.size), dtype)
            )
            variances = (
                np.concatenate(var_blocks)
                if var_blocks and all(v is not None for v in var_blocks)
                else None
            )
            coords[cid] = RandomEffectArtifact(re_type, shard, entity_ids, means, variances)

    if not coords:
        raise FileNotFoundError(f"No models could be loaded from: {models_dir}")
    return GameModelArtifact(
        task=task, coordinates=coords, opt_configs=_load_metadata_opt_configs(models_dir)
    )


def write_basic_statistics(
    output_dir: str,
    stats,
    index_map: IndexMap,
) -> int:
    """Feature-shard summary as FeatureSummarizationResultAvro records
    (ModelProcessingUtils.writeBasicStatistics:516-606): one record per
    feature (intercept excluded) with the metrics map
    {max, min, mean, normL1, normL2, numNonzeros, variance}, written to
    `<output_dir>/part-00000.avro` in feature-id order. `stats` is a
    data.stats.FeatureDataStatistics. Returns the record count."""
    cols = {
        "max": np.asarray(stats.max, np.float64),
        "min": np.asarray(stats.min, np.float64),
        "mean": np.asarray(stats.mean, np.float64),
        "normL1": np.asarray(stats.norm_l1, np.float64),
        "normL2": np.asarray(stats.norm_l2, np.float64),
        "numNonzeros": np.asarray(stats.num_nonzeros, np.float64),
        "variance": np.asarray(stats.variance, np.float64),
    }
    skip = stats.intercept_index if stats.intercept_index is not None else -1

    def records():
        for key, idx in sorted(index_map.items(), key=lambda kv: kv[1]):
            if idx == skip:
                continue
            name, term = _split_key(key)
            yield {
                "featureName": name,
                "featureTerm": term,
                "metrics": {m: float(col[idx]) for m, col in cols.items()},
            }

    os.makedirs(output_dir, exist_ok=True)
    return avro_io.write_container(
        os.path.join(output_dir, DEFAULT_AVRO_FILE),
        schemas.FEATURE_SUMMARIZATION,
        records(),
    )


def _save_metadata(output_dir: str, artifact: GameModelArtifact) -> None:
    """saveGameModelMetadataToHDFS (:489-514) + gameOptConfigToJson (:408-487)."""
    doc = {
        MODEL_TYPE: artifact.task.value,
        "optimizationConfigurations": artifact.opt_configs,
    }
    with open(os.path.join(output_dir, METADATA_FILE), "w") as f:
        json.dump(doc, f, indent=2)


def _load_metadata_task(models_dir: str) -> TaskType:
    """loadGameModelMetadataFromHDFS (:608+): extract "modelType"."""
    path = os.path.join(models_dir, METADATA_FILE)
    with open(path) as f:
        doc = json.load(f)
    if MODEL_TYPE not in doc:
        raise RuntimeError(f"Couldn't find '{MODEL_TYPE}' in metadata file: {path}")
    return TaskType(doc[MODEL_TYPE])


def _load_metadata_opt_configs(models_dir: str) -> Dict[str, dict]:
    with open(os.path.join(models_dir, METADATA_FILE)) as f:
        return json.load(f).get("optimizationConfigurations", {})

"""Minimal Avro implementation: binary codec + object container files.

The reference interchanges everything — training data, models, scores —
as Avro object container files (photon-avro-schemas/src/main/avro/*.avsc,
read/written through avro-mapred in AvroUtils.scala:47). This image ships no
Avro library, so the format is implemented here from the public Avro 1.x
specification: zigzag-varint longs, little-endian IEEE floats, length-prefixed
bytes/strings, block-encoded arrays/maps, index-prefixed unions, and the
`Obj\\x01` container framing with null/deflate codecs.

Schemas are plain Python dicts in Avro JSON form (see
photon_ml_tpu.io.schemas); data values are plain dicts/lists/scalars. This is
a host-side ETL path — device code never sees Avro.
"""

from __future__ import annotations

import io
import json
import logging
import os
import struct
import zlib
from typing import Any, BinaryIO, Dict, Iterable, Iterator, List, Optional, Union

logger = logging.getLogger(__name__)

Schema = Union[str, dict, list]

MAGIC = b"Obj\x01"
SYNC_SIZE = 16
_PRIMITIVES = {"null", "boolean", "int", "long", "float", "double", "bytes", "string"}


# ---------------------------------------------------------------------------
# Binary encoding


class BinaryEncoder:
    def __init__(self, out: BinaryIO):
        self._out = out

    def write_long(self, n: int) -> None:
        # zigzag then varint (Avro spec "long").
        n = (n << 1) ^ (n >> 63)
        while (n & ~0x7F) != 0:
            self._out.write(bytes([(n & 0x7F) | 0x80]))
            n >>= 7
        self._out.write(bytes([n]))

    def write_boolean(self, v: bool) -> None:
        self._out.write(b"\x01" if v else b"\x00")

    def write_float(self, v: float) -> None:
        self._out.write(struct.pack("<f", v))

    def write_double(self, v: float) -> None:
        self._out.write(struct.pack("<d", v))

    def write_bytes(self, v: bytes) -> None:
        self.write_long(len(v))
        self._out.write(v)

    def write_string(self, v: str) -> None:
        self.write_bytes(v.encode("utf-8"))

    def write_fixed(self, v: bytes) -> None:
        self._out.write(v)


class BinaryDecoder:
    def __init__(self, data: bytes, pos: int = 0):
        self._data = data
        self.pos = pos

    def read_long(self) -> int:
        b = self._data[self.pos]
        self.pos += 1
        n = b & 0x7F
        shift = 7
        while b & 0x80:
            b = self._data[self.pos]
            self.pos += 1
            n |= (b & 0x7F) << shift
            shift += 7
        return (n >> 1) ^ -(n & 1)

    def read_boolean(self) -> bool:
        v = self._data[self.pos] == 1
        self.pos += 1
        return v

    def read_float(self) -> float:
        (v,) = struct.unpack_from("<f", self._data, self.pos)
        self.pos += 4
        return v

    def read_double(self) -> float:
        (v,) = struct.unpack_from("<d", self._data, self.pos)
        self.pos += 8
        return v

    def read_bytes(self) -> bytes:
        n = self.read_long()
        v = self._data[self.pos : self.pos + n]
        self.pos += n
        return v

    def read_string(self) -> str:
        return self.read_bytes().decode("utf-8")

    def read_fixed(self, n: int) -> bytes:
        v = self._data[self.pos : self.pos + n]
        self.pos += n
        return v

    @property
    def remaining(self) -> int:
        return len(self._data) - self.pos


# ---------------------------------------------------------------------------
# Schema-driven datum codec


class _Names:
    """Resolves named-type references within one schema tree."""

    def __init__(self):
        self.types: Dict[str, dict] = {}

    def register(self, schema: dict) -> None:
        name = schema.get("name")
        if name:
            ns = schema.get("namespace")
            self.types[name] = schema
            if ns:
                self.types[f"{ns}.{name}"] = schema

    def resolve(self, schema: Schema) -> Schema:
        if isinstance(schema, str) and schema not in _PRIMITIVES:
            if schema not in self.types:
                raise ValueError(f"Unknown named type {schema!r}")
            return self.types[schema]
        return schema


def _collect_names(schema: Schema, names: _Names) -> None:
    if isinstance(schema, list):
        for s in schema:
            _collect_names(s, names)
    elif isinstance(schema, dict):
        t = schema.get("type")
        if t in ("record", "enum", "fixed"):
            names.register(schema)
        if t == "record":
            for f in schema["fields"]:
                _collect_names(f["type"], names)
        elif t == "array":
            _collect_names(schema["items"], names)
        elif t == "map":
            _collect_names(schema["values"], names)


def _matches(branch: Schema, datum: Any, names: _Names) -> bool:
    branch = names.resolve(branch)
    t = branch if isinstance(branch, str) else branch["type"]
    if t == "null":
        return datum is None
    if t == "boolean":
        return isinstance(datum, bool)
    if t in ("int", "long"):
        return isinstance(datum, int) and not isinstance(datum, bool)
    if t in ("float", "double"):
        return isinstance(datum, (int, float)) and not isinstance(datum, bool)
    if t == "string":
        return isinstance(datum, str)
    if t in ("bytes", "fixed"):
        return isinstance(datum, (bytes, bytearray))
    if t == "enum":
        return isinstance(datum, str) and datum in branch["symbols"]
    if t == "array":
        return isinstance(datum, (list, tuple))
    if t == "map":
        return isinstance(datum, dict)
    if t == "record":
        return isinstance(datum, dict)
    return False


def write_datum(enc: BinaryEncoder, schema: Schema, datum: Any, names: _Names) -> None:
    schema = names.resolve(schema)
    if isinstance(schema, list):  # union: branch index then value
        for i, branch in enumerate(schema):
            if _matches(branch, datum, names):
                enc.write_long(i)
                write_datum(enc, branch, datum, names)
                return
        raise ValueError(f"datum {datum!r} matches no union branch {schema!r}")
    t = schema if isinstance(schema, str) else schema["type"]
    if t == "null":
        return
    if t == "boolean":
        enc.write_boolean(datum)
    elif t == "int" or t == "long":
        enc.write_long(int(datum))
    elif t == "float":
        enc.write_float(float(datum))
    elif t == "double":
        enc.write_double(float(datum))
    elif t == "bytes":
        enc.write_bytes(bytes(datum))
    elif t == "string":
        enc.write_string(datum)
    elif t == "fixed":
        enc.write_fixed(bytes(datum))
    elif t == "enum":
        enc.write_long(schema["symbols"].index(datum))
    elif t == "array":
        if datum:
            enc.write_long(len(datum))
            for item in datum:
                write_datum(enc, schema["items"], item, names)
        enc.write_long(0)
    elif t == "map":
        if datum:
            enc.write_long(len(datum))
            for k, v in datum.items():
                enc.write_string(k)
                write_datum(enc, schema["values"], v, names)
        enc.write_long(0)
    elif t == "record":
        for field in schema["fields"]:
            name = field["name"]
            if name in datum:
                value = datum[name]
            elif "default" in field:
                value = field["default"]
            else:
                raise ValueError(f"record missing field {name!r} with no default")
            write_datum(enc, field["type"], value, names)
    else:
        raise ValueError(f"unsupported schema {schema!r}")


def read_datum(dec: BinaryDecoder, schema: Schema, names: _Names) -> Any:
    schema = names.resolve(schema)
    if isinstance(schema, list):
        return read_datum(dec, schema[dec.read_long()], names)
    t = schema if isinstance(schema, str) else schema["type"]
    if t == "null":
        return None
    if t == "boolean":
        return dec.read_boolean()
    if t == "int" or t == "long":
        return dec.read_long()
    if t == "float":
        return dec.read_float()
    if t == "double":
        return dec.read_double()
    if t == "bytes":
        return dec.read_bytes()
    if t == "string":
        return dec.read_string()
    if t == "fixed":
        return dec.read_fixed(schema["size"])
    if t == "enum":
        return schema["symbols"][dec.read_long()]
    if t == "array":
        out: List[Any] = []
        n = dec.read_long()
        while n != 0:
            if n < 0:  # block with byte-size prefix
                n = -n
                dec.read_long()
            for _ in range(n):
                out.append(read_datum(dec, schema["items"], names))
            n = dec.read_long()
        return out
    if t == "map":
        m: Dict[str, Any] = {}
        n = dec.read_long()
        while n != 0:
            if n < 0:
                n = -n
                dec.read_long()
            for _ in range(n):
                k = dec.read_string()
                m[k] = read_datum(dec, schema["values"], names)
            n = dec.read_long()
        return m
    if t == "record":
        return {f["name"]: read_datum(dec, f["type"], names) for f in schema["fields"]}
    raise ValueError(f"unsupported schema {schema!r}")


# ---------------------------------------------------------------------------
# Object container files


def write_container(
    path: str,
    schema: Schema,
    records: Iterable[dict],
    *,
    codec: str = "deflate",
    block_records: int = 4096,
    sync: Optional[bytes] = None,
) -> int:
    """Write records to an Avro object container file; returns record count."""
    names = _Names()
    _collect_names(schema, names)
    sync = sync or os.urandom(SYNC_SIZE)
    count_total = 0
    with open(path, "wb") as f:
        f.write(MAGIC)
        header = BinaryEncoder(f)
        meta = {
            "avro.schema": json.dumps(schema).encode("utf-8"),
            "avro.codec": codec.encode("utf-8"),
        }
        header.write_long(len(meta))
        for k, v in meta.items():
            header.write_string(k)
            header.write_bytes(v)
        header.write_long(0)
        f.write(sync)

        buf = io.BytesIO()
        enc = BinaryEncoder(buf)
        in_block = 0

        def flush():
            nonlocal in_block
            if in_block == 0:
                return
            raw = buf.getvalue()
            if codec == "deflate":
                raw = zlib.compress(raw)[2:-4]  # raw deflate stream (no zlib header/adler)
            elif codec != "null":
                raise ValueError(f"unsupported codec {codec!r}")
            blk = BinaryEncoder(f)
            blk.write_long(in_block)
            blk.write_long(len(raw))
            f.write(raw)
            f.write(sync)
            buf.seek(0)
            buf.truncate()
            in_block = 0

        for rec in records:
            write_datum(enc, schema, rec, names)
            in_block += 1
            count_total += 1
            if in_block >= block_records:
                flush()
        flush()
    return count_total


def read_header(data: bytes, path: str = "<bytes>"):
    """Parse the container header: (schema, codec, sync, body_start)."""
    if data[:4] != MAGIC:
        raise ValueError(f"{path} is not an Avro container file")
    dec = BinaryDecoder(data, 4)
    meta: Dict[str, bytes] = {}
    n = dec.read_long()
    while n != 0:
        if n < 0:
            n = -n
            dec.read_long()
        for _ in range(n):
            k = dec.read_string()
            meta[k] = dec.read_bytes()
        n = dec.read_long()
    schema = json.loads(meta["avro.schema"].decode("utf-8"))
    codec = meta.get("avro.codec", b"null").decode("utf-8")
    sync = dec.read_fixed(SYNC_SIZE)
    return schema, codec, sync, dec.pos


def list_container_files(path: str) -> List[str]:
    """The .avro part files `read_directory` would read, in its order."""
    if os.path.isfile(path):
        return [path]
    return [
        os.path.join(path, name)
        for name in sorted(os.listdir(path))
        if not name.startswith((".", "_")) and name.endswith(".avro")
    ]


def read_container(
    path: str, *, quarantine: bool = False
) -> tuple[Schema, List[Any]]:
    """Read every record from an Avro object container file."""
    records: List[Any] = []
    schema = None
    for schema, rec in iter_container(path, quarantine=quarantine):
        records.append(rec)
    if schema is None:  # empty container: still surface the schema
        with open(path, "rb") as f:
            data = f.read()
        schema, _, _, _ = read_header(data, path)
    return schema, records


def iter_container(path: str, *, quarantine: bool = False):
    """Stream (schema, record) pairs from an Avro container, decoding one
    block at a time — only a single block's decoded records are ever live
    (the file BYTES are read whole, but those are compact; the decoded
    Python dicts are the memory cost). The streaming path for consumers
    that must stay O(block), e.g. the online request-replay driver.

    Corrupt-block QUARANTINE (`quarantine=True`): a block that fails its
    sync-marker check, inflate, or datum decode is skipped — the reader
    re-synchronizes at the next sync marker, counts the block in
    COUNTERS["quarantined_blocks"], and keeps streaming (one flipped bit
    must not abort a whole replay/ingest file). The error is loud only
    when EVERY block in the file is bad — then there is nothing to salvage
    and silence would hide a truncated or garbage file. A torn tail block
    (crash mid-write) quarantines the same way.

    Quarantine is OPT-IN, for row-shaped data where a lost block costs
    rows (request replay, training-data ingest). Completeness-critical
    reads — model artifacts, checkpoints, scores — keep the default: any
    corrupt block raises, because a model silently missing a block of
    coefficients would serve wrong answers, not degraded ones."""
    from photon_ml_tpu.utils.faults import COUNTERS

    with open(path, "rb") as f:
        data = f.read()
    schema, codec, sync, pos = read_header(data, path)
    if codec not in ("null", "deflate"):
        # A codec this reader does not speak is a file-level contract
        # violation, not block corruption — never quarantined.
        raise ValueError(f"unsupported codec {codec!r}")
    dec = BinaryDecoder(data, pos)
    names = _Names()
    _collect_names(schema, names)

    total_blocks = good_blocks = 0
    first_error: Optional[Exception] = None
    while dec.remaining > 0:
        block_start = dec.pos
        try:
            count = dec.read_long()
            size = dec.read_long()
            if count < 0 or size < 0 or size > dec.remaining:
                raise ValueError(
                    f"implausible block framing (count={count}, size={size})"
                )
            block = dec.read_fixed(size)
            if dec.read_fixed(SYNC_SIZE) != sync:
                raise ValueError("sync marker mismatch")
            if codec == "deflate":
                block = zlib.decompress(block, -15)
            bdec = BinaryDecoder(block)
            # Decode the whole block BEFORE yielding: a datum error halfway
            # through must quarantine the block, not hand a consumer half
            # its records first.
            records = [read_datum(bdec, schema, names) for _ in range(count)]
        except Exception as exc:  # noqa: BLE001 - quarantined, counted below
            if not quarantine:
                raise ValueError(
                    f"{path}: corrupt block at byte {block_start} ({exc})"
                ) from exc
            total_blocks += 1
            first_error = first_error or exc
            COUNTERS.increment("quarantined_blocks")
            logger.warning(
                "%s: quarantined corrupt block at byte %d (%s)",
                path,
                block_start,
                exc,
            )
            # Re-synchronize: the 16-byte sync marker delimits blocks, so
            # the next occurrence past the corrupt region is the next
            # block boundary. No marker left -> the tail is unreadable.
            nxt = data.find(sync, block_start + 1)
            if nxt < 0:
                break
            dec.pos = nxt + SYNC_SIZE
            continue
        total_blocks += 1
        good_blocks += 1
        for rec in records:
            yield schema, rec
    if total_blocks and good_blocks == 0:
        raise ValueError(
            f"{path}: all {total_blocks} block(s) are corrupt "
            f"(first error: {first_error})"
        )


def write_part_files(
    output_dir: str,
    schema: Schema,
    records: Iterable[dict],
    n_records: int,
    *,
    records_per_file: int,
    file_limit: Optional[int] = None,
) -> int:
    """Write records as part-<k>.avro files, splitting by `records_per_file`
    (capped at `file_limit` files when given). Returns the record count."""
    import math

    os.makedirs(output_dir, exist_ok=True)
    if file_limit is not None:
        n_files = max(1, min(file_limit, n_records))
    else:
        n_files = max(1, math.ceil(n_records / records_per_file))
    per_file = math.ceil(n_records / n_files) if n_records else 1
    it = iter(records)
    total = 0
    for k in range(n_files):
        chunk = [r for _, r in zip(range(per_file), it)]
        if not chunk and k > 0:
            break
        total += write_container(
            os.path.join(output_dir, f"part-{k:05d}.avro"), schema, chunk
        )
    return total


def read_directory(
    path: str, *, quarantine: bool = False
) -> tuple[Optional[Schema], List[Any]]:
    """Read all .avro part-files under a directory (HDFS-dir convention the
    reference uses: AvroUtils.readAvroFiles globs part files)."""
    if os.path.isfile(path):
        return read_container(path, quarantine=quarantine)
    schema = None
    records: List[Any] = []
    for name in sorted(os.listdir(path)):
        if name.startswith((".", "_")) or not name.endswith(".avro"):
            continue
        s, recs = read_container(os.path.join(path, name), quarantine=quarantine)
        schema = schema or s
        records.extend(recs)
    return schema, records


def iter_directory(path: str, *, quarantine: bool = False):
    """Stream (schema, record) pairs across every .avro part-file under a
    directory (or a single file), in `list_container_files` order — the
    streaming twin of `read_directory`, for consumers that assemble in
    bounded chunks instead of materializing every row first (the chunked
    ingest path of io/avro_data.read_game_dataset)."""
    for part in list_container_files(path):
        yield from iter_container(part, quarantine=quarantine)

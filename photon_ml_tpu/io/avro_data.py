"""Avro training-data ingestion: records -> GameDataset feature shards.

Counterpart of photon-client data/avro/AvroDataReader.scala:54-490 (+
DataReader.scala:27, FeatureShardConfiguration.scala:26, AvroDataWriter.scala
and GameConverters.scala:44-129). The reference reads Avro GenericRecords
into a DataFrame with one sparse vector column per feature shard, unioning
the feature bags each shard configuration lists and appending an intercept;
GameConverters then turns rows into GameDatum objects. Here records go
straight to the columnar GameDataset: host-side CSR accumulation per shard,
packed to the TPU-friendly padded ELL layout, labels/offsets/weights as
columns, id tags captured from record fields or metadataMap.

Feature keys are `name + DELIMITER + term` ("nameterm" union key,
readFeaturesFromRecord:274-352); index maps are built per shard on first read
(generateIndexMapLoaders:223-244) or supplied for reuse (scoring path).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Mapping, Optional, Sequence, Tuple, Union

import numpy as np

from photon_ml_tpu.data.containers import pack_csr_to_ell
from photon_ml_tpu.data.game_dataset import GameDataset
from photon_ml_tpu.data.index_map import DELIMITER, INTERCEPT_KEY, IndexMap, feature_key
from photon_ml_tpu.io import avro as avro_io
from photon_ml_tpu.io import schemas

# InputColumnsNames defaults (photon-api data/InputColumnsNames.scala:65-73).
RESPONSE = "response"
LABEL = "label"
OFFSET = "offset"
WEIGHT = "weight"
UID = "uid"
META_DATA_MAP = "metadataMap"
_RESERVED = {RESPONSE, LABEL, OFFSET, WEIGHT, UID, META_DATA_MAP}


@dataclasses.dataclass(frozen=True)
class InputColumnNames:
    """Configurable record-field names (InputColumnsNames.scala:65-73;
    parsed from `default=actual` pairs by the drivers, mirroring
    ScoptParserHelpers.parseInputColumnNames:136-150)."""

    response: str = RESPONSE
    offset: str = OFFSET
    weight: str = WEIGHT
    uid: str = UID
    metadata_map: str = META_DATA_MAP

    _KEYS = ("response", "offset", "weight", "uid", "metadataMap")

    def __post_init__(self):
        # "Each column must have a unique name" (InputColumnsNames.scala:28):
        # a collision like response='weight' would silently read labels from
        # the weight field.
        names = [self.response, self.offset, self.weight, self.uid, self.metadata_map]
        if len(set(names)) != len(names):
            raise ValueError(f"input column names must be unique, got {names}")

    @classmethod
    def parse(cls, spec: str) -> "InputColumnNames":
        """Parse "response=the_label,weight=w,..." (unknown keys rejected)."""
        kwargs = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            key, _, value = part.partition("=")
            key = key.strip()
            if key not in cls._KEYS or not value:
                raise ValueError(
                    f"input column spec {part!r}: expected default=actual with "
                    f"default in {cls._KEYS}"
                )
            field = "metadata_map" if key == "metadataMap" else key
            if field in kwargs:
                raise ValueError(f"duplicate input column spec for {key!r}")
            kwargs[field] = value.strip()
        return cls(**kwargs)


@dataclasses.dataclass(frozen=True)
class FeatureShardConfig:
    """One feature shard = union of feature bags + optional intercept
    (FeatureShardConfiguration.scala:26)."""

    feature_bags: Tuple[str, ...] = ("features",)
    has_intercept: bool = True


def _record_features(record: dict, bags: Sequence[str]) -> List[Tuple[str, float]]:
    out: List[Tuple[str, float]] = []
    for bag in bags:
        for f in record.get(bag) or ():
            out.append((feature_key(f["name"], f.get("term", "")), float(f["value"])))
    return out


def _balanced_slice(
    files: List[str], process_index: int, process_count: int
) -> List[str]:
    """Deterministic per-host file assignment balanced by BYTES (greedy
    LPT), the way the reference's mapred input splits balance executors by
    split size (AvroUtils.scala:47) — a round-robin over file COUNT gives
    skewed hosts when file sizes differ. Every host computes the same
    assignment from the same sorted listing + sizes (shared filesystem).

    Byte balance does NOT guarantee ROW balance; consumers that assemble
    globally-sharded arrays (jax.make_array_from_process_local_data) must
    validate per-host row counts — parallel/multihost.py allgathers and
    checks them.
    """
    import heapq
    import os as _os

    sizes = [_os.path.getsize(f) for f in files]
    order = sorted(range(len(files)), key=lambda i: (-sizes[i], files[i]))
    heap = [(0, p) for p in range(process_count)]
    heapq.heapify(heap)
    mine: List[str] = []
    for i in order:
        load, p = heapq.heappop(heap)
        if p == process_index:
            mine.append(files[i])
        heapq.heappush(heap, (load + sizes[i], p))
    # Keep the deterministic global file order within the slice.
    return sorted(mine)


def read_game_dataset(
    path: Union[str, Sequence[str]],
    shard_configs: Mapping[str, FeatureShardConfig],
    *,
    index_maps: Optional[Mapping[str, IndexMap]] = None,
    id_tag_fields: Sequence[str] = (),
    response_field: str = RESPONSE,
    columns: Optional[InputColumnNames] = None,
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
) -> Tuple[GameDataset, Dict[str, IndexMap]]:
    """AvroDataReader.readMerged (:85-220) + GameConverters: Avro file(s)/
    dir(s) -> (GameDataset, per-shard IndexMaps).

    `path` may be one path or a sequence of paths (the reference's drivers
    take N input directories and union them, readMerged's `paths` argument);
    records concatenate in the given order. `id_tag_fields` names record
    fields (or metadataMap keys) to capture as id tags (entity/grouping
    keys). When `index_maps` is given, unseen features are dropped (the
    scoring path); otherwise maps are built from the data (the training
    path).

    Multi-host ingest: pass `process_index`/`process_count` (normally
    `jax.process_index()` / `jax.process_count()`) and each host reads a
    deterministic byte-balanced slice of the expanded FILE list (greedy
    LPT over file sizes, `_balanced_slice`) — the cluster-parallel reader
    split the reference gets from mapred input splits across executors
    (AvroUtils.scala:47). Feature ids must then
    agree across hosts, so a shared `index_maps` (an off-heap store built
    by cli/build_index.py, as the reference shares PalDB partitions via
    sc.addFile) is required.
    """
    paths = [path] if isinstance(path, str) else list(path)
    if (process_index is None) != (process_count is None):
        raise ValueError(
            "process_index and process_count must be passed together — one "
            "without the other would silently read the FULL dataset on "
            "every host"
        )
    if process_count is not None:
        # Validate the pair whenever passed (even process_count == 1):
        # misconfigured cluster wiring must fail loudly, not silently read
        # the full dataset.
        if process_count < 1:
            raise ValueError(f"process_count must be >= 1, got {process_count}")
        if not 0 <= process_index < process_count:
            raise ValueError("process_index must be in [0, process_count)")
    if process_count is not None and process_count > 1:
        missing_maps = [
            s
            for s in shard_configs
            if index_maps is None or s not in index_maps
        ]
        if missing_maps:
            raise ValueError(
                "multi-host ingest (process_count > 1) requires shared "
                f"index_maps for every shard (missing: {missing_maps}) — "
                "build an off-heap store first (cli/build_index.py) so "
                "feature ids agree across hosts"
            )
        files: List[str] = []
        for p in paths:
            files.extend(avro_io.list_container_files(p))
        # Uniform check: every host computes the same sorted file list, so
        # ALL hosts raise identically — an empty-slice host exiting alone
        # would strand the others in their first collective until the
        # distributed-runtime heartbeat timeout.
        if len(files) < process_count:
            raise ValueError(
                f"multi-host ingest needs at least one container file per "
                f"process ({len(files)} files < {process_count} processes) "
                "— split the data"
            )
        paths = _balanced_slice(files, process_index, process_count)

    if columns is not None and response_field != RESPONSE:
        raise ValueError(
            "pass the response name through `columns`, not both `columns` "
            "and `response_field`"
        )
    cols_early = columns or InputColumnNames(response=response_field)

    # Every ingest records its per-stage breakdown (INGEST_STAGES) into an
    # ambient scope and attaches it to the dataset — the bench e2e
    # contract fails loudly on a dataset-from-disk missing it, the same
    # discipline PR 1 set for fit_timing's prepare stages.
    from photon_ml_tpu.utils.observability import TimingRegistry, stage_scope

    reg = TimingRegistry()
    t_ingest = time.perf_counter()
    with stage_scope(reg):
        # Fast path: block-level native decode (io/avro_fast.py), streamed
        # per file. Falls back to the chunked per-datum Python codec for
        # any schema shape the native op-program compiler cannot express.
        try:
            from photon_ml_tpu.io import avro_fast

            fast = avro_fast.try_read_native(
                paths, shard_configs, index_maps, id_tag_fields, cols_early, LABEL
            )
        except Exception:
            fast = None
        if fast is not None:
            ds, built = fast
        else:
            ds, built = _read_python_chunked(
                paths, shard_configs, index_maps, id_tag_fields, cols_early
            )
    ds.ingest_timing = _ingest_timing(reg, time.perf_counter() - t_ingest)
    return ds, built


def _ingest_timing(reg, total_s: float) -> Dict[str, object]:
    """Assemble the INGEST_TIMING_REQUIRED_KEYS dict from the ingest stage
    registry. In a synchronous run the stages + `other` tile the ingest
    wall; a streaming run records decode where it ran (worker threads), so
    the stage sum can exceed the wall — that excess IS the overlap win."""
    from photon_ml_tpu.utils.contracts import INGEST_STAGES

    timing: Dict[str, object] = {k: reg.get(k) for k in INGEST_STAGES}
    timing["other"] = max(
        0.0, total_s - sum(timing[k] for k in INGEST_STAGES)
    )
    timing["ingest_path"] = reg.get_note("ingest_path") or "python"
    timing["streaming"] = reg.get_note("streaming") == "1"
    timing["chunks"] = int(reg.get_note("chunks") or "1")
    return timing


def _read_python_chunked(
    paths: Sequence[str],
    shard_configs: Mapping[str, FeatureShardConfig],
    index_maps: Optional[Mapping[str, IndexMap]],
    id_tag_fields: Sequence[str],
    cols: InputColumnNames,
) -> Tuple[GameDataset, Dict[str, IndexMap]]:
    """Pure-Python codec ingest, streamed in PHOTON_STREAM_CHUNK_ROWS-row
    column chunks: each chunk's records decode (io/avro.iter_directory),
    convert to columnar parts (labels/offsets/weights, parsed feature
    lists, id-tag strings), and are then FREED — decoded-record residency
    is bounded by one chunk instead of the whole dataset, and the chunk
    boundaries provably cannot change results (every per-record conversion
    is independent; tests pin bitwise parity across chunk sizes)."""
    from itertools import islice

    from photon_ml_tpu import planner
    from photon_ml_tpu.utils.observability import set_stage_note, stage_timer

    # Planned quantity (ISSUE 14): explicit PHOTON_STREAM_CHUNK_ROWS wins,
    # else the installed plan's ingest_chunk_rows, else the knob default —
    # chunk boundaries provably cannot change results (see above), so the
    # planner is free to move them.
    chunk_rows = max(1, int(planner.planned_value("ingest_chunk_rows")))

    def _records():
        for p in paths:
            # quarantine=True: training ingest is row-shaped — one corrupt
            # block costs its rows (counted in quarantined_blocks), not the
            # whole file. Model/score reads keep the loud default.
            for _, rec in avro_io.iter_directory(p, quarantine=True):
                yield rec

    def _get(rec: dict, field: str, default: float) -> float:
        v = rec.get(field)
        return default if v is None else float(v)

    n = 0
    n_chunks = 0
    labels_p: List[np.ndarray] = []
    offsets_p: List[np.ndarray] = []
    weights_p: List[np.ndarray] = []
    parsed: Dict[str, List[List[Tuple[str, float]]]] = {
        shard: [] for shard in shard_configs
    }
    keysets: Dict[str, set] = {shard: set() for shard in shard_configs}
    tag_parts: Dict[str, List[np.ndarray]] = {t: [] for t in id_tag_fields}
    uid_parts: List[np.ndarray] = []
    any_uid = False
    stream = iter(_records())
    while True:
        with stage_timer("decode"):
            records = list(islice(stream, chunk_rows))
        if not records:
            break
        n_chunks += 1
        m = len(records)
        with stage_timer("assemble"):
            # Parse feature bags once per shard; index-map key sets build
            # incrementally from the parsed chunk (feature parsing
            # dominates host ETL cost on this path).
            for shard, cfg in shard_configs.items():
                rows = [
                    _record_features(rec, cfg.feature_bags) for rec in records
                ]
                parsed[shard].extend(rows)
                if index_maps is None or shard not in index_maps:
                    ks = keysets[shard]
                    for row in rows:
                        ks.update(k for k, _ in row)
            la = np.empty(m, np.float32)
            of = np.empty(m, np.float32)
            we = np.empty(m, np.float32)
            for i, rec in enumerate(records):
                if cols.response in rec:
                    la[i] = _get(rec, cols.response, 0.0)
                else:
                    la[i] = _get(rec, LABEL, 0.0)
                of[i] = _get(rec, cols.offset, 0.0)
                we[i] = _get(rec, cols.weight, 1.0)
            labels_p.append(la)
            offsets_p.append(of)
            weights_p.append(we)
        with stage_timer("tags"):
            for tag in id_tag_fields:
                # Resolution order (GameConverters.getGameDatumFromRow
                # id-tag lookup): direct record field; "map.key" dotted
                # path into a map-typed column; metadataMap fallback.
                field, _, map_key = tag.partition(".")
                vals = []
                for rec in records:
                    v = rec.get(tag)
                    if v is None and map_key:
                        inner = rec.get(field)
                        if isinstance(inner, dict):
                            v = inner.get(map_key)
                    if v is None:
                        v = (rec.get(cols.metadata_map) or {}).get(tag, "")
                    vals.append(str(v))
                tag_parts[tag].append(np.asarray(vals))
            uids = [rec.get(cols.uid) for rec in records]
            any_uid = any_uid or any(u is not None for u in uids)
            uid_parts.append(
                np.asarray([str(u) if u is not None else "" for u in uids])
            )
        n += m
        del records
    if n == 0:
        raise ValueError(f"no records found under {list(paths)}")
    set_stage_note("ingest_path", "python")
    set_stage_note("chunks", str(n_chunks))
    set_stage_note("streaming", "0")

    from photon_ml_tpu.io.avro_fast import _concat_parts

    labels = _concat_parts(labels_p, np.float32)
    offsets = _concat_parts(offsets_p, np.float32)
    weights = _concat_parts(weights_p, np.float32)
    id_tags: Dict[str, np.ndarray] = {
        tag: _concat_parts(tag_parts[tag], object) for tag in id_tag_fields
    }
    if any_uid:
        id_tags[UID] = _concat_parts(uid_parts, object)

    built: Dict[str, IndexMap] = {}
    for shard, cfg in shard_configs.items():
        if index_maps is not None and shard in index_maps:
            built[shard] = index_maps[shard]
        else:
            built[shard] = IndexMap.from_feature_names(
                keysets[shard], add_intercept=cfg.has_intercept
            )

    # Per-shard CSR -> ELL.
    shards = {}
    for shard, cfg in shard_configs.items():
        imap = built[shard]
        intercept_idx = imap.intercept_index
        if cfg.has_intercept and intercept_idx is None:
            # A prebuilt (off-heap) index store that was created without the
            # intercept key cannot honor has_intercept=True; training would
            # silently fit without a bias term. Fail loudly instead.
            raise ValueError(
                f"feature shard '{shard}' is configured with an intercept but "
                "the index map has no intercept entry — rebuild the index "
                "store with the intercept key or set has_intercept=False"
            )
        indptr = np.zeros(n + 1, np.int64)
        idx_buf: List[int] = []
        val_buf: List[float] = []
        with stage_timer("assemble"):
            for i, row in enumerate(parsed[shard]):
                for key, value in row:
                    j = imap.get_index(key)
                    if j >= 0:
                        idx_buf.append(j)
                        val_buf.append(value)
                if cfg.has_intercept and intercept_idx is not None:
                    idx_buf.append(intercept_idx)
                    val_buf.append(1.0)
                indptr[i + 1] = len(idx_buf)
        with stage_timer("ell"):
            shards[shard] = pack_csr_to_ell(
                indptr,
                np.asarray(idx_buf, np.int64),
                np.asarray(val_buf, np.float32),
                imap.size,
            )

    ds = GameDataset.build(
        shards, labels, offsets=offsets, weights=weights, id_tags=id_tags
    )
    return ds, built


def write_training_examples(
    path: str,
    features: Sequence[Sequence[Tuple[str, float]]],
    labels: Sequence[float],
    *,
    offsets: Optional[Sequence[float]] = None,
    weights: Optional[Sequence[float]] = None,
    uids: Optional[Sequence[str]] = None,
    id_tags: Optional[Mapping[str, Sequence]] = None,
    codec: str = "deflate",
) -> int:
    """AvroDataWriter equivalent: write TrainingExampleAvro records.

    `features[i]` is a list of (feature_key, value); keys are split back into
    (name, term) on the reference DELIMITER.
    """

    def records():
        for i, label in enumerate(labels):
            feats = []
            for key, value in features[i]:
                if DELIMITER in key:
                    name, term = key.split(DELIMITER, 1)
                else:
                    name, term = key, ""
                if key == INTERCEPT_KEY:
                    continue  # intercept is appended at read time
                feats.append({"name": name, "term": term, "value": float(value)})
            meta = None
            if id_tags:
                meta = {k: str(v[i]) for k, v in id_tags.items()}
            yield {
                "uid": None if uids is None else str(uids[i]),
                "label": float(label),
                "features": feats,
                "weight": 1.0 if weights is None else float(weights[i]),
                "offset": 0.0 if offsets is None else float(offsets[i]),
                "metadataMap": meta,
            }

    return avro_io.write_container(
        path, schemas.TRAINING_EXAMPLE, records(), codec=codec
    )

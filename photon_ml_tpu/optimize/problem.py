"""Optimization problems: config + objective + optimizer + variance.

Counterpart of photon-api optimization/ (GeneralizedLinearOptimizationProblem
.scala:38, DistributedOptimizationProblem.scala:46-213,
SingleNodeOptimizationProblem.scala:40-138). The reference splits distributed
vs single-node problems because their Data types differ (RDD vs Iterable);
here one pure `solve` serves both — the fixed effect calls it on the full
(sharded) batch, random effects vmap it over entity blocks. Variance
computation (:84-103): SIMPLE = 1/diag(H), FULL = diag(H^-1) via Cholesky.

`solve` is not jitted itself: it composes jitted kernels (minimize_lbfgs /
minimize_tron) and is safe to call inside jit/vmap contexts.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.containers import LabeledData
from photon_ml_tpu.data.sampling import down_sample
from photon_ml_tpu.ops import objective
from photon_ml_tpu.ops.pallas_glm import DispatchMode
from photon_ml_tpu.ops.losses import PointwiseLoss
from photon_ml_tpu.ops.normalization import NormalizationContext
from photon_ml_tpu.optimize.common import OptResult
from photon_ml_tpu.optimize.config import CoordinateOptimizationConfig
from photon_ml_tpu.optimize.lbfgs import minimize_lbfgs
from photon_ml_tpu.optimize.tron import minimize_tron
from photon_ml_tpu.types import OptimizerType, TaskType, VarianceComputationType

Array = jax.Array


def solve(
    loss: PointwiseLoss,
    data: LabeledData,
    config: CoordinateOptimizationConfig,
    w0: Array,
    norm: Optional[NormalizationContext] = None,
    use_pallas: Optional[DispatchMode] = None,
) -> OptResult:
    """Run the configured optimizer on one GLM problem.

    Mirrors GeneralizedLinearOptimizationProblem.run + OptimizerFactory
    dispatch: LBFGS (plain), OWLQN when L1/elastic (reference selects OWLQN
    inside LBFGS config when l1 > 0), LBFGSB via box constraints, TRON via
    Hessian-vector products.
    """
    l2 = config.l2_weight
    vg = lambda w: objective.value_and_gradient(loss, w, data, norm, l2, use_pallas)
    opt = config.optimizer
    ot = opt.optimizer_type

    if ot == OptimizerType.TRON:
        if not loss.has_hessian:
            raise ValueError(
                f"{loss.name} has no Hessian; TRON requires TwiceDiffFunction "
                "(reference restricts smoothed hinge to LBFGS)"
            )
        hvp = lambda w, v: objective.hessian_vector(
            loss, w, v, data, norm, l2, use_pallas
        )
        return minimize_tron(
            vg, hvp, w0, max_iterations=opt.max_iterations, tolerance=opt.tolerance
        )

    lower = upper = None
    if opt.box_constraints is not None:
        lower, upper = opt.box_constraints
    # The L1-vs-plain decision must be static (reg weights may be traced):
    # it follows the regularization *type*, as in OptimizerFactory.
    from photon_ml_tpu.types import RegularizationType

    use_l1 = ot == OptimizerType.OWLQN or config.regularization.reg_type in (
        RegularizationType.L1,
        RegularizationType.ELASTIC_NET,
    )
    l1 = config.l1_weight
    return minimize_lbfgs(
        vg,
        w0,
        max_iterations=opt.max_iterations,
        tolerance=opt.tolerance,
        l1_weight=l1 if use_l1 else None,
        lower_bounds=lower,
        upper_bounds=upper,
    )


def solve_with_sampling(
    loss: PointwiseLoss,
    data: LabeledData,
    config: CoordinateOptimizationConfig,
    w0: Array,
    norm: Optional[NormalizationContext] = None,
    *,
    task: TaskType,
    key: Optional[jax.Array] = None,
    use_pallas: Optional[DispatchMode] = None,
) -> OptResult:
    """DistributedOptimizationProblem.runWithSampling (:144-170): apply the
    coordinate's DownSampler before optimizing when rate < 1."""
    if config.down_sampling_rate < 1.0:
        if key is None:
            raise ValueError("down-sampling requires a PRNG key")
        data = down_sample(key, data, config.down_sampling_rate, task)
    return solve(loss, data, config, w0, norm, use_pallas)


def compute_variances(
    loss: PointwiseLoss,
    data: LabeledData,
    config: CoordinateOptimizationConfig,
    w: Array,
    norm: Optional[NormalizationContext] = None,
) -> Optional[Array]:
    """Coefficient variances at the optimum
    (DistributedOptimizationProblem.scala:84-103):
      SIMPLE: 1 / diag(H)  — elementwise inverse of the Hessian diagonal
      FULL:   diag(H^-1)   — via Cholesky factorization of the full Hessian
    Returns None for NONE.
    """
    vc = config.variance_computation
    if vc == VarianceComputationType.NONE:
        return None
    l2 = config.l2_weight
    if vc == VarianceComputationType.SIMPLE:
        diag = objective.hessian_diagonal(loss, w, data, norm, l2)
        return jnp.where(jnp.abs(diag) > 0.0, 1.0 / diag, jnp.inf)
    H = objective.hessian_matrix(loss, w, data, norm, l2)
    # diag(H^-1) via Cholesky solve against the identity.
    chol = jnp.linalg.cholesky(H)
    inv = jax.scipy.linalg.cho_solve((chol, True), jnp.eye(H.shape[0], dtype=H.dtype))
    return jnp.diagonal(inv)

"""L-BFGS / OWLQN / box-projected L-BFGS as a single vmappable JAX kernel.

TPU-native counterpart of the reference's Breeze-wrapping optimizers:
  - LBFGS.scala:39-157  (breeze.optimize.LBFGS, maxIter=100, m=10, tol=1e-7;
    post-step projection into box constraints at LBFGS.scala:70-75)
  - OWLQN.scala:40-86   (L1/elastic-net via orthant-wise learning)
  - LBFGSB.scala:40-95  (box constraints; realized here as projected L-BFGS,
    matching the projection the reference applies after every step)

Instead of an iterator of JVM states driving RDD jobs, the whole optimization
is one `lax.while_loop` over a fixed-size carry: circular (s, y) history for
the two-loop recursion, backtracking line search as an inner while_loop, and
integer convergence-reason codes. Because every shape is static, the same
kernel is

  * jitted once for the fixed effect (one big data-parallel problem), and
  * vmapped over entity blocks for random effects — thousands of co-resident
    L-BFGS instances that stop at different iterations via the reason mask
    (the JAX batching rule for while_loop keeps finished lanes frozen).

OWLQN mode (l1_weight not None) uses the standard orthant-wise method: the
pseudo-gradient seeds the two-loop recursion, the direction is sign-projected
against it, steps are projected onto the orthant, and the line-search
objective includes the L1 term.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optimize.common import (
    ConvergenceReason,
    OptResult,
    check_convergence,
    empty_coef_history,
    empty_history,
    record_coefficients,
    record_loss,
    safe_div,
)

Array = jax.Array
ValueAndGrad = Callable[[Array], Tuple[Array, Array]]

DEFAULT_MAX_ITERATIONS = 100  # LBFGS.scala:152-157
DEFAULT_TOLERANCE = 1e-7
DEFAULT_HISTORY = 10
_CURVATURE_EPS = 1e-10
_MAX_LINE_SEARCH = 30
_ARMIJO_C1 = 1e-4


def _pseudo_gradient(x: Array, g: Array, l1: Array) -> Array:
    """OWLQN pseudo-gradient of f(x) + l1*||x||_1.

    For x_i != 0 the subgradient is g_i + l1*sign(x_i); at x_i == 0 pick the
    direction of steepest descent if one exists, else 0.
    """
    right = g + l1
    left = g - l1
    at_zero = jnp.where(right < 0.0, right, jnp.where(left > 0.0, left, 0.0))
    return jnp.where(x > 0.0, right, jnp.where(x < 0.0, left, at_zero))


class _Carry(NamedTuple):
    x: Array
    f: Array  # objective incl. L1 term in OWLQN mode
    g: Array  # smooth gradient
    pg: Array  # pseudo-gradient (== g in plain mode)
    S: Array  # (m, D) step history
    Y: Array  # (m, D) smooth-gradient-difference history
    rho: Array  # (m,)
    k: Array  # number of history updates so far
    iteration: Array
    reason: Array
    init_f: Array
    init_gnorm: Array
    loss_history: Array
    gnorm_history: Array
    coef_history: Array
    evals: Array  # cumulative objective evaluations (incl. line search)


def _two_loop(pg: Array, S: Array, Y: Array, rho: Array, k: Array) -> Array:
    """Classic two-loop recursion over a circular (s, y) buffer with masking."""
    m = S.shape[0]
    order = jnp.mod(k - 1 - jnp.arange(m), m)  # newest first
    valid = jnp.arange(m) < jnp.minimum(k, m)

    def loop1(i, carry):
        q, alphas = carry
        j = order[i]
        a = jnp.where(valid[i], rho[j] * jnp.dot(S[j], q), 0.0)
        return q - a * Y[j], alphas.at[i].set(a)

    q, alphas = lax.fori_loop(0, m, loop1, (pg, jnp.zeros((m,), dtype=pg.dtype)))

    newest = jnp.mod(k - 1, m)
    sy = jnp.dot(S[newest], Y[newest])
    yy = jnp.dot(Y[newest], Y[newest])
    gamma = jnp.where(k > 0, safe_div(sy, yy), 1.0)
    gamma = jnp.where(gamma > 0.0, gamma, 1.0)
    r = gamma * q

    def loop2(i, r):
        pos = m - 1 - i  # oldest first
        j = order[pos]
        b = jnp.where(valid[pos], rho[j] * jnp.dot(Y[j], r), 0.0)
        return r + S[j] * jnp.where(valid[pos], alphas[pos] - b, 0.0)

    return lax.fori_loop(0, m, loop2, r)


@partial(
    jax.jit,
    static_argnames=(
        "value_and_grad_fn",
        "value_fn",
        "max_iterations",
        "history_size",
        "use_l1",
        "use_box",
        "max_line_search",
        "tracking",
        "track_coefficients",
    ),
)
def _minimize(
    value_and_grad_fn: ValueAndGrad,
    w0: Array,
    l1_weight: Array,
    lower: Array,
    upper: Array,
    *,
    value_fn,
    max_iterations: int,
    tolerance: float,
    history_size: int,
    use_l1: bool,
    use_box: bool,
    max_line_search: int,
    tracking: bool,
    track_coefficients: bool,
) -> OptResult:
    dtype = w0.dtype
    dim = w0.shape[0]
    m = history_size
    l1 = jnp.asarray(l1_weight, dtype)

    def clip_box(x: Array) -> Array:
        return jnp.clip(x, lower, upper) if use_box else x

    def total_value(x: Array) -> Array:
        # Line-search trials need the value only; the caller may supply a
        # cheaper value_fn (otherwise XLA's DCE drops the unused gradient).
        f = value_fn(x) if value_fn is not None else value_and_grad_fn(x)[0]
        return f + l1 * jnp.sum(jnp.abs(x)) if use_l1 else f

    w0 = clip_box(w0)
    f0s, g0 = value_and_grad_fn(w0)
    f0 = f0s + l1 * jnp.sum(jnp.abs(w0)) if use_l1 else f0s
    pg0 = _pseudo_gradient(w0, g0, l1) if use_l1 else g0
    init_gnorm = jnp.linalg.norm(pg0)

    history = empty_history(max_iterations, tracking, dtype)
    history = record_loss(history, jnp.zeros((), jnp.int32), f0)
    gnorm_history = empty_history(max_iterations, tracking, dtype)
    gnorm_history = record_loss(gnorm_history, jnp.zeros((), jnp.int32), init_gnorm)
    coef_history = empty_coef_history(max_iterations, track_coefficients, w0)

    init = _Carry(
        x=w0,
        f=f0,
        g=g0,
        pg=pg0,
        S=jnp.zeros((m, dim), dtype),
        Y=jnp.zeros((m, dim), dtype),
        rho=jnp.zeros((m,), dtype),
        k=jnp.zeros((), jnp.int32),
        iteration=jnp.zeros((), jnp.int32),
        reason=jnp.asarray(
            jnp.where(init_gnorm == 0.0, ConvergenceReason.GRADIENT_CONVERGED, 0),
            jnp.int32,
        ),
        init_f=f0,
        init_gnorm=init_gnorm,
        loss_history=history,
        gnorm_history=gnorm_history,
        coef_history=coef_history,
        evals=jnp.ones((), jnp.int32),
    )

    def cond(c: _Carry) -> Array:
        return c.reason == ConvergenceReason.NOT_CONVERGED

    def body(c: _Carry) -> _Carry:
        d = -_two_loop(c.pg, c.S, c.Y, c.rho, c.k)
        if use_l1:
            # Constrain the direction to the descent orthant of the
            # pseudo-gradient (zero misaligned components).
            d = jnp.where(d * c.pg < 0.0, d, 0.0)
            # Orthant for this step: sign(x), or sign(-pg) where x == 0.
            orthant = jnp.where(c.x != 0.0, jnp.sign(c.x), jnp.sign(-c.pg))

        def take_step(t: Array) -> Array:
            x_new = c.x + t * d
            if use_l1:
                x_new = jnp.where(x_new * orthant >= 0.0, x_new, 0.0)
            return clip_box(x_new)

        t0 = jnp.where(c.k == 0, safe_div(1.0, jnp.linalg.norm(d)), 1.0)
        t0 = jnp.where(t0 > 0.0, t0, 1.0)

        def ls_cond(s):
            t, f_new, x_new, tries, ok = s
            return (~ok) & (tries < max_line_search)

        def ls_body(s):
            t, _, _, tries, _ = s
            x_new = take_step(t)
            f_new = total_value(x_new)
            # Armijo on the projected step: f_new <= f + c1 * pg.(x_new - x).
            ok = f_new <= c.f + _ARMIJO_C1 * jnp.dot(c.pg, x_new - c.x)
            ok = ok & jnp.isfinite(f_new)
            return (jnp.where(ok, t, t * 0.5), f_new, x_new, tries + 1, ok)

        t, f_new, x_new, ls_tries, ls_ok = lax.while_loop(
            ls_cond, ls_body, (t0, c.f, c.x, jnp.zeros((), jnp.int32), jnp.zeros((), bool))
        )

        f_sm_new, g_new = value_and_grad_fn(x_new)
        pg_new = _pseudo_gradient(x_new, g_new, l1) if use_l1 else g_new

        s_vec = x_new - c.x
        y_vec = g_new - c.g
        sy = jnp.dot(s_vec, y_vec)
        do_update = ls_ok & (sy > _CURVATURE_EPS)
        slot = jnp.mod(c.k, m)
        S = jnp.where(do_update, c.S.at[slot].set(s_vec), c.S)
        Y = jnp.where(do_update, c.Y.at[slot].set(y_vec), c.Y)
        rho = jnp.where(do_update, c.rho.at[slot].set(safe_div(1.0, sy)), c.rho)
        k = jnp.where(do_update, c.k + 1, c.k)

        iteration = c.iteration + 1
        reason = check_convergence(
            loss=f_new,
            prev_loss=c.f,
            init_loss=c.init_f,
            grad_norm=jnp.linalg.norm(pg_new),
            init_grad_norm=c.init_gnorm,
            iteration=iteration,
            max_iterations=max_iterations,
            tolerance=tolerance,
        )
        # Failed line search: no progress possible along any remembered
        # curvature — stop with OBJECTIVE_NOT_IMPROVING (reference
        # ObjectiveNotImproving reason) and keep the previous point.
        reason = jnp.where(
            ls_ok, reason, jnp.asarray(ConvergenceReason.OBJECTIVE_NOT_IMPROVING, jnp.int32)
        )
        x_out = jnp.where(ls_ok, x_new, c.x)
        f_out = jnp.where(ls_ok, f_new, c.f)
        g_out = jnp.where(ls_ok, g_new, c.g)
        pg_out = jnp.where(ls_ok, pg_new, c.pg)

        return _Carry(
            x=x_out,
            f=f_out,
            g=g_out,
            pg=pg_out,
            S=S,
            Y=Y,
            rho=rho,
            k=k,
            iteration=iteration,
            reason=reason,
            init_f=c.init_f,
            init_gnorm=c.init_gnorm,
            loss_history=record_loss(c.loss_history, iteration, f_out),
            gnorm_history=record_loss(
                c.gnorm_history, iteration, jnp.linalg.norm(pg_out)
            ),
            coef_history=record_coefficients(c.coef_history, iteration, x_out),
            evals=c.evals + ls_tries + 1,
        )

    final = lax.while_loop(cond, body, init)
    return OptResult(
        coefficients=final.x,
        loss=final.f,
        gradient_norm=jnp.linalg.norm(final.pg),
        iterations=final.iteration,
        reason=final.reason,
        loss_history=final.loss_history,
        gradient_norm_history=final.gnorm_history,
        fn_evals=final.evals,
        coefficients_history=final.coef_history if final.coef_history.shape[0] else None,
    )


def minimize_lbfgs(
    value_and_grad_fn: ValueAndGrad,
    w0: Array,
    *,
    value_fn: Optional[Callable[[Array], Array]] = None,
    max_iterations: int = DEFAULT_MAX_ITERATIONS,
    tolerance: float = DEFAULT_TOLERANCE,
    history_size: int = DEFAULT_HISTORY,
    l1_weight: Optional[float | Array] = None,
    lower_bounds: Optional[Array] = None,
    upper_bounds: Optional[Array] = None,
    max_line_search: int = _MAX_LINE_SEARCH,
    tracking: bool = False,
    track_coefficients: bool = False,
) -> OptResult:
    """Minimize `value_and_grad_fn` (smooth part) from `w0`.

    - `l1_weight` not None => OWLQN mode (reference OWLQN.scala); the weight
      itself may be a traced scalar (the reference mutates l1RegWeight across
      the regularization sweep the same way).
    - `lower_bounds`/`upper_bounds` => projected L-BFGS (reference
      LBFGS.scala:70-75 / LBFGSB).
    The function is jittable and vmappable; `value_and_grad_fn` must be pure.
    """
    use_box = lower_bounds is not None or upper_bounds is not None
    dtype = w0.dtype
    neg_inf = jnp.full_like(w0, -jnp.inf)
    pos_inf = jnp.full_like(w0, jnp.inf)
    lower = jnp.asarray(lower_bounds, dtype) if lower_bounds is not None else neg_inf
    upper = jnp.asarray(upper_bounds, dtype) if upper_bounds is not None else pos_inf
    use_l1 = l1_weight is not None
    l1 = jnp.asarray(0.0 if l1_weight is None else l1_weight, dtype)
    return _minimize(
        value_and_grad_fn,
        w0,
        l1,
        lower,
        upper,
        value_fn=value_fn,
        max_iterations=max_iterations,
        tolerance=tolerance,
        history_size=history_size,
        use_l1=use_l1,
        use_box=use_box,
        max_line_search=max_line_search,
        # Requesting snapshots implies state tracking (no silent None).
        tracking=tracking or track_coefficients,
        track_coefficients=track_coefficients,
    )

"""Box-constraint maps: JSON constraint strings -> per-feature bounds.

Counterpart of photon-client io/deprecated/GLMSuite.createConstraintFeatureMap
(GLMSuite.scala:190-265) and ConstraintMapKeys.scala. The constraint string
is a JSON array of maps, each with mandatory "name"/"term" keys and optional
"lowerBound"/"upperBound" (missing = -Inf/+Inf):

    [{"name": "age", "term": "", "lowerBound": 0.0},
     {"name": "*",   "term": "*", "upperBound": 1.0}]

Wildcard rules, verbatim from the reference:
  * name == "*" requires term == "*" and applies the bound to every
    non-intercept feature; it must be the ONLY constraint.
  * term == "*" applies to every term of `name`.
  * Overlapping constraints for the same feature are an error.
  * lowerBound < upperBound required; both infinite is an error.

The resolved map feeds `bounds_arrays`, producing the (lower, upper) vectors
`OptimizerConfig.box_constraints` consumes (projected L-BFGS,
optimize/lbfgs.py; reference LBFGS.scala:70-75 / LBFGSB).
"""

from __future__ import annotations

import json
import math
from typing import Dict, Optional, Tuple

import numpy as np

from photon_ml_tpu.data.index_map import DELIMITER, INTERCEPT_KEY, IndexMap

WILDCARD = "*"

_NAME = "name"
_TERM = "term"
_LOWER = "lowerBound"
_UPPER = "upperBound"


def create_constraint_feature_map(
    constraint_string: Optional[str], index_map: IndexMap
) -> Optional[Dict[int, Tuple[float, float]]]:
    """GLMSuite.createConstraintFeatureMap: JSON -> {feature id: (lb, ub)}.

    Returns None for an empty/absent constraint string or when nothing in the
    map resolves against the index map.
    """
    if not constraint_string:
        return None
    entries = json.loads(constraint_string)
    if not isinstance(entries, list):
        raise ValueError(f"constraint string must be a JSON array: {constraint_string!r}")

    cmap: Dict[int, Tuple[float, float]] = {}
    for entry in entries:
        if _NAME not in entry or _TERM not in entry:
            raise ValueError(
                "Each map in the constraint map is expected to have the "
                f"feature name and term fields specified; malformed map: {entry!r}"
            )
        name = str(entry[_NAME])
        term = str(entry[_TERM])
        lower = float(entry.get(_LOWER, -math.inf))
        upper = float(entry.get(_UPPER, math.inf))
        if not (lower > -math.inf or upper < math.inf):
            raise ValueError(
                f"The lower and upper bound are respectively -Inf and +Inf for "
                f"the feature with name [{name}] and term [{term}]."
            )
        if not lower < upper:
            raise ValueError(
                f"The lower bound [{lower}] is incorrectly specified as greater "
                f"than the upper bound [{upper}] for the feature with name "
                f"[{name}] and term [{term}]."
            )

        if name == WILDCARD:
            if term != WILDCARD:
                raise ValueError(
                    "We do not support wildcard in feature name alone; if the "
                    "name is a wildcard the term must also be a wildcard"
                )
            if cmap:
                raise ValueError(
                    "Potentially conflicting constraints: an all-feature "
                    "wildcard must be the only constraint"
                )
            for key, idx in index_map.items():
                if key != INTERCEPT_KEY:
                    cmap[idx] = (lower, upper)
        elif term == WILDCARD:
            prefix = name + DELIMITER
            for key, idx in index_map.items():
                if key == name or key.startswith(prefix):
                    if idx in cmap:
                        raise ValueError(
                            f"Conflicting bounds for feature name [{name}]: "
                            f"feature id {idx} already constrained"
                        )
                    cmap[idx] = (lower, upper)
        else:
            from photon_ml_tpu.data.index_map import feature_key

            idx = index_map.get_index(feature_key(name, term))
            if idx >= 0:
                if idx in cmap:
                    raise ValueError(
                        f"Conflicting bounds for feature [{name}]/[{term}]"
                    )
                cmap[idx] = (lower, upper)
    return cmap or None


def bounds_arrays(
    cmap: Optional[Dict[int, Tuple[float, float]]], dim: int
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Constraint map -> dense (lower, upper) vectors for the optimizer
    (unconstrained features get (-Inf, +Inf))."""
    if not cmap:
        return None
    lower = np.full(dim, -np.inf, np.float32)
    upper = np.full(dim, np.inf, np.float32)
    for idx, (lb, ub) in cmap.items():
        lower[idx] = lb
        upper[idx] = ub
    return lower, upper

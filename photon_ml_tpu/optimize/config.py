"""Optimization configuration model.

Counterpart of photon-api optimization configs: OptimizerConfig.scala:47,
RegularizationContext.scala:31-134, RegularizationType.scala,
OptimizerType.scala, OptimizerFactory.scala:46-74,
game/CoordinateOptimizationConfiguration.scala:34-99 and
VarianceComputationType.scala. Plain frozen dataclasses consumed by
`optimize.problem` — the host-side "what to run" description, kept separate
from the jitted kernels.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from photon_ml_tpu.types import OptimizerType, RegularizationType, VarianceComputationType


@dataclasses.dataclass(frozen=True)
class RegularizationContext:
    """Splits a total regularization weight into L1/L2 parts
    (RegularizationContext.scala:31-134).

    ELASTIC_NET with mixing alpha: L1 = alpha * weight,
    L2 = (1 - alpha) * weight.
    """

    reg_type: RegularizationType = RegularizationType.NONE
    elastic_net_alpha: Optional[float] = None

    def __post_init__(self):
        if self.reg_type == RegularizationType.ELASTIC_NET:
            a = self.elastic_net_alpha
            if a is None or not (0.0 <= a <= 1.0):
                raise ValueError(
                    f"ELASTIC_NET requires alpha in [0, 1], got {self.elastic_net_alpha}"
                )
        elif self.elastic_net_alpha is not None:
            raise ValueError("elastic_net_alpha only applies to ELASTIC_NET")

    def l1_weight(self, reg_weight: float) -> float:
        if self.reg_type == RegularizationType.L1:
            return reg_weight
        if self.reg_type == RegularizationType.ELASTIC_NET:
            return self.elastic_net_alpha * reg_weight
        return 0.0

    def l2_weight(self, reg_weight: float) -> float:
        if self.reg_type == RegularizationType.L2:
            return reg_weight
        if self.reg_type == RegularizationType.ELASTIC_NET:
            return (1.0 - self.elastic_net_alpha) * reg_weight
        return 0.0


L2 = RegularizationContext(RegularizationType.L2)
L1 = RegularizationContext(RegularizationType.L1)
NO_REG = RegularizationContext(RegularizationType.NONE)


def elastic_net(alpha: float) -> RegularizationContext:
    return RegularizationContext(RegularizationType.ELASTIC_NET, alpha)


def static_config_key(cfg: "CoordinateOptimizationConfig") -> Tuple:
    """Structural hash key over the static (non-reg-weight) parts of a
    coordinate config. `repr()` is NOT usable here: numpy box-constraint
    arrays repr with truncation, so two different constraint vectors could
    silently collide. Array contents hash by bytes. Used for the
    estimator's compiled-coordinate cache and the checkpoint fingerprint."""
    import numpy as np

    opt = cfg.optimizer
    box_key = None
    if opt.box_constraints is not None:
        lo = np.asarray(opt.box_constraints[0])
        up = np.asarray(opt.box_constraints[1])
        box_key = (
            lo.shape, str(lo.dtype), lo.tobytes(),
            up.shape, str(up.dtype), up.tobytes(),
        )
    return (
        opt.optimizer_type,
        opt.max_iterations,
        opt.tolerance,
        box_key,
        cfg.regularization.reg_type,
        cfg.regularization.elastic_net_alpha,
        cfg.down_sampling_rate,
        cfg.variance_computation,
    )


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    """Which optimizer, how long, how tight (OptimizerConfig.scala:47).

    `box_constraints` is an optional (lower, upper) pair of per-feature host
    arrays (the reference's constraintMap).
    """

    optimizer_type: OptimizerType = OptimizerType.LBFGS
    max_iterations: int = 100
    tolerance: float = 1e-7
    box_constraints: Optional[Tuple[object, object]] = None

    def validate(self, reg: RegularizationContext) -> None:
        """Mirror OptimizerFactory's constraints (OptimizerFactory.scala:46-74):
        TRON requires a twice-differentiable objective and supports L2/NONE
        only; L1/elastic-net requires the OWLQN path."""
        if self.optimizer_type == OptimizerType.TRON and reg.reg_type in (
            RegularizationType.L1,
            RegularizationType.ELASTIC_NET,
        ):
            raise ValueError("TRON supports only L2/NONE regularization")
        if self.optimizer_type == OptimizerType.TRON and self.box_constraints is not None:
            raise ValueError(
                "TRON does not support box constraints (no projection step; "
                "the reference routes constrained problems to LBFGSB) — use "
                "LBFGS/OWLQN"
            )


@dataclasses.dataclass(frozen=True)
class CoordinateOptimizationConfig:
    """Per-coordinate optimization settings
    (game/CoordinateOptimizationConfiguration.scala:34-99).

    `down_sampling_rate` < 1 applies only to fixed-effect coordinates
    (FixedEffectOptimizationConfiguration's downSamplingRate).
    """

    optimizer: OptimizerConfig = OptimizerConfig()
    regularization: RegularizationContext = NO_REG
    reg_weight: float = 0.0
    down_sampling_rate: float = 1.0
    variance_computation: VarianceComputationType = VarianceComputationType.NONE

    def __post_init__(self):
        if not (0.0 < self.down_sampling_rate <= 1.0):
            raise ValueError("down_sampling_rate must be in (0, 1]")
        # reg_weight may be a traced jax scalar inside jit (the reg-weight
        # sweep passes it as an argument to avoid recompiles) — only validate
        # concrete host values.
        if isinstance(self.reg_weight, (int, float)) and self.reg_weight < 0.0:
            raise ValueError("reg_weight must be non-negative")
        self.optimizer.validate(self.regularization)

    def with_reg_weight(self, w: float) -> "CoordinateOptimizationConfig":
        """The regularization sweep mutates only the weight
        (DistributedOptimizationProblem.updateRegularizationWeight)."""
        return dataclasses.replace(self, reg_weight=w)

    @property
    def l1_weight(self) -> float:
        return self.regularization.l1_weight(self.reg_weight)

    @property
    def l2_weight(self) -> float:
        return self.regularization.l2_weight(self.reg_weight)

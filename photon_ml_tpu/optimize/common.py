"""Optimizer state, results, and convergence criteria.

Counterpart of the reference's Optimizer template
(photon-lib optimization/Optimizer.scala:36-249, OptimizerState.scala:35,
util/ConvergenceReason.scala, OptimizationStatesTracker.scala). The JVM
template-method loop becomes: each optimizer is a pure function
`minimize(fun, w0, ...) -> OptResult` built on lax.while_loop, with
convergence encoded as an integer reason code inside the carry so the whole
thing jits and vmaps. State tracking (per-iteration loss/time history kept by
OptimizationStatesTracker) is returned as fixed-size arrays when requested.
"""

from __future__ import annotations

import enum
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

Array = jax.Array


class ConvergenceReason(enum.IntEnum):
    """Why optimization stopped (reference util/ConvergenceReason.scala).

    Values are stable — they are stored in OptResult arrays.
    """

    NOT_CONVERGED = 0
    MAX_ITERATIONS = 1
    FUNCTION_VALUES_CONVERGED = 2
    GRADIENT_CONVERGED = 3
    OBJECTIVE_NOT_IMPROVING = 4


class OptResult(NamedTuple):
    """Terminal optimizer state (reference OptimizerState + convergenceReason).

    All fields are arrays so a vmapped solve returns per-problem results.
    `loss_history` is all-NaN-padded beyond `iterations` when tracking is on,
    otherwise a zero-length array (reference isTrackingState,
    Optimizer.scala:46-99).
    """

    coefficients: Array
    loss: Array
    gradient_norm: Array
    iterations: Array
    reason: Array  # int32 ConvergenceReason code
    loss_history: Array
    # Full state tracking (reference OptimizationStatesTracker keeps
    # (coefficients, loss, gradient) per iteration; here the per-iteration
    # scalars ride along as fixed-size arrays, NaN beyond `iterations`).
    gradient_norm_history: Optional[Array] = None
    # Total objective-data passes: value/gradient evaluations plus (TRON)
    # Hessian-vector products — each streams the design matrix once on the
    # fused path, so wall-clock / fn_evals is the per-pass cost.
    fn_evals: Optional[Array] = None
    # (max_iterations + 1, D) per-iteration coefficient snapshots when
    # track_coefficients is requested (the reference OptimizationStatesTracker
    # keeps full OptimizerStates; here it is an opt-in fixed-size array).
    coefficients_history: Optional[Array] = None
    # TRON-only per-iteration diagnostics under tracking (TRON.scala:217-218
    # logs actual/predicted reduction, trust radius delta and CG count).
    trust_radius_history: Optional[Array] = None
    cg_iterations_history: Optional[Array] = None

    @property
    def converged(self) -> Array:
        return self.reason != ConvergenceReason.NOT_CONVERGED


def check_convergence(
    *,
    loss: Array,
    prev_loss: Array,
    init_loss: Array,
    grad_norm: Array,
    init_grad_norm: Array,
    iteration: Array,
    max_iterations: int,
    tolerance: float,
) -> Array:
    """Reference Optimizer.scala:135-149 convergence tests, as a reason code.

    - FUNCTION_VALUES_CONVERGED: |loss - prev_loss| <= tolerance * |init_loss|
    - GRADIENT_CONVERGED:        ||g||_2 <= tolerance * ||g0||_2
    - MAX_ITERATIONS:            iteration >= max_iterations
    Priority mirrors the reference's check order (function values first).
    """
    dtype = loss.dtype
    tol = jnp.asarray(tolerance, dtype)
    func_conv = jnp.abs(loss - prev_loss) <= tol * jnp.abs(init_loss)
    grad_conv = grad_norm <= tol * init_grad_norm
    reason = jnp.where(
        func_conv,
        ConvergenceReason.FUNCTION_VALUES_CONVERGED,
        jnp.where(
            grad_conv,
            ConvergenceReason.GRADIENT_CONVERGED,
            jnp.where(
                iteration >= max_iterations,
                ConvergenceReason.MAX_ITERATIONS,
                ConvergenceReason.NOT_CONVERGED,
            ),
        ),
    )
    return reason.astype(jnp.int32)


def record_loss(history: Array, iteration: Array, loss: Array) -> Array:
    """Append to the fixed-size loss history if tracking is enabled."""
    if history.shape[0] == 0:
        return history
    return history.at[iteration].set(loss)


def empty_history(max_iterations: int, tracking: bool, dtype) -> Array:
    n = max_iterations + 1 if tracking else 0
    return jnp.full((n,), jnp.nan, dtype=dtype)


def empty_coef_history(max_iterations: int, tracking: bool, w0: Array) -> Array:
    """(max_iterations + 1, D) NaN-filled snapshot buffer with w0 at row 0
    (zero rows when tracking is off)."""
    rows = max_iterations + 1 if tracking else 0
    hist = jnp.full((rows, w0.shape[0]), jnp.nan, w0.dtype)
    return hist.at[0].set(w0) if rows else hist


# Coefficient snapshots use the same guard/record semantics as the scalar
# histories; `record_loss` is rank-agnostic (`.at[iteration].set` works for
# the (rows, D) buffer too).
record_coefficients = record_loss


def safe_div(a: Array, b: Array, eps: float = 0.0) -> Array:
    """a / b with 0 where |b| is (near-)zero — guards CG/line-search ratios."""
    bad = jnp.abs(b) <= eps
    return jnp.where(bad, 0.0, a / jnp.where(bad, 1.0, b))

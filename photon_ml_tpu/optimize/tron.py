"""Trust-region Newton (TRON) as a vmappable JAX kernel.

TPU-native counterpart of photon-lib optimization/TRON.scala:80-339 — itself a
port of LIBLINEAR's TRON (Lin & More, "Newton's method for large-scale
logistic regression"). The algorithm semantics mirror the reference exactly:

  * trust radius initialised to ||g0||  (TRON.scala init)
  * constants (eta0, eta1, eta2) = (1e-4, 0.25, 0.75),
    (sigma1, sigma2, sigma3) = (0.25, 0.5, 4.0)      (TRON.scala:97-98)
  * inner truncated conjugate-gradient solve of the trust-region subproblem,
    max 20 iterations, tolerance 0.1*||g||, with the boundary-crossing
    quadratic solve (TRON.scala:278-338)
  * step acceptance when actual > eta0 * predicted reduction; radius update
    by the four-branch sigma rule; up to `max_failures`=5 consecutive
    rejected steps (TRON.scala:206-262)
  * defaults maxIter=15, tol=1e-5 (TRON.scala:256-262)

Structurally it is one lax.while_loop whose body contains the CG while_loop;
Hessian-vector products come from the caller (for GLMs,
ops.objective.hessian_vector — a pair of matvecs that XLA turns into MXU work
with an ICI all-reduce when the data is sharded). Requires a twice-
differentiable objective, like the reference (TwiceDiffFunction bound).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optimize.common import (
    ConvergenceReason,
    OptResult,
    check_convergence,
    empty_coef_history,
    empty_history,
    record_coefficients,
    record_loss,
    safe_div,
)

Array = jax.Array
ValueAndGrad = Callable[[Array], Tuple[Array, Array]]
HessianVector = Callable[[Array, Array], Array]

DEFAULT_MAX_ITERATIONS = 15  # TRON.scala:256-262
DEFAULT_TOLERANCE = 1e-5
DEFAULT_MAX_FAILURES = 5
MAX_CG_ITERATIONS = 20

_ETA0, _ETA1, _ETA2 = 1e-4, 0.25, 0.75
_SIGMA1, _SIGMA2, _SIGMA3 = 0.25, 0.5, 4.0


class _CGCarry(NamedTuple):
    step: Array
    residual: Array
    direction: Array
    rtr: Array
    iteration: Array
    hvps: Array  # exact Hessian-vector products executed
    done: Array


def _truncated_cg(
    hvp: Callable[[Array], Array],
    gradient: Array,
    boundary: Array,
) -> Tuple[Array, Array, Array]:
    """Approximately solve min_s g.s + 0.5 s.H.s s.t. ||s|| <= boundary.

    Returns (cg_iterations, step, residual). Mirrors
    TRON.truncatedConjugateGradientMethod (TRON.scala:278-338) including the
    boundary quadratic: when ||s + alpha*d|| crosses the trust radius, solve
    ||s + alpha*d||^2 = boundary^2 for the positive root.
    """
    tol = 0.1 * jnp.linalg.norm(gradient)
    init = _CGCarry(
        step=jnp.zeros_like(gradient),
        residual=-gradient,
        direction=-gradient,
        rtr=jnp.dot(gradient, gradient),
        iteration=jnp.zeros((), jnp.int32),
        hvps=jnp.zeros((), jnp.int32),
        done=jnp.zeros((), bool),
    )

    def cond(c: _CGCarry) -> Array:
        return (~c.done) & (c.iteration < MAX_CG_ITERATIONS)

    def body(c: _CGCarry) -> _CGCarry:
        converged = jnp.linalg.norm(c.residual) <= tol

        hd = hvp(c.direction)
        alpha = safe_div(c.rtr, jnp.dot(c.direction, hd))
        step_try = c.step + alpha * c.direction
        crossed = jnp.linalg.norm(step_try) > boundary

        # Boundary case: back off, then advance to the trust-region surface.
        std = jnp.dot(c.step, c.direction)
        sts = jnp.dot(c.step, c.step)
        dtd = jnp.dot(c.direction, c.direction)
        dsq = boundary * boundary
        rad = jnp.sqrt(jnp.maximum(std * std + dtd * (dsq - sts), 0.0))
        alpha_b = jnp.where(
            std >= 0.0, safe_div(dsq - sts, std + rad), safe_div(rad - std, dtd)
        )
        step_bound = c.step + alpha_b * c.direction
        resid_bound = c.residual - alpha_b * hd

        # Interior case: standard CG update.
        resid_in = c.residual - alpha * hd
        rtr_new = jnp.dot(resid_in, resid_in)
        beta = safe_div(rtr_new, c.rtr)
        dir_in = resid_in + beta * c.direction

        active = ~converged
        new_done = converged | (active & crossed)
        sel = active & crossed

        return _CGCarry(
            step=jnp.where(converged, c.step, jnp.where(sel, step_bound, step_try)),
            residual=jnp.where(converged, c.residual, jnp.where(sel, resid_bound, resid_in)),
            direction=jnp.where(sel | converged, c.direction, dir_in),
            rtr=jnp.where(sel | converged, c.rtr, rtr_new),
            iteration=jnp.where(converged, c.iteration, c.iteration + 1),
            hvps=c.hvps + 1,
            done=new_done,
        )

    out = lax.while_loop(cond, body, init)
    return out.hvps, out.step, out.residual


class _Carry(NamedTuple):
    x: Array
    f: Array
    g: Array
    delta: Array
    iteration: Array
    failures: Array
    reason: Array
    init_f: Array
    init_gnorm: Array
    loss_history: Array
    gnorm_history: Array
    coef_history: Array
    delta_history: Array  # trust radius per iteration (tracking only)
    cg_history: Array  # CG Hessian-vector products per iteration (tracking)
    evals: Array  # value/gradient evaluations + CG Hessian-vector products


@partial(
    jax.jit,
    static_argnames=(
        "value_and_grad_fn",
        "hessian_vector_fn",
        "max_iterations",
        "max_failures",
        "tracking",
        "track_coefficients",
    ),
)
def minimize_tron(
    value_and_grad_fn: ValueAndGrad,
    hessian_vector_fn: HessianVector,
    w0: Array,
    *,
    max_iterations: int = DEFAULT_MAX_ITERATIONS,
    tolerance: float = DEFAULT_TOLERANCE,
    max_failures: int = DEFAULT_MAX_FAILURES,
    tracking: bool = False,
    track_coefficients: bool = False,
) -> OptResult:
    """Minimize with trust-region Newton; `hessian_vector_fn(w, v) -> H(w) v`."""
    # Requesting snapshots implies state tracking (no silent None).
    tracking = tracking or track_coefficients
    dtype = w0.dtype
    f0, g0 = value_and_grad_fn(w0)
    init_gnorm = jnp.linalg.norm(g0)

    history = empty_history(max_iterations, tracking, dtype)
    history = record_loss(history, jnp.zeros((), jnp.int32), f0)
    gnorm_history = empty_history(max_iterations, tracking, dtype)
    gnorm_history = record_loss(gnorm_history, jnp.zeros((), jnp.int32), init_gnorm)
    coef_history = empty_coef_history(max_iterations, track_coefficients, w0)
    delta_history = empty_history(max_iterations, tracking, dtype)
    delta_history = record_loss(delta_history, jnp.zeros((), jnp.int32), init_gnorm)
    cg_history = empty_history(max_iterations, tracking, dtype)

    init = _Carry(
        x=w0,
        f=f0,
        g=g0,
        delta=init_gnorm,  # reference TRON.init: delta = ||g0||
        iteration=jnp.zeros((), jnp.int32),
        failures=jnp.zeros((), jnp.int32),
        reason=jnp.asarray(
            jnp.where(init_gnorm == 0.0, ConvergenceReason.GRADIENT_CONVERGED, 0),
            jnp.int32,
        ),
        init_f=f0,
        init_gnorm=init_gnorm,
        loss_history=history,
        gnorm_history=gnorm_history,
        coef_history=coef_history,
        delta_history=delta_history,
        cg_history=cg_history,
        evals=jnp.ones((), jnp.int32),
    )

    def cond(c: _Carry) -> Array:
        return c.reason == ConvergenceReason.NOT_CONVERGED

    def body(c: _Carry) -> _Carry:
        hvp_calls, step, residual = _truncated_cg(
            lambda v: hessian_vector_fn(c.x, v), c.g, c.delta
        )
        gs = jnp.dot(c.g, step)
        predicted = -0.5 * (gs - jnp.dot(step, residual))
        x_try = c.x + step
        f_try, g_try = value_and_grad_fn(x_try)
        actual = c.f - f_try
        step_norm = jnp.linalg.norm(step)

        # Radius update (TRON.scala:200-214): alpha from the quadratic
        # interpolation of f along the step, then the four-branch rule.
        denom = f_try - c.f - gs
        alpha = jnp.where(
            denom <= 0.0, _SIGMA3, jnp.maximum(_SIGMA1, -0.5 * safe_div(gs, denom))
        )
        delta = jnp.where(
            actual < _ETA0 * predicted,
            jnp.minimum(jnp.maximum(alpha, _SIGMA1) * step_norm, _SIGMA2 * c.delta),
            jnp.where(
                actual < _ETA1 * predicted,
                jnp.maximum(_SIGMA1 * c.delta, jnp.minimum(alpha * step_norm, _SIGMA2 * c.delta)),
                jnp.where(
                    actual < _ETA2 * predicted,
                    jnp.maximum(_SIGMA1 * c.delta, jnp.minimum(alpha * step_norm, _SIGMA3 * c.delta)),
                    jnp.maximum(c.delta, jnp.minimum(alpha * step_norm, _SIGMA3 * c.delta)),
                ),
            ),
        )

        improved = actual > _ETA0 * predicted
        x_new = jnp.where(improved, x_try, c.x)
        f_new = jnp.where(improved, f_try, c.f)
        g_new = jnp.where(improved, g_try, c.g)
        iteration = jnp.where(improved, c.iteration + 1, c.iteration)
        # Failure budget is per accepted step, as in the reference's do-while
        # inside runOneIteration (numImprovementFailure reset each call).
        failures = jnp.where(improved, 0, c.failures + 1)

        reason = check_convergence(
            loss=f_new,
            prev_loss=c.f,
            init_loss=c.init_f,
            grad_norm=jnp.linalg.norm(g_new),
            init_grad_norm=c.init_gnorm,
            iteration=iteration,
            max_iterations=max_iterations,
            tolerance=tolerance,
        )
        # A rejected step must not trigger FUNCTION_VALUES_CONVERGED (loss
        # delta is 0 by construction); keep running unless failures exhausted.
        reason = jnp.where(
            improved,
            reason,
            jnp.where(
                failures >= max_failures,
                jnp.asarray(ConvergenceReason.OBJECTIVE_NOT_IMPROVING, jnp.int32),
                jnp.asarray(ConvergenceReason.NOT_CONVERGED, jnp.int32),
            ),
        )

        return _Carry(
            x=x_new,
            f=f_new,
            g=g_new,
            delta=delta,
            iteration=iteration,
            failures=failures,
            reason=reason,
            init_f=c.init_f,
            init_gnorm=c.init_gnorm,
            loss_history=record_loss(c.loss_history, iteration, f_new),
            gnorm_history=record_loss(
                c.gnorm_history, iteration, jnp.linalg.norm(g_new)
            ),
            coef_history=record_coefficients(c.coef_history, iteration, x_new),
            # Diagnostics record only on ACCEPTED steps: a rejected attempt
            # must not clobber slot k's accepted radius/CG count (iteration
            # does not advance on rejection).
            delta_history=jnp.where(
                improved,
                record_loss(c.delta_history, iteration, delta),
                c.delta_history,
            ),
            cg_history=jnp.where(
                improved,
                record_loss(c.cg_history, iteration, hvp_calls.astype(dtype)),
                c.cg_history,
            ),
            evals=c.evals + hvp_calls + 1,
        )

    final = lax.while_loop(cond, body, init)
    return OptResult(
        coefficients=final.x,
        loss=final.f,
        gradient_norm=jnp.linalg.norm(final.g),
        iterations=final.iteration,
        reason=final.reason,
        loss_history=final.loss_history,
        gradient_norm_history=final.gnorm_history,
        fn_evals=final.evals,
        coefficients_history=final.coef_history if final.coef_history.shape[0] else None,
        trust_radius_history=final.delta_history if final.delta_history.shape[0] else None,
        cg_iterations_history=final.cg_history if final.cg_history.shape[0] else None,
    )

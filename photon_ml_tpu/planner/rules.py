"""Plan construction: profile-driven rules, calibration, the env gate.

`plan_from_profile` turns a persisted run profile (utils/telemetry
`read_profile` — the loud-contract artifact every fit/serve run writes)
into a typed Plan. Each rule is small, monotone, and evidence-first: it
reads the measured stage walls / dispatch decisions the profile recorded,
chooses a value, and records WHY (the evidence dict) beside WHAT (the
value) and WHAT IT DISPLACED (the fallback). A profile measured on
different hardware refuses loudly (`check_topology` names the
mismatching field) — planning this container from that container's cost
model is exactly the silent mis-tuning the planner exists to end.

The rules deliberately ADOPT what the profile measured wherever the
measured run already made the decision (layout, pack/assembly routing):
those decisions were made by the same auto policies on the same
hardware, so a matching-topology plan reproduces today's defaults — and
therefore today's bits. The genuinely cost-model rules (prefetch depth,
chunk rows, fusion granularity, serving wait/bucket ceiling) only plan
quantities that are bitwise-neutral by construction (PR 9 pins ingest
parity across chunk sizes; scan chunking preserves per-bucket op order;
prefetch is an async upload of data that uploads anyway).

`plan_from_calibration` is the cold-start path for a run with no profile
(PHOTON_PLAN=1): a fast startup probe — host parallelism, backend, a
small host->device bandwidth / dispatch round-trip measurement, the same
roofline vocabulary bench.py records — feeding the subset of rules that
need no stage history. `ensure_ambient_plan` is the one gate the CLI
drivers, bench, and the estimator call: explicit `--profile` beats
`PHOTON_PLAN_PROFILE`, `PHOTON_PLAN=0` kills everything, and an
r06-era profile (no `plan` block) still loads — the block is provenance,
not a requirement.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Dict, Mapping, Optional

from photon_ml_tpu.planner.plan import (
    KNOB_FOR,
    NOVEL_SHAPE_FUSE,
    Plan,
    PlanDecision,
    PlanTopologyError,
    current_plan,
    default_for,
    install_plan,
    normalize,
    plan_suppression_active,
)
from photon_ml_tpu.utils.knobs import _FALSE, _TRUE, get_knob, knob_is_set

logger = logging.getLogger(__name__)

# Topology fields a profile must match before its measurements may plan
# this run. host_cpus is deliberately absent: the cgroup-visible core
# count varies across schedulers of the SAME machine class, and every
# host-parallelism decision re-reads the live effective parallelism.
TOPOLOGY_MATCH_FIELDS = (
    "platform",
    "device_count",
    "device_kind",
    "process_count",
)

# Cost-model constants (rule thresholds, not planned quantities): see
# each rule's comment for the measurement grounding.
_INGEST_SKEW = 4.0  # decode/assemble imbalance before chunk size moves
_CHUNK_ROWS_MIN = 65_536
_CHUNK_ROWS_MAX = 1_048_576
_WAIT_FLOOR_MS = 0.5


def check_topology(
    profile_topology: Mapping[str, object],
    current: Optional[Mapping[str, object]] = None,
) -> Dict[str, object]:
    """Loud refusal when the profile was measured on different hardware;
    returns the current topology on success."""
    if current is None:
        from photon_ml_tpu.utils.telemetry import device_topology

        current = device_topology()
    for field in TOPOLOGY_MATCH_FIELDS:
        have, want = current.get(field), profile_topology.get(field)
        if str(have) != str(want):
            raise PlanTopologyError(
                f"profile topology mismatch on {field!r}: the profile was "
                f"measured with {field}={want!r} but this run has "
                f"{field}={have!r} — refusing to plan from another "
                "machine's cost model (re-profile on this topology, or "
                "run without a profile)"
            )
    return dict(current)


def _decide(
    decisions: Dict[str, PlanDecision],
    name: str,
    value: object,
    source: str,
    evidence: Dict[str, object],
) -> None:
    """Record one decision — knob precedence applied HERE as well as at
    consult time, so the audit block shows `source: "knob"` the moment an
    operator override is in play (the consult-time check in
    planned_value keeps them honest if the env changes afterwards)."""
    fallback = default_for(name)
    knob = KNOB_FOR.get(name)
    if knob is not None and knob_is_set(knob):
        value = normalize(name, get_knob(knob))
        source = "knob"
        evidence = {**evidence, "knob": knob}
    decisions[name] = PlanDecision(
        decision=name,
        value=value,
        source=source,
        evidence=evidence,
        fallback=fallback,
    )


def plan_from_profile(
    profile: Mapping[str, object], profile_path: Optional[str] = None
) -> Plan:
    """Build a Plan from a run profile (fit or serve kind), refusing a
    mismatched topology loudly. r06-era profiles (no `plan` block) are
    the cold-start input this function exists for — the block is what
    THIS plan will add when its run persists a profile."""
    topology = check_topology(profile["device_topology"])
    decisions: Dict[str, PlanDecision] = {}
    src = "profile"
    dispatch = dict(profile.get("dispatch") or {})
    stages = dict(profile.get("stages") or {})

    if profile.get("kind") == "fit":
        ft = dict(profile.get("fit_timing") or {})

        # -- pack / RE-assembly routing: adopt where the measured run
        # placed the pass. The auto policy chose that placement on this
        # same hardware and the walls prove it ran; re-deriving it from
        # the backend would just be auto again, while the profile also
        # covers forced runs an operator validated.
        pack_path = str(dispatch.get("pack_path") or ft.get("pack_path") or "none")
        if pack_path != "none":
            _decide(
                decisions,
                "pack_routing",
                "device" if pack_path == "device" else "host",
                src,
                {
                    "pack_path": pack_path,
                    "pack_device_s": ft.get("pack_device_s"),
                    "pack_host_s": ft.get("pack_host_s"),
                },
            )
        re_path = str(dispatch.get("re_path") or ft.get("re_path") or "none")
        if re_path != "none":
            _decide(
                decisions,
                "assembly_routing",
                "device" if re_path == "device" else "host",
                src,
                {
                    "re_path": re_path,
                    "re_device_s": ft.get("re_device_s"),
                    "re_host_s": ft.get("re_host_s"),
                },
            )

        # -- sparse level-1 layout: adopt the recorded choice (it is the
        # Poisson-economics output for this data/hardware). NOTE this is
        # the one results-affecting decision the planner makes: forcing
        # a layout has exactly the semantics of the PHOTON_SPARSE_LAYOUT
        # knob (rowalign and grouped packings are allclose-, not
        # bitwise-, equivalent), so it is only planned when the profiled
        # run's packs all agreed on ONE layout — a mixed-layout fit
        # records "mixed" and plans nothing, letting each shard's
        # economics re-decide.
        layout = normalize("sparse_layout", dispatch.get("layout") or "auto")
        # normalize maps "mixed"/"none" to "auto", so both skip here.
        if layout != "auto":
            _decide(
                decisions,
                "sparse_layout",
                layout,
                src,
                {"recorded_layout": dispatch.get("layout")},
            )

        # -- prefetch depth: on a pipelined fit, go two coordinates ahead
        # when the host has cores to feed concurrent shard uploads.
        # Deliberately NOT keyed on the profile's upload-stage wall: the
        # stage records where upload work RAN, and prefetched uploads
        # that were fully hidden behind the solve still land there, so
        # the wall cannot distinguish hidden from un-hidden transfers.
        # Host parallelism is re-read LIVE (it is the one topology field
        # check_topology deliberately does not pin). Async prefetch is
        # bitwise-neutral (the shards upload either way).
        from photon_ml_tpu.data.pipeline import effective_host_parallelism

        pipelined = bool(dispatch.get("pipeline"))
        cores = int(effective_host_parallelism())
        depth = int(default_for("prefetch_depth"))
        if pipelined and cores > 2:
            depth = 2
        _decide(
            decisions,
            "prefetch_depth",
            depth,
            src,
            {"pipeline": pipelined, "host_parallelism": cores},
        )

        # -- ingest chunk rows: streamed pure-Python ingest balances the
        # decode pool against in-order assembly; a heavy skew either way
        # means the chunk boundary is in the wrong place. Bitwise-neutral
        # (tests pin parity across chunk sizes), bounded both ways.
        ingest = dict(profile.get("ingest") or {})
        chunk_rows = int(default_for("ingest_chunk_rows"))
        decode_s = float(ingest.get("decode") or 0.0)
        assemble_s = float(ingest.get("assemble") or 0.0)
        if bool(ingest.get("streaming")) and min(decode_s, assemble_s) > 0:
            if decode_s > _INGEST_SKEW * assemble_s:
                chunk_rows //= 2  # decode-bound: smaller chunks overlap more
            elif assemble_s > _INGEST_SKEW * decode_s:
                chunk_rows *= 2  # assembly-bound: fewer chunk boundaries
        chunk_rows = min(max(chunk_rows, _CHUNK_ROWS_MIN), _CHUNK_ROWS_MAX)
        _decide(
            decisions,
            "ingest_chunk_rows",
            chunk_rows,
            src,
            {"decode_s": decode_s, "assemble_s": assemble_s,
             "streaming": bool(ingest.get("streaming"))},
        )

        # -- RE bucket shape set + scan fusion granularity: shapes the
        # profile proved on this hardware fuse unboundedly (one scan
        # program per shape, today's default); shapes it never saw chunk
        # at a conservative cap so a first-dispatch failure or hang costs
        # one small group, not the whole shape. A fit whose robustness
        # counters show collective re-dispatches or watchdog trips caps
        # EVERY group: a re-dispatch repeats one chunk's work instead of
        # the whole fused program. Chunking preserves per-bucket op
        # order, so any cap is bitwise-identical to unbounded fusion.
        shapes = {
            cid: [list(map(int, s)) for s in shape_list]
            for cid, shape_list in dict(
                profile.get("bucket_shapes") or {}
            ).items()
        }
        _decide(
            decisions,
            "re_bucket_shapes",
            shapes,
            src,
            {"coordinates": sorted(shapes)},
        )
        robustness = dict(ft.get("robustness") or {})
        flaky = int(robustness.get("collective_retries") or 0) + int(
            robustness.get("watchdog_trips") or 0
        )
        fuse = int(default_for("scan_fusion_max"))
        if flaky > 0:
            fuse = NOVEL_SHAPE_FUSE
        _decide(
            decisions,
            "scan_fusion_max",
            fuse,
            src,
            {
                "collective_retries": robustness.get("collective_retries"),
                "watchdog_trips": robustness.get("watchdog_trips"),
            },
        )

        # -- bench scoring rep count: a prior round's rtt<5% adaptation
        # result, persisted so repeat rounds start calibrated (recorded
        # by bench.py into the e2e profile's dispatch block).
        reps = dispatch.get("bench_score_reps")
        if reps is not None:
            _decide(
                decisions,
                "bench_score_reps",
                max(1, int(reps)),  # a corrupt profile must not plan 0
                src,
                {"adapted_by": "bench scoring rtt<5% loop"},
            )

    else:  # serve profile
        serving = dict(profile.get("serving") or {})

        # -- serving bucket ceiling: the power-of-two bucket ladder only
        # needs to reach the batches traffic actually forms. p95 batch
        # size (recorded by the batcher) rounded up to a power of two,
        # floored at 8 so a warm engine never compiles a degenerate set,
        # bounded by the BUILT-IN ceiling — deliberately not the prior
        # run's planned ceiling, so round-over-round re-planning is not a
        # one-way downward ratchet. Saturated evidence (p95 at the prior
        # run's own ceiling) means traffic wanted MORE than that run
        # could form, so the plan recovers to the larger of the default
        # and the observed ceiling instead of pinning the shrink.
        observed_ceiling = int(
            dispatch.get("max_batch") or default_for("serving_max_batch")
        )
        hard_ceiling = int(default_for("serving_max_batch"))
        p95_batch = serving.get("batch_size_p95")
        if p95_batch is None:
            # The batcher observes every batch into the mergeable
            # serving_batch_size histogram; the profile's metrics
            # snapshot carries it.
            hist = (dict(profile.get("metrics") or {}).get("histograms") or {}).get(
                "serving_batch_size"
            )
            if hist:
                from photon_ml_tpu.utils.telemetry import snapshot_quantile

                p95_batch = snapshot_quantile(hist, 0.95)
        # The clamp ceiling honors BOTH bounds upward: the built-in
        # default and a larger operator-validated ceiling the profile
        # ran (a 512-ceiling run whose p95 was 300 must not be planned
        # DOWN to 256 — never plan below demonstrated traffic).
        upper = max(hard_ceiling, observed_ceiling)
        max_batch = observed_ceiling
        if p95_batch:
            if int(p95_batch) >= observed_ceiling:
                # Saturated: the observed p95 itself hit the prior run's
                # ceiling (not the 8-floored ladder value, which would
                # misread every small-ceiling run as saturated).
                max_batch = upper
            else:
                b = 8
                while b < int(p95_batch):
                    b <<= 1
                max_batch = min(max(b, 8), upper)
        _decide(
            decisions,
            "serving_max_batch",
            max_batch,
            src,
            {"profile_max_batch": observed_ceiling, "batch_size_p95": p95_batch},
        )

        # -- micro-batch wait: a partial batch should not wait longer
        # than the latency budget traffic demonstrated. Half the observed
        # p50, clamped to [floor, BUILT-IN default] — each round derives
        # from that round's fresh p50, never min'd against the prior
        # plan's wait, so the wait recovers when latency grows back.
        # Without p50 evidence, adopt the profile's recorded wait.
        # `is None`, not `or`: a recorded wait of 0.0 (immediate flush, a
        # valid operator config) must be adopted, not silently replanned
        # to the default.
        profile_wait = dispatch.get("max_wait_ms")
        p50 = serving.get("p50_ms")
        if p50:
            # Clamp ceiling honors BOTH bounds upward (the bucket-ceiling
            # rule's discipline): the built-in default and a LARGER
            # operator-validated recorded wait — evidence may tighten the
            # wait within that ceiling, never ignore the bigger budget
            # the profiled run validated.
            upper_wait = max(
                float(default_for("serving_max_wait_ms")),
                0.0 if profile_wait is None else float(profile_wait),
            )
            wait = min(upper_wait, max(float(p50) / 2.0, _WAIT_FLOOR_MS))
        else:
            wait = float(
                default_for("serving_max_wait_ms")
                if profile_wait is None
                else profile_wait
            )
        _decide(
            decisions,
            "serving_max_wait_ms",
            wait,
            src,
            {"p50_ms": p50, "profile_max_wait_ms": dispatch.get("max_wait_ms")},
        )

    return Plan(
        source="profile",
        profile_path=profile_path,
        topology=topology,
        decisions=decisions,
    )


def calibration_probe() -> Dict[str, object]:
    """The fast cold-start measurement (no profile): backend + effective
    host parallelism + one small host->device upload bandwidth / dispatch
    round-trip sample — the roofline vocabulary bench.py records, cheap
    enough for startup (<~1s, one tiny compile)."""
    from photon_ml_tpu.data.pipeline import effective_host_parallelism
    from photon_ml_tpu.utils.telemetry import device_topology

    topo = device_topology()
    probe: Dict[str, object] = {
        "host_parallelism": effective_host_parallelism(),
        "platform": topo.get("platform"),
        "device_count": topo.get("device_count"),
    }
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np

        buf = np.zeros((1 << 20,), np.float32)  # 4 MB: small but > caches
        t0 = time.perf_counter()
        dev = jax.device_put(buf)
        jax.block_until_ready(dev)
        probe["upload_gb_per_s"] = round(
            buf.nbytes / max(time.perf_counter() - t0, 1e-9) / 1e9, 3
        )
        one = jnp.ones((8,))
        fn = jax.jit(lambda x: x + 1.0)
        jax.block_until_ready(fn(one))  # compile outside the sample
        t0 = time.perf_counter()
        jax.block_until_ready(fn(one))
        probe["dispatch_rtt_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 3
        )
    except Exception:  # noqa: BLE001 - a probe must never kill a run
        logger.debug("calibration device probe failed", exc_info=True)
    return probe


def plan_from_calibration(
    probe: Optional[Mapping[str, object]] = None,
) -> Plan:
    """Cold-start plan (PHOTON_PLAN=1, no profile): only the rules whose
    evidence a startup probe can supply. Routing follows the measured
    backend (identical to the auto policies — bitwise); prefetch depth
    follows host parallelism (deeper prefetch needs cores to feed it)."""
    from photon_ml_tpu.utils.telemetry import device_topology

    probe = dict(probe if probe is not None else calibration_probe())
    decisions: Dict[str, PlanDecision] = {}
    src = "calibration"
    accel = str(probe.get("platform")) in ("tpu", "gpu")
    routing = "device" if accel else "host"
    _decide(
        decisions, "pack_routing", routing, src, {"platform": probe.get("platform")}
    )
    _decide(
        decisions,
        "assembly_routing",
        routing,
        src,
        {"platform": probe.get("platform")},
    )
    cores = int(probe.get("host_parallelism") or 1)
    _decide(
        decisions,
        "prefetch_depth",
        2 if cores > 2 else int(default_for("prefetch_depth")),
        src,
        {"host_parallelism": cores},
    )
    _decide(
        decisions,
        "ingest_chunk_rows",
        int(default_for("ingest_chunk_rows")),
        src,
        {"host_parallelism": cores},
    )
    return Plan(
        source="calibration",
        profile_path=None,
        topology=device_topology(),
        decisions=decisions,
    )


def plan_mode() -> Optional[bool]:
    """PHOTON_PLAN tri-state: True = force (calibrate without a
    profile), False = off, None = auto (plan only when a profile is
    supplied via --profile / PHOTON_PLAN_PROFILE)."""
    env = str(get_knob("PHOTON_PLAN")).strip().lower()
    if env in _TRUE:
        return True
    if env in _FALSE:
        return False
    return None


def ensure_ambient_plan(profile_path: Optional[str] = None) -> Optional[Plan]:
    """The one planner gate (CLI drivers / bench / estimator startup):
    install a plan if configuration asks for one and none is installed.
    Explicit `profile_path` (--profile) beats PHOTON_PLAN_PROFILE;
    PHOTON_PLAN=0 disables everything; topology mismatches and broken
    profiles refuse LOUDLY (a mis-planned run is worse than an unplanned
    one). Returns the active plan, or None when planning is off."""
    if plan_suppression_active():
        return None
    active = current_plan()
    if active is not None:
        return active
    mode = plan_mode()
    if mode is False:
        return None
    path = profile_path or str(get_knob("PHOTON_PLAN_PROFILE")).strip()
    if path and profile_path is None and not os.path.exists(path):
        # PHOTON_PLAN_PROFILE is a cache HANDLE, not only an input: bench
        # (and any repeat-round workflow) points it at the path the run
        # will WRITE its profile to, so on the first round the file does
        # not exist yet. Run unplanned and let this round populate it —
        # but an explicit --profile argument stays loud: the operator
        # named a specific artifact, and a missing one is an error.
        logger.info(
            "PHOTON_PLAN_PROFILE=%s does not exist yet; running unplanned "
            "(this run can write it for the next round)",
            path,
        )
        path = ""
    if path:
        from photon_ml_tpu.utils.telemetry import read_profile

        return install_plan(plan_from_profile(read_profile(path), path))
    if mode is True:
        return install_plan(plan_from_calibration())
    return None

"""The typed runtime plan: decisions, precedence, and the ambient install.

Photon ML inherited Spark's pathology of hand-tuned runtime knobs — the
Spark-ML performance study (PAPERS.md) measures exactly our knob set
(partitioning/layout, batch granularity, host-vs-executor routing)
dominating end-to-end cost, and Flare's whole-pipeline-compilation thesis
argues those decisions should be made once, from measured cost, per
hardware. This module is the decision SUBSTRATE: a `Plan` is a typed set
of `PlanDecision`s (name, chosen value, source, the evidence that chose
it, and the default it displaced), built by `photon_ml_tpu.planner.rules`
from a persisted run profile (utils/telemetry.read_profile) or a startup
calibration, installed process-ambient, and consulted by every site that
used to hard-code the quantity:

    value = planner.planned_value("ingest_chunk_rows")

Precedence is fixed and auditable: an EXPLICITLY SET `PHOTON_*` knob
always wins over the plan (recorded as `source: "knob"`), the plan wins
over the built-in default, and with no plan installed every site returns
exactly the default it returned before the planner existed — `PHOTON_PLAN=0`
(or simply never supplying a profile) is bitwise-identical to the
pre-planner tree by construction.

Every fit and serving run records the active plan as a `plan` block
(contracts.PLAN_BLOCK_KEYS) in `fit_timing` / `serving-summary.json`, and
`install_plan` journals one `plan_decision` event per decision so
`cli/obs journal --validate` covers planned runs.

`DEFAULTS` below is the ONE home for the planned-quantity constants; the
static analyzer's `planner-constant` check fails the build when a planned
quantity is re-hard-coded as a magic number anywhere else in the package.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import threading
from typing import Dict, Optional

from photon_ml_tpu.utils.contracts import (
    PLAN_BLOCK_KEYS,
    PLAN_DECISION_KEYS,
)
from photon_ml_tpu.utils.knobs import (
    _FALSE,
    _TRUE,
    KNOBS,
    get_knob,
    knob_is_set,
)

logger = logging.getLogger(__name__)


class PlanTopologyError(ValueError):
    """A profile measured on different hardware must not silently plan
    this run: the refusal names the mismatching topology field."""


# The planned quantities and their built-in defaults — the values every
# consulting site used before the planner existed, so an absent plan is
# bitwise-identical to the pre-planner tree. Knob-backed quantities
# (KNOB_FOR) take their default from the typed knob registry instead so
# the two sources cannot drift.
DEFAULTS: Dict[str, object] = {
    # Host data plane: how many upcoming coordinates the coordinate-
    # descent loop prefetches while the current one solves.
    "prefetch_depth": 1,
    # RE sweep fusion: max same-shape buckets fused into one lax.scan
    # program (0 = unbounded, today's behavior: one program per shape).
    "scan_fusion_max": 0,
    # RE bucket shape set the profile proved on this hardware (list of
    # [entities, capacity] pairs per coordinate); consulted by the scan
    # grouping to fuse proven shapes unboundedly while novel shapes
    # chunk conservatively. Empty = no evidence, everything fuses.
    "re_bucket_shapes": {},
    # Serving: the compiled bucket ceiling (bucket set = the power-of-two
    # ladder up to it) and the micro-batcher's partial-batch flush wait.
    "serving_max_batch": 256,
    "serving_max_wait_ms": 2.0,
    # bench.py scoring section: lax.scan rep count whose rtt correction
    # measured <5% of wall (the adaptation result a repeat round reuses).
    "bench_score_reps": 64,
}

# Scan-fuse cap for RE bucket shapes the plan's profile never proved on
# this hardware: a novel shape's first dispatch (fresh compile, unknown
# cost) runs in small chunks so a failure/hang costs one group. Proven
# shapes (re_bucket_shapes) fuse per scan_fusion_max.
NOVEL_SHAPE_FUSE = 8

# Decision -> the PHOTON_* knob whose EXPLICIT setting overrides the plan
# (and whose registry default is the decision's fallback).
KNOB_FOR: Dict[str, str] = {
    "ingest_chunk_rows": "PHOTON_STREAM_CHUNK_ROWS",
    "sparse_layout": "PHOTON_SPARSE_LAYOUT",
    "pack_routing": "PHOTON_DEVICE_PACK",
    "assembly_routing": "PHOTON_DEVICE_ASSEMBLY",
    # Continuous refresh (ISSUE 16): how many streamed rows to batch
    # before an incremental fit + delta swap, and how much churn the
    # delta path absorbs before forcing a warm full refit.
    "refresh_batch_rows": "PHOTON_REFRESH_BATCH_ROWS",
    "refresh_max_delta_fraction": "PHOTON_REFRESH_MAX_DELTA_FRACTION",
    # Precision ladder (ISSUE 20): the HBM-pressure thresholds at which
    # the autopilot quantizes a tenant down one rung.
    "tier_bf16_pressure": "PHOTON_TIER_BF16_PRESSURE",
    "tier_int8_pressure": "PHOTON_TIER_INT8_PRESSURE",
}

# Knob-value -> decision-vocabulary normalizers: tri-state str knobs
# store "" for "auto" and accept the registry's bool spellings (imported
# from utils/knobs so a new spelling there cannot silently drift past
# these maps); the decision vocabulary says "auto"/"device"/"host"
# (routing) and "auto"/"rowalign"/"grouped" (layout) so plan blocks read
# unambiguously.


def _norm_routing(raw: object) -> str:
    low = str(raw).strip().lower()
    if low in _TRUE:
        return "device"
    if low in _FALSE:
        return "host"
    return "auto"


def _norm_layout(raw: object) -> str:
    low = str(raw).strip().lower()
    if low in ("rowalign", "row_aligned", "aligned"):
        return "rowalign"
    if low in ("grouped", "feature", "legacy"):
        return "grouped"
    return "auto"


_NORMALIZE = {
    "pack_routing": _norm_routing,
    "assembly_routing": _norm_routing,
    "sparse_layout": _norm_layout,
}


def normalize(name: str, value: object) -> object:
    fn = _NORMALIZE.get(name)
    return value if fn is None else fn(value)


def default_for(name: str) -> object:
    """The value a consulting site gets with no plan installed — knob
    registry default for knob-backed decisions, DEFAULTS otherwise."""
    knob = KNOB_FOR.get(name)
    if knob is not None:
        return normalize(name, KNOBS[knob].default)
    if name not in DEFAULTS:
        raise KeyError(
            f"unknown planned quantity {name!r} "
            f"(known: {sorted((*DEFAULTS, *KNOB_FOR))})"
        )
    return DEFAULTS[name]


@dataclasses.dataclass(frozen=True)
class PlanDecision:
    """One planned quantity: what was chosen, by what, from what."""

    decision: str
    value: object
    source: str  # "profile" | "calibration" | "knob" | "default"
    evidence: Dict[str, object]
    fallback: object  # the default the chosen value displaced

    def as_dict(self) -> Dict[str, object]:
        return {k: getattr(self, k) for k in PLAN_DECISION_KEYS}


@dataclasses.dataclass(frozen=True)
class Plan:
    """A typed runtime plan: the decision set plus its provenance."""

    source: str  # "profile" | "calibration"
    profile_path: Optional[str]
    topology: Dict[str, object]
    decisions: Dict[str, PlanDecision]

    # NOTE: deliberately no per-plan value accessor — planned_value() is
    # the ONE precedence implementation (knob > plan > default); a
    # plan-local lookup would silently skip operator knob overrides.

    def block(self) -> Dict[str, object]:
        """The `plan` block fit_timing / serving-summary.json carry
        (contracts.PLAN_BLOCK_KEYS, in order)."""
        return dict(
            zip(
                PLAN_BLOCK_KEYS,
                (
                    True,
                    self.source,
                    self.profile_path,
                    [
                        self.decisions[k].as_dict()
                        for k in sorted(self.decisions)
                    ],
                ),
            )
        )


def inactive_block() -> Dict[str, object]:
    """The `plan` block of an unplanned run — always present so a missing
    block is loud, never ambiguous with 'planner off'."""
    return dict(zip(PLAN_BLOCK_KEYS, (False, "off", None, [])))


# ------------------------------------------------------------ ambient plan
# One plan per process, installed by the CLI drivers / bench / estimator
# startup and consulted by the decision sites. A module global guarded by
# a lock (install/uninstall only; reads are a single attribute load).
_LOCK = threading.Lock()
_ACTIVE: Optional[Plan] = None
# Suppression depth (plan_suppressed): >0 forces every consult back to
# the built-in defaults and makes ensure_ambient_plan a no-op —
# process-wide (not thread-local) because consults happen on prepare-pool
# worker threads too.
_SUPPRESS = 0


@contextlib.contextmanager
def plan_suppressed():
    """Scope that measures the HAND-TUNED DEFAULT config: inside it,
    planned_value ignores any installed plan and any PHOTON_PLAN*
    configuration (explicit per-quantity knobs still win — they are
    operator intent, not planning), ensure_ambient_plan installs
    nothing, and plan_block() reads inactive. The bench planner
    section's pilot fits run under this so a repeat round with
    PHOTON_PLAN_PROFILE set cannot silently plan its own baseline."""
    global _SUPPRESS
    with _LOCK:
        _SUPPRESS += 1
    try:
        yield
    finally:
        with _LOCK:
            _SUPPRESS -= 1


def plan_suppression_active() -> bool:
    return _SUPPRESS > 0


def install_plan(plan: Plan) -> Plan:
    """Make `plan` the process-ambient plan and journal every decision
    (one `plan_decision` event each — cli/obs journal --validate covers
    planned runs)."""
    global _ACTIVE
    from photon_ml_tpu.utils import telemetry

    with _LOCK:
        _ACTIVE = plan
    for name in sorted(plan.decisions):
        d = plan.decisions[name]
        telemetry.emit_event(
            "plan_decision",
            decision=d.decision,
            value=d.value,
            source=d.source,
            fallback=d.fallback,
        )
    logger.info(
        "runtime plan installed (%s%s): %d decision(s)",
        plan.source,
        f" from {plan.profile_path}" if plan.profile_path else "",
        len(plan.decisions),
    )
    return plan


def uninstall_plan() -> None:
    global _ACTIVE
    with _LOCK:
        _ACTIVE = None


def apply_online_decision(
    name: str,
    value: object,
    *,
    evidence: Optional[Dict[str, object]] = None,
) -> Optional[PlanDecision]:
    """The autopilot's online re-plan (ISSUE 19): update ONE planned
    quantity mid-run, with exactly the startup precedence — an EXPLICITLY
    SET `PHOTON_*` knob for the quantity pins it (operator intent
    outranks the controller; returns None, nothing changes), otherwise
    the decision lands in the ambient plan (installing a minimal
    `source="autopilot"` plan when none is active) where every future
    `planned_value` consult sees it, and is journaled as a
    `plan_decision` with `source: "autopilot"` like any other decision.
    Under `plan_suppressed` (the hand-tuned-default measurement scope)
    this is a no-op. Returns the applied PlanDecision, whose `fallback`
    is the value the decision displaced — what a rollback restores."""
    global _ACTIVE
    from photon_ml_tpu.utils import telemetry

    knob = KNOB_FOR.get(name)
    if knob is not None and knob_is_set(knob):
        return None
    if plan_suppression_active():
        return None
    with _LOCK:
        plan = _ACTIVE
        prior = plan.decisions.get(name) if plan is not None else None
        fallback = prior.value if prior is not None else default_for(name)
        d = PlanDecision(
            decision=name,
            value=normalize(name, value),
            source="autopilot",
            evidence=dict(evidence or {}),
            fallback=fallback,
        )
        if plan is None:
            plan = Plan(
                source="autopilot",
                profile_path=None,
                topology={},
                decisions={name: d},
            )
        else:
            decisions = dict(plan.decisions)
            decisions[name] = d
            plan = dataclasses.replace(plan, decisions=decisions)
        _ACTIVE = plan
    telemetry.emit_event(
        "plan_decision",
        decision=d.decision,
        value=d.value,
        source=d.source,
        fallback=d.fallback,
    )
    return d


def current_plan() -> Optional[Plan]:
    return _ACTIVE


def plan_block(
    overrides: Optional[Dict[str, object]] = None,
) -> Dict[str, object]:
    """The active plan's block, or the inactive block — what every
    fit_timing / serving summary records unconditionally.

    `overrides` (decision name -> value actually used) re-sources those
    decisions as `"knob"` in the recorded block: an explicit CLI flag is
    operator intent exactly like an env knob, and the audit trail must
    show what the run actually ran with, not what the plan proposed."""
    plan = current_plan()
    if plan is None or plan_suppression_active():
        return inactive_block()
    block = plan.block()
    if overrides:
        decisions = [dict(d) for d in block["decisions"]]
        for d in decisions:
            name = d.get("decision")
            # Re-source unconditionally — even when the flag happens to
            # equal the plan's choice, the OPERATOR pinned this value and
            # the audit must say so (a "profile" source implies the next
            # replan may move it; a pinned value will not move).
            if name in overrides:
                d["value"] = overrides[name]
                d["source"] = "knob"
                d["evidence"] = {
                    **dict(d.get("evidence") or {}),
                    "explicit_override": True,
                }
        block["decisions"] = decisions
    return block


_UNSET = object()


def planned_value(name: str, *, default: object = _UNSET) -> object:
    """The one accessor decision sites call. Precedence, in order:

    1. an EXPLICITLY SET `PHOTON_*` knob for this quantity (the operator
       said so; the plan block records it as `source: "knob"`),
    2. the installed plan's decision,
    3. the built-in default (`default` argument when given, else the
       knob-registry / DEFAULTS value) — with no plan installed this is
       exactly the pre-planner behavior, bit for bit.
    """
    knob = KNOB_FOR.get(name)
    if knob is not None and knob_is_set(knob):
        return normalize(name, get_knob(knob))
    if not plan_suppression_active():
        plan = current_plan()
        if plan is not None and name in plan.decisions:
            return plan.decisions[name].value
    if default is not _UNSET:
        return default
    return default_for(name)

"""photon-planner: the adaptive runtime plan layer (ISSUE 14).

A `Plan` replaces the tree's hand-tuned runtime constants — sparse
layout, pack/assembly device-vs-host routing, ingest chunk rows,
coordinate prefetch depth, RE scan-fusion granularity, the serving
bucket ceiling and micro-batch wait — with typed, evidence-carrying
decisions built from a persisted run profile
(`utils/telemetry.read_profile`) or a fast startup calibration.

Precedence everywhere: explicit `PHOTON_*` knob > plan > default. With
no plan installed (or `PHOTON_PLAN=0`) every consulting site returns the
exact pre-planner default — bitwise-identical behavior by construction.
Every run records the active plan as a `plan` block
(contracts.PLAN_BLOCK_KEYS) in `fit_timing` / `serving-summary.json`.

See `plan.py` (types, ambient install, consult accessor) and `rules.py`
(profile rules, calibration, topology guard, the env gate).
"""

from photon_ml_tpu.planner.plan import (  # noqa: F401
    DEFAULTS,
    KNOB_FOR,
    Plan,
    PlanDecision,
    PlanTopologyError,
    apply_online_decision,
    current_plan,
    default_for,
    inactive_block,
    install_plan,
    plan_block,
    plan_suppressed,
    plan_suppression_active,
    planned_value,
    uninstall_plan,
)
from photon_ml_tpu.planner.rules import (  # noqa: F401
    TOPOLOGY_MATCH_FIELDS,
    calibration_probe,
    check_topology,
    ensure_ambient_plan,
    plan_from_calibration,
    plan_from_profile,
    plan_mode,
)

__all__ = [
    "DEFAULTS",
    "KNOB_FOR",
    "Plan",
    "PlanDecision",
    "PlanTopologyError",
    "TOPOLOGY_MATCH_FIELDS",
    "apply_online_decision",
    "calibration_probe",
    "check_topology",
    "current_plan",
    "default_for",
    "ensure_ambient_plan",
    "inactive_block",
    "install_plan",
    "plan_block",
    "plan_from_calibration",
    "plan_from_profile",
    "plan_mode",
    "plan_suppressed",
    "plan_suppression_active",
    "planned_value",
    "uninstall_plan",
]

"""Supervised GLM model classes with link functions.

Counterpart of photon-api supervised/** :
  - model/GeneralizedLinearModel.scala:33-51 (abstract `computeMean`)
  - classification/LogisticRegressionModel.scala:31 (sigmoid link,
    0.5 posterior threshold via BinaryClassifier)
  - classification/SmoothedHingeLossLinearSVMModel.scala (margin sign)
  - regression/LinearRegressionModel.scala (identity link)
  - regression/PoissonRegressionModel.scala (exp link)
  - classification/BinaryClassifier.scala (predictClassWithThreshold)

A model is a frozen pytree (Coefficients + static task tag), so it passes
through jit/vmap; the per-task classes only pin the link function and add the
classifier surface. `create_model` is the `glmConstructor` lambda the
estimator wires per task (GameEstimator.scala:714-720).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Union

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.containers import Features, LabeledData, SparseFeatures
from photon_ml_tpu.game.model import Coefficients
from photon_ml_tpu.ops.losses import mean_for_task
from photon_ml_tpu.types import TaskType

Array = jax.Array

# MathConst.POSITIVE_RESPONSE_THRESHOLD equivalent for binary classification.
DEFAULT_THRESHOLD = 0.5


def _margins(features: Features, w: Array, offsets: Optional[Array]) -> Array:
    if isinstance(features, SparseFeatures):
        z = features.matvec(w)
    else:
        z = features @ w
    if offsets is not None:
        z = z + offsets
    return z


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class GeneralizedLinearModel:
    """Coefficients + task-specific mean link (GeneralizedLinearModel.scala:33).

    `compute_score` is the raw margin x.w (+offset); `compute_mean` applies
    the task link function (:51).
    """

    coefficients: Coefficients
    task: TaskType = dataclasses.field(metadata=dict(static=True))

    @property
    def dim(self) -> int:
        return self.coefficients.dim

    def compute_score(
        self, features: Features, offsets: Optional[Array] = None
    ) -> Array:
        return _margins(features, self.coefficients.means, offsets)

    def compute_mean(
        self, features: Features, offsets: Optional[Array] = None
    ) -> Array:
        return mean_for_task(self.task, self.compute_score(features, offsets))

    def predict(self, features: Features, offsets: Optional[Array] = None) -> Array:
        """Mean response (GeneralizedLinearModel.predictWithOffset)."""
        return self.compute_mean(features, offsets)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class BinaryClassifier(GeneralizedLinearModel):
    """Adds class prediction at a posterior threshold
    (BinaryClassifier.scala predictClassWithThreshold)."""

    def predict_class(
        self,
        features: Features,
        offsets: Optional[Array] = None,
        threshold: float = DEFAULT_THRESHOLD,
    ) -> Array:
        # >= threshold is positive (BinaryClassifier.scala: "greater than or
        # equal to this threshold is identified as positive").
        return (self.compute_mean(features, offsets) >= threshold).astype(jnp.float32)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class LogisticRegressionModel(BinaryClassifier):
    """Sigmoid link (LogisticRegressionModel.scala:31)."""


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class SmoothedHingeLossLinearSVMModel(BinaryClassifier):
    """Margin-based classifier; 'mean' is the raw margin and the class
    threshold applies to it (SmoothedHingeLossLinearSVMModel.scala)."""


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class LinearRegressionModel(GeneralizedLinearModel):
    """Identity link (LinearRegressionModel.scala)."""


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class PoissonRegressionModel(GeneralizedLinearModel):
    """Exponential link (PoissonRegressionModel.scala)."""


_MODEL_CLASS = {
    TaskType.LOGISTIC_REGRESSION: LogisticRegressionModel,
    TaskType.LINEAR_REGRESSION: LinearRegressionModel,
    TaskType.POISSON_REGRESSION: PoissonRegressionModel,
    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: SmoothedHingeLossLinearSVMModel,
}


def create_model(
    task: TaskType, coefficients: Union[Coefficients, Array]
) -> GeneralizedLinearModel:
    """TaskType -> concrete model (the estimator's glmConstructor,
    GameEstimator.scala:714-720)."""
    if not isinstance(coefficients, Coefficients):
        coefficients = Coefficients(jnp.asarray(coefficients))
    return _MODEL_CLASS[task](coefficients=coefficients, task=task)

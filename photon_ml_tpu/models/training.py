"""Legacy single-GLM training workflow: reg-weight sweep + model selection.

Counterpart of photon-api ModelTraining.scala:34-213 and photon-client
ModelSelection.scala:26-92. The reference builds ONE
DistributedOptimizationProblem, sorts the regularization weights descending,
and foldLefts over them with warm start (ModelTraining.scala:175-213,
updateRegularizationWeight per step). Here the solve kernel is jitted once
with the reg weight as a traced argument, so the whole sweep reuses one XLA
executable — the TPU translation of "one problem object, mutate the weight".

Model selection (ModelSelection.scala): best weight by AUC for classifiers
(larger better), by RMSE / Poisson loss for regressions (smaller better).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.containers import LabeledData
from photon_ml_tpu.evaluation.suite import (
    EvaluationSuite,
    better_than,
    default_evaluator_for_task,
)
from photon_ml_tpu.game.model import Coefficients
from photon_ml_tpu.models.glm import GeneralizedLinearModel, create_model
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.ops.normalization import NormalizationContext
from photon_ml_tpu.optimize.common import OptResult
from photon_ml_tpu.optimize.config import CoordinateOptimizationConfig
from photon_ml_tpu.optimize.problem import compute_variances, solve
from photon_ml_tpu.types import TaskType, VarianceComputationType

Array = jax.Array


@dataclasses.dataclass
class SweepResult:
    """Per-regularization-weight trained models + optimizer diagnostics."""

    models: Dict[float, GeneralizedLinearModel]
    results: Dict[float, OptResult]

    def weights_descending(self) -> List[float]:
        return sorted(self.models, reverse=True)


def train_glm_sweep(
    data: LabeledData,
    task: TaskType,
    config: CoordinateOptimizationConfig,
    reg_weights: Sequence[float],
    *,
    norm: Optional[NormalizationContext] = None,
    initial: Optional[Array] = None,
    warm_start: bool = True,
) -> SweepResult:
    """Train one GLM per regularization weight with warm start across the
    descending-sorted sweep (ModelTraining.scala:175-213).

    The solve is jitted with reg_weight as a traced scalar: every weight in
    the sweep reuses the same compiled program.
    """
    loss = loss_for_task(task)
    dim = data.feature_dim
    w0 = jnp.zeros((dim,), jnp.float32) if initial is None else jnp.asarray(initial)

    @jax.jit
    def _solve(w_init: Array, reg_weight: Array) -> OptResult:
        cfg = config.with_reg_weight(reg_weight)
        return solve(loss, data, cfg, w_init, norm)

    models: Dict[float, GeneralizedLinearModel] = {}
    results: Dict[float, OptResult] = {}
    w = w0
    for rw in sorted(reg_weights, reverse=True):
        res = _solve(w, jnp.asarray(float(rw), jnp.float32))
        results[rw] = res
        variances = None
        if config.variance_computation != VarianceComputationType.NONE:
            variances = compute_variances(
                loss, data, config.with_reg_weight(float(rw)), res.coefficients, norm
            )
        # The optimizer works in transformed space (normalization folded into
        # effective coefficients, ValueAndGradientAggregator.scala:36-49); the
        # returned models live in ORIGINAL space so scoring/persistence sees
        # raw features — the legacy driver's modelToOriginalSpace step
        # (Driver.scala train + NormalizationContext.scala:73-90).
        means = res.coefficients
        if norm is not None:
            means, variances = norm.coefficients_to_original_space(means, variances)
        models[rw] = create_model(task, Coefficients(means, variances))
        if warm_start:
            w = res.coefficients
    return SweepResult(models=models, results=results)


def select_best_model(
    sweep: SweepResult,
    validation: LabeledData,
    task: TaskType,
) -> Tuple[float, GeneralizedLinearModel, float]:
    """Pick the best reg weight on validation data by the task's default
    metric (ModelSelection.scala:26-92: AUC for binary tasks, error loss for
    regressions). Returns (weight, model, metric value)."""
    et = default_evaluator_for_task(task)
    suite = EvaluationSuite([et], validation.labels, validation.weights)
    best: Optional[Tuple[float, GeneralizedLinearModel, float]] = None
    for rw, model in sweep.models.items():
        # Evaluators consume raw margins (the convention of the validation
        # path in game/coordinate_descent.py); POISSON_LOSS in particular is
        # l(z, y), not l(mean, y).
        scores = model.compute_score(validation.features, validation.offsets)
        value = suite.evaluate(scores).primary_value
        if best is None or better_than(et, value, best[2]):
            best = (rw, model, value)
    assert best is not None
    return best

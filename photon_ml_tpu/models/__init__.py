"""Supervised GLM model classes and the legacy training workflow."""

from photon_ml_tpu.models.glm import (
    BinaryClassifier,
    GeneralizedLinearModel,
    LinearRegressionModel,
    LogisticRegressionModel,
    PoissonRegressionModel,
    SmoothedHingeLossLinearSVMModel,
    create_model,
)
from photon_ml_tpu.models.training import (
    SweepResult,
    select_best_model,
    train_glm_sweep,
)

"""Pod-parallel hyperparameter sweep tests (ISSUE 12).

The batched trial executor's contract: trial-stacked and shard-group
evaluation are BITWISE-equal to the serial per-trial loop on the same
candidate matrix — cold rounds, warm-started rounds, and the explicit
warm-start-disabled parity mode — and the finalized winner is bitwise-equal
to a standalone fit of the winning configuration. Plus the executor's
operational surface: stack-plan splitting, mode choice via the sweep knobs,
and trial_start/trial_finish journal events.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from photon_ml_tpu.data.game_dataset import (
    FixedEffectDataConfig,
    GameDataset,
    RandomEffectDataConfig,
)
from photon_ml_tpu.estimators.game_estimator import GameEstimator
from photon_ml_tpu.hyperparameter import (
    HyperparameterConfig,
    HyperparameterTuningMode,
    SweepExecutor,
    get_tuner,
)
from photon_ml_tpu.optimize.config import (
    L2,
    CoordinateOptimizationConfig,
    OptimizerConfig,
)
from photon_ml_tpu.types import TaskType, VarianceComputationType


def _make_data(n, n_entities, d_fixed=4, d_re=3, seed=0):
    r = np.random.default_rng(seed)
    entity = r.integers(0, n_entities, size=n)
    Xf = r.normal(size=(n, d_fixed)).astype(np.float32)
    Xe = r.normal(size=(n, d_re)).astype(np.float32)
    w = r.normal(size=d_fixed).astype(np.float32)
    u = r.normal(size=(n_entities, d_re)).astype(np.float32)
    margin = Xf @ w + np.einsum("nd,nd->n", Xe, u[entity])
    y = (r.uniform(size=n) < 1 / (1 + np.exp(-margin))).astype(np.float32)
    return GameDataset.build(
        {"global": jnp.asarray(Xf), "per_entity": jnp.asarray(Xe)},
        y,
        id_tags={"entityId": entity},
    )


def _opt_config(max_iter=8, variance=VarianceComputationType.NONE):
    return CoordinateOptimizationConfig(
        optimizer=OptimizerConfig(max_iterations=max_iter, tolerance=1e-7),
        regularization=L2,
        reg_weight=1.0,
    ) if variance == VarianceComputationType.NONE else (
        CoordinateOptimizationConfig(
            optimizer=OptimizerConfig(max_iterations=max_iter, tolerance=1e-7),
            regularization=L2,
            reg_weight=1.0,
            variance_computation=variance,
        )
    )


_DATA_CFGS = {
    "fixed": FixedEffectDataConfig("global"),
    "re": RandomEffectDataConfig("entityId", "per_entity", min_bucket=4),
}


@pytest.fixture(scope="module")
def sweep_problem():
    return _make_data(96, 6, seed=1), _make_data(64, 6, seed=2)


def _executor(problem, mode, *, variance=VarianceComputationType.NONE,
              warm_start=True, max_stack=None, shard_groups=None,
              iterations=1, seed=4):
    train, val = problem
    est = GameEstimator(
        TaskType.LOGISTIC_REGRESSION,
        _DATA_CFGS,
        coordinate_descent_iterations=iterations,
        seed=seed,
    )
    base = {"fixed": _opt_config(variance=variance),
            "re": _opt_config(variance=variance)}
    return est, est.sweep_executor(
        train, val, base, mode=mode, warm_start=warm_start,
        max_stack=max_stack, shard_groups=shard_groups,
    )


def _assert_models_equal(a, b, what=""):
    assert len(a) == len(b)
    for i, (x, z) in enumerate(zip(a, b)):
        assert x.keys() == z.keys()
        for cid in x:
            for name in x[cid]:
                u, v = x[cid][name], z[cid][name]
                if u is None and v is None:
                    continue
                np.testing.assert_array_equal(
                    np.asarray(u),
                    np.asarray(v),
                    err_msg=f"{what} trial {i} {cid}/{name} not bitwise",
                )


_POINTS = np.array([[0.1, 0.5], [10.0, 0.02]])
_POINTS2 = np.array([[0.7, 1.5], [3.0, 0.2]])


class TestStackedParity:
    def test_stacked_matches_serial_bitwise_cold_and_warm(self, sweep_problem):
        _, ex_serial = _executor(sweep_problem, "serial")
        _, ex_stacked = _executor(sweep_problem, "stacked")
        vs1 = ex_serial.evaluate_batch(_POINTS)
        vt1 = ex_stacked.evaluate_batch(_POINTS)
        assert vs1 == vt1
        # warm-started round: the incumbent seeds every trial
        ms1, mt1 = ex_serial.last_trial_models, ex_stacked.last_trial_models
        _assert_models_equal(ms1, mt1, "cold round")
        vs2 = ex_serial.evaluate_batch(_POINTS2)
        vt2 = ex_stacked.evaluate_batch(_POINTS2)
        assert vs2 == vt2
        _assert_models_equal(
            ex_serial.last_trial_models,
            ex_stacked.last_trial_models,
            "warm round",
        )
        assert [t.mode for t in ex_stacked.trials] == ["stacked"] * 4

    def test_warm_start_disabled_parity(self, sweep_problem):
        """The explicit parity mode: every round cold, so round 2 results
        are independent of round 1's incumbent in BOTH modes."""
        _, ex_serial = _executor(sweep_problem, "serial", warm_start=False)
        _, ex_stacked = _executor(sweep_problem, "stacked", warm_start=False)
        ex_serial.evaluate_batch(_POINTS)
        ex_stacked.evaluate_batch(_POINTS)
        vs = ex_serial.evaluate_batch(_POINTS2)
        vt = ex_stacked.evaluate_batch(_POINTS2)
        assert vs == vt
        _assert_models_equal(
            ex_serial.last_trial_models, ex_stacked.last_trial_models,
            "warm-start-disabled",
        )
        # Cold rounds: a FRESH serial executor evaluating the same points
        # produces the same models — round 2 never saw round 1.
        _, ex_fresh = _executor(sweep_problem, "serial", warm_start=False)
        ex_fresh.evaluate_batch(_POINTS2)
        _assert_models_equal(
            ex_fresh.last_trial_models, ex_stacked.last_trial_models,
            "round independence",
        )

    def test_stacked_variance_parity(self, sweep_problem):
        """FE variances are recomputed post-dispatch through the serial
        `_variance_fn` program; RE variances ride the shared scan — both
        must be bitwise."""
        _, ex_serial = _executor(
            sweep_problem, "serial", variance=VarianceComputationType.SIMPLE
        )
        _, ex_stacked = _executor(
            sweep_problem, "stacked", variance=VarianceComputationType.SIMPLE
        )
        vs = ex_serial.evaluate_batch(_POINTS)
        vt = ex_stacked.evaluate_batch(_POINTS)
        assert vs == vt
        _assert_models_equal(
            ex_serial.last_trial_models, ex_stacked.last_trial_models,
            "variance",
        )
        for trial in ex_stacked.last_trial_models:
            assert trial["fixed"]["var"] is not None
            assert trial["re"]["v"] is not None

    def test_stack_plan_splits_rounds(self, sweep_problem):
        """k > max_stack splits into chunks; results identical to serial."""
        pts = np.array([[0.1, 0.5], [10.0, 0.02], [1.0, 1.0]])
        _, ex_serial = _executor(sweep_problem, "serial")
        _, ex_stacked = _executor(sweep_problem, "stacked", max_stack=2)
        vs = ex_serial.evaluate_batch(pts)
        vt = ex_stacked.evaluate_batch(pts)
        assert vs == vt
        _assert_models_equal(
            ex_serial.last_trial_models, ex_stacked.last_trial_models,
            "split round",
        )
        (dec,) = ex_stacked.stack_decisions
        assert dec["chunks"] == [2, 1]
        assert dec["k"] == 3 and dec["max_stack"] == 2
        assert dec["per_trial_bytes"] > 0


class TestShardGroupParity:
    def test_single_device_groups_bitwise(self, sweep_problem):
        """Default shard groups (one device each) run the serial loop's
        exact programs on other chips — bitwise, cold and warm rounds."""
        _, ex_serial = _executor(sweep_problem, "serial")
        _, ex_group = _executor(sweep_problem, "shard_group")
        assert ex_serial.evaluate_batch(_POINTS) == ex_group.evaluate_batch(_POINTS)
        _assert_models_equal(
            ex_serial.last_trial_models, ex_group.last_trial_models,
            "group cold",
        )
        assert ex_serial.evaluate_batch(_POINTS2) == ex_group.evaluate_batch(_POINTS2)
        _assert_models_equal(
            ex_serial.last_trial_models, ex_group.last_trial_models,
            "group warm",
        )
        assert [t.mode for t in ex_group.trials] == ["shard_group"] * 4

    def test_multi_device_groups_bitwise(self, sweep_problem):
        """Groups of >1 device: sample data replicated, RE store row-sharded
        (the PR 7 ring sweep inside the group) — still bitwise vs serial."""
        if len(jax.devices()) < 4:
            pytest.skip("needs >= 4 devices")
        _, ex_serial = _executor(sweep_problem, "serial")
        _, ex_group = _executor(sweep_problem, "shard_group", shard_groups=2)
        assert ex_serial.evaluate_batch(_POINTS) == ex_group.evaluate_batch(_POINTS)
        _assert_models_equal(
            ex_serial.last_trial_models, ex_group.last_trial_models,
            "multi-dev cold",
        )
        assert ex_serial.evaluate_batch(_POINTS2) == ex_group.evaluate_batch(_POINTS2)
        _assert_models_equal(
            ex_serial.last_trial_models, ex_group.last_trial_models,
            "multi-dev warm",
        )


class TestExecutorSurface:
    def test_finalize_winner_bitwise_vs_standalone(self, sweep_problem):
        train, val = sweep_problem
        est, ex = _executor(sweep_problem, "stacked")
        ex.evaluate_batch(_POINTS)
        res = ex.finalize()
        assert res.best_trial in (0, 1)
        assert np.isfinite(res.winner_value)
        assert res.winner_refit_s >= 0
        # Standalone fit of the winning config through the estimator's own
        # serial path — the deliverable model must be bitwise-equal even
        # though the search itself warm-started and stacked trials.
        import dataclasses

        base = {"fixed": _opt_config(), "re": _opt_config()}
        win_cfg = {
            "fixed": dataclasses.replace(
                base["fixed"], reg_weight=float(res.best_point[0])
            ),
            "re": dataclasses.replace(
                base["re"], reg_weight=float(res.best_point[1])
            ),
        }
        standalone = est.fit(train, val, [win_cfg])[0]
        np.testing.assert_array_equal(
            np.asarray(res.winner_model["fixed"].coefficients.means),
            np.asarray(standalone.model["fixed"].coefficients.means),
        )
        np.testing.assert_array_equal(
            np.asarray(res.winner_model["re"].coefficients_matrix),
            np.asarray(standalone.model["re"].coefficients_matrix),
        )

    def test_mode_knob_forcing(self, sweep_problem, monkeypatch):
        _, ex = _executor(sweep_problem, None)
        # auto on a replicated store prefers stacking
        assert ex._choose_mode(2) == "stacked"
        monkeypatch.setenv("PHOTON_SWEEP_TRIAL_STACK", "0")
        assert ex._choose_mode(2) in ("shard_group", "serial")
        monkeypatch.setenv("PHOTON_SWEEP_TRIAL_STACK", "1")
        assert ex._choose_mode(2) == "stacked"

    def test_candidate_matrix_shape_validation(self, sweep_problem):
        _, ex = _executor(sweep_problem, "serial")
        with pytest.raises(ValueError, match="columns"):
            ex.evaluate_batch(np.ones((2, 3)))
        with pytest.raises(ValueError, match="unknown sweep mode"):
            _executor(sweep_problem, "bogus")

    def test_reset_keeps_programs(self, sweep_problem):
        _, ex = _executor(sweep_problem, "stacked")
        ex.evaluate_batch(_POINTS)
        programs = dict(ex._programs)
        assert programs
        ex.reset()
        assert ex.trials == [] and ex.rounds == 0 and ex._best is None
        assert ex._programs == programs

    def test_trial_journal_events(self, sweep_problem, tmp_path):
        from photon_ml_tpu.utils import telemetry

        journal = telemetry.RunJournal(str(tmp_path / "journal.jsonl"))
        telemetry.install_journal(journal)
        try:
            _, ex = _executor(sweep_problem, "serial")
            ex.evaluate_batch(_POINTS)
        finally:
            telemetry.uninstall_journal()
            journal.close()
        n_ok, errors = telemetry.validate_journal(str(tmp_path / "journal.jsonl"))
        assert errors == []
        import json

        lines = [
            json.loads(l)
            for l in open(tmp_path / "journal.jsonl")
            if l.strip()
        ]
        starts = [l for l in lines if l["type"] == "trial_start"]
        finishes = [l for l in lines if l["type"] == "trial_finish"]
        assert len(starts) == 2 and len(finishes) == 2
        assert {f["trial"] for f in finishes} == {0, 1}
        assert all(f["mode"] == "serial" for f in finishes)
        assert all(np.isfinite(f["value"]) for f in finishes)

    def test_all_rejected_trial_falls_back_to_zeros_in_every_mode(
        self, sweep_problem
    ):
        """A NaN reg weight drives every update of a coordinate non-finite:
        the divergence guard rejects them all, the serial loop keeps NO
        model for that coordinate, and the trial must report the zeros
        model (matching the stacked where-carry) instead of crashing."""
        bad = np.array([[np.nan, 1.0]])
        _, ex_serial = _executor(sweep_problem, "serial")
        _, ex_stacked = _executor(sweep_problem, "stacked")
        vs = ex_serial.evaluate_batch(bad)
        vt = ex_stacked.evaluate_batch(bad)
        assert vs == vt
        _assert_models_equal(
            ex_serial.last_trial_models, ex_stacked.last_trial_models,
            "all-rejected",
        )
        # Whether the degenerate solve is rejected (diverged) or resolves
        # to an accepted zeros step, the COUNT must be mode-invariant
        # (stacked charges 1 + PHOTON_SOLVE_RETRIES per rejection, the
        # serial attempt loop's own arithmetic).
        assert (
            ex_serial.trials[0].diverged_steps
            == ex_stacked.trials[0].diverged_steps
        )
        # The fallback itself, directly: a coordinate the serial loop kept
        # NO model for reports the zeros model instead of KeyError.
        from photon_ml_tpu.game.model import GameModel

        zeros = ex_serial._trial_arrays("fixed", GameModel({}))
        np.testing.assert_array_equal(np.asarray(zeros["w"]), 0.0)

    def test_tuner_sweep_drives_executor(self, sweep_problem):
        """HyperparameterTuner.sweep: batched Bayesian rounds through the
        executor, finalize() winner returned."""
        dims = [
            HyperparameterConfig("fixed", 1e-2, 1e2, transform="LOG"),
            HyperparameterConfig("re", 1e-2, 1e2, transform="LOG"),
        ]
        _, ex = _executor(sweep_problem, "stacked")
        tuner = get_tuner(HyperparameterTuningMode.BAYESIAN)
        out = tuner.sweep(
            4, dims, HyperparameterTuningMode.BAYESIAN, ex, seed=3,
            batch_size=2,
        )
        assert out is not None
        search_result, sweep_result = out
        assert len(search_result.observations) == 4
        assert len(sweep_result.trials) == 4
        assert ex.rounds == 2
        assert sweep_result.winner_model is not None

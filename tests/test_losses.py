"""Unit tests for pointwise losses — derivative consistency and known values.

Counterpart of the reference's loss unit tests
(photon-api src/test/.../function/glm/*LossFunctionTest.scala): values match
the closed forms, and d1/d2 match autodiff of the loss.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.ops import losses
from photon_ml_tpu.types import TaskType

ALL_LOSSES = [losses.LOGISTIC, losses.SQUARED, losses.POISSON, losses.SMOOTHED_HINGE]


def _labels_for(loss):
    if loss.name == "poisson":
        return np.array([0.0, 1.0, 3.0, 7.0])
    if loss.name == "squared":
        return np.array([-1.3, 0.0, 2.5, 4.0])
    return np.array([0.0, 1.0, 0.0, 1.0])


@pytest.mark.parametrize("loss", ALL_LOSSES, ids=lambda l: l.name)
def test_d1_matches_autodiff(loss):
    z = jnp.linspace(-3.0, 3.0, 25)
    y = jnp.asarray(np.resize(_labels_for(loss), 25), jnp.float32)
    auto = jax.vmap(jax.grad(lambda zz, yy: loss.loss(zz, yy)))(z, y)
    np.testing.assert_allclose(loss.d1(z, y), auto, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize(
    "loss", [losses.LOGISTIC, losses.SQUARED, losses.POISSON], ids=lambda l: l.name
)
def test_d2_matches_autodiff(loss):
    z = jnp.linspace(-3.0, 3.0, 25)
    y = jnp.asarray(np.resize(_labels_for(loss), 25), jnp.float32)
    auto = jax.vmap(jax.grad(jax.grad(lambda zz, yy: loss.loss(zz, yy))))(z, y)
    np.testing.assert_allclose(loss.d2(z, y), auto, rtol=1e-4, atol=1e-4)


def test_logistic_values():
    # l(0, y) = log 2 for either label; stable at extreme margins.
    z = jnp.array([0.0, 0.0, 50.0, -50.0, 500.0])
    y = jnp.array([1.0, 0.0, 1.0, 0.0, 0.0])
    vals = losses.LOGISTIC.loss(z, y)
    np.testing.assert_allclose(vals[:2], np.log(2.0), rtol=1e-6)
    np.testing.assert_allclose(vals[2:4], 0.0, atol=1e-6)
    assert np.isfinite(vals[4]) and vals[4] == pytest.approx(500.0)


def test_poisson_values():
    z = jnp.array([0.0, 1.0])
    y = jnp.array([2.0, 1.0])
    np.testing.assert_allclose(
        losses.POISSON.loss(z, y), [1.0, np.e - 1.0], rtol=1e-5
    )


def test_smoothed_hinge_piecewise():
    # Positive sample: margin z = m directly.
    y = jnp.ones(4)
    z = jnp.array([-1.0, 0.0, 0.5, 2.0])
    np.testing.assert_allclose(
        losses.SMOOTHED_HINGE.loss(z, y), [1.5, 0.5, 0.125, 0.0], rtol=1e-5
    )
    # Negative sample mirrors.
    np.testing.assert_allclose(
        losses.SMOOTHED_HINGE.loss(-z, jnp.zeros(4)), [1.5, 0.5, 0.125, 0.0], rtol=1e-5
    )


def test_task_routing():
    assert losses.loss_for_task(TaskType.LOGISTIC_REGRESSION) is losses.LOGISTIC
    assert losses.loss_for_task(TaskType.LINEAR_REGRESSION) is losses.SQUARED
    assert losses.loss_for_task(TaskType.POISSON_REGRESSION) is losses.POISSON
    assert not losses.loss_for_task(TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM).has_hessian


def test_mean_for_task():
    z = jnp.array([0.0, 1.0])
    np.testing.assert_allclose(
        losses.mean_for_task(TaskType.LOGISTIC_REGRESSION, z), [0.5, 1 / (1 + np.exp(-1))]
    )
    np.testing.assert_allclose(losses.mean_for_task(TaskType.LINEAR_REGRESSION, z), z)
    np.testing.assert_allclose(
        losses.mean_for_task(TaskType.POISSON_REGRESSION, z), np.exp([0.0, 1.0])
    )

"""Model bridge space-conversion invariants (reference:
NormalizationContext.scala:73-107 modelToOriginalSpace/TransformedSpace,
RandomEffectCoordinate warm start)."""

import numpy as np
import pytest

import jax.numpy as jnp

from photon_ml_tpu.data.game_dataset import (
    FixedEffectDataConfig,
    GameDataset,
    RandomEffectDataConfig,
)
from photon_ml_tpu.estimators.game_estimator import GameEstimator
from photon_ml_tpu.io import model_bridge
from photon_ml_tpu.optimize.config import L2, CoordinateOptimizationConfig, OptimizerConfig
from photon_ml_tpu.transformers.game_transformer import GameTransformer
from photon_ml_tpu.types import NormalizationType, TaskType


def _data(seed, n=300, n_entities=6):
    rng = np.random.default_rng(seed)
    X = np.concatenate(
        [rng.normal(loc=3.0, scale=[5.0, 0.5, 1.0], size=(n, 3)), np.ones((n, 1))],
        axis=1,
    ).astype(np.float32)
    entity = rng.integers(0, n_entities, size=n)
    w = np.array([0.3, -2.0, 1.0, 0.5])
    b = rng.normal(size=(n_entities, 4)) * 0.5
    m = X @ w + np.einsum("nd,nd->n", X, b[entity])
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-m))).astype(np.float32)
    return GameDataset.build(
        {"s": jnp.asarray(X)}, y, id_tags={"memberId": entity}
    )


@pytest.mark.parametrize(
    "norm",
    [NormalizationType.STANDARDIZATION, NormalizationType.SCALE_WITH_STANDARD_DEVIATION],
)
def test_save_load_round_trip_with_normalization(norm):
    """Scores from the training-space transformer and from the saved
    original-space artifact must agree — including shift-based normalization
    on an identity-projected (dense) RE shard."""
    train = _data(0)
    cfg = CoordinateOptimizationConfig(
        optimizer=OptimizerConfig(max_iterations=25), regularization=L2, reg_weight=1.0
    )
    est = GameEstimator(
        TaskType.LOGISTIC_REGRESSION,
        {
            "fixed": FixedEffectDataConfig("s"),
            "per-m": RandomEffectDataConfig("memberId", "s", min_bucket=4),
        },
        normalization=norm,
        intercept_indices={"s": 3},
    )
    model = est.fit(train, None, [{"fixed": cfg, "per-m": cfg}])[0].model
    specs = est.scoring_specs()

    holdout = _data(1)
    trained_scores = np.asarray(
        GameTransformer(model, specs, TaskType.LOGISTIC_REGRESSION)
        .transform(holdout)
        .scores
    )

    artifact = model_bridge.artifact_from_game_model(
        model, specs, TaskType.LOGISTIC_REGRESSION
    )
    loaded_model, loaded_specs = model_bridge.game_model_from_artifact(artifact)
    loaded_scores = np.asarray(
        GameTransformer(loaded_model, loaded_specs, TaskType.LOGISTIC_REGRESSION)
        .transform(holdout)
        .scores
    )
    np.testing.assert_allclose(loaded_scores, trained_scores, rtol=1e-4, atol=1e-4)

    # Warm-start direction: artifact re-imported into the estimator's
    # training representation must reproduce the training-space matrices.
    ws = model_bridge.warm_start_model_for_estimator(artifact, specs)
    np.testing.assert_allclose(
        np.asarray(ws["fixed"].coefficients.means),
        np.asarray(model["fixed"].coefficients.means),
        rtol=1e-4,
        atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(ws["per-m"].coefficients_matrix),
        np.asarray(model["per-m"].coefficients_matrix),
        rtol=1e-4,
        atol=1e-5,
    )

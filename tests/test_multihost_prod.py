"""Multi-host production mode (ISSUE 17): `cli/train --multihost` /
`cli/serve --multihost` with whole-host loss as a survivable failure
domain.

What is certified here, each against the reference semantics Photon ML
got from Spark/YARN for free (PARITY.md "Mesh failure semantics"):

* a 2-process fit is bitwise-equal to the single-process fit on the
  same data (mirrored sample arrays + entity-sharded buckets over the
  cross-process mesh change the topology, never the floats);
* per-host disjoint file-set ingest partitions the corpus exactly —
  no file read twice, none dropped, merged arrays equal the monolithic
  read's;
* SIGKILLing a whole host mid-fit costs exactly one repeated sweep:
  the supervisor journals the typed `host_loss`, relaunches on the
  survivor set, and the fit resumes from the last committed step;
* a torn multi-host checkpoint (a host's shards never reached the
  commit barrier) is refused loudly, NAMING the host that wrote the
  missing shards;
* SIGKILLing a serving host mid-replay fails ZERO requests: the lost
  host's rows degrade to the pinned-zero FE-only tier through the
  survivors (PR 10 shard-loss semantics), every resident row stays
  bitwise-identical to the single-process serve.

All out of tier-1 (slow + multihost): every test spawns OS processes
that bring up their own jax runtime.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

pytestmark = [pytest.mark.slow, pytest.mark.multihost]

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SHARD_DSL = "name=globalShard,feature.bags=features,intercept=true"
COORD_DSLS = [
    "name=global,feature.shard=globalShard,optimizer=LBFGS,"
    "tolerance=1e-7,max.iter=25,regularization=L2,reg.weights=0.1",
    "name=per-member,random.effect.type=memberId,feature.shard=globalShard,"
    "optimizer=LBFGS,max.iter=15,regularization=L2,reg.weights=1,"
    "min.bucket=4,projector=IDENTITY",
]
FILE_SIZES = (120, 80, 100, 60)
N_ENTITIES = 10


def _subprocess_env(**extra):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra)
    return env


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """Four Avro part files (360 rows, 10 entities) + the prebuilt
    off-heap feature index — one corpus for every fit/serve below."""
    from photon_ml_tpu.cli import build_index
    from photon_ml_tpu.io.avro_data import write_training_examples

    root = tmp_path_factory.mktemp("mh_corpus")
    data = root / "data"
    data.mkdir()
    w_true = np.random.default_rng(99).normal(size=4)
    b_true = np.random.default_rng(98).normal(size=(N_ENTITIES, 2))
    for seed, n in enumerate(FILE_SIZES):
        rng = np.random.default_rng(seed)
        X = rng.normal(size=(n, 4))
        entity = rng.integers(0, N_ENTITIES, size=n)
        margins = X @ w_true + np.einsum(
            "nd,nd->n", X[:, :2], b_true[entity]
        )
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-margins))).astype(
            np.float32
        )
        write_training_examples(
            str(data / f"part-{seed}.avro"),
            [[(f"f{j}", float(X[i, j])) for j in range(4)] for i in range(n)],
            y.tolist(),
            uids=[f"uid{seed}_{i}" for i in range(n)],
            id_tags={"memberId": [f"m{e}" for e in entity]},
        )
    idx = root / "index"
    build_index.main([
        "--input-data-directories", str(data),
        "--feature-shard-configurations", SHARD_DSL,
        "--output-dir", str(idx),
    ])
    return {"data": str(data), "index": str(idx)}


def _train_argv(corpus, out, n_hosts, iterations):
    return [
        sys.executable, "-m", "photon_ml_tpu.cli.train",
        "--training-task", "LOGISTIC_REGRESSION",
        "--input-data-directories", corpus["data"],
        "--root-output-directory", str(out),
        "--feature-shard-configurations", SHARD_DSL,
        "--coordinate-configurations", *COORD_DSLS,
        "--coordinate-descent-iterations", str(iterations),
        "--offheap-indexmap-dir", corpus["index"],
        "--checkpoint-directory", os.path.join(str(out), "ckpt"),
        "--multihost", str(n_hosts),
        "--multihost-devices-per-host", str(8 // n_hosts),
        "--random-seed", "7",
    ]


def _run_fit(corpus, out, n_hosts, iterations=2):
    r = subprocess.run(
        _train_argv(corpus, out, n_hosts, iterations),
        env=_subprocess_env(),
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert r.returncode == 0, (
        f"--multihost {n_hosts} fit failed:\n{r.stderr[-4000:]}\n"
        + _worker_errs(out)
    )
    with open(os.path.join(str(out), "training-summary.json")) as f:
        return json.load(f)


def _worker_errs(out) -> str:
    chunks = []
    for dirpath, _, files in os.walk(str(out)):
        for fn in files:
            if fn.endswith(".err") or fn == "worker.err":
                body = open(os.path.join(dirpath, fn)).read()
                if body.strip():
                    chunks.append(f"--- {dirpath}/{fn} ---\n{body[-3000:]}")
    return "\n".join(chunks)


def _model_records(out):
    """models/best as comparable blobs: Avro files at the PARSED-record
    level (container files embed a random sync marker, raw bytes differ
    on every write), everything else raw."""
    from photon_ml_tpu.io import avro as avro_io

    blobs = {}
    mdir = os.path.join(str(out), "models", "best")
    for dirpath, _, files in os.walk(mdir):
        for fn in sorted(files):
            p = os.path.join(dirpath, fn)
            rel = os.path.relpath(p, mdir)
            if fn.endswith(".avro"):
                _, recs = avro_io.read_container(p)
                blobs[rel] = repr(recs)
            else:
                with open(p, "rb") as f:
                    blobs[rel] = f.read()
    return blobs


@pytest.fixture(scope="module")
def fit_single(corpus, tmp_path_factory):
    out = tmp_path_factory.mktemp("fit1")
    return out, _run_fit(corpus, out, 1)


@pytest.fixture(scope="module")
def fit_two_host(corpus, tmp_path_factory):
    out = tmp_path_factory.mktemp("fit2")
    return out, _run_fit(corpus, out, 2)


def test_two_process_fit_bitwise_parity(fit_single, fit_two_host):
    """The acceptance contract: same data, same seed, same GLOBAL device
    count — one process vs two processes over DCN produce the SAME model
    artifact, record for record."""
    out1, s1 = fit_single
    out2, s2 = fit_two_host
    assert s1["multihost"]["num_hosts"] == 1
    assert s2["multihost"]["num_hosts"] == 2
    assert s2["multihost"]["host_losses"] == 0
    b1, b2 = _model_records(out1), _model_records(out2)
    assert set(b1) == set(b2), set(b1) ^ set(b2)
    differing = [k for k in b1 if b1[k] != b2[k]]
    assert not differing, f"artifact diverged across host counts: {differing}"


def test_disjoint_ingest_partition(corpus):
    """The exchange_ingest mechanism, piecewise: the byte-balanced host
    slices (`_balanced_slice`, the mapred-input-split analogue) are
    disjoint and cover every file, and per-FILE reads reassembled in
    sorted-file order (`concat_datasets`) reproduce the monolithic read
    bitwise — row order is a property of the file list, never of which
    host decoded what."""
    from photon_ml_tpu.cli.config import parse_feature_shard_config
    from photon_ml_tpu.data.game_dataset import concat_datasets
    from photon_ml_tpu.io import avro as avro_io
    from photon_ml_tpu.io.avro_data import _balanced_slice, read_game_dataset
    from photon_ml_tpu.io.paldb import resolve_offheap_index_maps

    shard_configs = dict([parse_feature_shard_config(SHARD_DSL)])
    index_maps = resolve_offheap_index_maps(corpus["index"], shard_configs)
    files = sorted(avro_io.list_container_files(corpus["data"]))

    def _read(paths):
        ds, _ = read_game_dataset(
            paths,
            shard_configs,
            index_maps=index_maps,
            id_tag_fields=["memberId"],
        )
        return ds

    mine = {k: _balanced_slice(files, k, 2) for k in (0, 1)}
    assert not (set(mine[0]) & set(mine[1])), "hosts decode a file twice"
    assert set(mine[0]) | set(mine[1]) == set(files), "a file was dropped"
    assert mine[0] and mine[1], "a host got no files"

    whole = _read(files)
    per_file = {f: _read([f]) for f in files}  # who decodes is irrelevant
    assert (
        sum(d.num_samples for d in per_file.values()) == whole.num_samples
    )
    merged = per_file[files[0]]
    for f in files[1:]:
        merged = concat_datasets(merged, per_file[f])
    np.testing.assert_array_equal(
        np.asarray(merged.labels), np.asarray(whole.labels)
    )
    np.testing.assert_array_equal(
        np.asarray(merged.offsets), np.asarray(whole.offsets)
    )
    for s in whole.shards:
        np.testing.assert_array_equal(
            np.asarray(merged.shards[s].values),
            np.asarray(whole.shards[s].values),
        )


def test_sigkill_midfit_costs_one_sweep(corpus, tmp_path):
    """SIGKILL a whole worker process after the first checkpoint commit:
    the supervisor journals the typed `host_loss`, relaunches on the
    survivor set, and the fit completes having repeated exactly ONE
    sweep — the YARN-relaunch semantics, one level stronger (bitwise
    checkpointed resume instead of lineage recompute)."""
    out = tmp_path / "chaos"
    env = _subprocess_env()
    sup = subprocess.Popen(
        _train_argv(corpus, out, 2, iterations=8),
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )
    state = os.path.join(str(out), "ckpt", "state.json")
    pid_file = os.path.join(str(out), "hosts", "attempt0-host1", "pid")
    deadline = time.time() + 300
    try:
        while time.time() < deadline and not os.path.exists(state):
            assert sup.poll() is None, (
                f"supervisor exited early rc={sup.returncode}:\n"
                f"{sup.communicate()[1][-4000:]}\n{_worker_errs(out)}"
            )
            time.sleep(0.05)
        assert os.path.exists(state), "no checkpoint commit within timeout"
        os.kill(int(open(pid_file).read()), signal.SIGKILL)
        so, se = sup.communicate(timeout=600)
    finally:
        if sup.poll() is None:
            sup.kill()
    assert sup.returncode == 0, f"{se[-4000:]}\n{_worker_errs(out)}"

    with open(os.path.join(str(out), "training-summary.json")) as f:
        mh = json.load(f)["multihost"]
    assert mh["host_losses"] == 1, mh
    assert mh["repeated_sweeps"] == 1, mh
    assert mh["attempts"] == 2, mh
    assert mh["final_hosts"] == 1, mh
    # The supervisor's journal carries the schema-validated host_loss
    # event (a SIGKILLed worker never writes its own).
    with open(os.path.join(str(out), "journal.jsonl")) as f:
        events = [json.loads(ln) for ln in f if ln.strip()]
    losses = [e for e in events if e.get("type") == "host_loss"]
    assert len(losses) == 1, events
    assert losses[0]["host"] == 1 and losses[0]["num_hosts"] == 2, losses
    from photon_ml_tpu.utils.contracts import JOURNAL_EVENT_SCHEMAS

    for field in JOURNAL_EVENT_SCHEMAS["host_loss"]:
        assert field in losses[0], (field, losses[0])
    assert os.path.isfile(
        os.path.join(str(out), "models", "best", "model-metadata.json")
    )


def test_torn_multihost_checkpoint_refused(fit_two_host):
    """Delete one host's committed shard out from under state.json: the
    load refuses before touching any file, naming the host that wrote
    the missing shard — a torn checkpoint is never silently part-loaded."""
    import types

    from photon_ml_tpu.game.checkpoint import CheckpointIntegrityError
    from photon_ml_tpu.parallel.hostmesh import MultihostCheckpoint

    out, _ = fit_two_host
    ckpt_dir = os.path.join(str(out), "ckpt")
    with open(os.path.join(ckpt_dir, "state.json")) as f:
        state = json.load(f)
    shard_hosts = state["multihost"]["shard_hosts"]
    victim = sorted(r for r in shard_hosts if shard_hosts[r] == 1)[0]
    os.remove(os.path.join(ckpt_dir, victim))
    hm = types.SimpleNamespace(
        host_id=0, num_hosts=2, devices_per_host=4, mesh=None, rendezvous=""
    )
    ckpt = MultihostCheckpoint(ckpt_dir, hm, attempt=0)
    with pytest.raises(CheckpointIntegrityError, match="host 1"):
        ckpt.load("LOGISTIC_REGRESSION")


# ----------------------------------------------------------------- serving


def _serve_argv(corpus, model_dir, out):
    return [
        sys.executable, "-m", "photon_ml_tpu.cli.serve",
        "--model-input-directory", str(model_dir),
        "--requests", corpus["data"],
        "--root-output-directory", str(out),
        "--feature-shard-configurations", SHARD_DSL,
        "--offheap-indexmap-dir", corpus["index"],
        "--model-id", "m1",
    ]


def _read_scores(out):
    from photon_ml_tpu.io import avro as avro_io

    recs = {}
    for p in sorted(
        avro_io.list_container_files(os.path.join(str(out), "scores"))
    ):
        for r in avro_io.read_container(p)[1]:
            recs[r["uid"]] = r["predictionScore"]
    return recs


def test_sigkill_midreplay_zero_failed_requests(
    corpus, fit_single, tmp_path
):
    """SIGKILL one of two serving hosts mid-replay with no retry budget:
    every request is still answered (zero failed), the lost host's rows
    degrade to the pinned-zero FE-only tier through the survivor, and
    every answer WITHOUT a shard-loss fallback is bitwise-identical to
    the single-process serve of the same artifact."""
    model_dir = os.path.join(str(fit_single[0]), "models", "best")

    ref_out = tmp_path / "ref"
    r = subprocess.run(
        _serve_argv(corpus, model_dir, ref_out),
        env=_subprocess_env(
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            PHOTON_SERVING_ENTITY_SHARD="1",
        ),
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert r.returncode == 0, r.stderr[-4000:]
    ref = _read_scores(ref_out)
    assert len(ref) == sum(FILE_SIZES)

    mh_out = tmp_path / "mh"
    sup = subprocess.Popen(
        _serve_argv(corpus, model_dir, mh_out) + ["--multihost", "2"],
        env=_subprocess_env(PHOTON_HOST_LOSS_RETRIES="0"),
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )
    pid_file = os.path.join(
        str(mh_out), "hosts", "attempt0-host1", "pid"
    )
    deadline = time.time() + 300
    try:
        while time.time() < deadline and not os.path.exists(pid_file):
            assert sup.poll() is None, (
                f"serve supervisor exited early rc={sup.returncode}:\n"
                f"{sup.communicate()[1][-4000:]}\n{_worker_errs(mh_out)}"
            )
            time.sleep(0.02)
        os.kill(int(open(pid_file).read()), signal.SIGKILL)
        so, se = sup.communicate(timeout=600)
    finally:
        if sup.poll() is None:
            sup.kill()
    assert sup.returncode == 0, f"{se[-4000:]}\n{_worker_errs(mh_out)}"

    with open(os.path.join(str(mh_out), "serving-summary.json")) as f:
        summary = json.load(f)
    mh = summary["multihost"]
    assert summary["failed_requests"] == 0, summary
    assert mh["host_losses"] == 1 and mh["survivor_hosts"] == 1, mh
    assert mh["fe_only_answers"] > 0, mh
    with open(os.path.join(str(mh_out), "journal.jsonl")) as f:
        events = [json.loads(ln) for ln in f if ln.strip()]
    losses = [e for e in events if e.get("type") == "host_loss"]
    assert len(losses) == 1 and losses[0]["source"] == "serve-supervisor"

    got = _read_scores(mh_out)
    assert set(got) == set(ref)
    differing = [u for u in ref if ref[u] != got[u]]
    # Only degraded answers may move, and they must actually be counted.
    assert len(differing) <= mh["fe_only_answers"], (
        len(differing), mh["fe_only_answers"],
    )
